"""Capture BENCH_r06.json — host-plane rerun for the event-driven
streaming runtime round: full-size wordcount + 2-proc exchange
efficiency + streaming latency-vs-rate with the per-stage breakdown.

Run from the repo root: ``JAX_PLATFORMS=cpu python scripts/bench_r06.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PATHWAY_GC_INTERVAL_S", "10")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402  (repo-root import)


def main() -> None:
    extra: dict = {}
    t0 = time.perf_counter()
    bench.bench_wordcount(extra)
    bench.bench_wordcount_multiprocess(extra)
    bench.bench_streaming_latency(extra)
    wall = time.perf_counter() - t0
    doc = {
        "cmd": (
            "JAX_PLATFORMS=cpu python scripts/bench_r06.py "
            "(bench.bench_wordcount + bench.bench_wordcount_multiprocess "
            "+ bench.bench_streaming_latency, full 2M-line corpus)"
        ),
        "host": "1-core driver box, CPU-only (no TPU attached)",
        "wall_seconds": round(wall, 1),
        "parsed": {
            "metric": "streaming_latency_p99_ms_30k",
            "value": extra["streaming_latency_vs_rate"]["30000"]["p99_ms"],
            "unit": "ms",
            "extra": extra,
        },
    }
    out = os.path.join(os.path.dirname(__file__), "..", "BENCH_r06.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc["parsed"]))


if __name__ == "__main__":
    main()
