#!/usr/bin/env python3
"""Concurrency-discipline lint for the engine's threaded runtime.

AST-based checks over ``engine/cluster.py`` and ``engine/scheduler.py``
(and any file passed on the command line):

- **LK001** — condition-variable ``wait()`` without a predicate
  discipline: a ``cv.wait(...)`` must sit inside a ``while`` loop OR be
  followed by a re-check of shared state in the same function (the
  generation-wait idiom ``if self._seq != seen: ...; self._cv.wait(t);
  return self._seq != seen`` re-checks after the wait).  A bare wait as
  the final statement misses wakeups and races on spurious returns.
- **LK002** — inconsistent lock acquisition order: two locks taken via
  nested ``with`` blocks in both A→B and B→A order anywhere in the
  linted set is a deadlock waiting for contention.
- **LK003** — bare ``time.sleep`` in scheduler paths: the event-driven
  scheduler must park on notified waits (``Event.wait`` /
  ``WakeupHub.wait``), never on fixed sleeps that put a floor under
  latency.  Connection-dial retry loops in ``cluster.py`` are exempt
  (the peer genuinely isn't there yet).
- **LK004** — ``cv.notify()`` / ``cv.notify_all()`` without a lexically
  enclosing ``with`` over the condvar or a lock: ``threading.Condition``
  raises RuntimeError; a hand-rolled condvar silently races the waiter's
  predicate check (the classic lost-wakeup window).
- **LK005** — unbounded blocking in cluster paths: a dead peer must be
  *detected*, never waited on forever.  In files whose name contains
  ``cluster`` (override with ``cluster_path=``) this flags
  ``settimeout(None)`` (re-arms an infinite socket), condvar ``wait()``
  calls with no timeout argument, and ``recv``/``recv_into`` inside a
  class that never arms a finite ``settimeout`` — each is an infinite
  wait that turns a peer crash into a hang instead of a bounded-time
  liveness failure.
- **LK007** — whole-repo lock-order deadlock lint: an inter-procedural
  **may-hold-while-acquiring** graph built over every class and function
  in the scanned tree (``engine/``, ``internals/``, ``stdlib/indexing/``,
  ``serving/`` by default).  Nodes are locks (``Class.attr`` for
  ``self._lock``-style members, ``module:name`` for globals like
  ``segments:_main_mutex``); an edge A→B means some code path acquires B
  — directly or through any chain of resolvable calls (``self.m()``,
  ``self.attr.m()`` via ``self.attr = Class(...)`` assignments, bare
  same-module calls) — while holding A.  Any cycle is a potential
  deadlock and is reported once with the full lock-order path and the
  call chain witnessing each edge.
- **LK008** — unbounded in-memory growth: a ``queue.Queue()`` /
  ``deque()`` instance member constructed without ``maxsize``/``maxlen``
  that some method inserts into while no method in the class ever
  drains it (``get``/``popleft``/``pop``/``clear``/``del``/swap), or a
  dict/list/set member whose name admits it is a cache (*cache*,
  *memo*, *history*, *dedup*) with inserts but no eviction.  Either one
  is operator state that grows with the stream — the runtime
  counterpart of the analyzer's PW-M001.
- **LK009** — backpressure discipline in producer-consumer paths: in
  files under ``engine/``, ``io/``, or ``serving/`` (override with
  ``pressure_path=``) every ``queue.Queue()`` / ``deque()`` constructed
  without ``maxsize``/``maxlen`` is flagged at its assignment site —
  an unbounded handoff queue is a backpressure hole: the producer
  never feels a slow consumer, memory does.  Unlike LK008 this fires
  even when the queue *is* drained (a drained-but-unbounded queue
  still grows whenever the producer outruns the consumer).  Queues
  whose bound lives elsewhere (byte-credit accounting, an epoch
  budget) are allowlisted with an ``# lk009: <why it is bounded>``
  comment on the construction line.
- **LK010** — device work under a lock: in files that import jax
  (override with ``device_path=``), a device dispatch or host<->device
  transfer inside a lexical ``with <lock>:`` block — ``jax.device_put``
  / ``device_get``, any ``jnp.*`` call (implicit upload + dispatch), a
  ``.block_until_ready()`` sync, or a call whose name marks it jitted
  (``*_jit*`` / assigned from ``jax.jit``).  Device dispatch enqueues
  work whose completion the lock holder may then wait on, so every
  other thread contending the lock eats the device's latency; a
  blocking sync under an index lock turns one slow kernel into a
  serving-wide stall.  Stage arrays outside the lock and hold it only
  for the pointer swap.  ``copy_to_host_async`` is exempt (it is the
  non-blocking idiom this check pushes toward); a transfer whose
  bounded cost is understood is allowlisted with an ``# lk010: <why>``
  comment on the call line.
- **LK006** — serving-path wait discipline: in files under ``serving/``
  (override with ``serving_path=``) every queue handoff must ride the
  WakeupHub and every admission-path wait must be finite.  Flags bare
  ``time.sleep`` (polling puts a floor under tail latency), any
  ``.wait()`` with no timeout (or an explicit ``None``), and zero-
  argument ``.join()`` / ``.result()`` / ``.get()`` (each blocks a
  serving thread forever if its producer died).

Usage: ``python scripts/check_locks.py [files...]``; exits 1 on
findings.  Importable — tests feed synthetic sources through
``check_source``.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass

#: attribute/variable names treated as condition variables
CV_NAMES = {"_cv", "cv", "cond", "_cond", "condition", "_condition"}

#: receivers whose .wait() is a notified single-waiter primitive, not a
#: condvar (threading.Event, our WakeupHub generation-wait)
NON_CV_WAIT = {"_stop", "stop", "hub", "_hub", "event", "_event", "_barrier"}


@dataclass(frozen=True)
class Finding:
    file: str
    line: int
    code: str
    message: str

    def format(self) -> str:
        return f"{self.file}:{self.line}: {self.code} {self.message}"


def _recv_name(func: ast.expr) -> str | None:
    """The receiver identifier of ``recv.meth(...)``: last attribute of
    the receiver chain, or the bare variable name."""
    if not isinstance(func, ast.Attribute):
        return None
    recv = func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return recv.id
    return None


def _locky(name: str) -> bool:
    n = name.lower()
    return "lock" in n or "mutex" in n


def _lock_name(expr: ast.expr) -> str | None:
    """Identifier for a ``with <expr>:`` item that looks like a lock."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return None
    return name if _locky(name) else None


class _FunctionScanner(ast.NodeVisitor):
    """Per-function scan for LK001: cv waits that are neither inside a
    while loop nor followed by further statements (the re-check)."""

    def __init__(self, filename: str, findings: list[Finding]):
        self.filename = filename
        self.findings = findings

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scan_function(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _scan_function(self, fn: ast.AST) -> None:
        waits: list[ast.Call] = []
        in_while: set[int] = set()

        def walk(node: ast.AST, while_depth: int) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue  # nested functions scan separately
                d = while_depth + (1 if isinstance(child, ast.While) else 0)
                if isinstance(child, ast.Call):
                    recv = _recv_name(child.func)
                    if (
                        isinstance(child.func, ast.Attribute)
                        and child.func.attr == "wait"
                        and recv in CV_NAMES
                    ):
                        waits.append(child)
                        if d > 0:
                            in_while.add(id(child))
                walk(child, d)

        walk(fn, 0)
        if not waits:
            return
        # a wait outside any while loop needs a post-wait re-check: at
        # least one statement in the function strictly after the wait
        last_stmt_line = max(
            getattr(n, "lineno", 0) for n in ast.walk(fn)
        )
        for w in waits:
            if id(w) in in_while:
                continue
            if last_stmt_line > w.lineno:
                continue  # something (a predicate re-check) follows
            self.findings.append(
                Finding(
                    self.filename,
                    w.lineno,
                    "LK001",
                    "condition-variable wait() outside a while loop with "
                    "no predicate re-check after it; spurious wakeups and "
                    "missed notifies will race",
                )
            )


#: condvar methods that require the condvar's lock to be held
_NOTIFY_METHODS = {"notify", "notify_all"}


def _check_notify_discipline(
    tree: ast.AST, filename: str, findings: list[Finding]
) -> None:
    """LK004: ``cv.notify()`` / ``cv.notify_all()`` outside any lexically
    enclosing ``with`` over the condvar (or a lock).  ``threading.
    Condition`` raises RuntimeError at runtime; a hand-rolled condvar
    silently races the waiter's predicate check instead."""

    def _held_name(expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        else:
            return None
        if name in CV_NAMES or "lock" in name.lower():
            return name
        return None

    def walk(node: ast.AST, held: frozenset[str]) -> None:
        for child in ast.iter_child_nodes(node):
            inner = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                names = {
                    n
                    for n in (
                        _held_name(item.context_expr) for item in child.items
                    )
                    if n is not None
                }
                if names:
                    inner = held | names
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and child.func.attr in _NOTIFY_METHODS
                and _recv_name(child.func) in CV_NAMES
                and not held
            ):
                findings.append(
                    Finding(
                        filename,
                        child.lineno,
                        "LK004",
                        f"{child.func.attr}() on a condition variable "
                        "without holding its lock (no enclosing `with` "
                        "over the condvar or a lock); the wakeup races "
                        "the waiter's predicate check",
                    )
                )
            walk(child, inner)

    walk(tree, frozenset())


def _collect_lock_pairs(
    tree: ast.AST, filename: str
) -> dict[tuple[str, str], int]:
    """(outer, inner) -> first line where that nesting order occurs."""
    pairs: dict[tuple[str, str], int] = {}

    def walk(node: ast.AST, held: list[str]) -> None:
        for child in ast.iter_child_nodes(node):
            acquired: list[str] = []
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    ln = _lock_name(item.context_expr)
                    if ln is not None:
                        for h in held + acquired:
                            if h != ln:
                                pairs.setdefault((h, ln), child.lineno)
                        acquired.append(ln)
            walk(child, held + acquired)

    walk(tree, [])
    return pairs


def _check_liveness_discipline(
    tree: ast.AST, filename: str, findings: list[Finding]
) -> None:
    """LK005 (cluster paths only): no unbounded blocking primitive may
    wait on a peer — ``settimeout(None)``, a condvar ``wait()`` without a
    timeout, or ``recv``/``recv_into`` in a class that never arms a
    finite socket timeout all turn a dead peer into an infinite hang."""

    def _is_none(expr: ast.expr) -> bool:
        return isinstance(expr, ast.Constant) and expr.value is None

    def _scan_scope(scope: ast.AST, scope_name: str) -> None:
        has_finite_settimeout = False
        recvs: list[ast.Call] = []
        for node in ast.walk(scope):
            if node is not scope and isinstance(node, ast.ClassDef):
                continue  # nested classes scan as their own scope
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            meth = node.func.attr
            if meth == "settimeout":
                if node.args and _is_none(node.args[0]):
                    findings.append(
                        Finding(
                            filename,
                            node.lineno,
                            "LK005",
                            "settimeout(None) re-arms an infinite socket "
                            "in a cluster path; a dead peer then hangs "
                            "recv forever instead of tripping the "
                            "liveness deadline",
                        )
                    )
                else:
                    has_finite_settimeout = True
            elif (
                meth == "wait"
                and _recv_name(node.func) in CV_NAMES
                and not node.args
                and not node.keywords
            ):
                findings.append(
                    Finding(
                        filename,
                        node.lineno,
                        "LK005",
                        "condvar wait() without a timeout in a cluster "
                        "path; the notifier may be a peer that just "
                        "died — bound the wait or register with the "
                        "WakeupHub",
                    )
                )
            elif meth in ("recv", "recv_into"):
                recvs.append(node)
        if recvs and not has_finite_settimeout:
            for node in recvs:
                findings.append(
                    Finding(
                        filename,
                        node.lineno,
                        "LK005",
                        f"{node.func.attr}() in {scope_name} with no "  # type: ignore[union-attr]
                        "finite settimeout anywhere in the class; a "
                        "silent peer blocks this thread forever",
                    )
                )

    # each class is its own liveness scope (a class that arms a finite
    # timeout once may recv anywhere); module-level code is one scope
    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    for cls in classes:
        _scan_scope(cls, f"class {cls.name}")
    _scan_scope(tree, "module scope") if not classes else None
    if classes:
        # module-level statements outside any class still need the scan;
        # build a shallow pseudo-scope excluding class bodies
        module_nodes = [
            n
            for n in ast.iter_child_nodes(tree)
            if not isinstance(n, ast.ClassDef)
        ]
        pseudo = ast.Module(body=module_nodes, type_ignores=[])
        _scan_scope(pseudo, "module scope")


def _check_serving_discipline(
    tree: ast.AST, filename: str, findings: list[Finding]
) -> None:
    """LK006 (serving paths only): finite waits everywhere.  The serving
    layer's contract is bounded everything — queues are capped by
    admission, so the only way a request hangs is an unbounded wait.
    Flags bare ``time.sleep``, ``.wait()`` with no timeout (or a literal
    ``None`` timeout), and zero-argument ``.join()``/``.result()``/
    ``.get()``."""

    def _none_arg(node: ast.Call) -> bool:
        for a in node.args:
            if isinstance(a, ast.Constant) and a.value is None:
                return True
        for kw in node.keywords:
            if kw.arg == "timeout" and (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            ):
                return True
        return False

    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
        ):
            continue
        meth = node.func.attr
        if (
            meth == "sleep"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("time", "_time")
        ):
            findings.append(
                Finding(
                    filename,
                    node.lineno,
                    "LK006",
                    "polling time.sleep in a serving path; park on a "
                    "WakeupHub generation-wait (or Event.wait with a "
                    "timeout) so a notify wakes the handoff immediately",
                )
            )
        elif meth == "wait":
            if (not node.args and not node.keywords) or _none_arg(node):
                findings.append(
                    Finding(
                        filename,
                        node.lineno,
                        "LK006",
                        "wait() without a finite timeout in a serving "
                        "path; every admission-path wait must have a "
                        "deadline or a request can hang forever",
                    )
                )
        elif meth in ("join", "result", "get") and not node.args and not node.keywords:
            findings.append(
                Finding(
                    filename,
                    node.lineno,
                    "LK006",
                    f"{meth}() with no timeout in a serving path blocks "
                    "this thread forever if the producer died; pass a "
                    "finite timeout",
                )
            )


#: substrings marking an instance dict/list/set as a cache (LK008's
#: second arm only fires on members whose name admits they accumulate)
CACHE_NAME_HINTS = ("cache", "memo", "history", "dedup")

#: call methods that add entries to a container
_GROW_METHODS = {
    "append",
    "appendleft",
    "add",
    "setdefault",
    "put",
    "put_nowait",
    "extend",
    "insert",
    "update",
}

#: call methods that remove entries from a queue-like container
_QUEUE_DRAIN_METHODS = {"get", "get_nowait", "pop", "popleft", "clear"}

#: call methods that evict entries from a cache-like container
_CACHE_EVICT_METHODS = {"pop", "popitem", "popleft", "clear", "remove", "discard"}


def _self_attr(expr: ast.expr) -> str | None:
    """``x`` for a plain ``self.x`` expression, else None."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def _unbounded_container(value: ast.expr) -> str | None:
    """Classify an assigned value as an unbounded long-lived container.

    Returns ``"queue"`` for ``queue.Queue()`` with no maxsize /
    ``deque()`` with no maxlen, ``"dict"``/``"list"``/``"set"`` for the
    corresponding empty literals or zero-arg constructors, None for
    anything bounded or unrecognised."""
    if isinstance(value, ast.Dict) and not value.keys:
        return "dict"
    if isinstance(value, (ast.List, ast.Set)) and not value.elts:
        return "list" if isinstance(value, ast.List) else "set"
    if not isinstance(value, ast.Call):
        return None
    func = value.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else None
    )
    if name == "Queue":
        # maxsize is the first positional; Queue(0) is explicitly infinite
        bounded = any(kw.arg == "maxsize" for kw in value.keywords)
        if value.args:
            a = value.args[0]
            bounded = not (isinstance(a, ast.Constant) and a.value == 0)
        return None if bounded else "queue"
    if name == "deque":
        # deque(iterable, maxlen) — second positional or keyword bounds it
        bounded = len(value.args) >= 2 or any(
            kw.arg == "maxlen" for kw in value.keywords
        )
        return None if bounded else "queue"
    if name in ("dict", "list", "set") and not value.args and not value.keywords:
        return name
    return None


def _check_unbounded_growth(
    tree: ast.AST, filename: str, findings: list[Finding]
) -> None:
    """LK008: long-lived instance state that only ever grows.

    Two arms, both scoped to a class (the unit of object lifetime):

    - an unbounded ``queue.Queue()`` / ``deque()`` member that some
      method inserts into while **no** method in the class ever drains
      it (``get``/``popleft``/``pop``/``clear``, ``del``, or swapping
      the attribute out) — producer-only queues grow with the stream;
    - a dict/list/set member whose name admits it is a cache
      (``CACHE_NAME_HINTS``) that is inserted into with no eviction
      anywhere in the class and no bound at construction.

    A drained queue or an evicted cache is flow control's problem
    (LK005/LK006 police the blocking side); LK008 is purely about
    accumulation with no consumer."""
    for cls in (n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)):
        # first construction site per attribute, with its container kind
        containers: dict[str, tuple[str, int]] = {}
        assigns: dict[str, int] = {}
        grows: set[str] = set()
        drains: set[str] = set()
        for node in ast.walk(cls):
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                value = node.value
                for t in node.targets:
                    targets.extend(t.elts if isinstance(t, ast.Tuple) else [t])
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                assigns[attr] = assigns.get(attr, 0) + 1
                if value is not None and attr not in containers:
                    kind = _unbounded_container(value)
                    if kind is not None:
                        containers[attr] = (kind, node.lineno)
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    if node.func.attr in _GROW_METHODS:
                        grows.add(attr)
                    if node.func.attr in (
                        _QUEUE_DRAIN_METHODS | _CACHE_EVICT_METHODS
                    ):
                        drains.add(attr)
            if isinstance(node, ast.Subscript):
                attr = _self_attr(node.value)
                if attr is not None and isinstance(node.ctx, ast.Store):
                    grows.add(attr)  # self.cache[k] = v
                if attr is not None and isinstance(node.ctx, ast.Del):
                    drains.add(attr)  # del self.cache[k]
            if isinstance(node, ast.AugAssign):
                attr = _self_attr(node.target)
                if attr is not None:
                    grows.add(attr)  # self.buf += [...]
        for attr, (kind, lineno) in sorted(
            containers.items(), key=lambda kv: kv[1][1]
        ):
            # a second assignment swaps the container out (the
            # batch, self._q = self._q, [] drain idiom)
            evicted = attr in drains or assigns.get(attr, 0) >= 2
            if attr not in grows or evicted:
                continue
            if kind == "queue":
                findings.append(
                    Finding(
                        filename,
                        lineno,
                        "LK008",
                        f"self.{attr} is an unbounded queue that "
                        f"{cls.name} inserts into but never drains; "
                        "state grows with the stream — pass maxsize/"
                        "maxlen or consume it",
                    )
                )
            elif any(h in attr.lower() for h in CACHE_NAME_HINTS):
                findings.append(
                    Finding(
                        filename,
                        lineno,
                        "LK008",
                        f"self.{attr} is a {kind} cache with inserts "
                        f"but no eviction anywhere in {cls.name}; "
                        "bound it or evict (pop/clear/del) on a policy",
                    )
                )


def _check_pressure_queues(
    tree: ast.AST, source: str, filename: str, findings: list[Finding]
) -> None:
    """LK009: unbounded handoff queues in producer-consumer paths.

    Every ``queue.Queue()`` / ``deque()`` constructed without
    ``maxsize``/``maxlen`` and assigned (instance member or local) is a
    backpressure hole — a producer that outruns its consumer grows the
    queue instead of slowing down.  Fires regardless of drain analysis
    (that is LK008's axis: accumulation with *no* consumer); the remedy
    here is a bound — ``maxsize``/``maxlen``, or an external accounting
    scheme declared on the construction line with an ``# lk009:``
    comment (the allowlist marker doubles as documentation of where the
    bound actually lives)."""
    lines = source.splitlines()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value = node.value
        else:
            continue
        if _unbounded_container(value) != "queue":
            continue
        line_src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "lk009:" in line_src:
            continue  # allowlisted: the bound lives elsewhere (documented)
        findings.append(
            Finding(
                filename,
                node.lineno,
                "LK009",
                "unbounded handoff queue in a producer-consumer path; "
                "a producer that outruns its consumer grows memory "
                "instead of slowing down — pass maxsize/maxlen, or "
                "document the external bound with an '# lk009: ...' "
                "comment on this line",
            )
        )


#: methods whose call is a device dispatch or transfer no matter the
#: receiver (jax module functions and Array methods)
_DEVICE_METHODS = {"device_put", "device_get", "block_until_ready"}


def _jax_aliases(tree: ast.AST) -> set[str]:
    """Local names bound to the jax package or its submodules
    (``import jax``, ``import jax.numpy as jnp``, ``from jax import
    numpy as jnp``); empty when the file never imports jax."""
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "jax" or node.module.startswith("jax."):
                for a in node.names:
                    aliases.add(a.asname or a.name)
    return aliases


def _check_device_under_lock(
    tree: ast.AST, source: str, filename: str, findings: list[Finding]
) -> None:
    """LK010: device dispatch or host<->device transfer while holding a
    lock.  Device calls enqueue asynchronous work — but the enqueue
    itself may block on a compile, an implicit upload serialises on the
    transfer engine, and an explicit sync (``block_until_ready`` /
    ``device_get``) parks the lock holder for the kernel's full
    latency.  Every contending thread then queues behind device time.
    The scatter-swap idiom (stage arrays outside the lock, ``with
    lock:`` only for the reference swap) keeps critical sections
    device-free.  ``copy_to_host_async`` is exempt; accepted transfers
    carry an ``# lk010: <why bounded>`` comment on the call line."""
    aliases = _jax_aliases(tree)
    lines = source.splitlines()
    # module/class-level names assigned from jax.jit(...) — calls to
    # these dispatch a (possibly compiling) executable
    jitted: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        f = node.value.func
        is_jit = (
            isinstance(f, ast.Attribute)
            and f.attr == "jit"
            and isinstance(f.value, ast.Name)
            and f.value.id in aliases
        ) or (isinstance(f, ast.Name) and f.id == "jit" and "jit" in aliases)
        if not is_jit:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                jitted.add(t.id)
            elif isinstance(t, ast.Attribute):
                jitted.add(t.attr)

    def _root_name(expr: ast.expr) -> str | None:
        while isinstance(expr, ast.Attribute):
            expr = expr.value
        return expr.id if isinstance(expr, ast.Name) else None

    def _device_call(call: ast.Call) -> str | None:
        """A short description of why this call touches the device."""
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr == "copy_to_host_async":
                return None  # the non-blocking idiom; explicitly exempt
            if f.attr in _DEVICE_METHODS:
                return f"{f.attr}()"
            root = _root_name(f.value)
            if root in aliases:
                return f"{root}.{f.attr}()"
            if "jit" in f.attr.lower() or f.attr in jitted:
                return f"jitted call {f.attr}()"
            return None
        if isinstance(f, ast.Name):
            if "jit" in f.id.lower() or f.id in jitted:
                return f"jitted call {f.id}()"
        return None

    def walk(node: ast.AST, held: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                # a nested def under `with lock:` runs later, at an
                # unknown lock state — scan its body lock-free
                walk(child, None)
                continue
            inner = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    ln = _lock_name(item.context_expr)
                    if ln is not None:
                        inner = ln
            if isinstance(child, ast.Call) and held is not None:
                what = _device_call(child)
                line_src = (
                    lines[child.lineno - 1]
                    if child.lineno <= len(lines)
                    else ""
                )
                if what is not None and "lk010:" not in line_src:
                    findings.append(
                        Finding(
                            filename,
                            child.lineno,
                            "LK010",
                            f"{what} while holding {held!r}: device "
                            "dispatch/transfer under a lock makes every "
                            "contending thread wait out device latency; "
                            "stage arrays outside the lock and hold it "
                            "only for the swap, or document the bound "
                            "with an '# lk010: ...' comment",
                        )
                    )
            walk(child, inner)

    walk(tree, None)


def check_source(
    source: str,
    filename: str,
    *,
    scheduler_path: bool | None = None,
    cluster_path: bool | None = None,
    serving_path: bool | None = None,
    pressure_path: bool | None = None,
    device_path: bool | None = None,
) -> list[Finding]:
    """Lint one file's source.  ``scheduler_path`` controls LK003
    (default: filename contains 'scheduler'); ``cluster_path`` controls
    LK005 (default: filename contains 'cluster'); ``serving_path``
    controls LK006 (default: the path contains 'serving');
    ``pressure_path`` controls LK009 (default: the path contains an
    ``engine/``, ``io/``, or ``serving/`` segment); ``device_path``
    controls LK010 (default: the file imports jax)."""
    findings: list[Finding] = []
    tree = ast.parse(source, filename=filename)

    _FunctionScanner(filename, findings).visit(tree)
    _check_notify_discipline(tree, filename, findings)
    _check_unbounded_growth(tree, filename, findings)

    if device_path is None:
        device_path = bool(_jax_aliases(tree))
    if device_path:
        _check_device_under_lock(tree, source, filename, findings)

    if pressure_path is None:
        p = "/" + filename.replace(os.sep, "/").lstrip("/")
        pressure_path = any(
            seg in p for seg in ("/engine/", "/io/", "/serving/")
        )
    if pressure_path:
        _check_pressure_queues(tree, source, filename, findings)

    if cluster_path is None:
        cluster_path = "cluster" in os.path.basename(filename)
    if cluster_path:
        _check_liveness_discipline(tree, filename, findings)

    if serving_path is None:
        serving_path = "serving" in filename.replace(os.sep, "/")
    if serving_path:
        _check_serving_discipline(tree, filename, findings)

    if scheduler_path is None:
        scheduler_path = "scheduler" in os.path.basename(filename)
    if scheduler_path:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "sleep"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("time", "_time")
            ):
                findings.append(
                    Finding(
                        filename,
                        node.lineno,
                        "LK003",
                        "bare time.sleep in a scheduler path; park on a "
                        "notified wait (Event.wait / WakeupHub.wait) "
                        "instead",
                    )
                )
    return findings


def check_lock_order(
    sources: list[tuple[str, str]]
) -> list[Finding]:
    """LK002 across a set of ``(source, filename)`` pairs: the same two
    locks nested in both orders."""
    findings: list[Finding] = []
    all_pairs: dict[tuple[str, str], tuple[str, int]] = {}
    for source, filename in sources:
        tree = ast.parse(source, filename=filename)
        for pair, line in _collect_lock_pairs(tree, filename).items():
            all_pairs.setdefault(pair, (filename, line))
    reported: set[frozenset[str]] = set()
    for (a, b), (fn, line) in sorted(all_pairs.items()):
        if (b, a) in all_pairs and frozenset((a, b)) not in reported:
            reported.add(frozenset((a, b)))
            other_fn, other_line = all_pairs[(b, a)]
            findings.append(
                Finding(
                    fn,
                    line,
                    "LK002",
                    f"locks {a!r} and {b!r} are acquired in both orders "
                    f"(other order at {other_fn}:{other_line}); pick one "
                    "global order",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# LK007: inter-procedural may-hold-while-acquiring lock graph
#
# Precise-resolution-only by design: an edge exists only when the callee
# is identified with certainty (same-class method, an attribute whose
# constructing class we saw assigned, a same-module function).  Missing
# an exotic call means a missed edge, never a false cycle — the right
# bias for a gate that must stay clean on the real tree.


def _qual(key: tuple) -> str:
    """Human name for a function key ('c', Class, meth) / ('m', mod, fn)."""
    if key[0] == "c":
        return f"{key[1]}.{key[2]}"
    return f"{key[1]}:{key[2]}"


def _lock_id(expr: ast.expr, cls_name: str | None, module_key: str) -> str | None:
    """Graph node for a ``with <expr>:`` item: ``Class.attr`` for
    ``self.X`` members, ``module:name`` for globals; None if not a lock."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        if cls_name and _locky(expr.attr):
            return f"{cls_name}.{expr.attr}"
        return None
    if isinstance(expr, ast.Attribute):
        return f"{module_key}:{expr.attr}" if _locky(expr.attr) else None
    if isinstance(expr, ast.Name):
        return f"{module_key}:{expr.id}" if _locky(expr.id) else None
    return None


def _call_spec(call: ast.Call) -> tuple | None:
    """Syntactic shape of a call we may be able to resolve."""
    f = call.func
    if isinstance(f, ast.Name):
        return ("bare", f.id)
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name) and v.id == "self":
            return ("self", f.attr)
        if (
            isinstance(v, ast.Attribute)
            and isinstance(v.value, ast.Name)
            and v.value.id == "self"
        ):
            return ("attr", v.attr, f.attr)
    return None


class _LockGraph:
    """Build per-function summaries over a set of sources, resolve calls,
    and expose the held-while-acquiring edge set."""

    def __init__(self, sources: list[tuple[str, str]]):
        #: class name -> {module, file, methods, bases, attr_types}
        self.classes: dict[str, dict] = {}
        #: (module_key, name) -> summary key for module-level functions
        self.mod_funcs: set[tuple[str, str]] = set()
        #: function key -> summary dict
        self.summaries: dict[tuple, dict] = {}
        self._acq_memo: dict[tuple, dict] = {}

        parsed = []
        for source, filename in sources:
            module_key = os.path.splitext(os.path.basename(filename))[0]
            tree = ast.parse(source, filename=filename)
            parsed.append((tree, filename, module_key))
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    methods = {
                        m.name: m
                        for m in node.body
                        if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                    }
                    bases = [
                        b.id if isinstance(b, ast.Name) else getattr(b, "attr", None)
                        for b in node.bases
                    ]
                    self.classes[node.name] = {
                        "module": module_key,
                        "file": filename,
                        "methods": methods,
                        "bases": [b for b in bases if b],
                        "attr_types": {},
                    }
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.mod_funcs.add((module_key, node.name))

        # second pass: attr types (self.x = Class(...)) + summaries
        for tree, filename, module_key in parsed:
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    info = self.classes[node.name]
                    for meth in info["methods"].values():
                        self._collect_attr_types(meth, info)
                    for mname, meth in info["methods"].items():
                        self.summaries[("c", node.name, mname)] = self._summarize(
                            meth, node.name, module_key, filename
                        )
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.summaries[("m", module_key, node.name)] = self._summarize(
                        node, None, module_key, filename
                    )

    def _collect_attr_types(self, fn: ast.AST, info: dict) -> None:
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            fexpr = node.value.func
            cname = (
                fexpr.id
                if isinstance(fexpr, ast.Name)
                else getattr(fexpr, "attr", None)
            )
            if cname not in self.classes:
                continue
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    info["attr_types"][tgt.attr] = cname

    def _summarize(
        self, fn: ast.AST, cls_name: str | None, module_key: str, filename: str
    ) -> dict:
        acquires: list[tuple[str, int]] = []
        under: list[tuple[str, tuple, int]] = []  # (held, event, line)
        calls: list[tuple[tuple, int]] = []

        def walk(node: ast.AST, held: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue  # closures run at unknown lock states
                new_held = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    got: list[str] = []
                    for item in child.items:
                        lid = _lock_id(item.context_expr, cls_name, module_key)
                        if lid is not None:
                            for h in held + got:
                                under.append((h, ("acq", lid), child.lineno))
                            got.append(lid)
                            acquires.append((lid, child.lineno))
                    new_held = held + got
                elif isinstance(child, ast.Call):
                    spec = _call_spec(child)
                    if spec is not None:
                        calls.append((spec, child.lineno))
                        for h in held:
                            under.append((h, ("call", spec), child.lineno))
                walk(child, new_held)

        walk(fn, [])
        return {
            "acquires": acquires,
            "under": under,
            "calls": calls,
            "cls": cls_name,
            "module": module_key,
            "file": filename,
        }

    # -- call resolution ------------------------------------------------
    def _method_on(self, cname: str | None, meth: str) -> tuple | None:
        seen: set[str] = set()
        while cname is not None and cname not in seen:
            seen.add(cname)
            info = self.classes.get(cname)
            if info is None:
                return None
            if meth in info["methods"]:
                return ("c", cname, meth)
            bases = info["bases"]
            cname = bases[0] if bases else None
        return None

    def resolve(self, spec: tuple, summary: dict) -> tuple | None:
        if spec[0] == "self":
            return self._method_on(summary["cls"], spec[1])
        if spec[0] == "attr":
            info = self.classes.get(summary["cls"] or "")
            tc = info["attr_types"].get(spec[1]) if info else None
            return self._method_on(tc, spec[2]) if tc else None
        # bare name: constructor of a known class, or same-module function
        name = spec[1]
        if name in self.classes:
            return self._method_on(name, "__init__")
        if (summary["module"], name) in self.mod_funcs:
            return ("m", summary["module"], name)
        return None

    # -- transitive acquisitions ----------------------------------------
    def acq_star(self, key: tuple, _stack: set | None = None) -> dict:
        """lock id -> witness call chain [(fn key, line), ...] for every
        lock ``key`` may acquire, transitively."""
        if key in self._acq_memo:
            return self._acq_memo[key]
        stack = _stack if _stack is not None else set()
        if key in stack:
            return {}
        s = self.summaries.get(key)
        if s is None:
            return {}
        out: dict[str, list] = {}
        for lid, line in s["acquires"]:
            out.setdefault(lid, [(key, line)])
        stack.add(key)
        for spec, line in s["calls"]:
            callee = self.resolve(spec, s)
            if callee is None:
                continue
            for lid, chain in self.acq_star(callee, stack).items():
                out.setdefault(lid, [(key, line)] + chain)
        stack.discard(key)
        if not stack:  # memoize only complete (non-recursive) results
            self._acq_memo[key] = out
        return out

    # -- the edge set ---------------------------------------------------
    def edges(self) -> dict[tuple[str, str], tuple[str, int, str]]:
        """(held, acquired) -> (file, line, witness description).  Edges
        between the SAME lock id are skipped: distinct instances of one
        class share an id here, so a self-edge is usually two objects."""
        out: dict[tuple[str, str], tuple[str, int, str]] = {}
        for key in sorted(self.summaries):
            s = self.summaries[key]
            for held, event, line in s["under"]:
                if event[0] == "acq":
                    lid = event[1]
                    if lid != held:
                        out.setdefault(
                            (held, lid), (s["file"], line, f"in {_qual(key)}")
                        )
                    continue
                callee = self.resolve(event[1], s)
                if callee is None:
                    continue
                for lid, chain in self.acq_star(callee).items():
                    if lid == held:
                        continue
                    via = " -> ".join(_qual(k) for k, _ in [(key, line)] + chain)
                    out.setdefault((held, lid), (s["file"], line, f"via {via}"))
        return out


def _find_cycles(edges: "dict[tuple[str, str], tuple]") -> list[list[str]]:
    """One representative cycle per distinct lock SET, deterministic."""
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    for v in adj.values():
        v.sort()
    cycles: list[list[str]] = []
    seen_sets: set[frozenset] = set()
    visited: set[str] = set()

    def dfs(node: str, path: list[str], on_path: set[str]) -> None:
        visited.add(node)
        for b in adj.get(node, ()):
            if b in on_path:
                cyc = path[path.index(b) :] + [b]
                key = frozenset(cyc)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(cyc)
            elif b not in visited:
                dfs(b, path + [b], on_path | {b})

    for start in sorted(adj):
        if start not in visited:
            dfs(start, [start], {start})
    return cycles


def check_lock_graph(sources: list[tuple[str, str]]) -> list[Finding]:
    """LK007 over a set of ``(source, filename)`` pairs: report every
    cycle in the may-hold-while-acquiring graph with its full path."""
    graph = _LockGraph(sources)
    edges = graph.edges()
    findings: list[Finding] = []
    for cyc in _find_cycles(edges):
        legs = []
        first_file, first_line = "", 0
        for a, b in zip(cyc, cyc[1:]):
            f, line, desc = edges[(a, b)]
            if not first_file:
                first_file, first_line = f, line
            legs.append(f"{a} -> {b} at {os.path.basename(f)}:{line} ({desc})")
        findings.append(
            Finding(
                first_file,
                first_line,
                "LK007",
                "potential deadlock: lock-order cycle "
                + " -> ".join(cyc)
                + "; "
                + "; ".join(legs)
                + "; break the cycle by imposing one global acquisition "
                "order or releasing before the cross-call",
            )
        )
    return findings


#: directories whose every .py feeds the LK007 whole-repo lock graph
LOCK_GRAPH_ROOTS = (
    "pathway_tpu/engine",
    "pathway_tpu/internals",
    "pathway_tpu/stdlib/indexing",
    "pathway_tpu/serving",
)


DEFAULT_TARGETS = (
    "pathway_tpu/engine/cluster.py",
    "pathway_tpu/engine/scheduler.py",
    "pathway_tpu/serving/admission.py",
    "pathway_tpu/serving/scheduler.py",
    "pathway_tpu/serving/coscheduler.py",
    "pathway_tpu/serving/graph.py",
    "pathway_tpu/serving/loadgen.py",
    "pathway_tpu/internals/tracing.py",
    # device surface: LK010 (device work under a lock) is the live check
    # here; the other per-file checks run too and must stay clean
    "pathway_tpu/parallel/sharded_knn.py",
    "pathway_tpu/parallel/ivf_knn.py",
    "pathway_tpu/parallel/executor.py",
    "pathway_tpu/stdlib/indexing/segments.py",
)


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = args or [os.path.join(repo_root, t) for t in DEFAULT_TARGETS]
    sources: list[tuple[str, str]] = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources.append((fh.read(), f))
    findings: list[Finding] = []
    for source, filename in sources:
        findings.extend(check_source(source, filename))
    findings.extend(check_lock_order(sources))

    # LK007 runs over the whole lock surface, not just the per-file
    # targets: explicit argv limits it to those files (tests), the
    # default run walks LOCK_GRAPH_ROOTS
    if args:
        graph_sources = sources
    else:
        graph_sources = []
        for root in LOCK_GRAPH_ROOTS:
            base = os.path.join(repo_root, root)
            for dirpath, _dirnames, filenames in os.walk(base):
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        p = os.path.join(dirpath, fn)
                        with open(p, encoding="utf-8") as fh:
                            graph_sources.append((fh.read(), p))
    findings.extend(check_lock_graph(graph_sources))
    for fd in findings:
        print(fd.format())
    if findings:
        print(f"{len(findings)} concurrency-discipline finding(s)", file=sys.stderr)
        return 1
    print(f"checked {len(files)} file(s): clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
