#!/usr/bin/env python3
"""Concurrency-discipline lint for the engine's threaded runtime.

AST-based checks over ``engine/cluster.py`` and ``engine/scheduler.py``
(and any file passed on the command line):

- **LK001** — condition-variable ``wait()`` without a predicate
  discipline: a ``cv.wait(...)`` must sit inside a ``while`` loop OR be
  followed by a re-check of shared state in the same function (the
  generation-wait idiom ``if self._seq != seen: ...; self._cv.wait(t);
  return self._seq != seen`` re-checks after the wait).  A bare wait as
  the final statement misses wakeups and races on spurious returns.
- **LK002** — inconsistent lock acquisition order: two locks taken via
  nested ``with`` blocks in both A→B and B→A order anywhere in the
  linted set is a deadlock waiting for contention.
- **LK003** — bare ``time.sleep`` in scheduler paths: the event-driven
  scheduler must park on notified waits (``Event.wait`` /
  ``WakeupHub.wait``), never on fixed sleeps that put a floor under
  latency.  Connection-dial retry loops in ``cluster.py`` are exempt
  (the peer genuinely isn't there yet).
- **LK004** — ``cv.notify()`` / ``cv.notify_all()`` without a lexically
  enclosing ``with`` over the condvar or a lock: ``threading.Condition``
  raises RuntimeError; a hand-rolled condvar silently races the waiter's
  predicate check (the classic lost-wakeup window).
- **LK005** — unbounded blocking in cluster paths: a dead peer must be
  *detected*, never waited on forever.  In files whose name contains
  ``cluster`` (override with ``cluster_path=``) this flags
  ``settimeout(None)`` (re-arms an infinite socket), condvar ``wait()``
  calls with no timeout argument, and ``recv``/``recv_into`` inside a
  class that never arms a finite ``settimeout`` — each is an infinite
  wait that turns a peer crash into a hang instead of a bounded-time
  liveness failure.
- **LK006** — serving-path wait discipline: in files under ``serving/``
  (override with ``serving_path=``) every queue handoff must ride the
  WakeupHub and every admission-path wait must be finite.  Flags bare
  ``time.sleep`` (polling puts a floor under tail latency), any
  ``.wait()`` with no timeout (or an explicit ``None``), and zero-
  argument ``.join()`` / ``.result()`` / ``.get()`` (each blocks a
  serving thread forever if its producer died).

Usage: ``python scripts/check_locks.py [files...]``; exits 1 on
findings.  Importable — tests feed synthetic sources through
``check_source``.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass

#: attribute/variable names treated as condition variables
CV_NAMES = {"_cv", "cv", "cond", "_cond", "condition", "_condition"}

#: receivers whose .wait() is a notified single-waiter primitive, not a
#: condvar (threading.Event, our WakeupHub generation-wait)
NON_CV_WAIT = {"_stop", "stop", "hub", "_hub", "event", "_event", "_barrier"}


@dataclass(frozen=True)
class Finding:
    file: str
    line: int
    code: str
    message: str

    def format(self) -> str:
        return f"{self.file}:{self.line}: {self.code} {self.message}"


def _recv_name(func: ast.expr) -> str | None:
    """The receiver identifier of ``recv.meth(...)``: last attribute of
    the receiver chain, or the bare variable name."""
    if not isinstance(func, ast.Attribute):
        return None
    recv = func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return recv.id
    return None


def _lock_name(expr: ast.expr) -> str | None:
    """Identifier for a ``with <expr>:`` item that looks like a lock."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return None
    return name if "lock" in name.lower() else None


class _FunctionScanner(ast.NodeVisitor):
    """Per-function scan for LK001: cv waits that are neither inside a
    while loop nor followed by further statements (the re-check)."""

    def __init__(self, filename: str, findings: list[Finding]):
        self.filename = filename
        self.findings = findings

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scan_function(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _scan_function(self, fn: ast.AST) -> None:
        waits: list[ast.Call] = []
        in_while: set[int] = set()

        def walk(node: ast.AST, while_depth: int) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue  # nested functions scan separately
                d = while_depth + (1 if isinstance(child, ast.While) else 0)
                if isinstance(child, ast.Call):
                    recv = _recv_name(child.func)
                    if (
                        isinstance(child.func, ast.Attribute)
                        and child.func.attr == "wait"
                        and recv in CV_NAMES
                    ):
                        waits.append(child)
                        if d > 0:
                            in_while.add(id(child))
                walk(child, d)

        walk(fn, 0)
        if not waits:
            return
        # a wait outside any while loop needs a post-wait re-check: at
        # least one statement in the function strictly after the wait
        last_stmt_line = max(
            getattr(n, "lineno", 0) for n in ast.walk(fn)
        )
        for w in waits:
            if id(w) in in_while:
                continue
            if last_stmt_line > w.lineno:
                continue  # something (a predicate re-check) follows
            self.findings.append(
                Finding(
                    self.filename,
                    w.lineno,
                    "LK001",
                    "condition-variable wait() outside a while loop with "
                    "no predicate re-check after it; spurious wakeups and "
                    "missed notifies will race",
                )
            )


#: condvar methods that require the condvar's lock to be held
_NOTIFY_METHODS = {"notify", "notify_all"}


def _check_notify_discipline(
    tree: ast.AST, filename: str, findings: list[Finding]
) -> None:
    """LK004: ``cv.notify()`` / ``cv.notify_all()`` outside any lexically
    enclosing ``with`` over the condvar (or a lock).  ``threading.
    Condition`` raises RuntimeError at runtime; a hand-rolled condvar
    silently races the waiter's predicate check instead."""

    def _held_name(expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        else:
            return None
        if name in CV_NAMES or "lock" in name.lower():
            return name
        return None

    def walk(node: ast.AST, held: frozenset[str]) -> None:
        for child in ast.iter_child_nodes(node):
            inner = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                names = {
                    n
                    for n in (
                        _held_name(item.context_expr) for item in child.items
                    )
                    if n is not None
                }
                if names:
                    inner = held | names
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and child.func.attr in _NOTIFY_METHODS
                and _recv_name(child.func) in CV_NAMES
                and not held
            ):
                findings.append(
                    Finding(
                        filename,
                        child.lineno,
                        "LK004",
                        f"{child.func.attr}() on a condition variable "
                        "without holding its lock (no enclosing `with` "
                        "over the condvar or a lock); the wakeup races "
                        "the waiter's predicate check",
                    )
                )
            walk(child, inner)

    walk(tree, frozenset())


def _collect_lock_pairs(
    tree: ast.AST, filename: str
) -> dict[tuple[str, str], int]:
    """(outer, inner) -> first line where that nesting order occurs."""
    pairs: dict[tuple[str, str], int] = {}

    def walk(node: ast.AST, held: list[str]) -> None:
        for child in ast.iter_child_nodes(node):
            acquired: list[str] = []
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    ln = _lock_name(item.context_expr)
                    if ln is not None:
                        for h in held + acquired:
                            if h != ln:
                                pairs.setdefault((h, ln), child.lineno)
                        acquired.append(ln)
            walk(child, held + acquired)

    walk(tree, [])
    return pairs


def _check_liveness_discipline(
    tree: ast.AST, filename: str, findings: list[Finding]
) -> None:
    """LK005 (cluster paths only): no unbounded blocking primitive may
    wait on a peer — ``settimeout(None)``, a condvar ``wait()`` without a
    timeout, or ``recv``/``recv_into`` in a class that never arms a
    finite socket timeout all turn a dead peer into an infinite hang."""

    def _is_none(expr: ast.expr) -> bool:
        return isinstance(expr, ast.Constant) and expr.value is None

    def _scan_scope(scope: ast.AST, scope_name: str) -> None:
        has_finite_settimeout = False
        recvs: list[ast.Call] = []
        for node in ast.walk(scope):
            if node is not scope and isinstance(node, ast.ClassDef):
                continue  # nested classes scan as their own scope
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            meth = node.func.attr
            if meth == "settimeout":
                if node.args and _is_none(node.args[0]):
                    findings.append(
                        Finding(
                            filename,
                            node.lineno,
                            "LK005",
                            "settimeout(None) re-arms an infinite socket "
                            "in a cluster path; a dead peer then hangs "
                            "recv forever instead of tripping the "
                            "liveness deadline",
                        )
                    )
                else:
                    has_finite_settimeout = True
            elif (
                meth == "wait"
                and _recv_name(node.func) in CV_NAMES
                and not node.args
                and not node.keywords
            ):
                findings.append(
                    Finding(
                        filename,
                        node.lineno,
                        "LK005",
                        "condvar wait() without a timeout in a cluster "
                        "path; the notifier may be a peer that just "
                        "died — bound the wait or register with the "
                        "WakeupHub",
                    )
                )
            elif meth in ("recv", "recv_into"):
                recvs.append(node)
        if recvs and not has_finite_settimeout:
            for node in recvs:
                findings.append(
                    Finding(
                        filename,
                        node.lineno,
                        "LK005",
                        f"{node.func.attr}() in {scope_name} with no "  # type: ignore[union-attr]
                        "finite settimeout anywhere in the class; a "
                        "silent peer blocks this thread forever",
                    )
                )

    # each class is its own liveness scope (a class that arms a finite
    # timeout once may recv anywhere); module-level code is one scope
    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    for cls in classes:
        _scan_scope(cls, f"class {cls.name}")
    _scan_scope(tree, "module scope") if not classes else None
    if classes:
        # module-level statements outside any class still need the scan;
        # build a shallow pseudo-scope excluding class bodies
        module_nodes = [
            n
            for n in ast.iter_child_nodes(tree)
            if not isinstance(n, ast.ClassDef)
        ]
        pseudo = ast.Module(body=module_nodes, type_ignores=[])
        _scan_scope(pseudo, "module scope")


def _check_serving_discipline(
    tree: ast.AST, filename: str, findings: list[Finding]
) -> None:
    """LK006 (serving paths only): finite waits everywhere.  The serving
    layer's contract is bounded everything — queues are capped by
    admission, so the only way a request hangs is an unbounded wait.
    Flags bare ``time.sleep``, ``.wait()`` with no timeout (or a literal
    ``None`` timeout), and zero-argument ``.join()``/``.result()``/
    ``.get()``."""

    def _none_arg(node: ast.Call) -> bool:
        for a in node.args:
            if isinstance(a, ast.Constant) and a.value is None:
                return True
        for kw in node.keywords:
            if kw.arg == "timeout" and (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            ):
                return True
        return False

    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
        ):
            continue
        meth = node.func.attr
        if (
            meth == "sleep"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("time", "_time")
        ):
            findings.append(
                Finding(
                    filename,
                    node.lineno,
                    "LK006",
                    "polling time.sleep in a serving path; park on a "
                    "WakeupHub generation-wait (or Event.wait with a "
                    "timeout) so a notify wakes the handoff immediately",
                )
            )
        elif meth == "wait":
            if (not node.args and not node.keywords) or _none_arg(node):
                findings.append(
                    Finding(
                        filename,
                        node.lineno,
                        "LK006",
                        "wait() without a finite timeout in a serving "
                        "path; every admission-path wait must have a "
                        "deadline or a request can hang forever",
                    )
                )
        elif meth in ("join", "result", "get") and not node.args and not node.keywords:
            findings.append(
                Finding(
                    filename,
                    node.lineno,
                    "LK006",
                    f"{meth}() with no timeout in a serving path blocks "
                    "this thread forever if the producer died; pass a "
                    "finite timeout",
                )
            )


def check_source(
    source: str,
    filename: str,
    *,
    scheduler_path: bool | None = None,
    cluster_path: bool | None = None,
    serving_path: bool | None = None,
) -> list[Finding]:
    """Lint one file's source.  ``scheduler_path`` controls LK003
    (default: filename contains 'scheduler'); ``cluster_path`` controls
    LK005 (default: filename contains 'cluster'); ``serving_path``
    controls LK006 (default: the path contains 'serving')."""
    findings: list[Finding] = []
    tree = ast.parse(source, filename=filename)

    _FunctionScanner(filename, findings).visit(tree)
    _check_notify_discipline(tree, filename, findings)

    if cluster_path is None:
        cluster_path = "cluster" in os.path.basename(filename)
    if cluster_path:
        _check_liveness_discipline(tree, filename, findings)

    if serving_path is None:
        serving_path = "serving" in filename.replace(os.sep, "/")
    if serving_path:
        _check_serving_discipline(tree, filename, findings)

    if scheduler_path is None:
        scheduler_path = "scheduler" in os.path.basename(filename)
    if scheduler_path:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "sleep"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("time", "_time")
            ):
                findings.append(
                    Finding(
                        filename,
                        node.lineno,
                        "LK003",
                        "bare time.sleep in a scheduler path; park on a "
                        "notified wait (Event.wait / WakeupHub.wait) "
                        "instead",
                    )
                )
    return findings


def check_lock_order(
    sources: list[tuple[str, str]]
) -> list[Finding]:
    """LK002 across a set of ``(source, filename)`` pairs: the same two
    locks nested in both orders."""
    findings: list[Finding] = []
    all_pairs: dict[tuple[str, str], tuple[str, int]] = {}
    for source, filename in sources:
        tree = ast.parse(source, filename=filename)
        for pair, line in _collect_lock_pairs(tree, filename).items():
            all_pairs.setdefault(pair, (filename, line))
    reported: set[frozenset[str]] = set()
    for (a, b), (fn, line) in sorted(all_pairs.items()):
        if (b, a) in all_pairs and frozenset((a, b)) not in reported:
            reported.add(frozenset((a, b)))
            other_fn, other_line = all_pairs[(b, a)]
            findings.append(
                Finding(
                    fn,
                    line,
                    "LK002",
                    f"locks {a!r} and {b!r} are acquired in both orders "
                    f"(other order at {other_fn}:{other_line}); pick one "
                    "global order",
                )
            )
    return findings


DEFAULT_TARGETS = (
    "pathway_tpu/engine/cluster.py",
    "pathway_tpu/engine/scheduler.py",
    "pathway_tpu/serving/admission.py",
    "pathway_tpu/serving/scheduler.py",
    "pathway_tpu/serving/coscheduler.py",
    "pathway_tpu/serving/graph.py",
    "pathway_tpu/serving/loadgen.py",
)


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = args or [os.path.join(repo_root, t) for t in DEFAULT_TARGETS]
    sources: list[tuple[str, str]] = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources.append((fh.read(), f))
    findings: list[Finding] = []
    for source, filename in sources:
        findings.extend(check_source(source, filename))
    findings.extend(check_lock_order(sources))
    for fd in findings:
        print(fd.format())
    if findings:
        print(f"{len(findings)} concurrency-discipline finding(s)", file=sys.stderr)
        return 1
    print(f"checked {len(files)} file(s): clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
