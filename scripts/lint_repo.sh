#!/usr/bin/env bash
# Repo-wide Python lint with a pinned, minimal rule set.
#
# Only rules that flag definite defects are enabled — this gate must
# stay green on a healthy tree, so style-opinion rules are out:
#   F63x — invalid comparisons (is-literal, ==/!= against tuples)
#   F7xx — misplaced statements (return/yield/break outside scope)
#   F82x — undefined names
#
# ruff is optional tooling: when it is not installed the script reports
# SKIP and exits 0 so environments without it (including CI base
# images) are not broken; exit 97 distinguishes the skip for callers
# that want to require the tool.
set -uo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
RULES="F63,F7,F82"

RUFF=""
if command -v ruff >/dev/null 2>&1; then
    RUFF="ruff"
elif python -c 'import ruff' >/dev/null 2>&1; then
    RUFF="python -m ruff"
fi

if [ -z "$RUFF" ]; then
    echo "lint_repo: ruff not available, SKIP" >&2
    if [ "${LINT_REPO_REQUIRE:-0}" = "1" ]; then
        exit 97
    fi
    exit 0
fi

set -e
$RUFF check --select "$RULES" --no-cache \
    "$REPO/pathway_tpu" "$REPO/scripts" "$REPO/tests" "$REPO/bench.py"
echo "lint_repo: clean ($RULES)" >&2
