#!/usr/bin/env bash
# Repo-wide Python lint with a pinned, minimal rule set.
#
# Only rules that flag definite defects are enabled — this gate must
# stay green on a healthy tree, so style-opinion rules are out:
#   F63x — invalid comparisons (is-literal, ==/!= against tuples)
#   F7xx — misplaced statements (return/yield/break outside scope)
#   F82x — undefined names
#
# ruff is optional tooling: when it is not installed the script reports
# SKIP and exits 0 so environments without it (including CI base
# images) are not broken; exit 97 distinguishes the skip for callers
# that want to require the tool.
#
# Before the ruff stage, a SELF-LINT stage runs with no external deps:
# the repo's own analyzer (`cli lint --werror`) over every committed
# example graph (accepted warnings baselined in lint_baseline.json,
# never silenced in code) and the concurrency lint (check_locks.py,
# including the LK007 whole-repo lock-order graph) over the full tree.
set -uo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
RULES="F63,F7,F82"

# ---- self-lint stage (runs wherever the repo's own deps import) -------
PYTHON=""
for cand in python python3; do
    if command -v "$cand" >/dev/null 2>&1 \
        && "$cand" -c 'import jax, pathway_tpu' >/dev/null 2>&1; then
        PYTHON="$cand"
        break
    fi
done
if [ -z "$PYTHON" ]; then
    echo "lint_repo: no python with pathway_tpu importable, self-lint SKIP" >&2
else
    echo "lint_repo: self-lint stage" >&2
    SELF_FAIL=0
    # capacity gate: the plan-aware memory report runs per example with a
    # concrete per-worker budget — a blown budget is a PW-M002 warning
    # (baselineable), O(stream) state reaching a sink is a PW-M001 error
    # (never baselineable)
    # --device adds the PW-J device-safety sweep over the example AND
    # the repo device surface (parallel/, ops/, serving/): PW-J001/J004
    # are errors and never baselineable — a recompile storm or a
    # collective deadlock does not get grandfathered in
    for ex in "$REPO"/examples/*.py; do
        if ! JAX_PLATFORMS=cpu \
            PATHWAY_MEMORY_BUDGET="${PATHWAY_MEMORY_BUDGET:-4GiB}" \
            "$PYTHON" -m pathway_tpu.cli lint --werror --memory --device \
            --baseline "$REPO/scripts/lint_baseline.json" "$ex"; then
            SELF_FAIL=1
        fi
    done
    if ! "$PYTHON" "$REPO/scripts/check_locks.py"; then
        SELF_FAIL=1
    fi
    if [ "$SELF_FAIL" != "0" ]; then
        echo "lint_repo: self-lint FAILED" >&2
        exit 1
    fi
    echo "lint_repo: self-lint clean" >&2
fi

RUFF=""
if command -v ruff >/dev/null 2>&1; then
    RUFF="ruff"
elif python -c 'import ruff' >/dev/null 2>&1; then
    RUFF="python -m ruff"
fi

if [ -z "$RUFF" ]; then
    echo "lint_repo: ruff not available, SKIP" >&2
    if [ "${LINT_REPO_REQUIRE:-0}" = "1" ]; then
        exit 97
    fi
    exit 0
fi

set -e
$RUFF check --select "$RULES" --no-cache \
    "$REPO/pathway_tpu" "$REPO/scripts" "$REPO/tests" "$REPO/bench.py"
echo "lint_repo: clean ($RULES)" >&2
