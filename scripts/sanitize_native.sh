#!/usr/bin/env bash
# Build native/pathway_native.cpp with AddressSanitizer + UBSan and run
# the native test suite against the instrumented extension.
#
# The python interpreter itself is uninstrumented, so libasan must be
# LD_PRELOADed and leak detection tuned: CPython's allocators hold
# arena/interned-object memory for the life of the process, which ASan's
# leak checker would misreport — the suppression file below keeps only
# leaks attributable to our extension.
#
# Usage: scripts/sanitize_native.sh [pytest args...]
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
SRC="$REPO/native/pathway_native.cpp"
BUILD="$REPO/native/build"
OUT="$BUILD/pathway_native_asan.so"

mkdir -p "$BUILD"

INCLUDE="$(python -c 'import sysconfig; print(sysconfig.get_paths()["include"])')"

echo "building $OUT with -fsanitize=address,undefined" >&2
g++ -O1 -g -fno-omit-frame-pointer \
    -fsanitize=address,undefined -fno-sanitize-recover=undefined \
    -shared -fPIC -std=c++17 \
    -I"$INCLUDE" "$SRC" -o "$OUT"

LIBASAN="$(g++ -print-file-name=libasan.so)"
if [ ! -e "$LIBASAN" ]; then
    echo "libasan.so not found; cannot preload into uninstrumented python" >&2
    exit 1
fi

SUPP="$BUILD/lsan_suppressions.txt"
cat > "$SUPP" <<'EOF'
# CPython keeps interpreter-lifetime allocations (arenas, interned
# strings, type objects) that LSan cannot see the roots of.
leak:Py
leak:_Py
leak:pymalloc
leak:libpython
# numpy's interpreter-lifetime allocator pools (default_malloc,
# NpyString_new_allocator) — third-party, not ours
leak:_multiarray_umath
leak:numpy
EOF

echo "running tests/test_native.py under ASan+UBSan" >&2
LD_PRELOAD="$LIBASAN" \
ASAN_OPTIONS="detect_leaks=1:halt_on_error=1:abort_on_error=1" \
LSAN_OPTIONS="suppressions=$SUPP" \
UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1" \
PATHWAY_NATIVE_SO="$OUT" \
JAX_PLATFORMS=cpu \
python -m pytest "$REPO/tests/test_native.py" -q -p no:cacheprovider "$@"

echo "sanitizer run clean" >&2

# ---- ThreadSanitizer job (mirrors the ASan+UBSan one) -----------------
# The extension's concurrency surface — the latency-histogram updates
# and the exchange codec the multi-worker scheduler drives from several
# threads — gets a separate -fsanitize=thread build: TSan and ASan
# cannot share a process.  The uninstrumented interpreter again means
# libtsan must be preloaded, and CPython's GIL-mediated accesses need a
# suppressions file so only our extension's races report.
LIBTSAN="$(g++ -print-file-name=libtsan.so)"
if [ ! -e "$LIBTSAN" ]; then
    echo "libtsan.so not found; SKIP ThreadSanitizer job" >&2
    exit 0
fi

TSAN_OUT="$BUILD/pathway_native_tsan.so"
echo "building $TSAN_OUT with -fsanitize=thread" >&2
g++ -O1 -g -fno-omit-frame-pointer \
    -fsanitize=thread \
    -shared -fPIC -std=c++17 \
    -I"$INCLUDE" "$SRC" -o "$TSAN_OUT"

TSAN_SUPP="$BUILD/tsan_suppressions.txt"
cat > "$TSAN_SUPP" <<'EOF'
# CPython serialises through the GIL with synchronisation TSan cannot
# see (it is uninstrumented), so interpreter internals false-positive.
race:Py
race:_Py
race:pymalloc
race:libpython
# numpy's uninstrumented internals, same story
race:_multiarray_umath
race:numpy
# glibc's dynamic loader / thread bootstrap
race:ld-linux
called_from_lib:libpython
called_from_lib:_multiarray_umath
EOF

# concurrency-relevant subset: histogram/exchange/groupby-partial paths
# that the threaded scheduler exercises from multiple workers, plus the
# columnar frame kernels and zero-copy pack/unpack (sender thread
# encodes with a shared TxPool while workers build frames)
echo "running concurrency-native tests under TSan" >&2
LD_PRELOAD="$LIBTSAN" \
TSAN_OPTIONS="suppressions=$TSAN_SUPP:halt_on_error=1:report_signal_unsafe=0" \
PATHWAY_NATIVE_SO="$TSAN_OUT" \
JAX_PLATFORMS=cpu \
python -m pytest "$REPO/tests/test_native.py" -q -p no:cacheprovider \
    -k "hash_parity or scan_lines or consolidate or per_key_changes or groupby_partials or multiset_reducer or frame" \
    "$@"

echo "thread-sanitizer run clean" >&2
