// pathway_native — C++ host-runtime hot paths for pathway_tpu.
//
// The reference implements its engine hot loops in Rust
// (src/engine/value.rs Key hashing, src/connectors tokenization); the
// TPU build keeps the numeric plane in XLA and implements the host-side
// hot paths here as a CPython extension:
//
//   - ref_scalar(args_tuple) / hash_rows(list[tuple]): 128-bit row-key
//     hashing, byte-for-byte identical to the Python implementation in
//     pathway_tpu/internals/keys.py (type-tagged serialization into
//     BLAKE2b-128) — keys are stable across the two paths, which
//     persistence snapshots rely on.
//   - scan_lines(bytes): newline scanning for the file data loader.
//
// Unsupported value types (big ints, ndarrays, datetimes, arbitrary
// objects) raise _Unsupported so the caller transparently falls back to
// the Python path for that call.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

#include "blake2b.h"

namespace {

PyObject* g_unsupported = nullptr;  // exception type for fallback
PyObject* g_pointer_type = nullptr;  // pathway_tpu Pointer class

const char kSalt[] = "pathway_tpu.key.v1";

struct Hasher {
    pwnative::Blake2bState S;
    Hasher() {
        pwnative::blake2b_init(&S, 16);
        pwnative::blake2b_update(
            &S, reinterpret_cast<const uint8_t*>(kSalt), sizeof(kSalt) - 1);
    }
    void bytes(const void* p, size_t n) {
        pwnative::blake2b_update(&S, static_cast<const uint8_t*>(p), n);
    }
    void tag(uint8_t t) { bytes(&t, 1); }
    void u64le(uint64_t v) { bytes(&v, 8); }
};

// mirror of keys._feed — must stay byte-identical
bool feed(Hasher& h, PyObject* v) {
    if (v == Py_None) {
        h.tag(0x00);
        return true;
    }
    if (PyBool_Check(v)) {
        h.tag(0x01);
        h.tag(v == Py_True ? 0x01 : 0x00);
        return true;
    }
    if (g_pointer_type != nullptr &&
        PyObject_TypeCheck(v, reinterpret_cast<PyTypeObject*>(g_pointer_type))) {
        uint8_t out[16];
        if (_PyLong_AsByteArray(reinterpret_cast<PyLongObject*>(v), out, 16,
                                /*little_endian=*/1, /*is_signed=*/0) < 0) {
            PyErr_Clear();
            return false;  // >128-bit pointer: fall back
        }
        h.tag(0x07);
        h.bytes(out, 16);
        return true;
    }
    if (PyLong_Check(v)) {
        int overflow = 0;
        long long val = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow != 0) return false;  // big int: fall back
        // python: n = (bit_length + 8) // 8 + 1 bytes, signed little
        unsigned long long mag =
            val < 0 ? (unsigned long long)(-(val + 1)) + 1ULL
                    : (unsigned long long)val;
        // bit_length (0 for val==0); `mag >> bl` would be UB at bl==64
        // (mag == 2^63 when val == INT64_MIN), so use clz instead.
        int bl = mag ? 64 - __builtin_clzll(mag) : 0;
        int n = (bl + 8) / 8 + 1;
        uint8_t buf[16];
        long long x = val;
        for (int i = 0; i < n; i++) {
            buf[i] = (uint8_t)(x & 0xff);
            x >>= 8;  // arithmetic shift: sign-extends
        }
        h.tag(0x02);
        h.bytes(buf, n);
        return true;
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        h.tag(0x03);
        h.bytes(&d, 8);
        return true;
    }
    if (PyUnicode_Check(v)) {
        Py_ssize_t n;
        const char* s = PyUnicode_AsUTF8AndSize(v, &n);
        if (s == nullptr) return false;
        h.tag(0x04);
        h.u64le((uint64_t)n);
        h.bytes(s, (size_t)n);
        return true;
    }
    if (PyBytes_Check(v)) {
        h.tag(0x05);
        h.u64le((uint64_t)PyBytes_GET_SIZE(v));
        h.bytes(PyBytes_AS_STRING(v), (size_t)PyBytes_GET_SIZE(v));
        return true;
    }
    if (PyTuple_Check(v)) {
        Py_ssize_t n = PyTuple_GET_SIZE(v);
        h.tag(0x06);
        h.u64le((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (!feed(h, PyTuple_GET_ITEM(v, i))) return false;
        }
        return true;
    }
    return false;  // datetime / ndarray / other: fall back
}

PyObject* digest_to_long(Hasher& h) {
    uint8_t out[16];
    pwnative::blake2b_final(&h.S, out);
    return _PyLong_FromByteArray(out, 16, /*little_endian=*/1, /*signed=*/0);
}

PyObject* py_ref_scalar(PyObject*, PyObject* args_tuple) {
    Hasher h;
    Py_ssize_t n = PyTuple_GET_SIZE(args_tuple);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!feed(h, PyTuple_GET_ITEM(args_tuple, i))) {
            if (!PyErr_Occurred())
                PyErr_SetString(g_unsupported, "unsupported value type");
            return nullptr;
        }
    }
    return digest_to_long(h);
}

PyObject* py_hash_rows(PyObject*, PyObject* rows) {
    // rows: sequence of tuples -> list of 128-bit ints
    PyObject* seq = PySequence_Fast(rows, "hash_rows expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(row)) {
            Py_DECREF(seq);
            Py_DECREF(out);
            PyErr_SetString(PyExc_TypeError, "rows must be tuples");
            return nullptr;
        }
        Hasher h;
        Py_ssize_t m = PyTuple_GET_SIZE(row);
        bool ok = true;
        for (Py_ssize_t j = 0; j < m && ok; j++)
            ok = feed(h, PyTuple_GET_ITEM(row, j));
        if (!ok) {
            Py_DECREF(seq);
            Py_DECREF(out);
            if (!PyErr_Occurred())
                PyErr_SetString(g_unsupported, "unsupported value type");
            return nullptr;
        }
        PyObject* key = digest_to_long(h);
        if (key == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, key);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_scan_lines(PyObject*, PyObject* arg) {
    // bytes -> list of (start, end) offsets of non-empty lines
    char* data;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &data, &len) < 0) return nullptr;
    std::vector<std::pair<Py_ssize_t, Py_ssize_t>> spans;
    Py_ssize_t start = 0;
    for (Py_ssize_t i = 0; i <= len; i++) {
        if (i == len || data[i] == '\n') {
            Py_ssize_t end = i;
            if (end > start && data[end - 1] == '\r') end--;
            if (end > start) spans.emplace_back(start, end);
            start = i + 1;
        }
    }
    PyObject* out = PyList_New((Py_ssize_t)spans.size());
    if (out == nullptr) return nullptr;
    for (size_t i = 0; i < spans.size(); i++) {
        PyObject* t = Py_BuildValue("(nn)", spans[i].first, spans[i].second);
        if (t == nullptr) {
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, (Py_ssize_t)i, t);
    }
    return out;
}

PyObject* py_set_pointer_type(PyObject*, PyObject* cls) {
    Py_XDECREF(g_pointer_type);
    Py_INCREF(cls);
    g_pointer_type = cls;
    Py_RETURN_NONE;
}

PyMethodDef kMethods[] = {
    {"ref_scalar", py_ref_scalar, METH_VARARGS,
     "128-bit key hash of the argument values"},
    {"hash_rows", py_hash_rows, METH_O,
     "batch 128-bit key hashes for a sequence of value tuples"},
    {"scan_lines", py_scan_lines, METH_O,
     "offsets of non-empty lines in a bytes buffer"},
    {"set_pointer_type", py_set_pointer_type, METH_O,
     "register the Pointer class for type-tagged hashing"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "pathway_native",
                       "pathway_tpu C++ host hot paths", -1, kMethods};

}  // namespace

PyMODINIT_FUNC PyInit_pathway_native(void) {
    PyObject* m = PyModule_Create(&kModule);
    if (m == nullptr) return nullptr;
    g_unsupported =
        PyErr_NewException("pathway_native.Unsupported", nullptr, nullptr);
    Py_INCREF(g_unsupported);
    PyModule_AddObject(m, "Unsupported", g_unsupported);
    return m;
}
