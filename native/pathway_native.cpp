// pathway_native — C++ host-runtime hot paths for pathway_tpu.
//
// The reference implements its engine hot loops in Rust
// (src/engine/value.rs Key hashing, src/connectors tokenization); the
// TPU build keeps the numeric plane in XLA and implements the host-side
// hot paths here as a CPython extension:
//
//   - ref_scalar(args_tuple) / hash_rows(list[tuple]): 128-bit row-key
//     hashing, byte-for-byte identical to the Python implementation in
//     pathway_tpu/internals/keys.py (type-tagged serialization into
//     BLAKE2b-128) — keys are stable across the two paths, which
//     persistence snapshots rely on.
//   - scan_lines(bytes): newline scanning for the file data loader.
//   - consolidate(batch, update_cls, hashable_row): merge update deltas
//     with equal (key, row) — the per-node compaction the reference runs
//     inside differential arrangements (src/engine/dataflow.rs
//     consolidation); single-occurrence updates are re-emitted by
//     reference (no allocation).
//   - per_key_changes(batch): group a batch into per-key (removals,
//     additions) lists.
//   - coerce_rows(rows, plan): bulk schema coercion of parsed row dicts
//     into value tuples (reference parser hot loop,
//     src/connectors/data_format.rs DsvParser/JsonLinesParser).
//   - build_adds(rows, update_cls): bulk Update(key, values, +1)
//     construction for chunked connector ingest.
//
// Unsupported value types (big ints, ndarrays, datetimes, arbitrary
// objects) raise _Unsupported so the caller transparently falls back to
// the Python path for that call.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include "blake2b.h"

namespace {

PyObject* g_unsupported = nullptr;  // exception type for fallback
PyObject* g_pointer_type = nullptr;  // pathway_tpu Pointer class

// ---------------------------------------------------------------------------
// CPython 3.13 removed _PyLong_NumBits / _PyLong_AsByteArray /
// _PyLong_FromByteArray from the public headers (and changed the
// _PyLong_AsByteArray signature), which would make this whole extension
// silently fail to compile and every fast path degrade to Python.  Wrap
// the int<->bytes conversions so 3.13+ uses the new stable
// PyLong_AsNativeBytes / PyLong_FromNativeBytes API instead.
// All helpers return 0 / non-NULL on success; on failure the caller is
// expected to PyErr_Clear() and fall back.
#if PY_VERSION_HEX >= 0x030D0000
inline int pt_long_as_bytes_unsigned(PyObject* v, uint8_t* out, size_t n) {
    Py_ssize_t r = PyLong_AsNativeBytes(
        v, out, (Py_ssize_t)n,
        Py_ASNATIVEBYTES_LITTLE_ENDIAN | Py_ASNATIVEBYTES_UNSIGNED_BUFFER |
            Py_ASNATIVEBYTES_REJECT_NEGATIVE);
    return (r < 0 || (size_t)r > n) ? -1 : 0;
}
inline int pt_long_as_bytes_signed(PyObject* v, uint8_t* out, size_t n) {
    // sign-extends into the full n-byte buffer, matching
    // int.to_bytes(n, "little", signed=True)
    Py_ssize_t r = PyLong_AsNativeBytes(v, out, (Py_ssize_t)n,
                                        Py_ASNATIVEBYTES_LITTLE_ENDIAN);
    return (r < 0 || (size_t)r > n) ? -1 : 0;
}
inline size_t pt_long_numbits(PyObject* v) {
    // no public C equivalent of _PyLong_NumBits; the object-protocol call
    // is acceptable because this only runs on the rare >64-bit path
    PyObject* bl = PyObject_CallMethod(v, "bit_length", nullptr);
    if (bl == nullptr) return (size_t)-1;
    size_t bits = PyLong_AsSize_t(bl);
    Py_DECREF(bl);
    if (bits == (size_t)-1 && PyErr_Occurred()) return (size_t)-1;
    return bits;
}
inline PyObject* pt_long_from_bytes_unsigned(const uint8_t* buf, size_t n) {
    return PyLong_FromNativeBytes(
        buf, (Py_ssize_t)n,
        Py_ASNATIVEBYTES_LITTLE_ENDIAN | Py_ASNATIVEBYTES_UNSIGNED_BUFFER);
}
#else
inline int pt_long_as_bytes_unsigned(PyObject* v, uint8_t* out, size_t n) {
    return _PyLong_AsByteArray(reinterpret_cast<PyLongObject*>(v), out, n,
                               /*little_endian=*/1, /*is_signed=*/0);
}
inline int pt_long_as_bytes_signed(PyObject* v, uint8_t* out, size_t n) {
    return _PyLong_AsByteArray(reinterpret_cast<PyLongObject*>(v), out, n,
                               /*little_endian=*/1, /*is_signed=*/1);
}
inline size_t pt_long_numbits(PyObject* v) { return _PyLong_NumBits(v); }
inline PyObject* pt_long_from_bytes_unsigned(const uint8_t* buf, size_t n) {
    return _PyLong_FromByteArray(buf, n, /*little_endian=*/1, /*signed=*/0);
}
#endif

const char kSalt[] = "pathway_tpu.key.v1";

struct Hasher {
    pwnative::Blake2bState S;
    Hasher() {
        pwnative::blake2b_init(&S, 16);
        pwnative::blake2b_update(
            &S, reinterpret_cast<const uint8_t*>(kSalt), sizeof(kSalt) - 1);
    }
    void bytes(const void* p, size_t n) {
        pwnative::blake2b_update(&S, static_cast<const uint8_t*>(p), n);
    }
    void tag(uint8_t t) { bytes(&t, 1); }
    void u64le(uint64_t v) { bytes(&v, 8); }
};

// mirror of keys._feed — must stay byte-identical
bool feed(Hasher& h, PyObject* v) {
    if (v == Py_None) {
        h.tag(0x00);
        return true;
    }
    if (PyBool_Check(v)) {
        h.tag(0x01);
        h.tag(v == Py_True ? 0x01 : 0x00);
        return true;
    }
    if (g_pointer_type != nullptr &&
        PyObject_TypeCheck(v, reinterpret_cast<PyTypeObject*>(g_pointer_type))) {
        uint8_t out[16];
        if (pt_long_as_bytes_unsigned(v, out, 16) < 0) {
            PyErr_Clear();
            return false;  // >128-bit pointer: fall back
        }
        h.tag(0x07);
        h.bytes(out, 16);
        return true;
    }
    if (PyLong_Check(v)) {
        int overflow = 0;
        long long val = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow != 0) {
            // big int (e.g. 128-bit join/derive key material): replicate
            // value.to_bytes((bit_length + 8)//8 + 1, "little", signed)
            size_t bits = pt_long_numbits(v);
            if (bits == (size_t)-1) {
                PyErr_Clear();
                return false;
            }
            size_t nb = (bits + 8) / 8 + 1;
            uint8_t buf[64];
            if (nb > sizeof(buf)) return false;  // >~500 bits: fall back
            if (pt_long_as_bytes_signed(v, buf, nb) < 0) {
                PyErr_Clear();
                return false;
            }
            h.tag(0x02);
            h.bytes(buf, nb);
            return true;
        }
        // python: n = (bit_length + 8) // 8 + 1 bytes, signed little
        unsigned long long mag =
            val < 0 ? (unsigned long long)(-(val + 1)) + 1ULL
                    : (unsigned long long)val;
        // bit_length (0 for val==0); `mag >> bl` would be UB at bl==64
        // (mag == 2^63 when val == INT64_MIN), so use clz instead.
        int bl = mag ? 64 - __builtin_clzll(mag) : 0;
        int n = (bl + 8) / 8 + 1;
        uint8_t buf[16];
        long long x = val;
        for (int i = 0; i < n; i++) {
            buf[i] = (uint8_t)(x & 0xff);
            x >>= 8;  // arithmetic shift: sign-extends
        }
        h.tag(0x02);
        h.bytes(buf, n);
        return true;
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        h.tag(0x03);
        h.bytes(&d, 8);
        return true;
    }
    if (PyUnicode_Check(v)) {
        Py_ssize_t n;
        const char* s = PyUnicode_AsUTF8AndSize(v, &n);
        if (s == nullptr) return false;
        h.tag(0x04);
        h.u64le((uint64_t)n);
        h.bytes(s, (size_t)n);
        return true;
    }
    if (PyBytes_Check(v)) {
        h.tag(0x05);
        h.u64le((uint64_t)PyBytes_GET_SIZE(v));
        h.bytes(PyBytes_AS_STRING(v), (size_t)PyBytes_GET_SIZE(v));
        return true;
    }
    if (PyTuple_Check(v)) {
        Py_ssize_t n = PyTuple_GET_SIZE(v);
        h.tag(0x06);
        h.u64le((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (!feed(h, PyTuple_GET_ITEM(v, i))) return false;
        }
        return true;
    }
    return false;  // datetime / ndarray / other: fall back
}

PyObject* digest_to_long(Hasher& h) {
    uint8_t out[16];
    pwnative::blake2b_final(&h.S, out);
    return pt_long_from_bytes_unsigned(out, 16);
}

PyObject* py_ref_scalar(PyObject*, PyObject* args_tuple) {
    Hasher h;
    Py_ssize_t n = PyTuple_GET_SIZE(args_tuple);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!feed(h, PyTuple_GET_ITEM(args_tuple, i))) {
            if (!PyErr_Occurred())
                PyErr_SetString(g_unsupported, "unsupported value type");
            return nullptr;
        }
    }
    return digest_to_long(h);
}

PyObject* py_hash_rows(PyObject*, PyObject* rows) {
    // rows: sequence of tuples -> list of 128-bit ints
    PyObject* seq = PySequence_Fast(rows, "hash_rows expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(row)) {
            Py_DECREF(seq);
            Py_DECREF(out);
            PyErr_SetString(PyExc_TypeError, "rows must be tuples");
            return nullptr;
        }
        Hasher h;
        Py_ssize_t m = PyTuple_GET_SIZE(row);
        bool ok = true;
        for (Py_ssize_t j = 0; j < m && ok; j++)
            ok = feed(h, PyTuple_GET_ITEM(row, j));
        if (!ok) {
            Py_DECREF(seq);
            Py_DECREF(out);
            if (!PyErr_Occurred())
                PyErr_SetString(g_unsupported, "unsupported value type");
            return nullptr;
        }
        PyObject* key = digest_to_long(h);
        if (key == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, key);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_scan_lines(PyObject*, PyObject* arg) {
    // bytes -> list of (start, end) offsets of non-empty lines
    char* data;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &data, &len) < 0) return nullptr;
    std::vector<std::pair<Py_ssize_t, Py_ssize_t>> spans;
    Py_ssize_t start = 0;
    for (Py_ssize_t i = 0; i <= len; i++) {
        if (i == len || data[i] == '\n') {
            Py_ssize_t end = i;
            if (end > start && data[end - 1] == '\r') end--;
            if (end > start) spans.emplace_back(start, end);
            start = i + 1;
        }
    }
    PyObject* out = PyList_New((Py_ssize_t)spans.size());
    if (out == nullptr) return nullptr;
    for (size_t i = 0; i < spans.size(); i++) {
        PyObject* t = Py_BuildValue("(nn)", spans[i].first, spans[i].second);
        if (t == nullptr) {
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, (Py_ssize_t)i, t);
    }
    return out;
}

// --------------------------------------------------------------------------
// update-stream batch ops

// Update is a Python NamedTuple (engine/stream.py); instances are plain
// tuple subclass objects, so tuple's own tp_new builds them without going
// through the Python-level __new__ (same trick as namedtuple._make).
PyObject* make_update(PyObject* cls, PyObject* key, PyObject* values,
                      long long diff) {
    PyObject* d = PyLong_FromLongLong(diff);
    if (d == nullptr) return nullptr;
    PyObject* inner = PyTuple_Pack(3, key, values, d);
    Py_DECREF(d);
    if (inner == nullptr) return nullptr;
    PyObject* args = PyTuple_Pack(1, inner);
    Py_DECREF(inner);
    if (args == nullptr) return nullptr;
    PyObject* u = PyTuple_Type.tp_new(reinterpret_cast<PyTypeObject*>(cls),
                                      args, nullptr);
    Py_DECREF(args);
    return u;
}

struct ConsEntry {
    PyObject* first;   // borrowed from seq until output
    PyObject* key;     // borrowed
    PyObject* values;  // borrowed
    long long diff;
    bool merged;
};

PyObject* py_consolidate(PyObject*, PyObject* args) {
    PyObject *batch, *update_cls, *hashable_row;
    if (!PyArg_ParseTuple(args, "OOO", &batch, &update_cls, &hashable_row))
        return nullptr;
    PyObject* seq = PySequence_Fast(batch, "consolidate expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* acc = PyDict_New();  // (key, row) -> index into entries
    if (acc == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    std::vector<ConsEntry> entries;
    entries.reserve((size_t)n);
    bool fail = false;
    for (Py_ssize_t i = 0; i < n && !fail; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            fail = true;
            break;
        }
        PyObject* key = PyTuple_GET_ITEM(u, 0);
        PyObject* values = PyTuple_GET_ITEM(u, 1);
        long long diff = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
        if (diff == -1 && PyErr_Occurred()) {
            fail = true;
            break;
        }
        PyObject* k2 = PyTuple_Pack(2, key, values);
        if (k2 == nullptr) {
            fail = true;
            break;
        }
        PyObject* found = PyDict_GetItemWithError(acc, k2);
        if (found == nullptr && PyErr_Occurred()) {
            if (!PyErr_ExceptionMatches(PyExc_TypeError)) {
                Py_DECREF(k2);
                fail = true;
                break;
            }
            // unhashable cell (ndarray/dict/list): type-tagged fallback key
            PyErr_Clear();
            Py_DECREF(k2);
            PyObject* tagged = PyObject_CallFunctionObjArgs(
                hashable_row, values, nullptr);
            if (tagged == nullptr) {
                fail = true;
                break;
            }
            k2 = PyTuple_Pack(2, key, tagged);
            Py_DECREF(tagged);
            if (k2 == nullptr) {
                fail = true;
                break;
            }
            found = PyDict_GetItemWithError(acc, k2);
            if (found == nullptr && PyErr_Occurred()) {
                Py_DECREF(k2);
                fail = true;
                break;
            }
        }
        if (found != nullptr) {
            size_t idx = (size_t)PyLong_AsSsize_t(found);
            entries[idx].diff += diff;
            entries[idx].merged = true;
            Py_DECREF(k2);
        } else {
            PyObject* idx = PyLong_FromSsize_t((Py_ssize_t)entries.size());
            if (idx == nullptr || PyDict_SetItem(acc, k2, idx) < 0) {
                Py_XDECREF(idx);
                Py_DECREF(k2);
                fail = true;
                break;
            }
            Py_DECREF(idx);
            Py_DECREF(k2);
            entries.push_back({u, key, values, diff, false});
        }
    }
    Py_DECREF(acc);
    if (fail) {
        Py_DECREF(seq);
        return nullptr;
    }
    PyObject* out = PyList_New(0);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (const ConsEntry& e : entries) {
        if (e.diff == 0) continue;
        PyObject* u;
        if (!e.merged) {
            u = e.first;  // unchanged: re-emit the input object
            Py_INCREF(u);
        } else {
            u = make_update(update_cls, e.key, e.values, e.diff);
            if (u == nullptr) {
                Py_DECREF(out);
                Py_DECREF(seq);
                return nullptr;
            }
        }
        if (PyList_Append(out, u) < 0) {
            Py_DECREF(u);
            Py_DECREF(out);
            Py_DECREF(seq);
            return nullptr;
        }
        Py_DECREF(u);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_per_key_changes(PyObject*, PyObject* batch) {
    PyObject* seq = PySequence_Fast(batch, "per_key_changes expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyDict_New();
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* key = PyTuple_GET_ITEM(u, 0);
            PyObject* values = PyTuple_GET_ITEM(u, 1);
            long long diff = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
            if (diff == -1 && PyErr_Occurred()) goto fail;
            PyObject* pair = PyDict_GetItemWithError(out, key);
            if (pair == nullptr) {
                if (PyErr_Occurred()) goto fail;
                PyObject* rem = PyList_New(0);
                PyObject* add = PyList_New(0);
                if (rem == nullptr || add == nullptr) {
                    Py_XDECREF(rem);
                    Py_XDECREF(add);
                    goto fail;
                }
                pair = PyTuple_Pack(2, rem, add);
                Py_DECREF(rem);
                Py_DECREF(add);
                if (pair == nullptr || PyDict_SetItem(out, key, pair) < 0) {
                    Py_XDECREF(pair);
                    goto fail;
                }
                Py_DECREF(pair);  // dict holds it; borrow below
                pair = PyDict_GetItemWithError(out, key);
                if (pair == nullptr) goto fail;
            }
            PyObject* lst = PyTuple_GET_ITEM(pair, diff < 0 ? 0 : 1);
            long long reps = diff < 0 ? -diff : diff;
            for (long long r = 0; r < reps; r++) {
                if (PyList_Append(lst, values) < 0) goto fail;
            }
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

PyObject* py_build_adds(PyObject*, PyObject* args) {
    PyObject *rows, *update_cls;
    if (!PyArg_ParseTuple(args, "OO", &rows, &update_cls)) return nullptr;
    PyObject* seq = PySequence_Fast(rows, "build_adds expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* kv = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *key, *values;
        if (PyTuple_Check(kv) && PyTuple_GET_SIZE(kv) == 2) {
            key = PyTuple_GET_ITEM(kv, 0);
            values = PyTuple_GET_ITEM(kv, 1);
        } else {
            PyErr_SetString(PyExc_TypeError, "rows must be (key, values) pairs");
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyObject* u = make_update(update_cls, key, values, 1);
        if (u == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, u);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_all_positive(PyObject*, PyObject* batch) {
    // True iff every update's diff > 0 (append-only batch check)
    PyObject* seq = PySequence_Fast(batch, "all_positive expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            Py_DECREF(seq);
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            return nullptr;
        }
        long long diff = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
        if (diff == -1 && PyErr_Occurred()) {
            Py_DECREF(seq);
            return nullptr;
        }
        if (diff <= 0) {
            Py_DECREF(seq);
            Py_RETURN_FALSE;
        }
    }
    Py_DECREF(seq);
    Py_RETURN_TRUE;
}

PyObject* py_all_dicts(PyObject*, PyObject* obj) {
    PyObject* seq = PySequence_Fast(obj, "all_dicts expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!PyDict_Check(PySequence_Fast_GET_ITEM(seq, i))) {
            Py_DECREF(seq);
            Py_RETURN_FALSE;
        }
    }
    Py_DECREF(seq);
    Py_RETURN_TRUE;
}

PyObject* py_rowwise_map(PyObject*, PyObject* args) {
    // rowwise_map(batch, fn, update_cls, error_obj, on_error) -> list
    // C loop of the expression_table hot path: vals = fn(key, values);
    // a raising row becomes (ERROR,) after on_error(exc).
    PyObject *batch, *fn, *update_cls, *error_obj, *on_error;
    if (!PyArg_ParseTuple(args, "OOOOO", &batch, &fn, &update_cls, &error_obj,
                          &on_error))
        return nullptr;
    PyObject* seq = PySequence_Fast(batch, "rowwise_map expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* key = PyTuple_GET_ITEM(u, 0);
            PyObject* values = PyTuple_GET_ITEM(u, 1);
            PyObject* diff = PyTuple_GET_ITEM(u, 2);
            PyObject* vals =
                PyObject_CallFunctionObjArgs(fn, key, values, nullptr);
            if (vals == nullptr) {
                // row-level containment (Exception only, like the Python
                // `except Exception`): report and emit an ERROR row
                if (!PyErr_ExceptionMatches(PyExc_Exception)) goto fail;
                PyObject *etype, *evalue, *etb;
                PyErr_Fetch(&etype, &evalue, &etb);
                PyErr_NormalizeException(&etype, &evalue, &etb);
                PyObject* r = PyObject_CallFunctionObjArgs(
                    on_error, evalue ? evalue : Py_None, nullptr);
                Py_XDECREF(etype);
                Py_XDECREF(evalue);
                Py_XDECREF(etb);
                if (r == nullptr) goto fail;
                Py_DECREF(r);
                vals = PyTuple_Pack(1, error_obj);
                if (vals == nullptr) goto fail;
            }
            PyObject* inner = PyTuple_Pack(3, key, vals, diff);
            Py_DECREF(vals);
            if (inner == nullptr) goto fail;
            PyObject* wrap = PyTuple_Pack(1, inner);
            Py_DECREF(inner);
            if (wrap == nullptr) goto fail;
            PyObject* nu = PyTuple_Type.tp_new(
                reinterpret_cast<PyTypeObject*>(update_cls), wrap, nullptr);
            Py_DECREF(wrap);
            if (nu == nullptr) goto fail;
            PyList_SET_ITEM(out, i, nu);
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

PyObject* py_filter_batch(PyObject*, PyObject* args) {
    // filter_batch(batch, pred, error_obj) -> list re-emitting the PASSING
    // input update objects unchanged (no allocation per surviving row).
    // Drop semantics mirror FilterNode: raising rows, None, and ERROR all
    // drop; anything else keeps by truthiness.
    PyObject *batch, *pred, *error_obj;
    if (!PyArg_ParseTuple(args, "OOO", &batch, &pred, &error_obj))
        return nullptr;
    PyObject* seq = PySequence_Fast(batch, "filter_batch expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(0);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* r = PyObject_CallFunctionObjArgs(
                pred, PyTuple_GET_ITEM(u, 0), PyTuple_GET_ITEM(u, 1),
                nullptr);
            if (r == nullptr) {
                if (!PyErr_ExceptionMatches(PyExc_Exception)) goto fail;
                PyErr_Clear();
                continue;  // raising predicate: drop the row
            }
            if (r == Py_None || r == error_obj) {
                Py_DECREF(r);
                continue;
            }
            int truthy = PyObject_IsTrue(r);
            Py_DECREF(r);
            // a raising truthiness test propagates (python parity: only
            // the predicate CALL is containable, bool(keep) is not)
            if (truthy < 0) goto fail;
            if (truthy && PyList_Append(out, u) < 0) goto fail;
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

// --------------------------------------------------------------------------
// groupby partial aggregation
//
// groupby_partials(batch, group_idx, red_specs, error_obj, hashable_fn)
// reduces an update batch into per-group PARTIAL aggregates in one C pass
// — the role of the reference's reduce arrangement inner loop
// (src/engine/reduce.rs SemigroupReducerImpl).  Python merges one partial
// per (dirty group, reducer) into the persistent accumulators, so the
// per-row interpreter work (group_fn, arg_fn, reducer.update) disappears.
//
// red_specs: tuple of (code, idx_tuple); idx >= 0 -> values[idx],
// idx == -1 -> row key.  Codes: 0 = count (partial: int), 1 = sum-like
// (partial: (total|None, n_contributions)), 2 = multiset (partial:
// {hashable_args: (delta, args)}).

struct MsItem {
    long long delta;
    PyObject* args;  // owned
    PyObject* h;     // owned
};

struct GPart {
    PyObject* total = nullptr;  // owned (sum-like)
    long long cnt = 0;
    PyObject* msdict = nullptr;  // owned: h -> PyLong index (multiset)
    std::vector<MsItem> msitems;
};

struct GEntry {
    long long count = 0;
    std::vector<GPart> parts;
};

void free_gentries(std::vector<GEntry>& entries) {
    for (GEntry& e : entries) {
        for (GPart& p : e.parts) {
            Py_XDECREF(p.total);
            Py_XDECREF(p.msdict);
            for (MsItem& it : p.msitems) {
                Py_XDECREF(it.args);
                Py_XDECREF(it.h);
            }
        }
    }
    entries.clear();
}

PyObject* py_groupby_partials(PyObject*, PyObject* args) {
    PyObject *batch, *group_idx, *red_specs, *error_obj, *hashable_fn;
    if (!PyArg_ParseTuple(args, "OOOOO", &batch, &group_idx, &red_specs,
                          &error_obj, &hashable_fn))
        return nullptr;

    // unpack specs
    if (!PyTuple_Check(group_idx) || !PyTuple_Check(red_specs)) {
        PyErr_SetString(PyExc_TypeError, "group_idx/red_specs must be tuples");
        return nullptr;
    }
    Py_ssize_t ngroup = PyTuple_GET_SIZE(group_idx);
    std::vector<Py_ssize_t> gidx((size_t)ngroup);
    for (Py_ssize_t i = 0; i < ngroup; i++) {
        gidx[(size_t)i] = PyLong_AsSsize_t(PyTuple_GET_ITEM(group_idx, i));
        if (gidx[(size_t)i] == -1 && PyErr_Occurred()) return nullptr;
    }
    Py_ssize_t nred = PyTuple_GET_SIZE(red_specs);
    std::vector<int> rcodes((size_t)nred);
    std::vector<std::vector<Py_ssize_t>> ridx((size_t)nred);
    for (Py_ssize_t r = 0; r < nred; r++) {
        PyObject* spec = PyTuple_GET_ITEM(red_specs, r);
        if (!PyTuple_Check(spec) || PyTuple_GET_SIZE(spec) != 2) {
            PyErr_SetString(PyExc_TypeError, "red_specs items must be pairs");
            return nullptr;
        }
        long code = PyLong_AsLong(PyTuple_GET_ITEM(spec, 0));
        if (code == -1 && PyErr_Occurred()) return nullptr;
        rcodes[(size_t)r] = (int)code;
        PyObject* idxs = PyTuple_GET_ITEM(spec, 1);
        if (!PyTuple_Check(idxs)) {
            PyErr_SetString(PyExc_TypeError, "red spec idx must be a tuple");
            return nullptr;
        }
        for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(idxs); j++) {
            Py_ssize_t v = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, j));
            if (v == -1 && PyErr_Occurred()) return nullptr;
            ridx[(size_t)r].push_back(v);
        }
    }

    PyObject* seq = PySequence_Fast(batch, "batch must be a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

    PyObject* gmap = PyDict_New();  // gvals -> PyLong entry index
    std::vector<GEntry> entries;
    std::vector<PyObject*> gvals_by_entry;  // borrowed (gmap holds refs)
    if (gmap == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }

    bool fail = false;
    bool unsupported = false;
    for (Py_ssize_t i = 0; i < n && !fail; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            fail = true;
            break;
        }
        PyObject* key = PyTuple_GET_ITEM(u, 0);
        PyObject* values = PyTuple_GET_ITEM(u, 1);
        if (!PyTuple_Check(values)) {
            PyErr_SetString(g_unsupported, "values must be tuples");
            fail = true;
            break;
        }
        Py_ssize_t nvals = PyTuple_GET_SIZE(values);
        long long diff = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
        if (diff == -1 && PyErr_Occurred()) {
            fail = true;
            break;
        }
        // group key tuple
        PyObject* gv = PyTuple_New(ngroup);
        if (gv == nullptr) {
            fail = true;
            break;
        }
        for (Py_ssize_t j = 0; j < ngroup; j++) {
            Py_ssize_t ix = gidx[(size_t)j];
            PyObject* cell;
            if (ix < 0) {
                cell = key;
            } else if (ix < nvals) {
                cell = PyTuple_GET_ITEM(values, ix);
            } else {
                PyErr_SetString(g_unsupported, "column index out of range");
                Py_DECREF(gv);
                fail = true;
                break;
            }
            Py_INCREF(cell);
            PyTuple_SET_ITEM(gv, j, cell);
        }
        if (fail) break;
        PyObject* found = PyDict_GetItemWithError(gmap, gv);
        if (found == nullptr && PyErr_Occurred()) {
            // unhashable group value: whole batch falls back to Python
            Py_DECREF(gv);
            if (PyErr_ExceptionMatches(PyExc_TypeError)) {
                PyErr_Clear();
                unsupported = true;
            }
            fail = true;
            break;
        }
        size_t ei;
        if (found != nullptr) {
            ei = (size_t)PyLong_AsSsize_t(found);
            Py_DECREF(gv);
        } else {
            ei = entries.size();
            PyObject* idx = PyLong_FromSsize_t((Py_ssize_t)ei);
            if (idx == nullptr || PyDict_SetItem(gmap, gv, idx) < 0) {
                Py_XDECREF(idx);
                Py_DECREF(gv);
                fail = true;
                break;
            }
            Py_DECREF(idx);
            gvals_by_entry.push_back(gv);
            Py_DECREF(gv);  // gmap key holds the reference
            entries.emplace_back();
            entries.back().parts.resize((size_t)nred);
        }
        GEntry& ge = entries[ei];
        ge.count += diff;
        for (Py_ssize_t r = 0; r < nred && !fail; r++) {
            GPart& part = ge.parts[(size_t)r];
            int code = rcodes[(size_t)r];
            if (code == 0) continue;  // count: uses ge.count
            if (code == 1) {
                Py_ssize_t ix = ridx[(size_t)r][0];
                PyObject* v = ix < 0 ? key
                              : ix < nvals ? PyTuple_GET_ITEM(values, ix)
                                           : nullptr;
                if (v == nullptr) {
                    PyErr_SetString(g_unsupported, "column index out of range");
                    fail = true;
                    break;
                }
                if (v == Py_None || v == error_obj) continue;
                PyObject* term;
                if (diff == 1 && (PyLong_Check(v) || PyFloat_Check(v))) {
                    // immutable scalars may alias; everything else (ndarray!)
                    // must copy via v * diff like the Python reducer does
                    term = v;
                    Py_INCREF(term);
                } else {
                    PyObject* d = PyLong_FromLongLong(diff);
                    if (d == nullptr) {
                        fail = true;
                        break;
                    }
                    term = PyNumber_Multiply(v, d);
                    Py_DECREF(d);
                    if (term == nullptr) {
                        fail = true;
                        break;
                    }
                }
                if (part.total == nullptr) {
                    part.total = term;
                } else {
                    PyObject* s = PyNumber_Add(part.total, term);
                    Py_DECREF(term);
                    if (s == nullptr) {
                        fail = true;
                        break;
                    }
                    Py_DECREF(part.total);
                    part.total = s;
                }
                part.cnt += diff;
            } else {  // code == 2: multiset of args
                const std::vector<Py_ssize_t>& idxs = ridx[(size_t)r];
                PyObject* margs = PyTuple_New((Py_ssize_t)idxs.size());
                if (margs == nullptr) {
                    fail = true;
                    break;
                }
                for (size_t j = 0; j < idxs.size(); j++) {
                    Py_ssize_t ix = idxs[j];
                    PyObject* cell;
                    if (ix < 0) {
                        cell = key;
                    } else if (ix < nvals) {
                        cell = PyTuple_GET_ITEM(values, ix);
                    } else {
                        PyErr_SetString(g_unsupported,
                                        "column index out of range");
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    Py_INCREF(cell);
                    PyTuple_SET_ITEM(margs, (Py_ssize_t)j, cell);
                }
                if (fail) break;
                if (part.msdict == nullptr) {
                    part.msdict = PyDict_New();
                    if (part.msdict == nullptr) {
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                }
                PyObject* h = margs;  // try the raw tuple as hash key first
                Py_INCREF(h);
                PyObject* mf = PyDict_GetItemWithError(part.msdict, h);
                if (mf == nullptr && PyErr_Occurred()) {
                    if (!PyErr_ExceptionMatches(PyExc_TypeError)) {
                        Py_DECREF(h);
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    PyErr_Clear();
                    Py_DECREF(h);
                    h = PyObject_CallFunctionObjArgs(hashable_fn, margs,
                                                     nullptr);
                    if (h == nullptr) {
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    mf = PyDict_GetItemWithError(part.msdict, h);
                    if (mf == nullptr && PyErr_Occurred()) {
                        Py_DECREF(h);
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                }
                if (mf != nullptr) {
                    size_t mi = (size_t)PyLong_AsSsize_t(mf);
                    part.msitems[mi].delta += diff;
                    Py_DECREF(h);
                    Py_DECREF(margs);
                } else {
                    PyObject* mi =
                        PyLong_FromSsize_t((Py_ssize_t)part.msitems.size());
                    if (mi == nullptr ||
                        PyDict_SetItem(part.msdict, h, mi) < 0) {
                        Py_XDECREF(mi);
                        Py_DECREF(h);
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    Py_DECREF(mi);
                    part.msitems.push_back({diff, margs, h});  // owns both
                }
            }
        }
    }
    Py_DECREF(seq);
    if (fail) {
        free_gentries(entries);
        Py_DECREF(gmap);
        if (unsupported && !PyErr_Occurred())
            PyErr_SetString(g_unsupported, "unhashable group values");
        return nullptr;
    }

    // build the result: {gvals: (count, (partial, ...))}
    PyObject* out = PyDict_New();
    if (out == nullptr) {
        free_gentries(entries);
        Py_DECREF(gmap);
        return nullptr;
    }
    for (size_t ei = 0; ei < entries.size() && !fail; ei++) {
        GEntry& ge = entries[ei];
        PyObject* parts = PyTuple_New(nred);
        if (parts == nullptr) {
            fail = true;
            break;
        }
        for (Py_ssize_t r = 0; r < nred && !fail; r++) {
            GPart& p = ge.parts[(size_t)r];
            PyObject* payload = nullptr;
            if (rcodes[(size_t)r] == 0) {
                payload = PyLong_FromLongLong(ge.count);
            } else if (rcodes[(size_t)r] == 1) {
                PyObject* tot = p.total ? p.total : Py_None;
                payload = Py_BuildValue("(OL)", tot, p.cnt);
            } else {
                payload = PyDict_New();
                if (payload != nullptr) {
                    for (MsItem& it : p.msitems) {
                        PyObject* dv =
                            Py_BuildValue("(LO)", it.delta, it.args);
                        if (dv == nullptr ||
                            PyDict_SetItem(payload, it.h, dv) < 0) {
                            Py_XDECREF(dv);
                            Py_DECREF(payload);
                            payload = nullptr;
                            break;
                        }
                        Py_DECREF(dv);
                    }
                }
            }
            if (payload == nullptr) {
                Py_DECREF(parts);
                fail = true;
                break;
            }
            PyTuple_SET_ITEM(parts, r, payload);
        }
        if (fail) break;
        PyObject* val = Py_BuildValue("(LO)", ge.count, parts);
        Py_DECREF(parts);
        if (val == nullptr ||
            PyDict_SetItem(out, gvals_by_entry[ei], val) < 0) {
            Py_XDECREF(val);
            fail = true;
            break;
        }
        Py_DECREF(val);
    }
    free_gentries(entries);
    Py_DECREF(gmap);
    if (fail) {
        Py_DECREF(out);
        return nullptr;
    }
    return out;
}

// --------------------------------------------------------------------------
// bulk schema coercion

enum CoerceCode {
    CO_ANY = 0,
    CO_INT = 1,
    CO_FLOAT = 2,
    CO_STR = 3,
    CO_BOOL = 4,
};

// mirrors io/_connector.py _column_coercer — must stay behaviour-identical
PyObject* coerce_one(PyObject* v, int code) {
    switch (code) {
        case CO_FLOAT: {
            if (PyFloat_Check(v)) break;
            if (PyLong_Check(v)) return PyNumber_Float(v);
            if (PyUnicode_Check(v)) {
                PyObject* f = PyFloat_FromString(v);
                if (f != nullptr) return f;
                PyErr_Clear();
            }
            break;
        }
        case CO_INT: {
            if (PyLong_Check(v)) break;  // bools stay bools (python parity)
            if (PyFloat_Check(v)) {
                double d = PyFloat_AS_DOUBLE(v);
                // float.is_integer() parity; PyLong_FromDouble is exact
                // for integer-valued doubles of any magnitude
                if (std::isfinite(d) && d == std::floor(d))
                    return PyLong_FromDouble(d);
                break;
            }
            if (PyUnicode_Check(v)) {
                PyObject* iv = PyLong_FromUnicodeObject(v, 10);
                if (iv != nullptr) return iv;
                PyErr_Clear();
            }
            break;
        }
        case CO_STR: {
            if (PyUnicode_Check(v)) break;
            return PyObject_Str(v);
        }
        case CO_BOOL: {
            if (PyUnicode_Check(v)) {
                PyObject* lower = PyObject_CallMethod(v, "lower", nullptr);
                if (lower == nullptr) return nullptr;
                bool truthy =
                    PyUnicode_CompareWithASCIIString(lower, "true") == 0 ||
                    PyUnicode_CompareWithASCIIString(lower, "1") == 0 ||
                    PyUnicode_CompareWithASCIIString(lower, "t") == 0 ||
                    PyUnicode_CompareWithASCIIString(lower, "yes") == 0;
                Py_DECREF(lower);
                return PyBool_FromLong(truthy ? 1 : 0);
            }
            break;
        }
        default:
            break;
    }
    Py_INCREF(v);
    return v;
}

PyObject* py_coerce_rows(PyObject*, PyObject* args) {
    // rows: list of dicts; plan: list of (name, default, code)
    PyObject *rows, *plan;
    if (!PyArg_ParseTuple(args, "OO", &rows, &plan)) return nullptr;
    PyObject* plan_seq = PySequence_Fast(plan, "plan must be a sequence");
    if (plan_seq == nullptr) return nullptr;
    Py_ssize_t ncols = PySequence_Fast_GET_SIZE(plan_seq);
    std::vector<PyObject*> names((size_t)ncols);
    std::vector<PyObject*> defaults((size_t)ncols);
    std::vector<int> codes((size_t)ncols);
    for (Py_ssize_t c = 0; c < ncols; c++) {
        PyObject* item = PySequence_Fast_GET_ITEM(plan_seq, c);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3) {
            PyErr_SetString(PyExc_TypeError, "plan items must be 3-tuples");
            Py_DECREF(plan_seq);
            return nullptr;
        }
        names[(size_t)c] = PyTuple_GET_ITEM(item, 0);
        defaults[(size_t)c] = PyTuple_GET_ITEM(item, 1);
        long code = PyLong_AsLong(PyTuple_GET_ITEM(item, 2));
        if (code == -1 && PyErr_Occurred()) {
            Py_DECREF(plan_seq);
            return nullptr;
        }
        codes[(size_t)c] = (int)code;
    }
    PyObject* rows_seq = PySequence_Fast(rows, "rows must be a sequence");
    if (rows_seq == nullptr) {
        Py_DECREF(plan_seq);
        return nullptr;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(rows_seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(plan_seq);
        Py_DECREF(rows_seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PySequence_Fast_GET_ITEM(rows_seq, i);
        if (!PyDict_Check(row)) {
            PyErr_SetString(g_unsupported, "rows must be dicts");
            goto fail;
        }
        {
            PyObject* tup = PyTuple_New(ncols);
            if (tup == nullptr) goto fail;
            for (Py_ssize_t c = 0; c < ncols; c++) {
                PyObject* v = PyDict_GetItemWithError(row, names[(size_t)c]);
                if (v == nullptr && PyErr_Occurred()) {
                    Py_DECREF(tup);
                    goto fail;
                }
                if (v == nullptr || v == Py_None) v = defaults[(size_t)c];
                PyObject* cv;
                if (v == nullptr || v == Py_None) {
                    cv = Py_None;
                    Py_INCREF(cv);
                } else {
                    cv = coerce_one(v, codes[(size_t)c]);
                    if (cv == nullptr) {
                        Py_DECREF(tup);
                        goto fail;
                    }
                }
                PyTuple_SET_ITEM(tup, c, cv);
            }
            PyList_SET_ITEM(out, i, tup);
        }
    }
    Py_DECREF(plan_seq);
    Py_DECREF(rows_seq);
    return out;
fail:
    Py_DECREF(plan_seq);
    Py_DECREF(rows_seq);
    Py_DECREF(out);
    return nullptr;
}

// --------------------------------------------------------------------------
// worker routing

// route_split(batch, idx_tuple, n_workers) -> [outbox_0, ..., outbox_W-1]
// One C pass splitting an update batch by the 128-bit hash of positional
// route cells (idx >= 0 -> values[idx], -1 -> row key) — byte-identical
// to cluster.stable_shard / keys.ref_scalar, including the repr fallback
// for unhashable cell types.
PyObject* py_route_split(PyObject*, PyObject* args) {
    PyObject *batch, *idxs;
    long W;
    if (!PyArg_ParseTuple(args, "OOl", &batch, &idxs, &W)) return nullptr;
    if (W <= 0 || !PyTuple_Check(idxs)) {
        PyErr_SetString(PyExc_ValueError, "bad route_split arguments");
        return nullptr;
    }
    Py_ssize_t nidx = PyTuple_GET_SIZE(idxs);
    std::vector<Py_ssize_t> pos((size_t)nidx);
    for (Py_ssize_t i = 0; i < nidx; i++) {
        pos[(size_t)i] = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, i));
        if (pos[(size_t)i] == -1 && PyErr_Occurred()) return nullptr;
    }
    PyObject* seq = PySequence_Fast(batch, "route_split expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(W);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (long w = 0; w < W; w++) {
        PyObject* lst = PyList_New(0);
        if (lst == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, w, lst);
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* key = PyTuple_GET_ITEM(u, 0);
            PyObject* values = PyTuple_GET_ITEM(u, 1);
            if (!PyTuple_Check(values)) {
                PyErr_SetString(PyExc_TypeError, "values must be tuples");
                goto fail;
            }
            Py_ssize_t nvals = PyTuple_GET_SIZE(values);
            if (nidx == 0) {
                // empty idx tuple = key-value routing (route_by_key):
                // dest = int(key) % W, NOT a re-hash — matches the Python
                // route_by_key closure exactly
                PyObject* wobj = PyLong_FromLong(W);
                if (wobj == nullptr) goto fail;
                PyObject* m = PyNumber_Remainder(key, wobj);
                Py_DECREF(wobj);
                if (m == nullptr) goto fail;
                long dest = PyLong_AsLong(m);
                Py_DECREF(m);
                if (dest == -1 && PyErr_Occurred()) goto fail;
                if (PyList_Append(PyList_GET_ITEM(out, dest), u) < 0)
                    goto fail;
                continue;
            }
            Hasher h;
            bool ok = true;
            for (Py_ssize_t j = 0; j < nidx && ok; j++) {
                Py_ssize_t ix = pos[(size_t)j];
                PyObject* cell;
                if (ix < 0) {
                    cell = key;
                } else if (ix < nvals) {
                    cell = PyTuple_GET_ITEM(values, ix);
                } else {
                    PyErr_SetString(PyExc_IndexError,
                                    "route column out of range");
                    goto fail;
                }
                ok = feed(h, cell);
            }
            if (!ok) {
                // cell type outside the native feed set (datetime,
                // ndarray, ...): the PYTHON hasher supports more tags, so
                // punt the WHOLE batch to the per-row stable_shard path —
                // a divergent native fallback hash would route rows of
                // the same group to different workers
                if (!PyErr_Occurred())
                    PyErr_SetString(g_unsupported, "unroutable cell type");
                goto fail;
            }
            uint8_t dg[16];
            pwnative::blake2b_final(&h.S, dg);
            uint64_t lo, hi;
            std::memcpy(&lo, dg, 8);
            std::memcpy(&hi, dg + 8, 8);
            unsigned __int128 v =
                ((unsigned __int128)hi << 64) | (unsigned __int128)lo;
            long dest = (long)(unsigned long long)(v % (unsigned long long)W);
            if (PyList_Append(PyList_GET_ITEM(out, dest), u) < 0) goto fail;
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

// --------------------------------------------------------------------------
// WordPiece tokenization (ASCII fast path)
//
// The BERT tokenize pipeline (models/wordpiece.py) is the host-side
// bottleneck of the embedding path.  This implements the exact pipeline
// for ASCII text — clean/control/whitespace handling, lowercasing,
// punctuation splitting, greedy longest-match-first WordPiece — in one C
// pass per text; non-ASCII texts return None so the caller falls back to
// the Python implementation per text (identical output either way: on
// ASCII input NFD accent-stripping and CJK spacing are no-ops).

struct WpVocab {
    std::unordered_map<std::string, int> map;
    int unk;
    int max_chars;
    size_t max_token_len = 0;  // longest vocab entry, bounds the scan
};

void wp_free(PyObject* cap) {
    delete static_cast<WpVocab*>(PyCapsule_GetPointer(cap, "pw.wordpiece"));
}

bool wp_is_punct(unsigned char c) {
    return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
           (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

PyObject* py_wp_build(PyObject*, PyObject* args) {
    PyObject* vocab;
    int unk, max_chars;
    if (!PyArg_ParseTuple(args, "Oii", &vocab, &unk, &max_chars))
        return nullptr;
    if (!PyDict_Check(vocab)) {
        PyErr_SetString(PyExc_TypeError, "vocab must be a dict");
        return nullptr;
    }
    auto* wv = new WpVocab{{}, unk, max_chars};
    wv->map.reserve((size_t)PyDict_Size(vocab) * 2);
    Py_ssize_t pos = 0;
    PyObject *k, *v;
    while (PyDict_Next(vocab, &pos, &k, &v)) {
        Py_ssize_t n;
        const char* s = PyUnicode_AsUTF8AndSize(k, &n);
        if (s == nullptr) {
            delete wv;
            return nullptr;
        }
        long id = PyLong_AsLong(v);
        if (id == -1 && PyErr_Occurred()) {
            delete wv;
            return nullptr;
        }
        wv->map.emplace(std::string(s, (size_t)n), (int)id);
        if ((size_t)n > wv->max_token_len) wv->max_token_len = (size_t)n;
    }
    return PyCapsule_New(wv, "pw.wordpiece", wp_free);
}

// greedy longest-match-first over one word; appends ids or a single unk
void wp_word(const WpVocab& wv, const std::string& word,
             std::vector<int>& out) {
    if ((int)word.size() > wv.max_chars) {
        out.push_back(wv.unk);
        return;
    }
    size_t start = 0;
    size_t base = out.size();
    std::string piece;
    while (start < word.size()) {
        size_t end = word.size();
        // longest vocab entry bounds the window ("##" adds 2 bytes)
        size_t limit = start + wv.max_token_len;
        if (end > limit) end = limit;
        int cur = -1;
        size_t cur_end = 0;
        while (end > start) {
            piece.clear();
            if (start > 0) piece = "##";
            piece.append(word, start, end - start);
            auto it = wv.map.find(piece);
            if (it != wv.map.end()) {
                cur = it->second;
                cur_end = end;
                break;
            }
            end--;
        }
        if (cur < 0) {
            out.resize(base);
            out.push_back(wv.unk);
            return;
        }
        out.push_back(cur);
        start = cur_end;
    }
}

PyObject* py_wp_encode(PyObject*, PyObject* args) {
    PyObject *cap, *texts;
    int lower;
    if (!PyArg_ParseTuple(args, "OOp", &cap, &texts, &lower)) return nullptr;
    auto* wv =
        static_cast<WpVocab*>(PyCapsule_GetPointer(cap, "pw.wordpiece"));
    if (wv == nullptr) return nullptr;
    PyObject* seq = PySequence_Fast(texts, "texts must be a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    std::vector<int> ids;
    std::string word;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* text = PySequence_Fast_GET_ITEM(seq, i);
        Py_ssize_t len;
        const char* s =
            PyUnicode_Check(text) ? PyUnicode_AsUTF8AndSize(text, &len)
                                  : nullptr;
        if (s == nullptr) {
            PyErr_Clear();
            Py_INCREF(Py_None);  // non-string: python path decides
            PyList_SET_ITEM(out, i, Py_None);
            continue;
        }
        bool ascii = true;
        for (Py_ssize_t j = 0; j < len; j++) {
            if ((unsigned char)s[j] >= 0x80) {
                ascii = false;
                break;
            }
        }
        if (!ascii) {
            Py_INCREF(Py_None);  // python fallback handles unicode rules
            PyList_SET_ITEM(out, i, Py_None);
            continue;
        }
        ids.clear();
        word.clear();
        for (Py_ssize_t j = 0; j <= len; j++) {
            unsigned char c = j < len ? (unsigned char)s[j] : ' ';
            if (c == 0 || (c < 0x20 && c != '\t' && c != '\n' && c != '\r') ||
                c == 0x7f)
                continue;  // _clean drops controls
            if (c == ' ' || c == '\t' || c == '\n' || c == '\r') {
                if (!word.empty()) {
                    wp_word(*wv, word, ids);
                    word.clear();
                }
                continue;
            }
            if (lower && c >= 'A' && c <= 'Z') c = (unsigned char)(c + 32);
            if (wp_is_punct(c)) {
                if (!word.empty()) {
                    wp_word(*wv, word, ids);
                    word.clear();
                }
                word.push_back((char)c);
                wp_word(*wv, word, ids);
                word.clear();
                continue;
            }
            word.push_back((char)c);
        }
        PyObject* row = PyList_New((Py_ssize_t)ids.size());
        if (row == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        for (size_t j = 0; j < ids.size(); j++) {
            PyObject* v = PyLong_FromLong(ids[j]);
            if (v == nullptr) {
                Py_DECREF(row);
                Py_DECREF(seq);
                Py_DECREF(out);
                return nullptr;
            }
            PyList_SET_ITEM(row, (Py_ssize_t)j, v);
        }
        PyList_SET_ITEM(out, i, row);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_set_pointer_type(PyObject*, PyObject* cls) {
    Py_XDECREF(g_pointer_type);
    Py_INCREF(cls);
    g_pointer_type = cls;
    Py_RETURN_NONE;
}

PyMethodDef kMethods[] = {
    {"ref_scalar", py_ref_scalar, METH_VARARGS,
     "128-bit key hash of the argument values"},
    {"hash_rows", py_hash_rows, METH_O,
     "batch 128-bit key hashes for a sequence of value tuples"},
    {"scan_lines", py_scan_lines, METH_O,
     "offsets of non-empty lines in a bytes buffer"},
    {"consolidate", py_consolidate, METH_VARARGS,
     "merge updates with equal (key, row), dropping zero-diff entries"},
    {"per_key_changes", py_per_key_changes, METH_O,
     "group a batch into per-key (removals, additions) lists"},
    {"build_adds", py_build_adds, METH_VARARGS,
     "bulk Update(key, values, +1) construction"},
    {"coerce_rows", py_coerce_rows, METH_VARARGS,
     "bulk schema coercion of row dicts into value tuples"},
    {"groupby_partials", py_groupby_partials, METH_VARARGS,
     "per-group partial aggregates of an update batch"},
    {"all_positive", py_all_positive, METH_O,
     "True iff every update diff is > 0"},
    {"all_dicts", py_all_dicts, METH_O,
     "True iff every element is a dict"},
    {"rowwise_map", py_rowwise_map, METH_VARARGS,
     "apply a row function across a batch, containing row errors"},
    {"route_split", py_route_split, METH_VARARGS,
     "split an update batch into per-worker outboxes by route-cell hash"},
    {"wp_build", py_wp_build, METH_VARARGS,
     "build a WordPiece vocab handle from a token->id dict"},
    {"wp_encode", py_wp_encode, METH_VARARGS,
     "BERT-tokenize a batch of ASCII texts (None marks python fallback)"},
    {"filter_batch", py_filter_batch, METH_VARARGS,
     "keep updates whose (key, values) satisfy the predicate"},
    {"set_pointer_type", py_set_pointer_type, METH_O,
     "register the Pointer class for type-tagged hashing"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "pathway_native",
                       "pathway_tpu C++ host hot paths", -1, kMethods};

}  // namespace

PyMODINIT_FUNC PyInit_pathway_native(void) {
    PyObject* m = PyModule_Create(&kModule);
    if (m == nullptr) return nullptr;
    g_unsupported =
        PyErr_NewException("pathway_native.Unsupported", nullptr, nullptr);
    Py_INCREF(g_unsupported);
    PyModule_AddObject(m, "Unsupported", g_unsupported);
    return m;
}
