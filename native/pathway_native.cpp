// pathway_native — C++ host-runtime hot paths for pathway_tpu.
//
// The reference implements its engine hot loops in Rust
// (src/engine/value.rs Key hashing, src/connectors tokenization); the
// TPU build keeps the numeric plane in XLA and implements the host-side
// hot paths here as a CPython extension:
//
//   - ref_scalar(args_tuple) / hash_rows(list[tuple]): 128-bit row-key
//     hashing, byte-for-byte identical to the Python implementation in
//     pathway_tpu/internals/keys.py (type-tagged serialization into
//     BLAKE2b-128) — keys are stable across the two paths, which
//     persistence snapshots rely on.
//   - scan_lines(bytes): newline scanning for the file data loader.
//   - consolidate(batch, update_cls, hashable_row): merge update deltas
//     with equal (key, row) — the per-node compaction the reference runs
//     inside differential arrangements (src/engine/dataflow.rs
//     consolidation); single-occurrence updates are re-emitted by
//     reference (no allocation).
//   - per_key_changes(batch): group a batch into per-key (removals,
//     additions) lists.
//   - coerce_rows(rows, plan): bulk schema coercion of parsed row dicts
//     into value tuples (reference parser hot loop,
//     src/connectors/data_format.rs DsvParser/JsonLinesParser).
//   - build_adds(rows, update_cls): bulk Update(key, values, +1)
//     construction for chunked connector ingest.
//
// Unsupported value types (big ints, ndarrays, datetimes, arbitrary
// objects) raise _Unsupported so the caller transparently falls back to
// the Python path for that call.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <datetime.h>

#include <algorithm>
#include <atomic>
#include <cctype>
#include <chrono>
#include <climits>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

#include "blake2b.h"

namespace {

PyObject* g_unsupported = nullptr;  // exception type for fallback
PyObject* g_pointer_type = nullptr;  // pathway_tpu Pointer class

// ---------------------------------------------------------------------------
// CPython 3.13 removed _PyLong_NumBits / _PyLong_AsByteArray /
// _PyLong_FromByteArray from the public headers (and changed the
// _PyLong_AsByteArray signature), which would make this whole extension
// silently fail to compile and every fast path degrade to Python.  Wrap
// the int<->bytes conversions so 3.13+ uses the new stable
// PyLong_AsNativeBytes / PyLong_FromNativeBytes API instead.
// All helpers return 0 / non-NULL on success; on failure the caller is
// expected to PyErr_Clear() and fall back.
#if PY_VERSION_HEX >= 0x030D0000
inline int pt_long_as_bytes_unsigned(PyObject* v, uint8_t* out, size_t n) {
    Py_ssize_t r = PyLong_AsNativeBytes(
        v, out, (Py_ssize_t)n,
        Py_ASNATIVEBYTES_LITTLE_ENDIAN | Py_ASNATIVEBYTES_UNSIGNED_BUFFER |
            Py_ASNATIVEBYTES_REJECT_NEGATIVE);
    return (r < 0 || (size_t)r > n) ? -1 : 0;
}
inline int pt_long_as_bytes_signed(PyObject* v, uint8_t* out, size_t n) {
    // sign-extends into the full n-byte buffer, matching
    // int.to_bytes(n, "little", signed=True)
    Py_ssize_t r = PyLong_AsNativeBytes(v, out, (Py_ssize_t)n,
                                        Py_ASNATIVEBYTES_LITTLE_ENDIAN);
    return (r < 0 || (size_t)r > n) ? -1 : 0;
}
inline size_t pt_long_numbits(PyObject* v) {
    // no public C equivalent of _PyLong_NumBits; the object-protocol call
    // is acceptable because this only runs on the rare >64-bit path
    PyObject* bl = PyObject_CallMethod(v, "bit_length", nullptr);
    if (bl == nullptr) return (size_t)-1;
    size_t bits = PyLong_AsSize_t(bl);
    Py_DECREF(bl);
    if (bits == (size_t)-1 && PyErr_Occurred()) return (size_t)-1;
    return bits;
}
inline PyObject* pt_long_from_bytes_unsigned(const uint8_t* buf, size_t n) {
    return PyLong_FromNativeBytes(
        buf, (Py_ssize_t)n,
        Py_ASNATIVEBYTES_LITTLE_ENDIAN | Py_ASNATIVEBYTES_UNSIGNED_BUFFER);
}
#else
inline int pt_long_as_bytes_unsigned(PyObject* v, uint8_t* out, size_t n) {
    return _PyLong_AsByteArray(reinterpret_cast<PyLongObject*>(v), out, n,
                               /*little_endian=*/1, /*is_signed=*/0);
}
inline int pt_long_as_bytes_signed(PyObject* v, uint8_t* out, size_t n) {
    return _PyLong_AsByteArray(reinterpret_cast<PyLongObject*>(v), out, n,
                               /*little_endian=*/1, /*is_signed=*/1);
}
inline size_t pt_long_numbits(PyObject* v) { return _PyLong_NumBits(v); }
inline PyObject* pt_long_from_bytes_unsigned(const uint8_t* buf, size_t n) {
    return _PyLong_FromByteArray(buf, n, /*little_endian=*/1, /*signed=*/0);
}
#endif

const char kSalt[] = "pathway_tpu.key.v1";

struct Hasher {
    pwnative::Blake2bState S;
    Hasher() {
        pwnative::blake2b_init(&S, 16);
        pwnative::blake2b_update(
            &S, reinterpret_cast<const uint8_t*>(kSalt), sizeof(kSalt) - 1);
    }
    void bytes(const void* p, size_t n) {
        pwnative::blake2b_update(&S, static_cast<const uint8_t*>(p), n);
    }
    void tag(uint8_t t) { bytes(&t, 1); }
    void u64le(uint64_t v) { bytes(&v, 8); }
};

// collects the exact byte stream ``feed`` would hash — used as the memo
// key for route_split's per-row digest cache
struct ByteSink {
    std::string& out;
    void bytes(const void* p, size_t n) {
        out.append(static_cast<const char*>(p), n);
    }
    void tag(uint8_t t) { out.push_back(static_cast<char>(t)); }
    void u64le(uint64_t v) {
        out.append(reinterpret_cast<const char*>(&v), 8);
    }
};

// mirror of keys._feed — must stay byte-identical.  Templated over the
// sink so route_split can serialize the fed bytes once (ByteSink) while
// key hashing keeps streaming straight into BLAKE2b (Hasher).
template <typename Sink>
bool feed(Sink& h, PyObject* v) {
    if (v == Py_None) {
        h.tag(0x00);
        return true;
    }
    if (PyBool_Check(v)) {
        h.tag(0x01);
        h.tag(v == Py_True ? 0x01 : 0x00);
        return true;
    }
    if (g_pointer_type != nullptr &&
        PyObject_TypeCheck(v, reinterpret_cast<PyTypeObject*>(g_pointer_type))) {
        uint8_t out[16];
        if (pt_long_as_bytes_unsigned(v, out, 16) < 0) {
            PyErr_Clear();
            return false;  // >128-bit pointer: fall back
        }
        h.tag(0x07);
        h.bytes(out, 16);
        return true;
    }
    if (PyLong_Check(v)) {
        int overflow = 0;
        long long val = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow != 0) {
            // big int (e.g. 128-bit join/derive key material): replicate
            // value.to_bytes((bit_length + 8)//8 + 1, "little", signed)
            size_t bits = pt_long_numbits(v);
            if (bits == (size_t)-1) {
                PyErr_Clear();
                return false;
            }
            size_t nb = (bits + 8) / 8 + 1;
            uint8_t buf[64];
            if (nb > sizeof(buf)) return false;  // >~500 bits: fall back
            if (pt_long_as_bytes_signed(v, buf, nb) < 0) {
                PyErr_Clear();
                return false;
            }
            h.tag(0x02);
            h.bytes(buf, nb);
            return true;
        }
        // python: n = (bit_length + 8) // 8 + 1 bytes, signed little
        unsigned long long mag =
            val < 0 ? (unsigned long long)(-(val + 1)) + 1ULL
                    : (unsigned long long)val;
        // bit_length (0 for val==0); `mag >> bl` would be UB at bl==64
        // (mag == 2^63 when val == INT64_MIN), so use clz instead.
        int bl = mag ? 64 - __builtin_clzll(mag) : 0;
        int n = (bl + 8) / 8 + 1;
        uint8_t buf[16];
        long long x = val;
        for (int i = 0; i < n; i++) {
            buf[i] = (uint8_t)(x & 0xff);
            x >>= 8;  // arithmetic shift: sign-extends
        }
        h.tag(0x02);
        h.bytes(buf, n);
        return true;
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        h.tag(0x03);
        h.bytes(&d, 8);
        return true;
    }
    if (PyUnicode_Check(v)) {
        Py_ssize_t n;
        const char* s = PyUnicode_AsUTF8AndSize(v, &n);
        if (s == nullptr) return false;
        h.tag(0x04);
        h.u64le((uint64_t)n);
        h.bytes(s, (size_t)n);
        return true;
    }
    if (PyBytes_Check(v)) {
        h.tag(0x05);
        h.u64le((uint64_t)PyBytes_GET_SIZE(v));
        h.bytes(PyBytes_AS_STRING(v), (size_t)PyBytes_GET_SIZE(v));
        return true;
    }
    if (PyTuple_Check(v)) {
        Py_ssize_t n = PyTuple_GET_SIZE(v);
        h.tag(0x06);
        h.u64le((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (!feed(h, PyTuple_GET_ITEM(v, i))) return false;
        }
        return true;
    }
    return false;  // datetime / ndarray / other: fall back
}

PyObject* digest_to_long(Hasher& h) {
    uint8_t out[16];
    pwnative::blake2b_final(&h.S, out);
    return pt_long_from_bytes_unsigned(out, 16);
}

// Pointer construction is a per-row cost in every hot loop (key hashing,
// frame unpack), and calling the class pays the full type-call protocol
// — comparable to parsing the whole row.  Pointer is a bare int subclass
// (``__slots__ = ()``), so pre-3.12, where the PyLongObject layout is
// public, clone the digits into a tp_alloc'd instance exactly as
// CPython's long_subtype_new does.  The guards drop back to the call
// protocol if Pointer ever grows a custom __new__/__init__ or storage
// (and on 3.12+, where the int layout went opaque).  Steals ``num``.
PyObject* pointer_from_long(PyObject* num) {
    if (num == nullptr || g_pointer_type == nullptr) return num;
    PyTypeObject* pt = reinterpret_cast<PyTypeObject*>(g_pointer_type);
#if PY_VERSION_HEX < 0x030C0000
    if (pt->tp_new == PyLong_Type.tp_new &&
        pt->tp_init == PyLong_Type.tp_init &&
        pt->tp_basicsize == PyLong_Type.tp_basicsize &&
        pt->tp_itemsize == PyLong_Type.tp_itemsize &&
        PyLong_CheckExact(num)) {
        Py_ssize_t sz = Py_SIZE(num);
        Py_ssize_t ndig = sz < 0 ? -sz : sz;
        PyLongObject* p =
            reinterpret_cast<PyLongObject*>(pt->tp_alloc(pt, ndig));
        if (p == nullptr) {
            Py_DECREF(num);
            return nullptr;
        }
        Py_SET_SIZE(p, sz);
        PyLongObject* src = reinterpret_cast<PyLongObject*>(num);
        for (Py_ssize_t i = 0; i < ndig; i++)
            p->ob_digit[i] = src->ob_digit[i];
        Py_DECREF(num);
        return reinterpret_cast<PyObject*>(p);
    }
#endif
    PyObject* ptr = PyObject_CallFunctionObjArgs(g_pointer_type, num, nullptr);
    Py_DECREF(num);
    return ptr;
}

PyObject* py_ref_scalar(PyObject*, PyObject* args_tuple) {
    Hasher h;
    Py_ssize_t n = PyTuple_GET_SIZE(args_tuple);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!feed(h, PyTuple_GET_ITEM(args_tuple, i))) {
            if (!PyErr_Occurred())
                PyErr_SetString(g_unsupported, "unsupported value type");
            return nullptr;
        }
    }
    return digest_to_long(h);
}

PyObject* py_hash_rows(PyObject*, PyObject* rows) {
    // rows: sequence of tuples -> list of 128-bit ints
    PyObject* seq = PySequence_Fast(rows, "hash_rows expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(row)) {
            Py_DECREF(seq);
            Py_DECREF(out);
            PyErr_SetString(PyExc_TypeError, "rows must be tuples");
            return nullptr;
        }
        Hasher h;
        Py_ssize_t m = PyTuple_GET_SIZE(row);
        bool ok = true;
        for (Py_ssize_t j = 0; j < m && ok; j++)
            ok = feed(h, PyTuple_GET_ITEM(row, j));
        if (!ok) {
            Py_DECREF(seq);
            Py_DECREF(out);
            if (!PyErr_Occurred())
                PyErr_SetString(g_unsupported, "unsupported value type");
            return nullptr;
        }
        PyObject* key = digest_to_long(h);
        if (key == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, key);
    }
    Py_DECREF(seq);
    return out;
}

// Feed a small (64-bit) signed int exactly like the PyLong branch of
// feed(): n = (bit_length + 8)//8 + 1 bytes, signed little-endian.
// Templated over the sink for the same reason feed() is.
template <typename Sink>
inline void feed_small_int(Sink& h, long long val) {
    unsigned long long mag =
        val < 0 ? (unsigned long long)(-(val + 1)) + 1ULL
                : (unsigned long long)val;
    int bl = mag ? 64 - __builtin_clzll(mag) : 0;
    int n = (bl + 8) / 8 + 1;
    uint8_t buf[16];
    long long x = val;
    for (int i = 0; i < n; i++) {
        buf[i] = (uint8_t)(x & 0xff);
        x >>= 8;
    }
    h.tag(0x02);
    h.bytes(buf, n);
}

// Feed any PyLong (including a Pointer) as a PLAIN int — tag 0x02 signed
// little-endian, matching ref_scalar(int(v)).  Returns false (no
// exception or cleared) when the value exceeds the big-int window.
bool feed_pylong_plain(Hasher& h, PyObject* v) {
    int overflow = 0;
    long long val = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow == 0) {
        if (val == -1 && PyErr_Occurred()) {
            PyErr_Clear();
            return false;
        }
        feed_small_int(h, val);
        return true;
    }
    size_t bits = pt_long_numbits(v);
    if (bits == (size_t)-1) {
        PyErr_Clear();
        return false;
    }
    size_t nb = (bits + 8) / 8 + 1;
    uint8_t buf[64];
    if (nb > sizeof(buf)) return false;
    if (pt_long_as_bytes_signed(v, buf, nb) < 0) {
        PyErr_Clear();
        return false;
    }
    h.tag(0x02);
    h.bytes(buf, nb);
    return true;
}

PyObject* py_hash_prefix_ints(PyObject*, PyObject* args) {
    // (prefix_tuple, seq_ints, offset=0) -> list of Pointer
    //
    // Bulk key generation for sequentially numbered connector rows
    // (io/fs emit_rows): the prefix ("__fs__", tag, path) hash state is
    // computed ONCE and copied per row, so neither the per-row Python
    // key tuple nor the re-hash of the constant prefix exists.  Rows
    // become Pointer objects here (one C call) instead of a Python
    // listcomp over hash_rows output.  Byte-identical to
    // ref_scalar(*prefix, seq + offset).
    PyObject* prefix;
    PyObject* seqs;
    long long offset = 0;
    if (!PyArg_ParseTuple(args, "O!O|L", &PyTuple_Type, &prefix, &seqs,
                          &offset))
        return nullptr;
    if (g_pointer_type == nullptr) {
        PyErr_SetString(g_unsupported, "Pointer type not registered");
        return nullptr;
    }
    Hasher base;
    Py_ssize_t m = PyTuple_GET_SIZE(prefix);
    for (Py_ssize_t j = 0; j < m; j++) {
        if (!feed(base, PyTuple_GET_ITEM(prefix, j))) {
            if (!PyErr_Occurred())
                PyErr_SetString(g_unsupported, "unsupported value type");
            return nullptr;
        }
    }
    PyObject* seq = PySequence_Fast(seqs, "hash_prefix_ints expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* s = PySequence_Fast_GET_ITEM(seq, i);
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(s, &overflow);
        if (overflow != 0 || (v == -1 && PyErr_Occurred())) {
            Py_DECREF(seq);
            Py_DECREF(out);
            if (!PyErr_Occurred())
                PyErr_SetString(g_unsupported, "seq out of int64 range");
            return nullptr;
        }
        Hasher h = base;  // copy of the prefix hash state
        feed_small_int(h, v + offset);
        PyObject* num = digest_to_long(h);
        if (num == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyObject* ptr = pointer_from_long(num);
        if (ptr == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, ptr);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_scan_lines(PyObject*, PyObject* arg) {
    // bytes -> list of (start, end) offsets of non-empty lines
    char* data;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(arg, &data, &len) < 0) return nullptr;
    std::vector<std::pair<Py_ssize_t, Py_ssize_t>> spans;
    Py_ssize_t start = 0;
    for (Py_ssize_t i = 0; i <= len; i++) {
        if (i == len || data[i] == '\n') {
            Py_ssize_t end = i;
            if (end > start && data[end - 1] == '\r') end--;
            if (end > start) spans.emplace_back(start, end);
            start = i + 1;
        }
    }
    PyObject* out = PyList_New((Py_ssize_t)spans.size());
    if (out == nullptr) return nullptr;
    for (size_t i = 0; i < spans.size(); i++) {
        PyObject* t = Py_BuildValue("(nn)", spans[i].first, spans[i].second);
        if (t == nullptr) {
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, (Py_ssize_t)i, t);
    }
    return out;
}

// --------------------------------------------------------------------------
// update-stream batch ops

// Update is a Python NamedTuple (engine/stream.py); instances are plain
// tuple subclass objects, so tuple's own tp_new builds them without going
// through the Python-level __new__ (same trick as namedtuple._make).
PyObject* make_update_obj(PyObject* cls, PyObject* key, PyObject* values,
                          PyObject* diff) {
    // Update is a NamedTuple: no state beyond the tuple items, and its
    // generated __new__ is a Python function — allocate the tuple
    // subclass directly (what tuple.__new__ itself does) instead of
    // calling it
    PyTypeObject* t = reinterpret_cast<PyTypeObject*>(cls);
    PyObject* u = t->tp_alloc(t, 3);
    if (u == nullptr) return nullptr;
    Py_INCREF(key);
    Py_INCREF(values);
    Py_INCREF(diff);
    PyTuple_SET_ITEM(u, 0, key);
    PyTuple_SET_ITEM(u, 1, values);
    PyTuple_SET_ITEM(u, 2, diff);
    return u;
}

PyObject* make_update(PyObject* cls, PyObject* key, PyObject* values,
                      long long diff) {
    PyObject* d = PyLong_FromLongLong(diff);
    if (d == nullptr) return nullptr;
    PyObject* u = make_update_obj(cls, key, values, d);
    Py_DECREF(d);
    return u;
}

struct ConsEntry {
    PyObject* first;   // borrowed from seq until output
    PyObject* key;     // borrowed
    PyObject* values;  // borrowed
    long long diff;
    bool merged;
};

PyObject* py_consolidate(PyObject*, PyObject* args) {
    PyObject *batch, *update_cls, *hashable_row;
    if (!PyArg_ParseTuple(args, "OOO", &batch, &update_cls, &hashable_row))
        return nullptr;
    PyObject* seq = PySequence_Fast(batch, "consolidate expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* acc = PyDict_New();  // (key, row) -> index into entries
    if (acc == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    std::vector<ConsEntry> entries;
    entries.reserve((size_t)n);
    bool fail = false;
    for (Py_ssize_t i = 0; i < n && !fail; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            fail = true;
            break;
        }
        PyObject* key = PyTuple_GET_ITEM(u, 0);
        PyObject* values = PyTuple_GET_ITEM(u, 1);
        long long diff = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
        if (diff == -1 && PyErr_Occurred()) {
            fail = true;
            break;
        }
        PyObject* k2 = PyTuple_Pack(2, key, values);
        if (k2 == nullptr) {
            fail = true;
            break;
        }
        PyObject* found = PyDict_GetItemWithError(acc, k2);
        if (found == nullptr && PyErr_Occurred()) {
            if (!PyErr_ExceptionMatches(PyExc_TypeError)) {
                Py_DECREF(k2);
                fail = true;
                break;
            }
            // unhashable cell (ndarray/dict/list): type-tagged fallback key
            PyErr_Clear();
            Py_DECREF(k2);
            PyObject* tagged = PyObject_CallFunctionObjArgs(
                hashable_row, values, nullptr);
            if (tagged == nullptr) {
                fail = true;
                break;
            }
            k2 = PyTuple_Pack(2, key, tagged);
            Py_DECREF(tagged);
            if (k2 == nullptr) {
                fail = true;
                break;
            }
            found = PyDict_GetItemWithError(acc, k2);
            if (found == nullptr && PyErr_Occurred()) {
                Py_DECREF(k2);
                fail = true;
                break;
            }
        }
        if (found != nullptr) {
            size_t idx = (size_t)PyLong_AsSsize_t(found);
            entries[idx].diff += diff;
            entries[idx].merged = true;
            Py_DECREF(k2);
        } else {
            PyObject* idx = PyLong_FromSsize_t((Py_ssize_t)entries.size());
            if (idx == nullptr || PyDict_SetItem(acc, k2, idx) < 0) {
                Py_XDECREF(idx);
                Py_DECREF(k2);
                fail = true;
                break;
            }
            Py_DECREF(idx);
            Py_DECREF(k2);
            entries.push_back({u, key, values, diff, false});
        }
    }
    Py_DECREF(acc);
    if (fail) {
        Py_DECREF(seq);
        return nullptr;
    }
    PyObject* out = PyList_New(0);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (const ConsEntry& e : entries) {
        if (e.diff == 0) continue;
        PyObject* u;
        if (!e.merged) {
            u = e.first;  // unchanged: re-emit the input object
            Py_INCREF(u);
        } else {
            u = make_update(update_cls, e.key, e.values, e.diff);
            if (u == nullptr) {
                Py_DECREF(out);
                Py_DECREF(seq);
                return nullptr;
            }
        }
        if (PyList_Append(out, u) < 0) {
            Py_DECREF(u);
            Py_DECREF(out);
            Py_DECREF(seq);
            return nullptr;
        }
        Py_DECREF(u);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_per_key_changes(PyObject*, PyObject* batch) {
    PyObject* seq = PySequence_Fast(batch, "per_key_changes expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyDict_New();
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* key = PyTuple_GET_ITEM(u, 0);
            PyObject* values = PyTuple_GET_ITEM(u, 1);
            long long diff = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
            if (diff == -1 && PyErr_Occurred()) goto fail;
            PyObject* pair = PyDict_GetItemWithError(out, key);
            if (pair == nullptr) {
                if (PyErr_Occurred()) goto fail;
                PyObject* rem = PyList_New(0);
                PyObject* add = PyList_New(0);
                if (rem == nullptr || add == nullptr) {
                    Py_XDECREF(rem);
                    Py_XDECREF(add);
                    goto fail;
                }
                pair = PyTuple_Pack(2, rem, add);
                Py_DECREF(rem);
                Py_DECREF(add);
                if (pair == nullptr || PyDict_SetItem(out, key, pair) < 0) {
                    Py_XDECREF(pair);
                    goto fail;
                }
                Py_DECREF(pair);  // dict holds it; borrow below
                pair = PyDict_GetItemWithError(out, key);
                if (pair == nullptr) goto fail;
            }
            PyObject* lst = PyTuple_GET_ITEM(pair, diff < 0 ? 0 : 1);
            long long reps = diff < 0 ? -diff : diff;
            for (long long r = 0; r < reps; r++) {
                if (PyList_Append(lst, values) < 0) goto fail;
            }
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

PyObject* py_build_adds(PyObject*, PyObject* args) {
    PyObject *rows, *update_cls;
    if (!PyArg_ParseTuple(args, "OO", &rows, &update_cls)) return nullptr;
    PyObject* seq = PySequence_Fast(rows, "build_adds expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* kv = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *key, *values;
        if (PyTuple_Check(kv) && PyTuple_GET_SIZE(kv) == 2) {
            key = PyTuple_GET_ITEM(kv, 0);
            values = PyTuple_GET_ITEM(kv, 1);
        } else {
            PyErr_SetString(PyExc_TypeError, "rows must be (key, values) pairs");
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyObject* u = make_update(update_cls, key, values, 1);
        if (u == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, u);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_all_positive(PyObject*, PyObject* batch) {
    // True iff every update's diff > 0 (append-only batch check)
    PyObject* seq = PySequence_Fast(batch, "all_positive expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            Py_DECREF(seq);
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            return nullptr;
        }
        long long diff = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
        if (diff == -1 && PyErr_Occurred()) {
            Py_DECREF(seq);
            return nullptr;
        }
        if (diff <= 0) {
            Py_DECREF(seq);
            Py_RETURN_FALSE;
        }
    }
    Py_DECREF(seq);
    Py_RETURN_TRUE;
}

PyObject* py_all_dicts(PyObject*, PyObject* obj) {
    PyObject* seq = PySequence_Fast(obj, "all_dicts expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!PyDict_Check(PySequence_Fast_GET_ITEM(seq, i))) {
            Py_DECREF(seq);
            Py_RETURN_FALSE;
        }
    }
    Py_DECREF(seq);
    Py_RETURN_TRUE;
}

PyObject* py_rowwise_map(PyObject*, PyObject* args) {
    // rowwise_map(batch, fn, update_cls, error_obj, on_error) -> list
    // C loop of the expression_table hot path: vals = fn(key, values);
    // a raising row becomes (ERROR,) after on_error(exc).
    PyObject *batch, *fn, *update_cls, *error_obj, *on_error;
    if (!PyArg_ParseTuple(args, "OOOOO", &batch, &fn, &update_cls, &error_obj,
                          &on_error))
        return nullptr;
    PyObject* seq = PySequence_Fast(batch, "rowwise_map expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* key = PyTuple_GET_ITEM(u, 0);
            PyObject* values = PyTuple_GET_ITEM(u, 1);
            PyObject* diff = PyTuple_GET_ITEM(u, 2);
            PyObject* vals =
                PyObject_CallFunctionObjArgs(fn, key, values, nullptr);
            if (vals == nullptr) {
                // row-level containment (Exception only, like the Python
                // `except Exception`): report and emit an ERROR row
                if (!PyErr_ExceptionMatches(PyExc_Exception)) goto fail;
                PyObject *etype, *evalue, *etb;
                PyErr_Fetch(&etype, &evalue, &etb);
                PyErr_NormalizeException(&etype, &evalue, &etb);
                PyObject* r = PyObject_CallFunctionObjArgs(
                    on_error, evalue ? evalue : Py_None, nullptr);
                Py_XDECREF(etype);
                Py_XDECREF(evalue);
                Py_XDECREF(etb);
                if (r == nullptr) goto fail;
                Py_DECREF(r);
                vals = PyTuple_Pack(1, error_obj);
                if (vals == nullptr) goto fail;
            }
            PyObject* nu = make_update_obj(update_cls, key, vals, diff);
            Py_DECREF(vals);
            if (nu == nullptr) goto fail;
            PyList_SET_ITEM(out, i, nu);
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

// the groupby fast path only needs the (rare) rows whose cells contain
// the ERROR sentinel — scanning for them per row in Python costs more
// than the whole native aggregation; this is one identity-compare pass
PyObject* py_rows_with_error(PyObject*, PyObject* args) {
    PyObject *batch, *sentinel;
    if (!PyArg_ParseTuple(args, "OO", &batch, &sentinel)) return nullptr;
    PyObject* seq =
        PySequence_Fast(batch, "rows_with_error expects a sequence");
    if (seq == nullptr) return nullptr;
    PyObject* out = PyList_New(0);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* values = PyTuple_GET_ITEM(u, 1);
            if (!PyTuple_Check(values)) {
                PyErr_SetString(PyExc_TypeError, "values must be tuples");
                goto fail;
            }
            Py_ssize_t nv = PyTuple_GET_SIZE(values);
            for (Py_ssize_t j = 0; j < nv; j++) {
                if (PyTuple_GET_ITEM(values, j) == sentinel) {
                    if (PyList_Append(out, u) < 0) goto fail;
                    break;
                }
            }
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

PyObject* py_filter_batch(PyObject*, PyObject* args) {
    // filter_batch(batch, pred, error_obj) -> list re-emitting the PASSING
    // input update objects unchanged (no allocation per surviving row).
    // Drop semantics mirror FilterNode: raising rows, None, and ERROR all
    // drop; anything else keeps by truthiness.
    PyObject *batch, *pred, *error_obj;
    if (!PyArg_ParseTuple(args, "OOO", &batch, &pred, &error_obj))
        return nullptr;
    PyObject* seq = PySequence_Fast(batch, "filter_batch expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(0);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* r = PyObject_CallFunctionObjArgs(
                pred, PyTuple_GET_ITEM(u, 0), PyTuple_GET_ITEM(u, 1),
                nullptr);
            if (r == nullptr) {
                if (!PyErr_ExceptionMatches(PyExc_Exception)) goto fail;
                PyErr_Clear();
                continue;  // raising predicate: drop the row
            }
            if (r == Py_None || r == error_obj) {
                Py_DECREF(r);
                continue;
            }
            int truthy = PyObject_IsTrue(r);
            Py_DECREF(r);
            // a raising truthiness test propagates (python parity: only
            // the predicate CALL is containable, bool(keep) is not)
            if (truthy < 0) goto fail;
            if (truthy && PyList_Append(out, u) < 0) goto fail;
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

// --------------------------------------------------------------------------
// groupby partial aggregation
//
// groupby_partials(batch, group_idx, red_specs, error_obj, hashable_fn)
// reduces an update batch into per-group PARTIAL aggregates in one C pass
// — the role of the reference's reduce arrangement inner loop
// (src/engine/reduce.rs SemigroupReducerImpl).  Python merges one partial
// per (dirty group, reducer) into the persistent accumulators, so the
// per-row interpreter work (group_fn, arg_fn, reducer.update) disappears.
//
// red_specs: tuple of (code, idx_tuple); idx >= 0 -> values[idx],
// idx == -1 -> row key.  Codes: 0 = count (partial: int), 1 = sum-like
// (partial: (total|None, n_contributions)), 2 = multiset (partial:
// {hashable_args: (delta, args)}).

struct MsItem {
    long long delta;
    PyObject* args;  // owned
    PyObject* h;     // owned
};

struct GPart {
    PyObject* total = nullptr;  // owned (sum-like)
    long long cnt = 0;
    PyObject* msdict = nullptr;  // owned: h -> PyLong index (multiset)
    std::vector<MsItem> msitems;
};

struct GEntry {
    long long count = 0;
    std::vector<GPart> parts;
};

void free_gentries(std::vector<GEntry>& entries) {
    for (GEntry& e : entries) {
        for (GPart& p : e.parts) {
            Py_XDECREF(p.total);
            Py_XDECREF(p.msdict);
            for (MsItem& it : p.msitems) {
                Py_XDECREF(it.args);
                Py_XDECREF(it.h);
            }
        }
    }
    entries.clear();
}

PyObject* py_groupby_partials(PyObject*, PyObject* args) {
    PyObject *batch, *group_idx, *red_specs, *error_obj, *hashable_fn;
    if (!PyArg_ParseTuple(args, "OOOOO", &batch, &group_idx, &red_specs,
                          &error_obj, &hashable_fn))
        return nullptr;

    // unpack specs
    if (!PyTuple_Check(group_idx) || !PyTuple_Check(red_specs)) {
        PyErr_SetString(PyExc_TypeError, "group_idx/red_specs must be tuples");
        return nullptr;
    }
    Py_ssize_t ngroup = PyTuple_GET_SIZE(group_idx);
    std::vector<Py_ssize_t> gidx((size_t)ngroup);
    for (Py_ssize_t i = 0; i < ngroup; i++) {
        gidx[(size_t)i] = PyLong_AsSsize_t(PyTuple_GET_ITEM(group_idx, i));
        if (gidx[(size_t)i] == -1 && PyErr_Occurred()) return nullptr;
    }
    Py_ssize_t nred = PyTuple_GET_SIZE(red_specs);
    std::vector<int> rcodes((size_t)nred);
    std::vector<std::vector<Py_ssize_t>> ridx((size_t)nred);
    for (Py_ssize_t r = 0; r < nred; r++) {
        PyObject* spec = PyTuple_GET_ITEM(red_specs, r);
        if (!PyTuple_Check(spec) || PyTuple_GET_SIZE(spec) != 2) {
            PyErr_SetString(PyExc_TypeError, "red_specs items must be pairs");
            return nullptr;
        }
        long code = PyLong_AsLong(PyTuple_GET_ITEM(spec, 0));
        if (code == -1 && PyErr_Occurred()) return nullptr;
        rcodes[(size_t)r] = (int)code;
        PyObject* idxs = PyTuple_GET_ITEM(spec, 1);
        if (!PyTuple_Check(idxs)) {
            PyErr_SetString(PyExc_TypeError, "red spec idx must be a tuple");
            return nullptr;
        }
        for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(idxs); j++) {
            Py_ssize_t v = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, j));
            if (v == -1 && PyErr_Occurred()) return nullptr;
            ridx[(size_t)r].push_back(v);
        }
    }

    PyObject* seq = PySequence_Fast(batch, "batch must be a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

    PyObject* gmap = PyDict_New();  // gvals -> PyLong entry index
    std::vector<GEntry> entries;
    std::vector<PyObject*> gvals_by_entry;  // borrowed (gmap holds refs)
    if (gmap == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }

    bool fail = false;
    bool unsupported = false;
    for (Py_ssize_t i = 0; i < n && !fail; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            fail = true;
            break;
        }
        PyObject* key = PyTuple_GET_ITEM(u, 0);
        PyObject* values = PyTuple_GET_ITEM(u, 1);
        if (!PyTuple_Check(values)) {
            PyErr_SetString(g_unsupported, "values must be tuples");
            fail = true;
            break;
        }
        Py_ssize_t nvals = PyTuple_GET_SIZE(values);
        long long diff = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
        if (diff == -1 && PyErr_Occurred()) {
            fail = true;
            break;
        }
        // group key tuple
        PyObject* gv = PyTuple_New(ngroup);
        if (gv == nullptr) {
            fail = true;
            break;
        }
        for (Py_ssize_t j = 0; j < ngroup; j++) {
            Py_ssize_t ix = gidx[(size_t)j];
            PyObject* cell;
            if (ix < 0) {
                cell = key;
            } else if (ix < nvals) {
                cell = PyTuple_GET_ITEM(values, ix);
            } else {
                PyErr_SetString(g_unsupported, "column index out of range");
                Py_DECREF(gv);
                fail = true;
                break;
            }
            Py_INCREF(cell);
            PyTuple_SET_ITEM(gv, j, cell);
        }
        if (fail) break;
        PyObject* found = PyDict_GetItemWithError(gmap, gv);
        if (found == nullptr && PyErr_Occurred()) {
            // unhashable group value: whole batch falls back to Python
            Py_DECREF(gv);
            if (PyErr_ExceptionMatches(PyExc_TypeError)) {
                PyErr_Clear();
                unsupported = true;
            }
            fail = true;
            break;
        }
        size_t ei;
        if (found != nullptr) {
            ei = (size_t)PyLong_AsSsize_t(found);
            Py_DECREF(gv);
        } else {
            ei = entries.size();
            PyObject* idx = PyLong_FromSsize_t((Py_ssize_t)ei);
            if (idx == nullptr || PyDict_SetItem(gmap, gv, idx) < 0) {
                Py_XDECREF(idx);
                Py_DECREF(gv);
                fail = true;
                break;
            }
            Py_DECREF(idx);
            gvals_by_entry.push_back(gv);
            Py_DECREF(gv);  // gmap key holds the reference
            entries.emplace_back();
            entries.back().parts.resize((size_t)nred);
        }
        GEntry& ge = entries[ei];
        ge.count += diff;
        for (Py_ssize_t r = 0; r < nred && !fail; r++) {
            GPart& part = ge.parts[(size_t)r];
            int code = rcodes[(size_t)r];
            if (code == 0) continue;  // count: uses ge.count
            if (code == 1) {
                Py_ssize_t ix = ridx[(size_t)r][0];
                PyObject* v = ix < 0 ? key
                              : ix < nvals ? PyTuple_GET_ITEM(values, ix)
                                           : nullptr;
                if (v == nullptr) {
                    PyErr_SetString(g_unsupported, "column index out of range");
                    fail = true;
                    break;
                }
                if (v == Py_None || v == error_obj) continue;
                PyObject* term;
                if (diff == 1 && (PyLong_Check(v) || PyFloat_Check(v))) {
                    // immutable scalars may alias; everything else (ndarray!)
                    // must copy via v * diff like the Python reducer does
                    term = v;
                    Py_INCREF(term);
                } else {
                    PyObject* d = PyLong_FromLongLong(diff);
                    if (d == nullptr) {
                        fail = true;
                        break;
                    }
                    term = PyNumber_Multiply(v, d);
                    Py_DECREF(d);
                    if (term == nullptr) {
                        fail = true;
                        break;
                    }
                }
                if (part.total == nullptr) {
                    part.total = term;
                } else {
                    PyObject* s = PyNumber_Add(part.total, term);
                    Py_DECREF(term);
                    if (s == nullptr) {
                        fail = true;
                        break;
                    }
                    Py_DECREF(part.total);
                    part.total = s;
                }
                part.cnt += diff;
            } else {  // code == 2: multiset of args
                const std::vector<Py_ssize_t>& idxs = ridx[(size_t)r];
                PyObject* margs = PyTuple_New((Py_ssize_t)idxs.size());
                if (margs == nullptr) {
                    fail = true;
                    break;
                }
                for (size_t j = 0; j < idxs.size(); j++) {
                    Py_ssize_t ix = idxs[j];
                    PyObject* cell;
                    if (ix < 0) {
                        cell = key;
                    } else if (ix < nvals) {
                        cell = PyTuple_GET_ITEM(values, ix);
                    } else {
                        PyErr_SetString(g_unsupported,
                                        "column index out of range");
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    Py_INCREF(cell);
                    PyTuple_SET_ITEM(margs, (Py_ssize_t)j, cell);
                }
                if (fail) break;
                if (part.msdict == nullptr) {
                    part.msdict = PyDict_New();
                    if (part.msdict == nullptr) {
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                }
                PyObject* h = margs;  // try the raw tuple as hash key first
                Py_INCREF(h);
                PyObject* mf = PyDict_GetItemWithError(part.msdict, h);
                if (mf == nullptr && PyErr_Occurred()) {
                    if (!PyErr_ExceptionMatches(PyExc_TypeError)) {
                        Py_DECREF(h);
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    PyErr_Clear();
                    Py_DECREF(h);
                    h = PyObject_CallFunctionObjArgs(hashable_fn, margs,
                                                     nullptr);
                    if (h == nullptr) {
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    mf = PyDict_GetItemWithError(part.msdict, h);
                    if (mf == nullptr && PyErr_Occurred()) {
                        Py_DECREF(h);
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                }
                if (mf != nullptr) {
                    size_t mi = (size_t)PyLong_AsSsize_t(mf);
                    part.msitems[mi].delta += diff;
                    Py_DECREF(h);
                    Py_DECREF(margs);
                } else {
                    PyObject* mi =
                        PyLong_FromSsize_t((Py_ssize_t)part.msitems.size());
                    if (mi == nullptr ||
                        PyDict_SetItem(part.msdict, h, mi) < 0) {
                        Py_XDECREF(mi);
                        Py_DECREF(h);
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    Py_DECREF(mi);
                    part.msitems.push_back({diff, margs, h});  // owns both
                }
            }
        }
    }
    Py_DECREF(seq);
    if (fail) {
        free_gentries(entries);
        Py_DECREF(gmap);
        if (unsupported && !PyErr_Occurred())
            PyErr_SetString(g_unsupported, "unhashable group values");
        return nullptr;
    }

    // build the result: {gvals: (count, (partial, ...))}
    PyObject* out = PyDict_New();
    if (out == nullptr) {
        free_gentries(entries);
        Py_DECREF(gmap);
        return nullptr;
    }
    for (size_t ei = 0; ei < entries.size() && !fail; ei++) {
        GEntry& ge = entries[ei];
        PyObject* parts = PyTuple_New(nred);
        if (parts == nullptr) {
            fail = true;
            break;
        }
        for (Py_ssize_t r = 0; r < nred && !fail; r++) {
            GPart& p = ge.parts[(size_t)r];
            PyObject* payload = nullptr;
            if (rcodes[(size_t)r] == 0) {
                payload = PyLong_FromLongLong(ge.count);
            } else if (rcodes[(size_t)r] == 1) {
                PyObject* tot = p.total ? p.total : Py_None;
                payload = Py_BuildValue("(OL)", tot, p.cnt);
            } else {
                payload = PyDict_New();
                if (payload != nullptr) {
                    for (MsItem& it : p.msitems) {
                        PyObject* dv =
                            Py_BuildValue("(LO)", it.delta, it.args);
                        if (dv == nullptr ||
                            PyDict_SetItem(payload, it.h, dv) < 0) {
                            Py_XDECREF(dv);
                            Py_DECREF(payload);
                            payload = nullptr;
                            break;
                        }
                        Py_DECREF(dv);
                    }
                }
            }
            if (payload == nullptr) {
                Py_DECREF(parts);
                fail = true;
                break;
            }
            PyTuple_SET_ITEM(parts, r, payload);
        }
        if (fail) break;
        PyObject* val = Py_BuildValue("(LO)", ge.count, parts);
        Py_DECREF(parts);
        if (val == nullptr ||
            PyDict_SetItem(out, gvals_by_entry[ei], val) < 0) {
            Py_XDECREF(val);
            fail = true;
            break;
        }
        Py_DECREF(val);
    }
    free_gentries(entries);
    Py_DECREF(gmap);
    if (fail) {
        Py_DECREF(out);
        return nullptr;
    }
    return out;
}

// --------------------------------------------------------------------------
// bulk schema coercion

enum CoerceCode {
    CO_ANY = 0,
    CO_INT = 1,
    CO_FLOAT = 2,
    CO_STR = 3,
    CO_BOOL = 4,
};

// mirrors io/_connector.py _column_coercer — must stay behaviour-identical
PyObject* coerce_one(PyObject* v, int code) {
    switch (code) {
        case CO_FLOAT: {
            if (PyFloat_Check(v)) break;
            if (PyLong_Check(v)) return PyNumber_Float(v);
            if (PyUnicode_Check(v)) {
                PyObject* f = PyFloat_FromString(v);
                if (f != nullptr) return f;
                PyErr_Clear();
            }
            break;
        }
        case CO_INT: {
            if (PyLong_Check(v)) break;  // bools stay bools (python parity)
            if (PyFloat_Check(v)) {
                double d = PyFloat_AS_DOUBLE(v);
                // float.is_integer() parity; PyLong_FromDouble is exact
                // for integer-valued doubles of any magnitude
                if (std::isfinite(d) && d == std::floor(d))
                    return PyLong_FromDouble(d);
                break;
            }
            if (PyUnicode_Check(v)) {
                PyObject* iv = PyLong_FromUnicodeObject(v, 10);
                if (iv != nullptr) return iv;
                PyErr_Clear();
            }
            break;
        }
        case CO_STR: {
            if (PyUnicode_Check(v)) break;
            return PyObject_Str(v);
        }
        case CO_BOOL: {
            if (PyUnicode_Check(v)) {
                PyObject* lower = PyObject_CallMethod(v, "lower", nullptr);
                if (lower == nullptr) return nullptr;
                bool truthy =
                    PyUnicode_CompareWithASCIIString(lower, "true") == 0 ||
                    PyUnicode_CompareWithASCIIString(lower, "1") == 0 ||
                    PyUnicode_CompareWithASCIIString(lower, "t") == 0 ||
                    PyUnicode_CompareWithASCIIString(lower, "yes") == 0;
                Py_DECREF(lower);
                return PyBool_FromLong(truthy ? 1 : 0);
            }
            break;
        }
        default:
            break;
    }
    Py_INCREF(v);
    return v;
}

PyObject* py_coerce_rows(PyObject*, PyObject* args) {
    // rows: list of dicts; plan: list of (name, default, code)
    PyObject *rows, *plan;
    if (!PyArg_ParseTuple(args, "OO", &rows, &plan)) return nullptr;
    PyObject* plan_seq = PySequence_Fast(plan, "plan must be a sequence");
    if (plan_seq == nullptr) return nullptr;
    Py_ssize_t ncols = PySequence_Fast_GET_SIZE(plan_seq);
    std::vector<PyObject*> names((size_t)ncols);
    std::vector<PyObject*> defaults((size_t)ncols);
    std::vector<int> codes((size_t)ncols);
    for (Py_ssize_t c = 0; c < ncols; c++) {
        PyObject* item = PySequence_Fast_GET_ITEM(plan_seq, c);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3) {
            PyErr_SetString(PyExc_TypeError, "plan items must be 3-tuples");
            Py_DECREF(plan_seq);
            return nullptr;
        }
        names[(size_t)c] = PyTuple_GET_ITEM(item, 0);
        defaults[(size_t)c] = PyTuple_GET_ITEM(item, 1);
        long code = PyLong_AsLong(PyTuple_GET_ITEM(item, 2));
        if (code == -1 && PyErr_Occurred()) {
            Py_DECREF(plan_seq);
            return nullptr;
        }
        codes[(size_t)c] = (int)code;
    }
    PyObject* rows_seq = PySequence_Fast(rows, "rows must be a sequence");
    if (rows_seq == nullptr) {
        Py_DECREF(plan_seq);
        return nullptr;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(rows_seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(plan_seq);
        Py_DECREF(rows_seq);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PySequence_Fast_GET_ITEM(rows_seq, i);
        if (!PyDict_Check(row)) {
            PyErr_SetString(g_unsupported, "rows must be dicts");
            goto fail;
        }
        {
            PyObject* tup = PyTuple_New(ncols);
            if (tup == nullptr) goto fail;
            for (Py_ssize_t c = 0; c < ncols; c++) {
                PyObject* v = PyDict_GetItemWithError(row, names[(size_t)c]);
                if (v == nullptr && PyErr_Occurred()) {
                    Py_DECREF(tup);
                    goto fail;
                }
                if (v == nullptr || v == Py_None) v = defaults[(size_t)c];
                PyObject* cv;
                if (v == nullptr || v == Py_None) {
                    cv = Py_None;
                    Py_INCREF(cv);
                } else {
                    cv = coerce_one(v, codes[(size_t)c]);
                    if (cv == nullptr) {
                        Py_DECREF(tup);
                        goto fail;
                    }
                }
                PyTuple_SET_ITEM(tup, c, cv);
            }
            PyList_SET_ITEM(out, i, tup);
        }
    }
    Py_DECREF(plan_seq);
    Py_DECREF(rows_seq);
    return out;
fail:
    Py_DECREF(plan_seq);
    Py_DECREF(rows_seq);
    Py_DECREF(out);
    return nullptr;
}

// --------------------------------------------------------------------------
// worker routing

// route_split(batch, idx_tuple, n_workers) -> [outbox_0, ..., outbox_W-1]
// One C pass splitting an update batch by the 128-bit hash of positional
// route cells (idx >= 0 -> values[idx], -1 -> row key) — byte-identical
// to cluster.stable_shard / keys.ref_scalar, including the repr fallback
// for unhashable cell types.
// Route cells are drawn from a small domain (group keys, join keys)
// while batches run to millions of rows, so the per-row BLAKE2b is
// mostly recomputation: memoize the digest by the serialized cell
// bytes.  The hash is a pure function of those bytes, so entries can
// never go stale, and caching the digest (not the destination) keeps
// the memo worker-count independent.  GIL-protected — route_split never
// releases it.  Past the cap we stop inserting: a high-cardinality
// route keeps its first entries hot and pays the hash for the rest.
struct RouteDigest {
    uint8_t b[16];
};
constexpr size_t kRouteMemoCap = 1 << 13;
std::string g_route_buf;
std::unordered_map<std::string, RouteDigest> g_route_memo;

void route_digest(const std::string& cells, uint8_t out[16]) {
    auto it = g_route_memo.find(cells);
    if (it != g_route_memo.end()) {
        std::memcpy(out, it->second.b, 16);
        return;
    }
    Hasher h;
    h.bytes(cells.data(), cells.size());
    pwnative::blake2b_final(&h.S, out);
    if (g_route_memo.size() < kRouteMemoCap) {
        RouteDigest d;
        std::memcpy(d.b, out, 16);
        g_route_memo.emplace(cells, d);
    }
}

PyObject* py_route_split(PyObject*, PyObject* args) {
    PyObject *batch, *idxs;
    long W;
    if (!PyArg_ParseTuple(args, "OOl", &batch, &idxs, &W)) return nullptr;
    if (W <= 0 || !PyTuple_Check(idxs)) {
        PyErr_SetString(PyExc_ValueError, "bad route_split arguments");
        return nullptr;
    }
    Py_ssize_t nidx = PyTuple_GET_SIZE(idxs);
    std::vector<Py_ssize_t> pos((size_t)nidx);
    for (Py_ssize_t i = 0; i < nidx; i++) {
        pos[(size_t)i] = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, i));
        if (pos[(size_t)i] == -1 && PyErr_Occurred()) return nullptr;
    }
    PyObject* seq = PySequence_Fast(batch, "route_split expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(W);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    for (long w = 0; w < W; w++) {
        PyObject* lst = PyList_New(0);
        if (lst == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, w, lst);
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* key = PyTuple_GET_ITEM(u, 0);
            PyObject* values = PyTuple_GET_ITEM(u, 1);
            if (!PyTuple_Check(values)) {
                PyErr_SetString(PyExc_TypeError, "values must be tuples");
                goto fail;
            }
            Py_ssize_t nvals = PyTuple_GET_SIZE(values);
            if (nidx == 0) {
                // empty idx tuple = key-value routing (route_by_key):
                // dest = int(key) % W, NOT a re-hash — matches the Python
                // route_by_key closure exactly
                PyObject* wobj = PyLong_FromLong(W);
                if (wobj == nullptr) goto fail;
                PyObject* m = PyNumber_Remainder(key, wobj);
                Py_DECREF(wobj);
                if (m == nullptr) goto fail;
                long dest = PyLong_AsLong(m);
                Py_DECREF(m);
                if (dest == -1 && PyErr_Occurred()) goto fail;
                if (PyList_Append(PyList_GET_ITEM(out, dest), u) < 0)
                    goto fail;
                continue;
            }
            g_route_buf.clear();
            ByteSink sink{g_route_buf};
            bool ok = true;
            for (Py_ssize_t j = 0; j < nidx && ok; j++) {
                Py_ssize_t ix = pos[(size_t)j];
                PyObject* cell;
                if (ix < 0) {
                    cell = key;
                } else if (ix < nvals) {
                    cell = PyTuple_GET_ITEM(values, ix);
                } else {
                    PyErr_SetString(PyExc_IndexError,
                                    "route column out of range");
                    goto fail;
                }
                ok = feed(sink, cell);
            }
            if (!ok) {
                // cell type outside the native feed set (datetime,
                // ndarray, ...): the PYTHON hasher supports more tags, so
                // punt the WHOLE batch to the per-row stable_shard path —
                // a divergent native fallback hash would route rows of
                // the same group to different workers
                if (!PyErr_Occurred())
                    PyErr_SetString(g_unsupported, "unroutable cell type");
                goto fail;
            }
            uint8_t dg[16];
            route_digest(g_route_buf, dg);
            uint64_t lo, hi;
            std::memcpy(&lo, dg, 8);
            std::memcpy(&hi, dg + 8, 8);
            unsigned __int128 v =
                ((unsigned __int128)hi << 64) | (unsigned __int128)lo;
            long dest = (long)(unsigned long long)(v % (unsigned long long)W);
            if (PyList_Append(PyList_GET_ITEM(out, dest), u) < 0) goto fail;
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

// --------------------------------------------------------------------------
// WordPiece tokenization (ASCII fast path)
//
// The BERT tokenize pipeline (models/wordpiece.py) is the host-side
// bottleneck of the embedding path.  This implements the exact pipeline
// for ASCII text — clean/control/whitespace handling, lowercasing,
// punctuation splitting, greedy longest-match-first WordPiece — in one C
// pass per text; non-ASCII texts return None so the caller falls back to
// the Python implementation per text (identical output either way: on
// ASCII input NFD accent-stripping and CJK spacing are no-ops).

struct WpVocab {
    std::unordered_map<std::string, int> map;
    int unk;
    int max_chars;
    size_t max_token_len = 0;  // longest vocab entry, bounds the scan
};

void wp_free(PyObject* cap) {
    delete static_cast<WpVocab*>(PyCapsule_GetPointer(cap, "pw.wordpiece"));
}

bool wp_is_punct(unsigned char c) {
    return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
           (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

PyObject* py_wp_build(PyObject*, PyObject* args) {
    PyObject* vocab;
    int unk, max_chars;
    if (!PyArg_ParseTuple(args, "Oii", &vocab, &unk, &max_chars))
        return nullptr;
    if (!PyDict_Check(vocab)) {
        PyErr_SetString(PyExc_TypeError, "vocab must be a dict");
        return nullptr;
    }
    auto* wv = new WpVocab{{}, unk, max_chars};
    wv->map.reserve((size_t)PyDict_Size(vocab) * 2);
    Py_ssize_t pos = 0;
    PyObject *k, *v;
    while (PyDict_Next(vocab, &pos, &k, &v)) {
        Py_ssize_t n;
        const char* s = PyUnicode_AsUTF8AndSize(k, &n);
        if (s == nullptr) {
            delete wv;
            return nullptr;
        }
        long id = PyLong_AsLong(v);
        if (id == -1 && PyErr_Occurred()) {
            delete wv;
            return nullptr;
        }
        wv->map.emplace(std::string(s, (size_t)n), (int)id);
        if ((size_t)n > wv->max_token_len) wv->max_token_len = (size_t)n;
    }
    return PyCapsule_New(wv, "pw.wordpiece", wp_free);
}

// greedy longest-match-first over one word; appends ids or a single unk
void wp_word(const WpVocab& wv, const std::string& word,
             std::vector<int>& out) {
    if ((int)word.size() > wv.max_chars) {
        out.push_back(wv.unk);
        return;
    }
    size_t start = 0;
    size_t base = out.size();
    std::string piece;
    while (start < word.size()) {
        size_t end = word.size();
        // longest vocab entry bounds the window ("##" adds 2 bytes)
        size_t limit = start + wv.max_token_len;
        if (end > limit) end = limit;
        int cur = -1;
        size_t cur_end = 0;
        while (end > start) {
            piece.clear();
            if (start > 0) piece = "##";
            piece.append(word, start, end - start);
            auto it = wv.map.find(piece);
            if (it != wv.map.end()) {
                cur = it->second;
                cur_end = end;
                break;
            }
            end--;
        }
        if (cur < 0) {
            out.resize(base);
            out.push_back(wv.unk);
            return;
        }
        out.push_back(cur);
        start = cur_end;
    }
}

PyObject* py_wp_encode(PyObject*, PyObject* args) {
    PyObject *cap, *texts;
    int lower;
    if (!PyArg_ParseTuple(args, "OOp", &cap, &texts, &lower)) return nullptr;
    auto* wv =
        static_cast<WpVocab*>(PyCapsule_GetPointer(cap, "pw.wordpiece"));
    if (wv == nullptr) return nullptr;
    PyObject* seq = PySequence_Fast(texts, "texts must be a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    std::vector<int> ids;
    std::string word;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* text = PySequence_Fast_GET_ITEM(seq, i);
        Py_ssize_t len;
        const char* s =
            PyUnicode_Check(text) ? PyUnicode_AsUTF8AndSize(text, &len)
                                  : nullptr;
        if (s == nullptr) {
            PyErr_Clear();
            Py_INCREF(Py_None);  // non-string: python path decides
            PyList_SET_ITEM(out, i, Py_None);
            continue;
        }
        bool ascii = true;
        for (Py_ssize_t j = 0; j < len; j++) {
            if ((unsigned char)s[j] >= 0x80) {
                ascii = false;
                break;
            }
        }
        if (!ascii) {
            Py_INCREF(Py_None);  // python fallback handles unicode rules
            PyList_SET_ITEM(out, i, Py_None);
            continue;
        }
        ids.clear();
        word.clear();
        for (Py_ssize_t j = 0; j <= len; j++) {
            unsigned char c = j < len ? (unsigned char)s[j] : ' ';
            if (c == 0 || (c < 0x20 && c != '\t' && c != '\n' && c != '\r') ||
                c == 0x7f)
                continue;  // _clean drops controls
            if (c == ' ' || c == '\t' || c == '\n' || c == '\r') {
                if (!word.empty()) {
                    wp_word(*wv, word, ids);
                    word.clear();
                }
                continue;
            }
            if (lower && c >= 'A' && c <= 'Z') c = (unsigned char)(c + 32);
            if (wp_is_punct(c)) {
                if (!word.empty()) {
                    wp_word(*wv, word, ids);
                    word.clear();
                }
                word.push_back((char)c);
                wp_word(*wv, word, ids);
                word.clear();
                continue;
            }
            word.push_back((char)c);
        }
        PyObject* row = PyList_New((Py_ssize_t)ids.size());
        if (row == nullptr) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return nullptr;
        }
        for (size_t j = 0; j < ids.size(); j++) {
            PyObject* v = PyLong_FromLong(ids[j]);
            if (v == nullptr) {
                Py_DECREF(row);
                Py_DECREF(seq);
                Py_DECREF(out);
                return nullptr;
            }
            PyList_SET_ITEM(row, (Py_ssize_t)j, v);
        }
        PyList_SET_ITEM(out, i, row);
    }
    Py_DECREF(seq);
    return out;
}

PyObject* py_set_pointer_type(PyObject*, PyObject* cls) {
    Py_XDECREF(g_pointer_type);
    Py_INCREF(cls);
    g_pointer_type = cls;
    Py_RETURN_NONE;
}

// ===========================================================================
// Expression stack VM
//
// The reference evaluates typed expression trees entirely in Rust
// (src/engine/expression.rs:26-491): no Python enters the per-row hot
// loop of select/filter.  The TPU build's equivalent is this bytecode VM:
// internals/expr_vm.py lowers each (already build-time-typed) expression
// AST to a flat postfix program with jump-based lazy constructs
// (if_else/coalesce/fill_error evaluate only the taken branch, exactly
// like the Python closures), and the whole select/filter batch runs in
// one C call.  Subtrees the lowerer cannot express (UDF apply, namespace
// methods) compile to their ordinary Python closure and appear as one
// CALL_PY instruction — mixed rows still avoid the per-node closure
// dispatch for everything else.
//
// Error semantics are byte-compatible with the Python closures in
// internals/expression.py:
//   - ERROR operands propagate (checked by identity before every op)
//   - TypeError with a None operand: `== -> a is b`, `!= -> a is not b`,
//     any other op -> None
//   - TypeError otherwise, ZeroDivisionError, ValueError, OverflowError
//     -> ERROR
//   - any other exception aborts the ROW (containment + error-log happen
//     in the batch loop, mirroring rowwise_map: the row becomes (ERROR,))

PyObject* g_json_type = nullptr;  // pathway_tpu Json class (VM convert/get)

PyObject* py_set_json_type(PyObject*, PyObject* cls) {
    Py_XDECREF(g_json_type);
    Py_INCREF(cls);
    g_json_type = cls;
    Py_RETURN_NONE;
}

// ---------------------------------------------------------------------------
// Native namespace methods (.str / .dt / .num).
//
// The reference evaluates DateTime/Duration/String expression enums
// entirely in Rust (src/engine/expression.rs:26-340); the first VM
// shipped every namespace method as a per-row CALL_PY closure.  These
// implementations move the high-traffic methods into the VM: Python
// semantics are pinned by the closure lambdas in
// internals/expressions.py and the differential tests in
// tests/test_expr_vm.py — on any input outside a method's native domain
// the op either falls through to calling the underlying Python method
// on the single value, or produces ERROR exactly where the closure
// would.

enum VmMethod : int64_t {
    M_STR_LOWER = 0, M_STR_UPPER, M_STR_SWAPCASE, M_STR_TITLE,
    M_STR_REVERSED, M_STR_LEN,
    M_STR_STRIP, M_STR_LSTRIP, M_STR_RSTRIP,   // arity 1 or 2
    M_STR_COUNT, M_STR_FIND, M_STR_RFIND,      // find: arity 3 or 4
    M_STR_STARTSWITH, M_STR_ENDSWITH,
    M_STR_REPLACE, M_STR_SLICE,
    M_STR_PARSE_INT, M_STR_PARSE_INT_OPT,
    M_STR_PARSE_FLOAT, M_STR_PARSE_FLOAT_OPT,
    M_STR_PARSE_BOOL, M_STR_PARSE_BOOL_OPT,
    M_STR_PARSE_DATETIME,                      // (s, fmt)
    M_DT_NANOSECOND, M_DT_MICROSECOND, M_DT_MILLISECOND,
    M_DT_SECOND, M_DT_MINUTE, M_DT_HOUR,
    M_DT_DAY, M_DT_MONTH, M_DT_YEAR,
    M_DT_DAY_OF_WEEK, M_DT_DAY_OF_YEAR,
    M_DT_TIMESTAMP,                            // (d, scale)
    M_DT_STRFTIME,                             // (d, fmt)
    M_DT_ROUND, M_DT_FLOOR,                    // (d, duration)
    M_DUR_NANOSECONDS, M_DUR_MICROSECONDS, M_DUR_MILLISECONDS,
    M_DUR_SECONDS, M_DUR_MINUTES, M_DUR_HOURS, M_DUR_DAYS, M_DUR_WEEKS,
    M_NUM_ABS, M_NUM_FILL_NA,
    M_NUM_ROUND,                               // (x, decimals)
    M_STR_SPLIT,                               // (s, maxsplit) | (s, sep, maxsplit)
    M_DT_FROM_TIMESTAMP,                       // (x, scale) -> naive UTC
    M_DT_UTC_FROM_TIMESTAMP,                   // (x, scale) -> aware UTC
    M_DT_TO_UTC,                               // (d, tz_table) naive local -> aware UTC
    M_DT_TO_NAIVE_TZ,                          // (d, tz_table) aware -> naive local
    M_METHOD_COUNT,
};

// Hinnant's civil-date algorithms (public domain): proleptic Gregorian
// days since 1970-01-01.
inline int64_t days_from_civil(int64_t y, int64_t m, int64_t d) {
    y -= m <= 2;
    const int64_t era = (y >= 0 ? y : y - 399) / 400;
    const int64_t yoe = y - era * 400;
    const int64_t doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
    const int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    return era * 146097 + doe - 719468;
}

inline void civil_from_days(int64_t z, int64_t* y, int64_t* m, int64_t* d) {
    z += 719468;
    const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
    const int64_t doe = z - era * 146097;
    const int64_t yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
    const int64_t yy = yoe + era * 400;
    const int64_t doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
    const int64_t mp = (5 * doy + 2) / 153;
    *d = doy - (153 * mp + 2) / 5 + 1;
    *m = mp + (mp < 10 ? 3 : -9);
    *y = yy + (*m <= 2);
}

inline bool is_leap(int64_t y) {
    return (y % 4 == 0 && y % 100 != 0) || y % 400 == 0;
}

const int kDaysBeforeMonth[13] = {0, 0,   31,  59,  90,  120, 151,
                                  181, 212, 243, 273, 304, 334};

// timedelta.total_seconds() double formula, replicated bit-for-bit
inline double td_total_seconds(int64_t days, int64_t secs, int64_t us) {
    return ((double)(days * 86400 + secs) * 1e6 + (double)us) / 1e6;
}

PyObject* g_dt_module_cache = nullptr;  // datetime module (strptime fallback)
PyObject* g_utc_singleton = nullptr;    // datetime.timezone.utc

bool ensure_datetime_cache() {
    if (g_dt_module_cache != nullptr) return true;
    PyObject* mod = PyImport_ImportModule("datetime");
    if (mod == nullptr) return false;
    PyObject* tz = PyObject_GetAttrString(mod, "timezone");
    if (tz == nullptr) {
        Py_DECREF(mod);
        return false;
    }
    g_utc_singleton = PyObject_GetAttrString(tz, "utc");
    Py_DECREF(tz);
    if (g_utc_singleton == nullptr) {
        Py_DECREF(mod);
        return false;
    }
    g_dt_module_cache = mod;
    return true;
}

// epoch-microseconds -> datetime with the given tzinfo (Py_None = naive)
// and fold; years outside datetime's [1, 9999] raise ValueError (the
// Python closures raise the same way -> row ERROR either path).
PyObject* dt_from_epoch_us(int64_t us_total, PyObject* tzinfo, int fold) {
    int64_t days = us_total >= 0
                       ? us_total / 86400000000LL
                       : -((-us_total + 86399999999LL) / 86400000000LL);
    int64_t rem = us_total - days * 86400000000LL;  // [0, 86400e6)
    int64_t y, mo, dd;
    civil_from_days(days, &y, &mo, &dd);
    if (y < 1 || y > 9999) {
        PyErr_SetString(PyExc_ValueError, "year out of range");
        return nullptr;
    }
    int64_t s = rem / 1000000, us = rem % 1000000;
    return PyDateTimeAPI->DateTime_FromDateAndTimeAndFold(
        (int)y, (int)mo, (int)dd, (int)(s / 3600), (int)((s / 60) % 60),
        (int)(s % 60), (int)us, tzinfo, fold, PyDateTimeAPI->DateTimeType);
}

// ---- packed tz transition tables (internals/tztable.py) --------------
//
// A full table is the 9-tuple (name, trans_utc, lkeys0, lkeys1, offs,
// off_before, after_off|None, zoneinfo_instance, fallback): the pure
// Python ``zoneinfo`` transition arrays packed as native int64 byte
// strings.  ``offs[i]`` is the utc offset (whole seconds) that applies
// AFTER transition i; ``lkeys{0,1}`` are the local-side bisection keys
// for fold 0/1 (``ZoneInfo._trans_local``), ``trans_utc`` the utc-side
// keys.  A 2-tuple (name, fallback) marks a zone that could not be
// packed: every value takes the Python fallback (the exact expression
// closure).  Timestamps outside the packed range with a rule footer
// (``_TZStr`` — post-2037 for most DST zones) also fall back per value,
// so native results are bit-identical to ``zoneinfo``'s answers.

struct TzTable {
    const int64_t* trans_utc;
    const int64_t* lk0;
    const int64_t* lk1;
    const int64_t* offs;
    int64_t n;
    int64_t off_before;
    bool has_after;
    int64_t after_off;
};

bool tz_table_view(PyObject* tbl, TzTable* out) {
    Py_ssize_t nb = -1;
    const char* arrs[4] = {nullptr, nullptr, nullptr, nullptr};
    for (int i = 0; i < 4; i++) {
        PyObject* b = PyTuple_GET_ITEM(tbl, i + 1);
        if (!PyBytes_Check(b) || (nb >= 0 && PyBytes_GET_SIZE(b) != nb) ||
            PyBytes_GET_SIZE(b) % 8 != 0) {
            PyErr_SetString(PyExc_TypeError, "bad tz table arrays");
            return false;
        }
        nb = PyBytes_GET_SIZE(b);
        arrs[i] = PyBytes_AS_STRING(b);
    }
    out->trans_utc = reinterpret_cast<const int64_t*>(arrs[0]);
    out->lk0 = reinterpret_cast<const int64_t*>(arrs[1]);
    out->lk1 = reinterpret_cast<const int64_t*>(arrs[2]);
    out->offs = reinterpret_cast<const int64_t*>(arrs[3]);
    out->n = nb / 8;
    PyObject* ob = PyTuple_GET_ITEM(tbl, 5);
    PyObject* oa = PyTuple_GET_ITEM(tbl, 6);
    if (!PyLong_Check(ob) || (oa != Py_None && !PyLong_Check(oa))) {
        PyErr_SetString(PyExc_TypeError, "bad tz table offsets");
        return false;
    }
    out->off_before = PyLong_AsLongLong(ob);
    out->has_after = oa != Py_None;
    out->after_off = out->has_after ? PyLong_AsLongLong(oa) : 0;
    return !PyErr_Occurred();
}

// ---- strptime (Python datetime.strptime semantics for the common
// directives; anything else falls back to the Python function) ----------

struct StrpResult {
    int64_t year = 1900, month = 1, day = 1;
    int64_t hour = 0, minute = 0, second = 0, us = 0;
    int64_t yday = -1;      // %j
    int hour12 = -1;        // %I
    int ampm = -1;          // %p: 0 AM, 1 PM
    bool has_tz = false;
    int64_t tz_off_s = 0;   // %z seconds east
    int64_t tz_off_us = 0;
};

// parse up to `maxd` ASCII digits (at least 1); returns count or 0
inline int parse_digits(const char* p, const char* end, int maxd,
                        int64_t* out) {
    int n = 0;
    int64_t v = 0;
    while (n < maxd && p + n < end && p[n] >= '0' && p[n] <= '9') {
        v = v * 10 + (p[n] - '0');
        n++;
    }
    if (n == 0) return 0;
    *out = v;
    return n;
}

// Returns: 1 parsed, 0 format has an unsupported directive (caller falls
// back to Python strptime), -1 value does not match (ValueError).
int c_strptime(const char* s, Py_ssize_t slen, const char* f,
               Py_ssize_t flen, StrpResult* R) {
    const char* p = s;
    const char* pe = s + slen;
    const char* q = f;
    const char* qe = f + flen;
    while (q < qe) {
        char c = *q++;
        if (c != '%') {
            if ((unsigned char)c >= 0x80)
                return 0;  // non-ASCII literal: Unicode-aware IGNORECASE
                           // matching is Python's business
            if (c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
                c == '\f' || c == '\v') {
                // Python compiles literal whitespace in the format to
                // \s+ (Lib/_strptime.py TimeRE.pattern)
                if (p >= pe || !isspace((unsigned char)*p)) return -1;
                while (p < pe && isspace((unsigned char)*p)) p++;
                while (q < qe && isspace((unsigned char)*q)) q++;
                continue;
            }
            // _strptime compiles the pattern with re.IGNORECASE, so
            // literal letters match either case
            if (p >= pe ||
                tolower((unsigned char)*p) != tolower((unsigned char)c))
                return -1;
            p++;
            continue;
        }
        if (q >= qe) return 0;  // trailing % — let Python raise its error
        char d = *q++;
        int n;
        switch (d) {
            case 'Y':
                n = parse_digits(p, pe, 4, &R->year);
                if (n == 0) return -1;
                p += n;
                break;
            case 'y':
                n = parse_digits(p, pe, 2, &R->year);
                if (n == 0) return -1;
                p += n;
                // Python 2-digit year rule (POSIX): 69-99 -> 1900s
                R->year += (R->year <= 68) ? 2000 : 1900;
                break;
            case 'm':
                n = parse_digits(p, pe, 2, &R->month);
                if (n == 0 || R->month < 1 || R->month > 12) return -1;
                p += n;
                break;
            case 'd':
                n = parse_digits(p, pe, 2, &R->day);
                if (n == 0 || R->day < 1 || R->day > 31) return -1;
                p += n;
                break;
            case 'H':
                n = parse_digits(p, pe, 2, &R->hour);
                if (n == 0 || R->hour > 23) return -1;
                p += n;
                break;
            case 'I': {
                int64_t h;
                n = parse_digits(p, pe, 2, &h);
                if (n == 0 || h < 1 || h > 12) return -1;
                R->hour12 = (int)h;
                p += n;
                break;
            }
            case 'M':
                n = parse_digits(p, pe, 2, &R->minute);
                if (n == 0 || R->minute > 59) return -1;
                p += n;
                break;
            case 'S':
                n = parse_digits(p, pe, 2, &R->second);
                if (n == 0 || R->second > 61) return -1;
                // leap seconds (60/61): let the Python implementation
                // decide what to do with them
                if (R->second > 59) return 0;
                p += n;
                break;
            case 'f': {
                int64_t v;
                n = parse_digits(p, pe, 6, &v);
                if (n == 0) return -1;
                for (int i = n; i < 6; i++) v *= 10;
                R->us = v;
                p += n;
                break;
            }
            case 'j':
                n = parse_digits(p, pe, 3, &R->yday);
                if (n == 0 || R->yday < 1 || R->yday > 366) return -1;
                p += n;
                break;
            case 'p': {
                if (p + 2 > pe) return -1;
                char a = (char)tolower((unsigned char)p[0]);
                char b = (char)tolower((unsigned char)p[1]);
                if (b != 'm' || (a != 'a' && a != 'p')) return -1;
                R->ampm = (a == 'p');
                p += 2;
                break;
            }
            case 'z': {
                // _strptime's %z branch is (?-i:Z): uppercase only
                if (p < pe && *p == 'Z') {
                    R->has_tz = true;
                    R->tz_off_s = 0;
                    p++;
                    break;
                }
                if (p >= pe || (*p != '+' && *p != '-')) return -1;
                int sign = (*p == '-') ? -1 : 1;
                p++;
                int64_t hh, mm, ss = 0;
                n = parse_digits(p, pe, 2, &hh);
                if (n != 2) return -1;
                p += n;
                if (p < pe && *p == ':') p++;
                n = parse_digits(p, pe, 2, &mm);
                if (n != 2 || mm > 59) return -1;
                p += n;
                int64_t us = 0;
                if (p < pe && (*p == ':' || (*p >= '0' && *p <= '9'))) {
                    const char* save = p;
                    if (*p == ':') p++;
                    n = parse_digits(p, pe, 2, &ss);
                    if (n == 2 && ss <= 59) {
                        p += n;
                        if (p < pe && *p == '.') {
                            p++;
                            int64_t fv;
                            n = parse_digits(p, pe, 6, &fv);
                            if (n == 0) return -1;
                            for (int i = n; i < 6; i++) fv *= 10;
                            us = fv;
                            p += n;
                        }
                    } else {
                        ss = 0;
                        p = save;  // digits belong to a later directive
                    }
                }
                R->has_tz = true;
                R->tz_off_s = sign * (hh * 3600 + mm * 60 + ss);
                R->tz_off_us = sign * us;
                break;
            }
            case '%':
                if (p >= pe || *p != '%') return -1;
                p++;
                break;
            default:
                return 0;  // %a/%A/%b/%B/%Z/%U/%W/%c/%x/%X/...: Python path
        }
    }
    if (p != pe) return -1;  // unconverted data remains
    return 1;
}

// build a datetime.timezone for an offset (Python strptime returns
// timezone.utc for Z/+00:00, else timezone(timedelta(...)))
PyObject* tz_from_offset(int64_t off_s, int64_t off_us) {
    if (!ensure_datetime_cache()) return nullptr;
    if (off_s == 0 && off_us == 0) {
        Py_INCREF(g_utc_singleton);
        return g_utc_singleton;
    }
    PyObject* delta = PyDelta_FromDSU(0, (int)off_s, (int)off_us);
    if (delta == nullptr) return nullptr;
    PyObject* tz_type = PyObject_GetAttrString(g_dt_module_cache, "timezone");
    if (tz_type == nullptr) {
        Py_DECREF(delta);
        return nullptr;
    }
    PyObject* tz = PyObject_CallFunctionObjArgs(tz_type, delta, nullptr);
    Py_DECREF(tz_type);
    Py_DECREF(delta);
    return tz;
}

// ---- strftime (numeric directives; names fall back to Python) ---------

// Returns 1 on success (out filled), 0 when the format needs the Python
// strftime (locale names), -1 on error (exception set).
int c_strftime(PyObject* d, const char* f, Py_ssize_t flen,
               std::string* out) {
    if (!PyDateTime_Check(d)) return 0;
    int64_t year = PyDateTime_GET_YEAR(d);
    int mon = PyDateTime_GET_MONTH(d);
    int day = PyDateTime_GET_DAY(d);
    int hour = PyDateTime_DATE_GET_HOUR(d);
    int minute = PyDateTime_DATE_GET_MINUTE(d);
    int sec = PyDateTime_DATE_GET_SECOND(d);
    int us = PyDateTime_DATE_GET_MICROSECOND(d);
    char buf[32];
    const char* q = f;
    const char* qe = f + flen;
    while (q < qe) {
        char c = *q++;
        if (c != '%') {
            out->push_back(c);
            continue;
        }
        if (q >= qe) {
            out->push_back('%');
            break;
        }
        char dd = *q++;
        switch (dd) {
            case 'Y':
                // glibc does not zero-pad %Y (Python delegates to it)
                snprintf(buf, sizeof buf, "%lld", (long long)year);
                out->append(buf);
                break;
            case 'y':
                snprintf(buf, sizeof buf, "%02lld",
                         (long long)(((year % 100) + 100) % 100));
                out->append(buf);
                break;
            case 'm':
                snprintf(buf, sizeof buf, "%02d", mon);
                out->append(buf);
                break;
            case 'd':
                snprintf(buf, sizeof buf, "%02d", day);
                out->append(buf);
                break;
            case 'H':
                snprintf(buf, sizeof buf, "%02d", hour);
                out->append(buf);
                break;
            case 'I': {
                int h12 = hour % 12;
                if (h12 == 0) h12 = 12;
                snprintf(buf, sizeof buf, "%02d", h12);
                out->append(buf);
                break;
            }
            case 'p':
                out->append(hour < 12 ? "AM" : "PM");
                break;
            case 'M':
                snprintf(buf, sizeof buf, "%02d", minute);
                out->append(buf);
                break;
            case 'S':
                snprintf(buf, sizeof buf, "%02d", sec);
                out->append(buf);
                break;
            case 'f':
                snprintf(buf, sizeof buf, "%06d", us);
                out->append(buf);
                break;
            case 'j': {
                int yday = kDaysBeforeMonth[mon] + day +
                           ((mon > 2 && is_leap(year)) ? 1 : 0);
                snprintf(buf, sizeof buf, "%03d", yday);
                out->append(buf);
                break;
            }
            case '%':
                out->push_back('%');
                break;
            default:
                return 0;  // %a %A %b %B %Z %z %c %x %X %G %u %V ...
        }
    }
    return 1;
}

// slice-style index clamp for str.find/slice
inline Py_ssize_t clamp_index(PyObject* idx, Py_ssize_t len, Py_ssize_t dflt,
                              bool* bad) {
    if (idx == Py_None) return dflt;
    if (!PyLong_Check(idx)) {
        *bad = true;
        return 0;
    }
    Py_ssize_t v = PyLong_AsSsize_t(idx);
    if (v == -1 && PyErr_Occurred()) {
        PyErr_Clear();
        // magnitude beyond Py_ssize_t clamps like a slice bound; compare
        // against zero for the sign (a >1e308 int also overflows the
        // double conversion, so the sign must not go through it)
        static PyObject* zero = nullptr;
        if (zero == nullptr) zero = PyLong_FromLong(0);
        int neg =
            zero != nullptr ? PyObject_RichCompareBool(idx, zero, Py_LT) : 0;
        if (neg < 0) {
            PyErr_Clear();
            neg = 0;
        }
        return neg == 1 ? 0 : len;
    }
    if (v < 0) {
        v += len;
        if (v < 0) v = 0;
    } else if (v > len) {
        v = len;
    }
    return v;
}

// whitespace / chars-set strip over any PyUnicode kind
PyObject* str_strip_impl(PyObject* s, PyObject* chars, int left, int right) {
    if (PyUnicode_READY(s) < 0) return nullptr;
    Py_ssize_t len = PyUnicode_GET_LENGTH(s);
    int kind = PyUnicode_KIND(s);
    const void* data = PyUnicode_DATA(s);
    Py_ssize_t lo = 0, hi = len;
    if (chars == nullptr) {
        while (left && lo < hi &&
               Py_UNICODE_ISSPACE(PyUnicode_READ(kind, data, lo)))
            lo++;
        while (right && hi > lo &&
               Py_UNICODE_ISSPACE(PyUnicode_READ(kind, data, hi - 1)))
            hi--;
    } else {
        if (PyUnicode_READY(chars) < 0) return nullptr;
        Py_ssize_t clen = PyUnicode_GET_LENGTH(chars);
        int ckind = PyUnicode_KIND(chars);
        const void* cdata = PyUnicode_DATA(chars);
        auto in_set = [&](Py_UCS4 ch) {
            for (Py_ssize_t i = 0; i < clen; i++)
                if (PyUnicode_READ(ckind, cdata, i) == ch) return true;
            return false;
        };
        while (left && lo < hi && in_set(PyUnicode_READ(kind, data, lo))) lo++;
        while (right && hi > lo && in_set(PyUnicode_READ(kind, data, hi - 1)))
            hi--;
    }
    if (lo == 0 && hi == len && PyUnicode_CheckExact(s)) {
        Py_INCREF(s);
        return s;
    }
    return PyUnicode_Substring(s, lo, hi);
}

// ASCII-only case transforms; returns nullptr with no error set when the
// string needs the full Unicode algorithm (caller calls the method)
PyObject* str_ascii_case(PyObject* s, int64_t mid) {
    if (PyUnicode_READY(s) < 0) return nullptr;
    if (!PyUnicode_IS_ASCII(s)) return nullptr;
    Py_ssize_t len = PyUnicode_GET_LENGTH(s);
    const char* src = (const char*)PyUnicode_1BYTE_DATA(s);
    PyObject* out = PyUnicode_New(len, 127);
    if (out == nullptr) return nullptr;
    char* dst = (char*)PyUnicode_1BYTE_DATA(out);
    bool prev_cased = false;
    for (Py_ssize_t i = 0; i < len; i++) {
        char c = src[i];
        switch (mid) {
            case M_STR_LOWER:
                dst[i] = (char)tolower((unsigned char)c);
                break;
            case M_STR_UPPER:
                dst[i] = (char)toupper((unsigned char)c);
                break;
            case M_STR_SWAPCASE:
                dst[i] = islower((unsigned char)c)
                             ? (char)toupper((unsigned char)c)
                             : (islower((unsigned char)c) == 0 &&
                                        isupper((unsigned char)c)
                                    ? (char)tolower((unsigned char)c)
                                    : c);
                break;
            case M_STR_TITLE: {
                bool cased = isalpha((unsigned char)c) != 0;
                if (cased && !prev_cased)
                    dst[i] = (char)toupper((unsigned char)c);
                else if (cased)
                    dst[i] = (char)tolower((unsigned char)c);
                else
                    dst[i] = c;
                prev_cased = cased;
                break;
            }
            default:
                dst[i] = c;
        }
    }
    return out;
}

// method call fallback for inputs outside a native fast path: the
// single-value Python method, same result the closure lambda produces
PyObject* vm_method_pyfallback(const char* name, PyObject* self) {
    return PyObject_CallMethod(self, name, nullptr);
}

// Evaluates method `mid` over `args[0..nargs)`.  Returns a NEW reference;
// nullptr with an exception set = treat as the closure's `except` path
// (caller converts to ERROR).
PyObject* vm_method_eval(int64_t mid, PyObject** args, int64_t nargs) {
    PyObject* a0 = args[0];
    switch (mid) {
        // ---- str -----------------------------------------------------
        case M_STR_LOWER:
        case M_STR_UPPER:
        case M_STR_SWAPCASE:
        case M_STR_TITLE: {
            if (!PyUnicode_Check(a0)) {
                PyErr_SetString(PyExc_TypeError, "expected str");
                return nullptr;
            }
            PyObject* r = str_ascii_case(a0, mid);
            if (r != nullptr || PyErr_Occurred()) return r;
            const char* nm = mid == M_STR_LOWER     ? "lower"
                             : mid == M_STR_UPPER   ? "upper"
                             : mid == M_STR_SWAPCASE ? "swapcase"
                                                     : "title";
            return vm_method_pyfallback(nm, a0);
        }
        case M_STR_REVERSED: {
            if (!PyUnicode_Check(a0) || PyUnicode_READY(a0) < 0) {
                if (!PyErr_Occurred())
                    PyErr_SetString(PyExc_TypeError, "expected str");
                return nullptr;
            }
            Py_ssize_t len = PyUnicode_GET_LENGTH(a0);
            int kind = PyUnicode_KIND(a0);
            const void* data = PyUnicode_DATA(a0);
            Py_UCS4 maxch = PyUnicode_MAX_CHAR_VALUE(a0);
            PyObject* out = PyUnicode_New(len, maxch);
            if (out == nullptr) return nullptr;
            for (Py_ssize_t i = 0; i < len; i++)
                PyUnicode_WRITE(PyUnicode_KIND(out), PyUnicode_DATA(out), i,
                                PyUnicode_READ(kind, data, len - 1 - i));
            return out;
        }
        case M_STR_LEN: {
            Py_ssize_t n = PyObject_Length(a0);
            if (n < 0) return nullptr;
            return PyLong_FromSsize_t(n);
        }
        case M_STR_STRIP:
        case M_STR_LSTRIP:
        case M_STR_RSTRIP: {
            if (!PyUnicode_Check(a0)) {
                PyErr_SetString(PyExc_TypeError, "expected str");
                return nullptr;
            }
            PyObject* chars = nargs >= 2 ? args[1] : nullptr;
            if (chars != nullptr && !PyUnicode_Check(chars)) {
                PyErr_SetString(PyExc_TypeError, "strip chars must be str");
                return nullptr;
            }
            return str_strip_impl(a0, chars, mid != M_STR_RSTRIP,
                                  mid != M_STR_LSTRIP);
        }
        case M_STR_COUNT: {
            if (!PyUnicode_Check(a0) || !PyUnicode_Check(args[1])) {
                PyErr_SetString(PyExc_TypeError, "expected str");
                return nullptr;
            }
            Py_ssize_t n =
                PyUnicode_Count(a0, args[1], 0, PY_SSIZE_T_MAX);
            if (n < 0) return nullptr;
            return PyLong_FromSsize_t(n);
        }
        case M_STR_FIND:
        case M_STR_RFIND: {
            if (!PyUnicode_Check(a0) || !PyUnicode_Check(args[1])) {
                PyErr_SetString(PyExc_TypeError, "expected str");
                return nullptr;
            }
            Py_ssize_t len = PyUnicode_GET_LENGTH(a0);
            bool bad = false;
            Py_ssize_t start = clamp_index(args[1 + 1], len, 0, &bad);
            Py_ssize_t end =
                nargs >= 4 ? clamp_index(args[3], len, len, &bad) : len;
            if (bad) {
                PyErr_SetString(PyExc_TypeError, "indices must be ints");
                return nullptr;
            }
            Py_ssize_t r = PyUnicode_Find(a0, args[1], start, end,
                                          mid == M_STR_FIND ? 1 : -1);
            if (r == -2) return nullptr;
            return PyLong_FromSsize_t(r);
        }
        case M_STR_STARTSWITH:
        case M_STR_ENDSWITH: {
            if (!PyUnicode_Check(a0) || !PyUnicode_Check(args[1])) {
                // tuple prefixes etc.: defer to the Python method
                return PyObject_CallMethod(
                    a0, mid == M_STR_STARTSWITH ? "startswith" : "endswith",
                    "O", args[1]);
            }
            Py_ssize_t r = PyUnicode_Tailmatch(
                a0, args[1], 0, PY_SSIZE_T_MAX,
                mid == M_STR_STARTSWITH ? -1 : 1);
            if (r < 0) return nullptr;
            return PyBool_FromLong(r != 0);
        }
        case M_STR_REPLACE: {
            if (!PyUnicode_Check(a0) || !PyUnicode_Check(args[1]) ||
                !PyUnicode_Check(args[2]) || !PyLong_Check(args[3])) {
                PyErr_SetString(PyExc_TypeError, "bad replace arguments");
                return nullptr;
            }
            Py_ssize_t cnt = PyLong_AsSsize_t(args[3]);
            if (cnt == -1 && PyErr_Occurred()) return nullptr;
            return PyUnicode_Replace(a0, args[1], args[2], cnt);
        }
        case M_STR_SLICE: {
            if (!PyUnicode_Check(a0)) {
                PyErr_SetString(PyExc_TypeError, "expected str");
                return nullptr;
            }
            Py_ssize_t len = PyUnicode_GET_LENGTH(a0);
            bool bad = false;
            Py_ssize_t lo = clamp_index(args[1], len, 0, &bad);
            Py_ssize_t hi = clamp_index(args[2], len, len, &bad);
            if (bad) {
                PyErr_SetString(PyExc_TypeError,
                                "slice indices must be integers");
                return nullptr;
            }
            if (hi < lo) hi = lo;
            return PyUnicode_Substring(a0, lo, hi);
        }
        case M_STR_PARSE_INT:
        case M_STR_PARSE_INT_OPT: {
            // int(s): the closure also accepts non-str (int(3.5) == 3)
            PyObject* r = PyUnicode_Check(a0)
                              ? PyLong_FromUnicodeObject(a0, 10)
                              : PyNumber_Long(a0);
            if (r == nullptr && mid == M_STR_PARSE_INT_OPT &&
                PyErr_ExceptionMatches(PyExc_ValueError)) {
                PyErr_Clear();
                Py_RETURN_NONE;
            }
            return r;
        }
        case M_STR_PARSE_FLOAT:
        case M_STR_PARSE_FLOAT_OPT: {
            PyObject* r = PyUnicode_Check(a0) ? PyFloat_FromString(a0)
                                              : PyNumber_Float(a0);
            if (r == nullptr && mid == M_STR_PARSE_FLOAT_OPT &&
                PyErr_ExceptionMatches(PyExc_ValueError)) {
                PyErr_Clear();
                Py_RETURN_NONE;
            }
            return r;
        }
        case M_STR_PARSE_BOOL:
        case M_STR_PARSE_BOOL_OPT: {
            // (s, true_values, false_values) — tuples of lowercase strs
            PyObject* low = PyObject_CallMethod(a0, "lower", nullptr);
            if (low == nullptr) return nullptr;
            int hit = PySequence_Contains(args[1], low);
            if (hit < 0) {
                Py_DECREF(low);
                return nullptr;
            }
            if (hit) {
                Py_DECREF(low);
                Py_RETURN_TRUE;
            }
            hit = PySequence_Contains(args[2], low);
            Py_DECREF(low);
            if (hit < 0) return nullptr;
            if (hit) Py_RETURN_FALSE;
            if (mid == M_STR_PARSE_BOOL_OPT) Py_RETURN_NONE;
            PyErr_Format(PyExc_ValueError, "Cannot parse %R as bool", a0);
            return nullptr;
        }
        case M_STR_PARSE_DATETIME: {
            Py_ssize_t slen, flen;
            const char* s = PyUnicode_AsUTF8AndSize(a0, &slen);
            if (s == nullptr) return nullptr;
            const char* f = PyUnicode_AsUTF8AndSize(args[1], &flen);
            if (f == nullptr) return nullptr;
            StrpResult R;
            int rc = c_strptime(s, slen, f, flen, &R);
            if (rc <= 0) {
                // unsupported directive (rc==0) OR native mismatch
                // (rc<0): both defer to the real datetime.strptime.  The
                // mismatch deferral is what guarantees parity — Python's
                // regex backtracks where the native parser is greedy
                // (e.g. "%H%M" over "29" parses as H=2, M=9), and \d
                // matches non-ASCII Unicode digits; rows the native
                // parser cannot handle get Python's verdict, whatever
                // it is
                if (!ensure_datetime_cache()) return nullptr;
                PyObject* dt_type =
                    PyObject_GetAttrString(g_dt_module_cache, "datetime");
                if (dt_type == nullptr) return nullptr;
                PyObject* r = PyObject_CallMethod(dt_type, "strptime", "OO",
                                                  a0, args[1]);
                Py_DECREF(dt_type);
                return r;
            }
            if (R.hour12 >= 0) {
                int h = R.hour12 % 12;
                if (R.ampm == 1) h += 12;
                R.hour = h;
            }
            if (R.yday > 0) {
                int64_t doy = R.yday;
                int64_t maxd = is_leap(R.year) ? 366 : 365;
                if (doy > maxd) {
                    PyErr_SetString(PyExc_ValueError,
                                    "day of year out of range");
                    return nullptr;
                }
                int64_t m = 1;
                while (m < 12) {
                    int64_t dim = kDaysBeforeMonth[m + 1] +
                                  ((m + 1 > 2 && is_leap(R.year)) ? 1 : 0);
                    if (doy <= dim) break;
                    m++;
                }
                R.month = m;
                R.day = doy - kDaysBeforeMonth[m] -
                        ((m > 2 && is_leap(R.year)) ? 1 : 0);
            }
            PyObject* tz = nullptr;
            if (R.has_tz) {
                tz = tz_from_offset(R.tz_off_s, R.tz_off_us);
                if (tz == nullptr) return nullptr;
            }
            PyObject* r = PyDateTimeAPI->DateTime_FromDateAndTime(
                (int)R.year, (int)R.month, (int)R.day, (int)R.hour,
                (int)R.minute, (int)R.second, (int)R.us,
                tz == nullptr ? Py_None : tz, PyDateTimeAPI->DateTimeType);
            Py_XDECREF(tz);
            return r;
        }
        // ---- datetime fields ----------------------------------------
        case M_DT_NANOSECOND:
        case M_DT_MICROSECOND:
        case M_DT_MILLISECOND:
        case M_DT_SECOND:
        case M_DT_MINUTE:
        case M_DT_HOUR:
        case M_DT_DAY:
        case M_DT_MONTH:
        case M_DT_YEAR:
        case M_DT_DAY_OF_WEEK:
        case M_DT_DAY_OF_YEAR: {
            if (!PyDateTime_Check(a0)) {
                PyErr_SetString(PyExc_TypeError, "expected datetime");
                return nullptr;
            }
            long long v;
            switch (mid) {
                case M_DT_NANOSECOND:
                    v = (long long)PyDateTime_DATE_GET_MICROSECOND(a0) * 1000;
                    break;
                case M_DT_MICROSECOND:
                    v = PyDateTime_DATE_GET_MICROSECOND(a0);
                    break;
                case M_DT_MILLISECOND:
                    v = PyDateTime_DATE_GET_MICROSECOND(a0) / 1000;
                    break;
                case M_DT_SECOND:
                    v = PyDateTime_DATE_GET_SECOND(a0);
                    break;
                case M_DT_MINUTE:
                    v = PyDateTime_DATE_GET_MINUTE(a0);
                    break;
                case M_DT_HOUR:
                    v = PyDateTime_DATE_GET_HOUR(a0);
                    break;
                case M_DT_DAY:
                    v = PyDateTime_GET_DAY(a0);
                    break;
                case M_DT_MONTH:
                    v = PyDateTime_GET_MONTH(a0);
                    break;
                case M_DT_YEAR:
                    v = PyDateTime_GET_YEAR(a0);
                    break;
                case M_DT_DAY_OF_WEEK: {
                    int64_t z = days_from_civil(PyDateTime_GET_YEAR(a0),
                                                PyDateTime_GET_MONTH(a0),
                                                PyDateTime_GET_DAY(a0));
                    v = (long long)(((z % 7) + 10) % 7);  // 1970-01-01 = Thu
                    break;
                }
                default: {  // day of year
                    int m = PyDateTime_GET_MONTH(a0);
                    v = kDaysBeforeMonth[m] + PyDateTime_GET_DAY(a0) +
                        ((m > 2 && is_leap(PyDateTime_GET_YEAR(a0))) ? 1 : 0);
                }
            }
            return PyLong_FromLongLong(v);
        }
        case M_DT_TIMESTAMP: {
            // (d, scale_float): naive treated as UTC (expressions.py ts())
            if (!PyDateTime_Check(a0) || !PyFloat_Check(args[1])) {
                PyErr_SetString(PyExc_TypeError, "expected datetime");
                return nullptr;
            }
            PyObject* tzinfo = PyDateTime_DATE_GET_TZINFO(a0);
            int64_t off_us = 0;
            if (tzinfo != Py_None) {
                // non-trivial tz: ask Python for the offset
                PyObject* off =
                    PyObject_CallMethod(a0, "utcoffset", nullptr);
                if (off == nullptr) return nullptr;
                if (off != Py_None) {
                    if (!PyDelta_Check(off)) {
                        Py_DECREF(off);
                        PyErr_SetString(PyExc_TypeError, "bad utcoffset");
                        return nullptr;
                    }
                    off_us = ((int64_t)PyDateTime_DELTA_GET_DAYS(off) * 86400 +
                              PyDateTime_DELTA_GET_SECONDS(off)) *
                                 1000000 +
                             PyDateTime_DELTA_GET_MICROSECONDS(off);
                }
                Py_DECREF(off);
            }
            int64_t days = days_from_civil(PyDateTime_GET_YEAR(a0),
                                           PyDateTime_GET_MONTH(a0),
                                           PyDateTime_GET_DAY(a0));
            int64_t secs = (int64_t)PyDateTime_DATE_GET_HOUR(a0) * 3600 +
                           PyDateTime_DATE_GET_MINUTE(a0) * 60 +
                           PyDateTime_DATE_GET_SECOND(a0);
            int64_t us_total = (days * 86400 + secs) * 1000000 +
                               PyDateTime_DATE_GET_MICROSECOND(a0) - off_us;
            // (d - epoch).total_seconds() bit-exact: split into the
            // timedelta fields Python would hold, then its double formula
            int64_t td_days = us_total >= 0
                                  ? us_total / 86400000000LL
                                  : -((-us_total + 86399999999LL) /
                                      86400000000LL);
            int64_t rem_us = us_total - td_days * 86400000000LL;
            double ts = td_total_seconds(td_days, rem_us / 1000000,
                                         rem_us % 1000000);
            return PyFloat_FromDouble(ts * PyFloat_AS_DOUBLE(args[1]));
        }
        case M_DT_STRFTIME: {
            if (!PyUnicode_Check(args[1])) {
                PyErr_SetString(PyExc_TypeError, "format must be str");
                return nullptr;
            }
            Py_ssize_t flen;
            const char* f = PyUnicode_AsUTF8AndSize(args[1], &flen);
            if (f == nullptr) return nullptr;
            std::string out;
            out.reserve((size_t)flen + 16);
            int rc = c_strftime(a0, f, flen, &out);
            if (rc < 0) return nullptr;
            if (rc == 0)
                return PyObject_CallMethod(a0, "strftime", "O", args[1]);
            return PyUnicode_FromStringAndSize(out.data(),
                                               (Py_ssize_t)out.size());
        }
        case M_DT_ROUND:
        case M_DT_FLOOR: {
            // replicate _round_dt/_floor_dt double math exactly
            if (!PyDateTime_Check(a0) || !PyDelta_Check(args[1])) {
                PyErr_SetString(PyExc_TypeError, "expected datetime+duration");
                return nullptr;
            }
            PyObject* tzinfo = PyDateTime_DATE_GET_TZINFO(a0);
            double delta;
            PyObject* epoch = nullptr;  // aware path only
            if (tzinfo == Py_None) {
                int64_t days = days_from_civil(PyDateTime_GET_YEAR(a0),
                                               PyDateTime_GET_MONTH(a0),
                                               PyDateTime_GET_DAY(a0));
                int64_t secs =
                    (int64_t)PyDateTime_DATE_GET_HOUR(a0) * 3600 +
                    PyDateTime_DATE_GET_MINUTE(a0) * 60 +
                    PyDateTime_DATE_GET_SECOND(a0);
                delta = td_total_seconds(
                    days, secs, PyDateTime_DATE_GET_MICROSECOND(a0));
            } else {
                // aware: (d - epoch(tz)).total_seconds() must go through
                // the real subtraction — a zoneinfo tz can have different
                // utcoffsets at d and at the epoch
                epoch = PyDateTimeAPI->DateTime_FromDateAndTime(
                    1970, 1, 1, 0, 0, 0, 0, tzinfo,
                    PyDateTimeAPI->DateTimeType);
                if (epoch == nullptr) return nullptr;
                PyObject* diff = PyNumber_Subtract(a0, epoch);
                if (diff == nullptr || !PyDelta_Check(diff)) {
                    Py_XDECREF(diff);
                    Py_DECREF(epoch);
                    if (!PyErr_Occurred())
                        PyErr_SetString(PyExc_TypeError, "bad subtraction");
                    return nullptr;
                }
                delta = td_total_seconds(
                    PyDateTime_DELTA_GET_DAYS(diff),
                    PyDateTime_DELTA_GET_SECONDS(diff),
                    PyDateTime_DELTA_GET_MICROSECONDS(diff));
                Py_DECREF(diff);
            }
            double step =
                td_total_seconds(PyDateTime_DELTA_GET_DAYS(args[1]),
                                 PyDateTime_DELTA_GET_SECONDS(args[1]),
                                 PyDateTime_DELTA_GET_MICROSECONDS(args[1]));
            if (step == 0.0) {
                Py_XDECREF(epoch);
                PyErr_SetString(PyExc_ZeroDivisionError, "zero duration");
                return nullptr;
            }
            double q = delta / step;
            double steps = mid == M_DT_FLOOR ? std::floor(q)
                                             : std::nearbyint(q);
            double result_s = steps * step;
            // timedelta(seconds=result_s) microsecond rounding: integer
            // part exact, fractional part round-half-even (datetime.c
            // accum()/delta_new)
            double ipart;
            double fpart = std::modf(result_s, &ipart);
            if (!(ipart >= -9.0e15 && ipart <= 9.0e15)) {
                Py_XDECREF(epoch);
                PyErr_SetString(PyExc_OverflowError, "duration too large");
                return nullptr;
            }
            int64_t total_us = (int64_t)ipart * 1000000 +
                               (int64_t)std::nearbyint(fpart * 1e6);
            if (epoch != nullptr) {
                // aware: epoch + timedelta via the datetime type itself
                int64_t rdays = total_us >= 0
                                    ? total_us / 86400000000LL
                                    : -((-total_us + 86399999999LL) /
                                        86400000000LL);
                int64_t rem = total_us - rdays * 86400000000LL;
                PyObject* td = PyDelta_FromDSU(
                    (int)rdays, (int)(rem / 1000000), (int)(rem % 1000000));
                if (td == nullptr) {
                    Py_DECREF(epoch);
                    return nullptr;
                }
                PyObject* r = PyNumber_Add(epoch, td);
                Py_DECREF(td);
                Py_DECREF(epoch);
                return r;
            }
            int64_t rdays = total_us >= 0
                                ? total_us / 86400000000LL
                                : -((-total_us + 86399999999LL) /
                                    86400000000LL);
            int64_t rem = total_us - rdays * 86400000000LL;
            int64_t y, mo, dd;
            civil_from_days(rdays, &y, &mo, &dd);
            if (y < 1 || y > 9999) {
                PyErr_SetString(PyExc_OverflowError, "date out of range");
                return nullptr;
            }
            return PyDateTimeAPI->DateTime_FromDateAndTime(
                (int)y, (int)mo, (int)dd, (int)(rem / 3600000000LL),
                (int)(rem / 60000000 % 60), (int)(rem / 1000000 % 60),
                (int)(rem % 1000000), Py_None, PyDateTimeAPI->DateTimeType);
        }
        // ---- duration accessors -------------------------------------
        case M_DUR_NANOSECONDS:
        case M_DUR_MICROSECONDS:
        case M_DUR_MILLISECONDS:
        case M_DUR_SECONDS:
        case M_DUR_MINUTES:
        case M_DUR_HOURS:
        case M_DUR_DAYS:
        case M_DUR_WEEKS: {
            if (!PyDelta_Check(a0)) {
                PyErr_SetString(PyExc_TypeError, "expected duration");
                return nullptr;
            }
            int64_t days = PyDateTime_DELTA_GET_DAYS(a0);
            if (mid == M_DUR_DAYS) return PyLong_FromLongLong(days);
            if (mid == M_DUR_WEEKS) {
                int64_t w = days >= 0 ? days / 7 : -((-days + 6) / 7);
                return PyLong_FromLongLong(w);
            }
            double ts = td_total_seconds(days, PyDateTime_DELTA_GET_SECONDS(a0),
                                         PyDateTime_DELTA_GET_MICROSECONDS(a0));
            double scaled;
            switch (mid) {
                case M_DUR_NANOSECONDS: scaled = ts * 1e9; break;
                case M_DUR_MICROSECONDS: scaled = ts * 1e6; break;
                case M_DUR_MILLISECONDS: scaled = ts * 1e3; break;
                case M_DUR_SECONDS: scaled = ts; break;
                case M_DUR_MINUTES: scaled = std::floor(ts / 60.0); break;
                default: scaled = std::floor(ts / 3600.0); break;
            }
            // int(double): PyLong_FromDouble truncates toward zero and
            // handles magnitudes beyond int64 as a big int, exactly like
            // the closure's int(...)
            return PyLong_FromDouble(scaled);
        }
        // ---- num ----------------------------------------------------
        case M_NUM_ABS:
            return PyNumber_Absolute(a0);
        case M_NUM_FILL_NA: {
            PyObject* r = a0;
            if (a0 == Py_None ||
                (PyFloat_Check(a0) && std::isnan(PyFloat_AS_DOUBLE(a0))))
                r = args[1];
            Py_INCREF(r);
            return r;
        }
        case M_NUM_ROUND: {
            // round(x, d): d is always passed by the closure, so the
            // result keeps x's type (round(2.5, 0) == 2.0, not 2)
            PyObject* d = args[1];
            if (PyLong_CheckExact(d)) {
                long nd = PyLong_AsLong(d);
                if (nd == -1 && PyErr_Occurred()) {
                    PyErr_Clear();  // huge ndigits: defer to __round__
                } else if (PyLong_CheckExact(a0) && nd >= 0) {
                    Py_INCREF(a0);  // ndigits >= 0 keeps an exact int
                    return a0;
                } else if (PyFloat_CheckExact(a0) && nd == 0) {
                    // ties-to-even to an integral double — exactly
                    // float.__round__(0), incl. nan/inf passthrough
                    return PyFloat_FromDouble(
                        std::nearbyint(PyFloat_AS_DOUBLE(a0)));
                }
            }
            // decimal ndigits / bools / odd types: the type's __round__
            // (what builtin round(x, d) dispatches to); missing __round__
            // raises, which the caller maps to ERROR like the closure
            return PyObject_CallMethod(a0, "__round__", "O", d);
        }
        case M_STR_SPLIT: {
            // (s, maxsplit) = whitespace split; (s, sep, maxsplit) = by
            // separator — exactly str.split(None|sep, maxsplit), wrapped
            // to a tuple like the closure
            if (!PyUnicode_Check(a0)) {
                PyErr_SetString(PyExc_TypeError, "expected str");
                return nullptr;
            }
            PyObject* sep = nargs >= 3 ? args[1] : nullptr;
            if (sep != nullptr && !PyUnicode_Check(sep)) {
                PyErr_SetString(PyExc_TypeError, "sep must be str");
                return nullptr;
            }
            PyObject* ms = args[nargs - 1];
            if (!PyLong_Check(ms)) {
                PyErr_SetString(PyExc_TypeError, "maxsplit must be an int");
                return nullptr;
            }
            Py_ssize_t maxsplit = PyLong_AsSsize_t(ms);
            if (maxsplit == -1 && PyErr_Occurred()) return nullptr;
            PyObject* lst = PyUnicode_Split(a0, sep, maxsplit);
            if (lst == nullptr) return nullptr;  // empty sep: ValueError
            PyObject* tup = PyList_AsTuple(lst);
            Py_DECREF(lst);
            return tup;
        }
        case M_DT_FROM_TIMESTAMP:
        case M_DT_UTC_FROM_TIMESTAMP: {
            // (x, scale): datetime.fromtimestamp(x / scale, tz=utc)
            // [.replace(tzinfo=None) for the naive variant].  Replicates
            // CPython's conversion: modf split, fractional microseconds
            // rounded half-even (_PyTime_ROUND_HALF_EVEN), carry
            // normalized into [0, 1e6).
            double xv;
            if (PyFloat_Check(a0)) {
                xv = PyFloat_AS_DOUBLE(a0);
            } else if (PyLong_Check(a0)) {
                xv = PyLong_AsDouble(a0);
                if (xv == -1.0 && PyErr_Occurred()) return nullptr;
            } else {
                PyErr_SetString(PyExc_TypeError, "expected int|float");
                return nullptr;
            }
            if (!PyFloat_Check(args[1])) {
                PyErr_SetString(PyExc_TypeError, "scale must be float");
                return nullptr;
            }
            double t = xv / PyFloat_AS_DOUBLE(args[1]);
            // datetime covers years [1, 9999]; anything outside (incl.
            // nan/inf) raises like fromtimestamp does -> row ERROR
            if (!(t >= -62135596800.0 && t <= 253402300800.0)) {
                PyErr_SetString(PyExc_OverflowError,
                                "timestamp out of range");
                return nullptr;
            }
            double intpart;
            double usf = std::modf(t, &intpart) * 1e6;
            double rounded = std::round(usf);
            if (std::fabs(usf - rounded) == 0.5)
                rounded = 2.0 * std::round(usf / 2.0);
            int64_t secs = (int64_t)intpart;
            int64_t us = (int64_t)rounded;
            if (us >= 1000000) {
                us -= 1000000;
                secs += 1;
            } else if (us < 0) {
                us += 1000000;
                secs -= 1;
            }
            if (!ensure_datetime_cache()) return nullptr;
            return dt_from_epoch_us(
                secs * 1000000 + us,
                mid == M_DT_UTC_FROM_TIMESTAMP ? g_utc_singleton : Py_None,
                0);
        }
        case M_DT_TO_UTC:
        case M_DT_TO_NAIVE_TZ: {
            // (d, tz_table): zoneinfo conversions over the packed
            // transition tables (see TzTable above).  to_utc mirrors
            // ZoneInfo._find_trans over the local-side keys (lookup
            // ignores microseconds, like _get_local_timestamp);
            // to_naive_in_timezone mirrors ZoneInfo.fromutc over the
            // utc-side keys including its fold detection.
            PyObject* tbl = args[1];
            if (!PyTuple_Check(tbl) || (PyTuple_GET_SIZE(tbl) != 9 &&
                                        PyTuple_GET_SIZE(tbl) != 2)) {
                PyErr_SetString(PyExc_TypeError, "bad tz table");
                return nullptr;
            }
            PyObject* fallback =
                PyTuple_GET_ITEM(tbl, PyTuple_GET_SIZE(tbl) - 1);
            if (!PyDateTime_Check(a0)) {
                PyErr_SetString(PyExc_TypeError, "expected datetime");
                return nullptr;
            }
            PyObject* tzinfo = PyDateTime_DATE_GET_TZINFO(a0);
            bool to_utc = mid == M_DT_TO_UTC;
            if (PyTuple_GET_SIZE(tbl) == 2 ||
                (!to_utc && tzinfo == Py_None))  // naive astimezone =
                                                 // system-local: Python
                return PyObject_CallFunctionObjArgs(fallback, a0, nullptr);
            if (!ensure_datetime_cache()) return nullptr;
            TzTable T;
            if (!tz_table_view(tbl, &T)) return nullptr;
            int64_t days = days_from_civil(PyDateTime_GET_YEAR(a0),
                                           PyDateTime_GET_MONTH(a0),
                                           PyDateTime_GET_DAY(a0));
            int64_t fsecs = (int64_t)PyDateTime_DATE_GET_HOUR(a0) * 3600 +
                            PyDateTime_DATE_GET_MINUTE(a0) * 60 +
                            PyDateTime_DATE_GET_SECOND(a0);
            int64_t field_us = (days * 86400 + fsecs) * 1000000 +
                               PyDateTime_DATE_GET_MICROSECOND(a0);
            if (to_utc) {
                // wall fields -> aware UTC; input tzinfo (if any) is
                // discarded, exactly like d.replace(tzinfo=zone)
                int64_t ts = days * 86400 + fsecs;
                const int64_t* lk =
                    PyDateTime_DATE_GET_FOLD(a0) ? T.lk1 : T.lk0;
                int64_t off;
                if (T.n == 0 || ts > lk[T.n - 1]) {
                    if (!T.has_after)  // rule footer: per-value Python
                        return PyObject_CallFunctionObjArgs(fallback, a0,
                                                            nullptr);
                    off = T.after_off;
                } else if (ts < lk[0]) {
                    off = T.off_before;
                } else {
                    int64_t idx =
                        (int64_t)(std::upper_bound(lk, lk + T.n, ts) - lk) -
                        1;
                    off = T.offs[idx];
                }
                return dt_from_epoch_us(field_us - off * 1000000,
                                        g_utc_singleton, 0);
            }
            // to_naive_in_timezone: aware -> naive local wall time.
            // astimezone short-circuits when the input already carries
            // the SAME zone instance (fields kept verbatim).
            if (tzinfo == PyTuple_GET_ITEM(tbl, 7))
                return PyDateTimeAPI->DateTime_FromDateAndTimeAndFold(
                    PyDateTime_GET_YEAR(a0), PyDateTime_GET_MONTH(a0),
                    PyDateTime_GET_DAY(a0), PyDateTime_DATE_GET_HOUR(a0),
                    PyDateTime_DATE_GET_MINUTE(a0),
                    PyDateTime_DATE_GET_SECOND(a0),
                    PyDateTime_DATE_GET_MICROSECOND(a0), Py_None,
                    PyDateTime_DATE_GET_FOLD(a0),
                    PyDateTimeAPI->DateTimeType);
            // input offset via Python (arbitrary tzinfo), the
            // M_DT_TIMESTAMP pattern
            PyObject* off_o = PyObject_CallMethod(a0, "utcoffset", nullptr);
            if (off_o == nullptr) return nullptr;
            if (off_o == Py_None) {
                Py_DECREF(off_o);
                return PyObject_CallFunctionObjArgs(fallback, a0, nullptr);
            }
            if (!PyDelta_Check(off_o)) {
                Py_DECREF(off_o);
                PyErr_SetString(PyExc_TypeError, "bad utcoffset");
                return nullptr;
            }
            int64_t in_off_us =
                ((int64_t)PyDateTime_DELTA_GET_DAYS(off_o) * 86400 +
                 PyDateTime_DELTA_GET_SECONDS(off_o)) *
                    1000000 +
                PyDateTime_DELTA_GET_MICROSECONDS(off_o);
            Py_DECREF(off_o);
            int64_t utc_us = field_us - in_off_us;
            // fromutc's lookup key: civil seconds of the utc-labelled
            // datetime, i.e. floor(utc_us / 1e6)
            int64_t ts = utc_us >= 0 ? utc_us / 1000000
                                     : -((-utc_us + 999999) / 1000000);
            int64_t off;
            int fold = 0;
            if (T.n >= 1 && ts < T.trans_utc[0]) {
                off = T.off_before;
            } else if (T.n == 0 || ts > T.trans_utc[T.n - 1]) {
                // footer region: fixed-offset zones with no transitions
                // are native; rule footers / post-last-transition go to
                // Python (fromutc's corner branches)
                if (T.n == 0 && T.has_after)
                    off = T.after_off;
                else
                    return PyObject_CallFunctionObjArgs(fallback, a0,
                                                        nullptr);
            } else {
                int64_t idx = (int64_t)(std::upper_bound(
                                            T.trans_utc, T.trans_utc + T.n,
                                            ts) -
                                        T.trans_utc);  // >= 1
                off = T.offs[idx - 1];
                int64_t off_prev =
                    idx >= 2 ? T.offs[idx - 2] : T.off_before;
                fold = (off_prev - off) > (ts - T.trans_utc[idx - 1]) ? 1
                                                                      : 0;
            }
            return dt_from_epoch_us(utc_us + off * 1000000, Py_None, fold);
        }
        default:
            PyErr_Format(PyExc_SystemError, "bad method id %lld",
                         (long long)mid);
            return nullptr;
    }
}

enum VmOp : int64_t {
    VM_LOAD_COL = 1,    // (pos)            push values[pos]
    VM_LOAD_KEY = 2,    //                  push key
    VM_LOAD_CONST = 3,  // (idx)            push consts[idx]
    VM_CALL_PY = 4,     // (idx)            push pyfuncs[idx]((key, values))
    VM_BIN = 5,         // (binop)
    VM_NEG = 6,
    VM_INV = 7,
    VM_IS_NONE = 8,
    VM_BRANCH = 9,      // (else_t, end_t)  pop cond
    VM_JUMP = 10,       // (t)
    VM_JUMP_NOT_NONE = 11,  // (t)          peek
    VM_POP = 12,
    VM_REQUIRE = 13,    // (end_t)          pop; None -> push None, jump
    VM_UNWRAP = 14,     //                  pop; None -> ERROR
    VM_FILL_JUMP = 15,  // (t)              peek; not ERROR -> jump
    VM_CAST = 16,       // (tid)            0 int 1 float 2 bool 3 str
    VM_CONVERT = 17,    // (tid, unwrap)    Json-aware strict conversion
    VM_MAKE_TUPLE = 18, // (n)
    VM_GET = 19,        // (strict, end_t)  pop idx, obj
    VM_POINTER = 20,    // (n, opt, rs_idx) pop n args -> Pointer key
    VM_METHOD = 21,     // (mid, nargs, propagate_none) namespace method
};

enum VmBin : int64_t {
    B_ADD = 0, B_SUB, B_MUL, B_TRUEDIV, B_FLOORDIV, B_MOD, B_POW,
    B_MATMUL, B_EQ, B_NE, B_LT, B_LE, B_GT, B_GE, B_AND, B_OR, B_XOR,
};

struct VmProgram {
    std::vector<int64_t> code;
    std::vector<PyObject*> consts;   // owned
    std::vector<PyObject*> pyfuncs;  // owned
    size_t max_stack = 0;
    ~VmProgram() {
        for (auto* o : consts) Py_XDECREF(o);
        for (auto* o : pyfuncs) Py_XDECREF(o);
    }
};

void vm_capsule_free(PyObject* cap) {
    delete static_cast<VmProgram*>(
        PyCapsule_GetPointer(cap, "pathway_tpu.vm"));
}

// operand count per opcode; -1 = invalid
inline int vm_n_operands(int64_t op) {
    switch (op) {
        case VM_LOAD_KEY: case VM_NEG: case VM_INV: case VM_IS_NONE:
        case VM_POP: case VM_UNWRAP:
            return 0;
        case VM_LOAD_COL: case VM_LOAD_CONST: case VM_CALL_PY: case VM_BIN:
        case VM_JUMP: case VM_JUMP_NOT_NONE: case VM_REQUIRE:
        case VM_FILL_JUMP: case VM_CAST: case VM_MAKE_TUPLE:
            return 1;
        case VM_BRANCH: case VM_CONVERT: case VM_GET:
            return 2;
        case VM_POINTER: case VM_METHOD:
            return 3;
        default:
            return -1;
    }
}

// "simple" builtin scalar: known-sane __eq__, so the None shortcut in
// binary ops cannot diverge from Python (e.g. ndarray == None is
// elementwise and must go through the generic object path)
inline bool vm_is_simple(PyObject* v) {
    return v == Py_None || PyLong_Check(v) || PyFloat_Check(v) ||
           PyUnicode_Check(v) || PyBytes_Check(v) || PyTuple_Check(v);
}

// generic binary op with the Python-closure exception mapping.
// Returns a new reference; nullptr = row-level error (exception set).
PyObject* vm_bin_generic(int64_t op, PyObject* a, PyObject* b,
                         PyObject* error_obj) {
    if ((a == Py_None && vm_is_simple(b)) ||
        (b == Py_None && vm_is_simple(a))) {
        // TypeError-with-None outcome, without paying for the exception
        if (op == B_EQ) return PyBool_FromLong(a == b);
        if (op == B_NE) return PyBool_FromLong(a != b);
        Py_RETURN_NONE;
    }
    PyObject* r = nullptr;
    switch (op) {
        case B_ADD: r = PyNumber_Add(a, b); break;
        case B_SUB: r = PyNumber_Subtract(a, b); break;
        case B_MUL: r = PyNumber_Multiply(a, b); break;
        case B_TRUEDIV: r = PyNumber_TrueDivide(a, b); break;
        case B_FLOORDIV: r = PyNumber_FloorDivide(a, b); break;
        case B_MOD: r = PyNumber_Remainder(a, b); break;
        case B_POW: r = PyNumber_Power(a, b, Py_None); break;
        case B_MATMUL: r = PyNumber_MatrixMultiply(a, b); break;
        case B_EQ: r = PyObject_RichCompare(a, b, Py_EQ); break;
        case B_NE: r = PyObject_RichCompare(a, b, Py_NE); break;
        case B_LT: r = PyObject_RichCompare(a, b, Py_LT); break;
        case B_LE: r = PyObject_RichCompare(a, b, Py_LE); break;
        case B_GT: r = PyObject_RichCompare(a, b, Py_GT); break;
        case B_GE: r = PyObject_RichCompare(a, b, Py_GE); break;
        case B_AND: r = PyNumber_And(a, b); break;
        case B_OR: r = PyNumber_Or(a, b); break;
        case B_XOR: r = PyNumber_Xor(a, b); break;
        default:
            PyErr_SetString(PyExc_SystemError, "bad binop");
            return nullptr;
    }
    if (r != nullptr) return r;
    if (PyErr_ExceptionMatches(PyExc_TypeError)) {
        PyErr_Clear();
        if (a == Py_None || b == Py_None) {
            if (op == B_EQ) return PyBool_FromLong(a == b);
            if (op == B_NE) return PyBool_FromLong(a != b);
            Py_RETURN_NONE;
        }
        Py_INCREF(error_obj);
        return error_obj;
    }
    if (PyErr_ExceptionMatches(PyExc_ZeroDivisionError) ||
        PyErr_ExceptionMatches(PyExc_ValueError) ||
        PyErr_ExceptionMatches(PyExc_OverflowError)) {
        PyErr_Clear();
        Py_INCREF(error_obj);
        return error_obj;
    }
    return nullptr;  // row-level error
}

// fast paths for exact int/float/bool operands; nullptr with NO exception
// set means "no fast path, use generic"
PyObject* vm_bin_fast(int64_t op, PyObject* a, PyObject* b,
                      PyObject* error_obj) {
    if (PyLong_CheckExact(a) && PyLong_CheckExact(b)) {
        int oa = 0, ob = 0;
        long long av = PyLong_AsLongLongAndOverflow(a, &oa);
        long long bv = PyLong_AsLongLongAndOverflow(b, &ob);
        if (oa != 0 || ob != 0) return nullptr;  // big ints: generic
        long long res;
        switch (op) {
            case B_ADD:
                if (!__builtin_add_overflow(av, bv, &res))
                    return PyLong_FromLongLong(res);
                return nullptr;
            case B_SUB:
                if (!__builtin_sub_overflow(av, bv, &res))
                    return PyLong_FromLongLong(res);
                return nullptr;
            case B_MUL:
                if (!__builtin_mul_overflow(av, bv, &res))
                    return PyLong_FromLongLong(res);
                return nullptr;
            case B_FLOORDIV:
            case B_MOD: {
                if (bv == 0) {  // ZeroDivisionError -> ERROR
                    Py_INCREF(error_obj);
                    return error_obj;
                }
                if (av == LLONG_MIN && bv == -1) return nullptr;
                long long q = av / bv, m = av % bv;
                if (m != 0 && ((m < 0) != (bv < 0))) {  // Python floor rules
                    q -= 1;
                    m += bv;
                }
                return PyLong_FromLongLong(op == B_FLOORDIV ? q : m);
            }
            case B_EQ: return PyBool_FromLong(av == bv);
            case B_NE: return PyBool_FromLong(av != bv);
            case B_LT: return PyBool_FromLong(av < bv);
            case B_LE: return PyBool_FromLong(av <= bv);
            case B_GT: return PyBool_FromLong(av > bv);
            case B_GE: return PyBool_FromLong(av >= bv);
            case B_AND: return PyLong_FromLongLong(av & bv);
            case B_OR: return PyLong_FromLongLong(av | bv);
            case B_XOR: return PyLong_FromLongLong(av ^ bv);
            default: return nullptr;  // truediv/pow/matmul: generic
        }
    }
    if (PyFloat_CheckExact(a) && PyFloat_CheckExact(b)) {
        double av = PyFloat_AS_DOUBLE(a), bv = PyFloat_AS_DOUBLE(b);
        switch (op) {
            case B_ADD: return PyFloat_FromDouble(av + bv);
            case B_SUB: return PyFloat_FromDouble(av - bv);
            case B_MUL: return PyFloat_FromDouble(av * bv);
            case B_TRUEDIV:
                if (bv == 0.0) {  // Python float/0.0 raises -> ERROR
                    Py_INCREF(error_obj);
                    return error_obj;
                }
                return PyFloat_FromDouble(av / bv);
            case B_EQ: return PyBool_FromLong(av == bv);
            case B_NE: return PyBool_FromLong(av != bv);
            case B_LT: return PyBool_FromLong(av < bv);
            case B_LE: return PyBool_FromLong(av <= bv);
            case B_GT: return PyBool_FromLong(av > bv);
            case B_GE: return PyBool_FromLong(av >= bv);
            default: return nullptr;  // //,%: sign rules differ -> generic
        }
    }
    if (PyBool_Check(a) && PyBool_Check(b)) {
        switch (op) {
            case B_AND: return PyBool_FromLong(a == Py_True && b == Py_True);
            case B_OR: return PyBool_FromLong(a == Py_True || b == Py_True);
            case B_XOR: return PyBool_FromLong((a == Py_True) != (b == Py_True));
            case B_EQ: return PyBool_FromLong(a == b);
            case B_NE: return PyBool_FromLong(a != b);
            default: return nullptr;
        }
    }
    return nullptr;
}

// Evaluate one program over one row.  Returns a new reference, or
// nullptr with a Python exception set (row-level error; batch loop
// contains it).  kv_cache: lazily built (key, values) tuple shared by
// every CALL_PY of this row across programs.
PyObject* vm_eval(VmProgram* P, PyObject* key, PyObject* values,
                  PyObject* error_obj, PyObject** kv_cache,
                  std::vector<PyObject*>& stack) {
    const int64_t* code = P->code.data();
    const size_t nc = P->code.size();
    size_t sp = 0, ip = 0;
    while (ip < nc) {
        int64_t op = code[ip++];
        switch (op) {
            case VM_LOAD_COL: {
                int64_t pos = code[ip++];
                if (!PyTuple_Check(values) ||
                    pos >= PyTuple_GET_SIZE(values)) {
                    PyErr_SetString(PyExc_IndexError, "row too short");
                    goto rowfail;
                }
                PyObject* v = PyTuple_GET_ITEM(values, pos);
                Py_INCREF(v);
                stack[sp++] = v;
                break;
            }
            case VM_LOAD_KEY:
                Py_INCREF(key);
                stack[sp++] = key;
                break;
            case VM_LOAD_CONST: {
                PyObject* v = P->consts[code[ip++]];
                Py_INCREF(v);
                stack[sp++] = v;
                break;
            }
            case VM_CALL_PY: {
                if (*kv_cache == nullptr) {
                    *kv_cache = PyTuple_Pack(2, key, values);
                    if (*kv_cache == nullptr) goto rowfail;
                }
                PyObject* r =
                    PyObject_CallOneArg(P->pyfuncs[code[ip++]], *kv_cache);
                if (r == nullptr) goto rowfail;
                stack[sp++] = r;
                break;
            }
            case VM_BIN: {
                int64_t bop = code[ip++];
                PyObject* b = stack[--sp];
                PyObject* a = stack[--sp];
                PyObject* r;
                if (a == error_obj || b == error_obj) {
                    Py_INCREF(error_obj);
                    r = error_obj;
                } else {
                    r = vm_bin_fast(bop, a, b, error_obj);
                    if (r == nullptr && !PyErr_Occurred())
                        r = vm_bin_generic(bop, a, b, error_obj);
                }
                Py_DECREF(a);
                Py_DECREF(b);
                if (r == nullptr) goto rowfail;
                stack[sp++] = r;
                break;
            }
            case VM_NEG:
            case VM_INV: {
                PyObject* v = stack[sp - 1];
                if (v == error_obj || v == Py_None) break;  // pass through
                PyObject* r;
                if (op == VM_INV && PyBool_Check(v)) {
                    r = PyBool_FromLong(v == Py_False);
                } else {
                    r = op == VM_NEG ? PyNumber_Negative(v)
                                     : PyNumber_Invert(v);
                    if (r == nullptr) {
                        if (!PyErr_ExceptionMatches(PyExc_TypeError))
                            goto rowfail;
                        PyErr_Clear();
                        Py_INCREF(error_obj);
                        r = error_obj;
                    }
                }
                Py_DECREF(v);
                stack[sp - 1] = r;
                break;
            }
            case VM_IS_NONE: {
                PyObject* v = stack[sp - 1];
                if (v == error_obj) break;
                PyObject* r = PyBool_FromLong(v == Py_None);
                Py_DECREF(v);
                stack[sp - 1] = r;
                break;
            }
            case VM_BRANCH: {
                int64_t else_t = code[ip], end_t = code[ip + 1];
                ip += 2;
                PyObject* c = stack[--sp];
                if (c == error_obj) {
                    stack[sp++] = c;  // keep the ref, reuse as result
                    ip = (size_t)end_t;
                    break;
                }
                int t = PyObject_IsTrue(c);
                Py_DECREF(c);
                if (t < 0) goto rowfail;
                if (!t) ip = (size_t)else_t;
                break;
            }
            case VM_JUMP:
                ip = (size_t)code[ip];
                break;
            case VM_JUMP_NOT_NONE: {
                int64_t t = code[ip++];
                if (stack[sp - 1] != Py_None) ip = (size_t)t;
                break;
            }
            case VM_POP:
                Py_DECREF(stack[--sp]);
                break;
            case VM_REQUIRE: {
                int64_t end_t = code[ip++];
                PyObject* v = stack[--sp];
                if (v == Py_None) {
                    stack[sp++] = v;  // None is the result
                    ip = (size_t)end_t;
                } else {
                    Py_DECREF(v);
                }
                break;
            }
            case VM_UNWRAP: {
                PyObject* v = stack[sp - 1];
                if (v == Py_None) {
                    Py_DECREF(v);
                    Py_INCREF(error_obj);
                    stack[sp - 1] = error_obj;
                }
                break;
            }
            case VM_FILL_JUMP: {
                int64_t t = code[ip++];
                if (stack[sp - 1] != error_obj) ip = (size_t)t;
                break;
            }
            case VM_CAST: {
                int64_t tid = code[ip++];
                PyObject* v = stack[sp - 1];
                if (v == error_obj || v == Py_None) break;
                PyObject* r = nullptr;
                switch (tid) {
                    case 0: r = PyNumber_Long(v); break;
                    case 1: r = PyNumber_Float(v); break;
                    case 2: {
                        int t = PyObject_IsTrue(v);
                        if (t >= 0) r = PyBool_FromLong(t);
                        break;
                    }
                    case 3: r = PyObject_Str(v); break;
                }
                if (r == nullptr) {
                    if (!PyErr_ExceptionMatches(PyExc_ValueError) &&
                        !PyErr_ExceptionMatches(PyExc_TypeError))
                        goto rowfail;
                    PyErr_Clear();
                    Py_INCREF(error_obj);
                    r = error_obj;
                }
                Py_DECREF(v);
                stack[sp - 1] = r;
                break;
            }
            case VM_CONVERT: {
                int64_t tid = code[ip], unwrap = code[ip + 1];
                ip += 2;
                PyObject* v = stack[sp - 1];
                if (v == error_obj) break;
                // Json unboxes to its .value first
                if (g_json_type != nullptr &&
                    PyObject_TypeCheck(
                        v, reinterpret_cast<PyTypeObject*>(g_json_type))) {
                    PyObject* inner = PyObject_GetAttrString(v, "value");
                    if (inner == nullptr) goto rowfail;
                    Py_DECREF(v);
                    v = stack[sp - 1] = inner;
                }
                if (v == Py_None) {
                    if (unwrap) {
                        Py_DECREF(v);
                        Py_INCREF(error_obj);
                        stack[sp - 1] = error_obj;
                    }
                    break;
                }
                PyObject* r = nullptr;
                bool type_ok;
                switch (tid) {
                    case 0:  // int: bool and non-numbers are ERROR
                    case 1:  // float
                        type_ok = !PyBool_Check(v) &&
                                  (PyLong_Check(v) || PyFloat_Check(v));
                        if (type_ok)
                            r = tid == 0 ? PyNumber_Long(v)
                                         : PyNumber_Float(v);
                        break;
                    case 2:
                        type_ok = PyBool_Check(v);
                        if (type_ok) {
                            Py_INCREF(v);
                            r = v;
                        }
                        break;
                    default:
                        type_ok = PyUnicode_Check(v);
                        if (type_ok) {
                            Py_INCREF(v);
                            r = v;
                        }
                        break;
                }
                if (r == nullptr) {
                    if (PyErr_Occurred()) {
                        if (!PyErr_ExceptionMatches(PyExc_ValueError) &&
                            !PyErr_ExceptionMatches(PyExc_TypeError))
                            goto rowfail;
                        PyErr_Clear();
                    }
                    Py_INCREF(error_obj);
                    r = error_obj;
                }
                Py_DECREF(v);
                stack[sp - 1] = r;
                break;
            }
            case VM_MAKE_TUPLE: {
                int64_t n = code[ip++];
                PyObject* t = PyTuple_New(n);
                if (t == nullptr) goto rowfail;
                for (int64_t j = n - 1; j >= 0; j--)
                    PyTuple_SET_ITEM(t, j, stack[--sp]);  // steals refs
                stack[sp++] = t;
                break;
            }
            case VM_GET: {
                int64_t strict = code[ip], end_t = code[ip + 1];
                ip += 2;
                PyObject* idx = stack[--sp];
                PyObject* obj = stack[--sp];
                if (obj == error_obj || idx == error_obj) {
                    Py_DECREF(obj);
                    Py_DECREF(idx);
                    Py_INCREF(error_obj);
                    stack[sp++] = error_obj;
                    ip = (size_t)end_t;
                    break;
                }
                PyObject* v = nullptr;
                bool is_json =
                    g_json_type != nullptr &&
                    PyObject_TypeCheck(
                        obj, reinterpret_cast<PyTypeObject*>(g_json_type));
                if (is_json) {
                    PyObject* inner = PyObject_GetAttrString(obj, "value");
                    if (inner == nullptr) {
                        Py_DECREF(obj);
                        Py_DECREF(idx);
                        goto rowfail;
                    }
                    v = PyObject_GetItem(inner, idx);
                    Py_DECREF(inner);
                    if (v != nullptr &&
                        !PyObject_TypeCheck(
                            v, reinterpret_cast<PyTypeObject*>(g_json_type))) {
                        // Json getitem re-wraps plain values as Json
                        PyObject* wrapped = PyObject_CallFunctionObjArgs(
                            g_json_type, v, nullptr);
                        Py_DECREF(v);
                        v = wrapped;
                        if (v == nullptr) {
                            Py_DECREF(obj);
                            Py_DECREF(idx);
                            goto rowfail;
                        }
                    }
                } else {
                    v = PyObject_GetItem(obj, idx);
                }
                Py_DECREF(obj);
                Py_DECREF(idx);
                if (v != nullptr) {
                    stack[sp++] = v;
                    ip = (size_t)end_t;
                    break;
                }
                if (!PyErr_ExceptionMatches(PyExc_KeyError) &&
                    !PyErr_ExceptionMatches(PyExc_IndexError) &&
                    !PyErr_ExceptionMatches(PyExc_TypeError))
                    goto rowfail;
                PyErr_Clear();
                if (strict) {
                    Py_INCREF(error_obj);
                    stack[sp++] = error_obj;
                    ip = (size_t)end_t;
                }
                // non-strict: fall through into the default's code
                break;
            }
            case VM_POINTER: {
                int64_t n = code[ip], opt = code[ip + 1],
                        rs_idx = code[ip + 2];
                ip += 3;
                PyObject** base = &stack[sp - n];
                if (opt) {
                    bool any_none = false;
                    for (int64_t j = 0; j < n; j++)
                        if (base[j] == Py_None) any_none = true;
                    if (any_none) {
                        for (int64_t j = 0; j < n; j++) Py_DECREF(base[j]);
                        sp -= (size_t)n;
                        Py_INCREF(Py_None);
                        stack[sp++] = Py_None;
                        break;
                    }
                }
                Hasher h;
                bool ok = g_pointer_type != nullptr;
                for (int64_t j = 0; j < n && ok; j++) ok = feed(h, base[j]);
                PyObject* r = nullptr;
                if (ok) {
                    PyObject* num = digest_to_long(h);
                    if (num == nullptr) goto rowfail_ptr;
                    r = pointer_from_long(num);
                } else {
                    if (PyErr_Occurred()) PyErr_Clear();
                    // unsupported value type: defer to Python ref_scalar
                    PyObject* t = PyTuple_New(n);
                    if (t == nullptr) goto rowfail_ptr;
                    for (int64_t j = 0; j < n; j++) {
                        Py_INCREF(base[j]);
                        PyTuple_SET_ITEM(t, j, base[j]);
                    }
                    r = PyObject_Call(P->consts[rs_idx], t, nullptr);
                    Py_DECREF(t);
                }
                if (r == nullptr) goto rowfail_ptr;
                for (int64_t j = 0; j < n; j++) Py_DECREF(base[j]);
                sp -= (size_t)n;
                stack[sp++] = r;
                break;
            rowfail_ptr:
                goto rowfail;
            }
            case VM_METHOD: {
                int64_t mid = code[ip], n = code[ip + 1],
                        prop_none = code[ip + 2];
                ip += 3;
                PyObject** base = &stack[sp - n];
                // closure contract (MethodCallExpression._compile run()):
                // any ERROR arg -> ERROR; any None arg -> None when the
                // method propagates None; an exception -> ERROR
                bool any_err = false, any_none = false;
                for (int64_t j = 0; j < n; j++) {
                    if (base[j] == error_obj) any_err = true;
                    if (base[j] == Py_None) any_none = true;
                }
                PyObject* r;
                if (any_err) {
                    Py_INCREF(error_obj);
                    r = error_obj;
                } else if (prop_none && any_none) {
                    Py_INCREF(Py_None);
                    r = Py_None;
                } else {
                    r = vm_method_eval(mid, base, n);
                    if (r == nullptr) {
                        if (PyErr_ExceptionMatches(PyExc_SystemError) ||
                            PyErr_ExceptionMatches(PyExc_MemoryError))
                            goto rowfail;
                        PyErr_Clear();
                        Py_INCREF(error_obj);
                        r = error_obj;
                    }
                }
                for (int64_t j = 0; j < n; j++) Py_DECREF(base[j]);
                sp -= (size_t)n;
                stack[sp++] = r;
                break;
            }
            default:
                PyErr_SetString(PyExc_SystemError, "bad VM opcode");
                goto rowfail;
        }
    }
    if (sp != 1) {
        PyErr_SetString(PyExc_SystemError, "VM stack imbalance");
        goto rowfail;
    }
    return stack[0];
rowfail:
    while (sp > 0) Py_DECREF(stack[--sp]);
    return nullptr;
}

PyObject* py_vm_compile(PyObject*, PyObject* args) {
    // (code_seq[int], consts_seq, pyfuncs_seq) -> capsule
    PyObject *code_obj, *consts_obj, *pyfuncs_obj;
    if (!PyArg_ParseTuple(args, "OOO", &code_obj, &consts_obj, &pyfuncs_obj))
        return nullptr;
    PyObject* code_seq = PySequence_Fast(code_obj, "code must be a sequence");
    if (code_seq == nullptr) return nullptr;
    auto P = std::make_unique<VmProgram>();
    Py_ssize_t nc = PySequence_Fast_GET_SIZE(code_seq);
    P->code.reserve((size_t)nc);
    for (Py_ssize_t i = 0; i < nc; i++) {
        long long v =
            PyLong_AsLongLong(PySequence_Fast_GET_ITEM(code_seq, i));
        if (v == -1 && PyErr_Occurred()) {
            Py_DECREF(code_seq);
            return nullptr;
        }
        P->code.push_back(v);
    }
    Py_DECREF(code_seq);
    PyObject* cseq = PySequence_Fast(consts_obj, "consts must be a sequence");
    if (cseq == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(cseq); i++) {
        PyObject* o = PySequence_Fast_GET_ITEM(cseq, i);
        Py_INCREF(o);
        P->consts.push_back(o);
    }
    Py_DECREF(cseq);
    PyObject* fseq =
        PySequence_Fast(pyfuncs_obj, "pyfuncs must be a sequence");
    if (fseq == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(fseq); i++) {
        PyObject* o = PySequence_Fast_GET_ITEM(fseq, i);
        Py_INCREF(o);
        P->pyfuncs.push_back(o);
    }
    Py_DECREF(fseq);
    // Validation pass: operand counts, jump targets (instruction
    // boundaries only), table indices, AND full stack discipline — a
    // worklist dataflow over (ip -> stack depth).  The VM itself trusts
    // the program completely, so this is the only guard against stack
    // underflow / imbalance from a buggy or hostile lowering.
    {
        const size_t n = P->code.size();
        // instruction boundaries
        std::vector<uint8_t> is_insn(n + 1, 0);
        size_t ip = 0;
        while (ip < n) {
            is_insn[ip] = 1;
            int64_t op = P->code[ip];
            int nops = vm_n_operands(op);
            if (nops < 0 || ip + 1 + (size_t)nops > n) {
                PyErr_SetString(PyExc_ValueError, "malformed VM program");
                return nullptr;
            }
            ip += 1 + (size_t)nops;
        }
        is_insn[n] = 1;  // falling off the end is the exit
        std::vector<int> depth_at(n + 1, -1);  // -1 = unvisited
        std::vector<size_t> work;
        auto fail = [&]() {
            PyErr_SetString(PyExc_ValueError, "malformed VM program");
        };
        auto flow = [&](size_t target, int depth) -> bool {
            if (target > n || !is_insn[target]) return false;
            if (target == n && depth != 1) return false;  // exit depth
            if (depth_at[target] == -1) {
                depth_at[target] = depth;
                if (target < n) work.push_back(target);
                return true;
            }
            return depth_at[target] == depth;  // merge must agree
        };
        if (!flow(0, 0)) {
            fail();
            return nullptr;
        }
        size_t max_depth = 1;
        while (!work.empty()) {
            size_t at = work.back();
            work.pop_back();
            int64_t op = P->code[at];
            const int64_t* o = &P->code[at + 1];
            int d = depth_at[at];
            size_t next = at + 1 + (size_t)vm_n_operands(op);
            bool ok = true;
            int nd = d;
            switch (op) {
                case VM_LOAD_COL:
                    ok = o[0] >= 0 && flow(next, d + 1);
                    nd = d + 1;
                    break;
                case VM_LOAD_KEY:
                    ok = flow(next, d + 1);
                    nd = d + 1;
                    break;
                case VM_LOAD_CONST:
                    ok = o[0] >= 0 && (size_t)o[0] < P->consts.size() &&
                         flow(next, d + 1);
                    nd = d + 1;
                    break;
                case VM_CALL_PY:
                    ok = o[0] >= 0 && (size_t)o[0] < P->pyfuncs.size() &&
                         flow(next, d + 1);
                    nd = d + 1;
                    break;
                case VM_BIN:
                    ok = o[0] >= 0 && o[0] <= B_XOR && d >= 2 &&
                         flow(next, d - 1);
                    break;
                case VM_NEG:
                case VM_INV:
                case VM_IS_NONE:
                case VM_UNWRAP:
                    ok = d >= 1 && flow(next, d);
                    break;
                case VM_CAST:
                    ok = o[0] >= 0 && o[0] <= 3 && d >= 1 && flow(next, d);
                    break;
                case VM_CONVERT:
                    ok = o[0] >= 0 && o[0] <= 3 && d >= 1 && flow(next, d);
                    break;
                case VM_BRANCH:
                    // pop cond; ERROR path pushes and jumps to end
                    ok = d >= 1 && flow(next, d - 1) &&
                         flow((size_t)o[0], d - 1) && flow((size_t)o[1], d);
                    break;
                case VM_JUMP:
                    ok = flow((size_t)o[0], d);
                    break;
                case VM_JUMP_NOT_NONE:
                case VM_FILL_JUMP:
                    ok = d >= 1 && flow(next, d) && flow((size_t)o[0], d);
                    break;
                case VM_POP:
                    ok = d >= 1 && flow(next, d - 1);
                    break;
                case VM_REQUIRE:
                    // pop; None path re-pushes and jumps to end
                    ok = d >= 1 && flow(next, d - 1) && flow((size_t)o[0], d);
                    break;
                case VM_MAKE_TUPLE:
                    // full int64 comparison: a truncated (int) cast would
                    // let counts like 2^32+2 slip past and underflow the
                    // runtime stack
                    ok = o[0] >= 0 && (int64_t)d >= o[0] &&
                         flow(next, d - (int)o[0] + 1);
                    nd = d - (int)o[0] + 1;
                    break;
                case VM_GET:
                    // pops obj+idx; success/ERROR jump to end with +1
                    ok = d >= 2 && flow((size_t)o[1], d - 1) &&
                         (o[0] != 0 || flow(next, d - 2));
                    break;
                case VM_POINTER:
                    ok = o[0] >= 1 && (int64_t)d >= o[0] && o[2] >= 0 &&
                         (size_t)o[2] < P->consts.size() &&
                         flow(next, d - (int)o[0] + 1);
                    nd = d - (int)o[0] + 1;
                    break;
                case VM_METHOD:
                    ok = o[0] >= 0 && o[0] < M_METHOD_COUNT && o[1] >= 1 &&
                         o[1] <= 8 && (int64_t)d >= o[1] &&
                         flow(next, d - (int)o[1] + 1);
                    nd = d - (int)o[1] + 1;
                    break;
                default:
                    ok = false;
            }
            if (!ok) {
                fail();
                return nullptr;
            }
            if ((size_t)(nd + 1) > max_depth) max_depth = (size_t)(nd + 1);
        }
        P->max_stack = max_depth + 2;
    }
    PyObject* cap =
        PyCapsule_New(P.release(), "pathway_tpu.vm", vm_capsule_free);
    return cap;
}

inline VmProgram* vm_from_capsule(PyObject* cap) {
    return static_cast<VmProgram*>(
        PyCapsule_GetPointer(cap, "pathway_tpu.vm"));
}

PyObject* py_vm_eval_batch(PyObject*, PyObject* args) {
    // (batch, progs_seq, update_cls, error_obj, on_error) -> list[Update]
    // Multi-column select: each program computes one output column; a
    // row whose evaluation raises becomes (ERROR,) after on_error(exc),
    // exactly like rowwise_map.
    PyObject *batch, *progs_obj, *update_cls, *error_obj, *on_error;
    if (!PyArg_ParseTuple(args, "OOOOO", &batch, &progs_obj, &update_cls,
                          &error_obj, &on_error))
        return nullptr;
    PyObject* progs =
        PySequence_Fast(progs_obj, "programs must be a sequence");
    if (progs == nullptr) return nullptr;
    Py_ssize_t np = PySequence_Fast_GET_SIZE(progs);
    std::vector<VmProgram*> P((size_t)np);
    size_t max_stack = 4;
    for (Py_ssize_t j = 0; j < np; j++) {
        P[(size_t)j] = vm_from_capsule(PySequence_Fast_GET_ITEM(progs, j));
        if (P[(size_t)j] == nullptr) {
            Py_DECREF(progs);
            return nullptr;
        }
        max_stack = std::max(max_stack, P[(size_t)j]->max_stack);
    }
    PyObject* seq = PySequence_Fast(batch, "vm_eval_batch expects a sequence");
    if (seq == nullptr) {
        Py_DECREF(progs);
        return nullptr;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        Py_DECREF(progs);
        return nullptr;
    }
    std::vector<PyObject*> stack(max_stack);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* key = PyTuple_GET_ITEM(u, 0);
            PyObject* values = PyTuple_GET_ITEM(u, 1);
            PyObject* diff = PyTuple_GET_ITEM(u, 2);
            PyObject* kv = nullptr;
            PyObject* vals = PyTuple_New(np);
            if (vals == nullptr) goto fail;
            for (Py_ssize_t j = 0; j < np; j++) {
                PyObject* v = vm_eval(P[(size_t)j], key, values, error_obj,
                                      &kv, stack);
                if (v == nullptr) {
                    Py_DECREF(vals);
                    vals = nullptr;
                    // row containment: Exception -> on_error + (ERROR,)
                    if (!PyErr_ExceptionMatches(PyExc_Exception)) {
                        Py_XDECREF(kv);
                        goto fail;
                    }
                    PyObject *etype, *evalue, *etb;
                    PyErr_Fetch(&etype, &evalue, &etb);
                    PyErr_NormalizeException(&etype, &evalue, &etb);
                    PyObject* r = PyObject_CallFunctionObjArgs(
                        on_error, evalue ? evalue : Py_None, nullptr);
                    Py_XDECREF(etype);
                    Py_XDECREF(evalue);
                    Py_XDECREF(etb);
                    if (r == nullptr) {
                        Py_XDECREF(kv);
                        goto fail;
                    }
                    Py_DECREF(r);
                    vals = PyTuple_Pack(1, error_obj);
                    if (vals == nullptr) {
                        Py_XDECREF(kv);
                        goto fail;
                    }
                    break;
                }
                PyTuple_SET_ITEM(vals, j, v);
            }
            Py_XDECREF(kv);
            PyObject* nu = make_update_obj(update_cls, key, vals, diff);
            Py_DECREF(vals);
            if (nu == nullptr) goto fail;
            PyList_SET_ITEM(out, i, nu);
        }
    }
    Py_DECREF(seq);
    Py_DECREF(progs);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(progs);
    Py_DECREF(out);
    return nullptr;
}

// ===========================================================================
// Native hash-join epoch pass
//
// The whole JoinNode.process hot loop (engine/graph.py JoinNode) in one
// C call, mirroring the reference's join arrangement machinery
// (src/engine/dataflow.rs join_tables): evaluate join keys via VM
// programs, snapshot old per-key output blocks, apply both deltas to the
// (Python-dict) arrangements, rebuild dirty blocks and emit the diff.
// State stays plain Python dicts {jk: {row_key: values}} so operator
// snapshots/resume and the Python fallback interoperate bit-for-bit.
//
// Any pre-mutation obstacle (unhashable join key, VM row error) raises
// Unsupported so the caller reruns the batch in Python; obstacles after
// mutation would desync state and therefore hard-fail instead — they
// cannot occur for values the VM produced (jk tuples are hashable by
// construction once PyObject_Hash succeeded).

// okey = ref_scalar("__join__", int(lk), int(rk)|None) — keys.join_key
PyObject* join_okey(PyObject* lk, PyObject* rk) {
    Hasher h;
    static const char kJ[] = "__join__";
    h.tag(0x04);
    h.u64le(sizeof(kJ) - 1);
    h.bytes(kJ, sizeof(kJ) - 1);
    if (!feed_pylong_plain(h, lk)) return nullptr;
    if (rk == Py_None || rk == nullptr) {
        h.tag(0x00);
    } else if (!feed_pylong_plain(h, rk)) {
        return nullptr;
    }
    PyObject* num = digest_to_long(h);
    if (num == nullptr) return nullptr;
    return pointer_from_long(num);
}

// okey = ref_scalar("__join_r__", int(rk)) — right-outer unmatched rows
PyObject* join_okey_r(PyObject* rk) {
    Hasher h;
    static const char kJ[] = "__join_r__";
    h.tag(0x04);
    h.u64le(sizeof(kJ) - 1);
    h.bytes(kJ, sizeof(kJ) - 1);
    if (!feed_pylong_plain(h, rk)) return nullptr;
    PyObject* num = digest_to_long(h);
    if (num == nullptr) return nullptr;
    return pointer_from_long(num);
}

struct JoinCtx {
    int64_t kind;  // 0 inner, 1 left, 2 right, 3 outer
    int left_id_only;
    Py_ssize_t lncols, rncols;
    PyObject* lnone;  // (None,)*lncols
    PyObject* rnone;
    PyObject* engine_error;
};

// output row = lv + rv + (lk, rk), built in one allocation
PyObject* join_row(JoinCtx& C, PyObject* lv, PyObject* rv, PyObject* lk,
                   PyObject* rk) {
    if (lv == nullptr) lv = C.lnone;
    if (rv == nullptr) rv = C.rnone;
    if (!PyTuple_Check(lv) || !PyTuple_Check(rv)) {
        // exotic row type: generic concat path
        PyObject* lr = PySequence_Concat(lv, rv);
        if (lr == nullptr) return nullptr;
        PyObject* tail = PyTuple_Pack(2, lk, rk);
        if (tail == nullptr) {
            Py_DECREF(lr);
            return nullptr;
        }
        PyObject* row = PySequence_Concat(lr, tail);
        Py_DECREF(lr);
        Py_DECREF(tail);
        return row;
    }
    Py_ssize_t ln = PyTuple_GET_SIZE(lv), rn = PyTuple_GET_SIZE(rv);
    PyObject* row = PyTuple_New(ln + rn + 2);
    if (row == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < ln; i++) {
        PyObject* x = PyTuple_GET_ITEM(lv, i);
        Py_INCREF(x);
        PyTuple_SET_ITEM(row, i, x);
    }
    for (Py_ssize_t i = 0; i < rn; i++) {
        PyObject* x = PyTuple_GET_ITEM(rv, i);
        Py_INCREF(x);
        PyTuple_SET_ITEM(row, ln + i, x);
    }
    Py_INCREF(lk);
    PyTuple_SET_ITEM(row, ln + rn, lk);
    Py_INCREF(rk);
    PyTuple_SET_ITEM(row, ln + rn + 1, rk);
    return row;
}

// Build the full output block {okey: lv+rv+(lk,rk)} for one join key.
// Returns a NEW dict, or nullptr with exception set.
// SQL outer semantics: a null-jk row never matches but IS retained
// unmatched on its preserved side.  Such rows are stateless
// passthroughs (mirrors JoinNode._split_null_keys on the Python
// fallback); rows are built by join_row/join_okey, the same
// constructors the blocks use.
int join_emit_null_passthroughs(JoinCtx& C, PyObject* seq, PyObject* jks,
                                bool left_side, PyObject* out,
                                PyObject* update_cls) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (PyList_GET_ITEM(jks, i) != Py_None) continue;
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* key = PyTuple_GET_ITEM(u, 0);
        PyObject* values = PyTuple_GET_ITEM(u, 1);
        PyObject* diff = PyTuple_GET_ITEM(u, 2);
        PyObject* okey;
        PyObject* row;
        if (left_side) {
            if (C.left_id_only) {
                Py_INCREF(key);
                okey = key;
            } else {
                okey = join_okey(key, nullptr);
                if (okey == nullptr) return -1;
            }
            row = join_row(C, values, nullptr, key, Py_None);
        } else {
            okey = join_okey_r(key);
            if (okey == nullptr) return -1;
            row = join_row(C, nullptr, values, Py_None, key);
        }
        if (row == nullptr) {
            Py_DECREF(okey);
            return -1;
        }
        PyObject* nu = make_update_obj(update_cls, okey, row, diff);
        Py_DECREF(okey);
        Py_DECREF(row);
        if (nu == nullptr || PyList_Append(out, nu) < 0) {
            Py_XDECREF(nu);
            return -1;
        }
        Py_DECREF(nu);
    }
    return 0;
}

PyObject* join_block(JoinCtx& C, PyObject* lrows, PyObject* rrows) {
    PyObject* out = PyDict_New();
    if (out == nullptr) return nullptr;
    Py_ssize_t nl = lrows ? PyDict_GET_SIZE(lrows) : 0;
    Py_ssize_t nr = rrows ? PyDict_GET_SIZE(rrows) : 0;
    if (nl > 0 && nr > 0) {
        if (C.left_id_only && nr > 1) {
            PyErr_Format(C.engine_error,
                         "join with id=left.id: left row has %zd right matches",
                         nr);
            Py_DECREF(out);
            return nullptr;
        }
        Py_ssize_t lpos = 0;
        PyObject *lk, *lv;
        while (PyDict_Next(lrows, &lpos, &lk, &lv)) {
            Py_ssize_t rpos = 0;
            PyObject *rk, *rv;
            while (PyDict_Next(rrows, &rpos, &rk, &rv)) {
                PyObject* okey;
                if (C.left_id_only) {
                    Py_INCREF(lk);
                    okey = lk;
                } else {
                    okey = join_okey(lk, rk);
                    if (okey == nullptr) {
                        if (!PyErr_Occurred())
                            PyErr_SetString(g_unsupported,
                                            "join key hash fallback");
                        Py_DECREF(out);
                        return nullptr;
                    }
                }
                PyObject* row = join_row(C, lv, rv, lk, rk);
                if (row == nullptr || PyDict_SetItem(out, okey, row) < 0) {
                    Py_XDECREF(row);
                    Py_DECREF(okey);
                    Py_DECREF(out);
                    return nullptr;
                }
                Py_DECREF(row);
                Py_DECREF(okey);
            }
        }
    } else if (nl > 0 && (C.kind == 1 || C.kind == 3)) {
        Py_ssize_t lpos = 0;
        PyObject *lk, *lv;
        while (PyDict_Next(lrows, &lpos, &lk, &lv)) {
            PyObject* okey;
            if (C.left_id_only) {
                Py_INCREF(lk);
                okey = lk;
            } else {
                okey = join_okey(lk, nullptr);
                if (okey == nullptr) {
                    Py_DECREF(out);
                    return nullptr;
                }
            }
            PyObject* row = join_row(C, lv, nullptr, lk, Py_None);
            if (row == nullptr || PyDict_SetItem(out, okey, row) < 0) {
                Py_XDECREF(row);
                Py_DECREF(okey);
                Py_DECREF(out);
                return nullptr;
            }
            Py_DECREF(row);
            Py_DECREF(okey);
        }
    } else if (nr > 0 && (C.kind == 2 || C.kind == 3)) {
        Py_ssize_t rpos = 0;
        PyObject *rk, *rv;
        while (PyDict_Next(rrows, &rpos, &rk, &rv)) {
            PyObject* okey = join_okey_r(rk);
            if (okey == nullptr) {
                Py_DECREF(out);
                return nullptr;
            }
            PyObject* row = join_row(C, nullptr, rv, Py_None, rk);
            if (row == nullptr || PyDict_SetItem(out, okey, row) < 0) {
                Py_XDECREF(row);
                Py_DECREF(okey);
                Py_DECREF(out);
                return nullptr;
            }
            Py_DECREF(row);
            Py_DECREF(okey);
        }
    }
    return out;
}

// Evaluate one side's join keys: list (same length as batch) of jk tuple
// or None (null join key).  Pre-mutation: any obstacle -> Unsupported.
PyObject* join_side_jks(VmProgram* prog, PyObject* seq, PyObject* error_obj,
                        std::vector<PyObject*>& stack) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (out == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            Py_DECREF(out);
            return nullptr;
        }
        PyObject* kv = nullptr;
        PyObject* jk = vm_eval(prog, PyTuple_GET_ITEM(u, 0),
                               PyTuple_GET_ITEM(u, 1), error_obj, &kv, stack);
        Py_XDECREF(kv);
        if (jk == nullptr) {
            // VM row error: punt the whole batch to the Python path
            PyErr_Clear();
            PyErr_SetString(g_unsupported, "join key eval fallback");
            Py_DECREF(out);
            return nullptr;
        }
        // null join keys never match
        bool null_jk = false;
        if (PyTuple_Check(jk)) {
            for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(jk); j++)
                if (PyTuple_GET_ITEM(jk, j) == Py_None) null_jk = true;
        } else {
            null_jk = jk == Py_None;
        }
        if (null_jk) {
            Py_DECREF(jk);
            Py_INCREF(Py_None);
            PyList_SET_ITEM(out, i, Py_None);
            continue;
        }
        if (PyObject_Hash(jk) == -1) {
            // unhashable cells (python path would use hashable_row):
            // pre-mutation, safe to fall back
            PyErr_Clear();
            PyErr_SetString(g_unsupported, "unhashable join key");
            Py_DECREF(jk);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, jk);
    }
    return out;
}

int join_apply_side(PyObject* side, PyObject* seq, PyObject* jks) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* jk = PyList_GET_ITEM(jks, i);
        if (jk == Py_None) continue;
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* key = PyTuple_GET_ITEM(u, 0);
        PyObject* values = PyTuple_GET_ITEM(u, 1);
        PyObject* diff = PyTuple_GET_ITEM(u, 2);
        PyObject* rows = PyDict_GetItemWithError(side, jk);  // borrowed
        if (rows == nullptr) {
            if (PyErr_Occurred()) return -1;
            rows = PyDict_New();
            if (rows == nullptr) return -1;
            if (PyDict_SetItem(side, jk, rows) < 0) {
                Py_DECREF(rows);
                return -1;
            }
            Py_DECREF(rows);  // dict holds it; borrow below is safe
            rows = PyDict_GetItemWithError(side, jk);
            if (rows == nullptr) return -1;
        }
        long d = PyLong_AsLong(diff);
        if (d == -1 && PyErr_Occurred()) return -1;
        if (d > 0) {
            if (PyDict_SetItem(rows, key, values) < 0) return -1;
        } else {
            if (PyDict_DelItem(rows, key) < 0) {
                if (!PyErr_ExceptionMatches(PyExc_KeyError)) return -1;
                PyErr_Clear();
            }
        }
    }
    return 0;
}

PyObject* py_join_process(PyObject*, PyObject* args) {
    // (lbatch, rbatch, lprog, rprog, lstate, rstate, kind, left_id_only,
    //  lncols, rncols, update_cls, error_obj, engine_error_cls)
    PyObject *lbatch, *rbatch, *lcap, *rcap, *lstate, *rstate;
    PyObject *update_cls, *error_obj, *engine_error;
    long long kind, left_id_only, lncols, rncols;
    if (!PyArg_ParseTuple(args, "OOOOO!O!LLLLOOO", &lbatch, &rbatch, &lcap,
                          &rcap, &PyDict_Type, &lstate, &PyDict_Type, &rstate,
                          &kind, &left_id_only, &lncols, &rncols, &update_cls,
                          &error_obj, &engine_error))
        return nullptr;
    if (g_pointer_type == nullptr) {
        PyErr_SetString(g_unsupported, "Pointer type not registered");
        return nullptr;
    }
    VmProgram* LP = vm_from_capsule(lcap);
    if (LP == nullptr) return nullptr;
    VmProgram* RP = vm_from_capsule(rcap);
    if (RP == nullptr) return nullptr;

    JoinCtx C;
    C.kind = kind;
    C.left_id_only = (int)left_id_only;
    C.lncols = (Py_ssize_t)lncols;
    C.rncols = (Py_ssize_t)rncols;
    C.engine_error = engine_error;
    C.lnone = PyTuple_New(C.lncols);
    C.rnone = PyTuple_New(C.rncols);
    if (C.lnone == nullptr || C.rnone == nullptr) {
        Py_XDECREF(C.lnone);
        Py_XDECREF(C.rnone);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < C.lncols; i++) {
        Py_INCREF(Py_None);
        PyTuple_SET_ITEM(C.lnone, i, Py_None);
    }
    for (Py_ssize_t i = 0; i < C.rncols; i++) {
        Py_INCREF(Py_None);
        PyTuple_SET_ITEM(C.rnone, i, Py_None);
    }

    PyObject *lseq = nullptr, *rseq = nullptr, *ljks = nullptr,
             *rjks = nullptr, *dirty = nullptr, *dirty_list = nullptr,
             *old_blocks = nullptr, *out = nullptr;
    bool mutated = false;
    std::vector<PyObject*> stack(
        std::max(LP->max_stack, RP->max_stack) + 2);

    lseq = PySequence_Fast(lbatch, "join: left batch");
    if (lseq == nullptr) goto fail;
    rseq = PySequence_Fast(rbatch, "join: right batch");
    if (rseq == nullptr) goto fail;
    ljks = join_side_jks(LP, lseq, error_obj, stack);
    if (ljks == nullptr) goto fail;
    rjks = join_side_jks(RP, rseq, error_obj, stack);
    if (rjks == nullptr) goto fail;

    // dirty key set (insertion-ordered via companion list)
    dirty = PySet_New(nullptr);
    dirty_list = PyList_New(0);
    if (dirty == nullptr || dirty_list == nullptr) goto fail;
    for (PyObject* jks : {ljks, rjks}) {
        Py_ssize_t n = PyList_GET_SIZE(jks);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject* jk = PyList_GET_ITEM(jks, i);
            if (jk == Py_None) continue;
            int has = PySet_Contains(dirty, jk);
            if (has < 0) goto fail;
            if (!has) {
                if (PySet_Add(dirty, jk) < 0) goto fail;
                if (PyList_Append(dirty_list, jk) < 0) goto fail;
            }
        }
    }

    // old blocks BEFORE mutation
    old_blocks = PyList_New(0);
    if (old_blocks == nullptr) goto fail;
    {
        Py_ssize_t nd = PyList_GET_SIZE(dirty_list);
        for (Py_ssize_t i = 0; i < nd; i++) {
            PyObject* jk = PyList_GET_ITEM(dirty_list, i);
            PyObject* lrows = PyDict_GetItemWithError(lstate, jk);
            if (lrows == nullptr && PyErr_Occurred()) goto fail;
            PyObject* rrows = PyDict_GetItemWithError(rstate, jk);
            if (rrows == nullptr && PyErr_Occurred()) goto fail;
            if ((lrows == nullptr || PyDict_GET_SIZE(lrows) == 0) &&
                (rrows == nullptr || PyDict_GET_SIZE(rrows) == 0)) {
                // brand-new join key (bulk-load common case): empty old
                // block — Py_None placeholder skips a dict allocation
                if (PyList_Append(old_blocks, Py_None) < 0) goto fail;
                continue;
            }
            PyObject* blk = join_block(C, lrows, rrows);
            if (blk == nullptr) goto fail;
            int rc = PyList_Append(old_blocks, blk);
            Py_DECREF(blk);
            if (rc < 0) goto fail;
        }
    }

    // mutate arrangements — from here on, Unsupported must NOT escape
    // (the Python fallback would re-apply the batch to mutated state)
    mutated = true;
    if (join_apply_side(lstate, lseq, ljks) < 0) goto fail;
    if (join_apply_side(rstate, rseq, rjks) < 0) goto fail;

    // new blocks + diff
    out = PyList_New(0);
    if (out == nullptr) goto fail;
    if (C.kind == 1 || C.kind == 3) {  // left / outer preserve left nulls
        if (join_emit_null_passthroughs(C, lseq, ljks, true, out,
                                        update_cls) < 0)
            goto fail;
    }
    if (C.kind == 2 || C.kind == 3) {  // right / outer preserve right nulls
        if (join_emit_null_passthroughs(C, rseq, rjks, false, out,
                                        update_cls) < 0)
            goto fail;
    }
    {
        PyObject* one = PyLong_FromLong(1);
        PyObject* neg = PyLong_FromLong(-1);
        if (one == nullptr || neg == nullptr) {
            Py_XDECREF(one);
            Py_XDECREF(neg);
            goto fail;
        }
        Py_ssize_t nd = PyList_GET_SIZE(dirty_list);
        bool ok = true;
        for (Py_ssize_t i = 0; i < nd && ok; i++) {
            PyObject* jk = PyList_GET_ITEM(dirty_list, i);
            PyObject* lrows = PyDict_GetItemWithError(lstate, jk);
            PyObject* rrows = PyDict_GetItemWithError(rstate, jk);
            PyObject* old_blk = PyList_GET_ITEM(old_blocks, i);
            if (old_blk == Py_None) {
                // brand-new join key: every block row is an addition and
                // okeys are unique per (lk, rk) pair — emit straight from
                // the arrangements, skipping the block dict entirely
                PyObject* blk = join_block(C, lrows, rrows);
                if (blk == nullptr) {
                    ok = false;
                    break;
                }
                Py_ssize_t pos2 = 0;
                PyObject *okey2, *vals2;
                while (ok && PyDict_Next(blk, &pos2, &okey2, &vals2)) {
                    PyObject* nu =
                        make_update_obj(update_cls, okey2, vals2, one);
                    if (nu == nullptr || PyList_Append(out, nu) < 0) {
                        Py_XDECREF(nu);
                        ok = false;
                        break;
                    }
                    Py_DECREF(nu);
                }
                Py_DECREF(blk);
                if (!ok) break;
                // same empty-arrangement cleanup as the diff path (an
                // add+retract within one epoch leaves empty dicts)
                bool lempty2 =
                    lrows == nullptr || PyDict_GET_SIZE(lrows) == 0;
                bool rempty2 =
                    rrows == nullptr || PyDict_GET_SIZE(rrows) == 0;
                if (lempty2 && rempty2) {
                    if (lrows != nullptr && PyDict_DelItem(lstate, jk) < 0)
                        PyErr_Clear();
                    if (rrows != nullptr && PyDict_DelItem(rstate, jk) < 0)
                        PyErr_Clear();
                }
                continue;
            }
            PyObject* new_blk = join_block(C, lrows, rrows);
            if (new_blk == nullptr) {
                ok = false;
                break;
            }
            // retractions: old rows missing/changed in new
            Py_ssize_t pos = 0;
            PyObject *okey, *vals;
            while (ok && old_blk != Py_None &&
                   PyDict_Next(old_blk, &pos, &okey, &vals)) {
                PyObject* nv = PyDict_GetItemWithError(new_blk, okey);
                if (nv == nullptr && PyErr_Occurred()) {
                    ok = false;
                    break;
                }
                int same = nv == nullptr
                               ? 0
                               : PyObject_RichCompareBool(nv, vals, Py_EQ);
                if (same < 0) {
                    ok = false;
                    break;
                }
                if (!same) {
                    PyObject* nu = make_update_obj(update_cls, okey, vals, neg);
                    if (nu == nullptr || PyList_Append(out, nu) < 0) {
                        Py_XDECREF(nu);
                        ok = false;
                        break;
                    }
                    Py_DECREF(nu);
                }
            }
            // additions: new rows missing/changed in old
            pos = 0;
            while (ok && PyDict_Next(new_blk, &pos, &okey, &vals)) {
                PyObject* ov =
                    old_blk == Py_None
                        ? nullptr
                        : PyDict_GetItemWithError(old_blk, okey);
                if (ov == nullptr && PyErr_Occurred()) {
                    ok = false;
                    break;
                }
                int same = ov == nullptr
                               ? 0
                               : PyObject_RichCompareBool(ov, vals, Py_EQ);
                if (same < 0) {
                    ok = false;
                    break;
                }
                if (!same) {
                    PyObject* nu = make_update_obj(update_cls, okey, vals, one);
                    if (nu == nullptr || PyList_Append(out, nu) < 0) {
                        Py_XDECREF(nu);
                        ok = false;
                        break;
                    }
                    Py_DECREF(nu);
                }
            }
            Py_DECREF(new_blk);
            if (!ok) break;
            // drop fully-empty arrangements
            bool lempty = lrows == nullptr || PyDict_GET_SIZE(lrows) == 0;
            bool rempty = rrows == nullptr || PyDict_GET_SIZE(rrows) == 0;
            if (lempty && rempty) {
                if (lrows != nullptr && PyDict_DelItem(lstate, jk) < 0)
                    PyErr_Clear();
                if (rrows != nullptr && PyDict_DelItem(rstate, jk) < 0)
                    PyErr_Clear();
            }
        }
        Py_DECREF(one);
        Py_DECREF(neg);
        if (!ok) goto fail;
    }

    Py_DECREF(lseq);
    Py_DECREF(rseq);
    Py_DECREF(ljks);
    Py_DECREF(rjks);
    Py_DECREF(dirty);
    Py_DECREF(dirty_list);
    Py_DECREF(old_blocks);
    Py_DECREF(C.lnone);
    Py_DECREF(C.rnone);
    return out;
fail:
    if (mutated && PyErr_ExceptionMatches(g_unsupported)) {
        // never let the caller rerun an already-applied batch
        PyErr_SetString(PyExc_RuntimeError,
                        "native join pass failed after state mutation");
    }
    Py_XDECREF(lseq);
    Py_XDECREF(rseq);
    Py_XDECREF(ljks);
    Py_XDECREF(rjks);
    Py_XDECREF(dirty);
    Py_XDECREF(dirty_list);
    Py_XDECREF(old_blocks);
    Py_XDECREF(C.lnone);
    Py_XDECREF(C.rnone);
    Py_XDECREF(out);
    return nullptr;
}

PyObject* py_vm_filter_batch(PyObject*, PyObject* args) {
    // (batch, prog_capsule, error_obj) -> surviving updates unchanged.
    // Drop semantics mirror FilterNode/filter_batch: raising rows, None,
    // and ERROR all drop; anything else keeps by truthiness.
    PyObject *batch, *cap, *error_obj;
    if (!PyArg_ParseTuple(args, "OOO", &batch, &cap, &error_obj))
        return nullptr;
    VmProgram* P = vm_from_capsule(cap);
    if (P == nullptr) return nullptr;
    PyObject* seq =
        PySequence_Fast(batch, "vm_filter_batch expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(0);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    std::vector<PyObject*> stack(P->max_stack);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            goto fail;
        }
        {
            PyObject* kv = nullptr;
            PyObject* r = vm_eval(P, PyTuple_GET_ITEM(u, 0),
                                  PyTuple_GET_ITEM(u, 1), error_obj, &kv,
                                  stack);
            Py_XDECREF(kv);
            if (r == nullptr) {
                if (!PyErr_ExceptionMatches(PyExc_Exception)) goto fail;
                PyErr_Clear();
                continue;  // raising predicate: drop the row
            }
            if (r == Py_None || r == error_obj) {
                Py_DECREF(r);
                continue;
            }
            int truthy = PyObject_IsTrue(r);
            Py_DECREF(r);
            if (truthy < 0) goto fail;
            if (truthy && PyList_Append(out, u) < 0) goto fail;
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

// ===========================================================================
// HNSW graph ANN index
//
// Host-side hierarchical navigable small-world index, the role of the
// reference's usearch integration
// (src/external_integration/usearch_integration.rs:1-163): greedy
// multi-layer descent + ef-bounded best-first search on layer 0, Malkov
// neighbor-selection heuristic, tombstone removals with slot reuse.
// The pointer-chasing walk is hostile to XLA, so unlike the brute-force
// and IVF indexes this one lives entirely on the host — in C++, since a
// per-hop Python interpreter step would dominate the traversal.
// Vectors are float32, contiguous; cos uses pre-normalized vectors with
// distance = -dot (the Python wrapper normalizes).

struct HnswIndex {
    int dim, M, M0, efc, metric;  // metric: 0 ip (-dot; cos = normalized ip), 1 l2sq
    //: add/search/remove release the GIL around the graph work; this
    //: mutex is what actually serializes them (search mutates the
    //: visited stamps too, so even concurrent reads need it)
    std::mutex mu;
    double inv_log_m;
    std::vector<float> vecs;                             // slot*dim
    std::vector<int> levels;                             // per slot
    std::vector<std::vector<std::vector<uint32_t>>> links;  // slot -> level -> ids
    std::vector<uint8_t> alive;
    std::vector<uint32_t> freelist;
    std::vector<uint32_t> visited_stamp;
    uint32_t stamp = 0;
    int64_t entry = -1;
    int max_level = -1;
    size_t n_alive = 0;
    uint64_t rng = 0x9e3779b97f4a7c15ULL;

    float dist(const float* a, const float* b) const {
        float acc = 0.f;
        if (metric == 1) {
            for (int i = 0; i < dim; i++) {
                float d = a[i] - b[i];
                acc += d * d;
            }
            return acc;
        }
        for (int i = 0; i < dim; i++) acc += a[i] * b[i];
        return -acc;
    }
    const float* vec(uint32_t s) const { return vecs.data() + (size_t)s * dim; }
    uint64_t next_rand() {
        rng ^= rng << 13;
        rng ^= rng >> 7;
        rng ^= rng << 17;
        return rng;
    }
    int random_level() {
        double u = ((next_rand() >> 11) + 1) * (1.0 / 9007199254740992.0);
        int l = (int)(-std::log(u) * inv_log_m);
        return l < 32 ? l : 32;
    }
    bool visit(uint32_t s) {  // true if first visit this query
        if (visited_stamp.size() < levels.size())
            visited_stamp.resize(levels.size(), 0);
        if (visited_stamp[s] == stamp) return false;
        visited_stamp[s] = stamp;
        return true;
    }
};

void hnsw_capsule_free(PyObject* cap) {
    delete static_cast<HnswIndex*>(
        PyCapsule_GetPointer(cap, "pathway_tpu.hnsw"));
}

inline HnswIndex* hnsw_from_capsule(PyObject* cap) {
    return static_cast<HnswIndex*>(
        PyCapsule_GetPointer(cap, "pathway_tpu.hnsw"));
}

using DistSlot = std::pair<float, uint32_t>;  // (distance, slot)

// best-first search on one layer; returns up to ef closest (sorted asc)
void hnsw_search_layer(HnswIndex& H, const float* q, uint32_t start, int ef,
                       int level, std::vector<DistSlot>& out) {
    H.stamp++;
    std::priority_queue<DistSlot, std::vector<DistSlot>,
                        std::greater<DistSlot>>
        cand;  // min-heap by distance
    std::priority_queue<DistSlot> best;  // max-heap by distance
    float d0 = H.dist(q, H.vec(start));
    H.visit(start);
    cand.push({d0, start});
    best.push({d0, start});
    while (!cand.empty()) {
        DistSlot c = cand.top();
        if (c.first > best.top().first && (int)best.size() >= ef) break;
        cand.pop();
        if ((int)H.links[c.second].size() <= level) continue;
        for (uint32_t nb : H.links[c.second][level]) {
            if (!H.visit(nb)) continue;
            float d = H.dist(q, H.vec(nb));
            if ((int)best.size() < ef || d < best.top().first) {
                cand.push({d, nb});
                best.push({d, nb});
                if ((int)best.size() > ef) best.pop();
            }
        }
    }
    out.clear();
    out.resize(best.size());
    for (size_t i = best.size(); i-- > 0;) {
        out[i] = best.top();
        best.pop();
    }
}

// Malkov heuristic: keep a candidate only if it is closer to q than to
// every already-selected neighbor (diversity), up to M
void hnsw_select_neighbors(HnswIndex& H, const float* q,
                           const std::vector<DistSlot>& cand, int M,
                           std::vector<uint32_t>& out) {
    out.clear();
    for (const auto& c : cand) {
        if ((int)out.size() >= M) break;
        bool good = true;
        for (uint32_t s : out) {
            if (H.dist(H.vec(c.second), H.vec(s)) < c.first) {
                good = false;
                break;
            }
        }
        if (good) out.push_back(c.second);
    }
    // backfill with closest skipped candidates if diversity starved us
    if ((int)out.size() < M) {
        for (const auto& c : cand) {
            if ((int)out.size() >= M) break;
            if (std::find(out.begin(), out.end(), c.second) == out.end())
                out.push_back(c.second);
        }
    }
}

void hnsw_prune(HnswIndex& H, uint32_t s, int level, int cap) {
    auto& lst = H.links[s][level];
    if ((int)lst.size() <= cap) return;
    std::vector<DistSlot> cand;
    cand.reserve(lst.size());
    for (uint32_t nb : lst) cand.push_back({H.dist(H.vec(s), H.vec(nb)), nb});
    std::sort(cand.begin(), cand.end());
    std::vector<uint32_t> kept;
    hnsw_select_neighbors(H, H.vec(s), cand, cap, kept);
    lst = std::move(kept);
}

uint32_t hnsw_insert(HnswIndex& H, const float* v) {
    uint32_t slot;
    bool reused = false;
    if (!H.freelist.empty()) {
        // hnswlib-style update-in-place: the tombstone's old links are
        // KEPT (they may be the only bridges through its neighborhood —
        // clearing them measurably disconnects the graph under churn)
        // and the fresh links from the normal insert procedure are
        // merged in below, with pruning gradually retiring the
        // wrong-distance old edges.
        slot = H.freelist.back();
        H.freelist.pop_back();
        reused = !H.links[slot].empty();
        std::copy(v, v + H.dim, H.vecs.begin() + (size_t)slot * H.dim);
        H.alive[slot] = 1;
        if (H.entry == (int64_t)slot) {
            // the reused slot WAS the (tombstoned) entry: the insert
            // below must not greedy-start from the node being inserted.
            // Re-seed the entry with the highest-level other node.
            int64_t other = -1;
            int best = -1;
            for (size_t i = 0; i < H.levels.size(); i++) {
                if (i == (size_t)slot) continue;
                int lv = (int)H.links[i].size() - 1;
                if (lv > best) {
                    best = lv;
                    other = (int64_t)i;
                }
            }
            H.entry = other;
            H.max_level = best < 0 ? -1 : best;
        }
    } else {
        slot = (uint32_t)H.levels.size();
        H.vecs.insert(H.vecs.end(), v, v + H.dim);
        H.levels.push_back(0);
        H.links.emplace_back();
        H.alive.push_back(1);
    }
    int level = H.random_level();
    if (reused)  // keep the inherited high-level edges reachable
        level = std::max(level, (int)H.links[slot].size() - 1);
    H.levels[slot] = level;
    H.links[slot].resize(level + 1);
    H.n_alive++;
    if (H.entry < 0) {
        H.entry = slot;
        H.max_level = level;
        return slot;
    }
    uint32_t cur = (uint32_t)H.entry;
    float dcur = H.dist(v, H.vec(cur));
    for (int l = H.max_level; l > level; l--) {
        bool moved = true;
        while (moved) {
            moved = false;
            if ((int)H.links[cur].size() <= l) break;
            for (uint32_t nb : H.links[cur][l]) {
                float d = H.dist(v, H.vec(nb));
                if (d < dcur) {
                    dcur = d;
                    cur = nb;
                    moved = true;
                }
            }
        }
    }
    std::vector<DistSlot> cand;
    std::vector<uint32_t> sel;
    for (int l = std::min(level, H.max_level); l >= 0; l--) {
        hnsw_search_layer(H, v, cur, H.efc, l, cand);
        if (reused) {
            // the node under (re)insertion is itself reachable through
            // its inherited in/out edges — it must not self-select
            cand.erase(std::remove_if(cand.begin(), cand.end(),
                                      [slot](const DistSlot& c) {
                                          return c.second == slot;
                                      }),
                       cand.end());
            if (cand.empty()) continue;
        }
        int cap = l == 0 ? H.M0 : H.M;
        hnsw_select_neighbors(H, v, cand, cap, sel);
        auto& own = H.links[slot][l];
        for (uint32_t nb : sel)
            if (std::find(own.begin(), own.end(), nb) == own.end())
                own.push_back(nb);
        hnsw_prune(H, slot, l, cap);
        for (uint32_t nb : sel) {
            if ((int)H.links[nb].size() <= l) H.links[nb].resize(l + 1);
            auto& lnb = H.links[nb][l];
            if (std::find(lnb.begin(), lnb.end(), slot) == lnb.end())
                lnb.push_back(slot);
            hnsw_prune(H, nb, l, l == 0 ? H.M0 : H.M);
        }
        if (!cand.empty()) cur = cand[0].second;
    }
    if (level > H.max_level) {
        H.max_level = level;
        H.entry = slot;
    }
    return slot;
}

PyObject* py_hnsw_new(PyObject*, PyObject* args) {
    // (dim, M, ef_construction, metric:int 0 ip | 1 l2sq) -> capsule
    long long dim, M, efc, metric;
    if (!PyArg_ParseTuple(args, "LLLL", &dim, &M, &efc, &metric))
        return nullptr;
    if (dim <= 0 || M < 2 || efc < M || (metric != 0 && metric != 1)) {
        PyErr_SetString(PyExc_ValueError, "bad HNSW parameters");
        return nullptr;
    }
    auto* H = new HnswIndex();
    H->dim = (int)dim;
    H->M = (int)M;
    H->M0 = (int)(2 * M);
    H->efc = (int)efc;
    H->metric = (int)metric;
    H->inv_log_m = 1.0 / std::log((double)M);
    return PyCapsule_New(H, "pathway_tpu.hnsw", hnsw_capsule_free);
}

// parse a C-contiguous float32 (n, dim) buffer
int hnsw_get_matrix(PyObject* obj, int dim, Py_buffer* view,
                    Py_ssize_t* n_out) {
    if (PyObject_GetBuffer(obj, view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0)
        return -1;
    bool f32 = view->format == nullptr || strcmp(view->format, "f") == 0;
    if (!f32 || view->itemsize != 4 || view->len % (dim * 4) != 0) {
        PyBuffer_Release(view);
        PyErr_SetString(PyExc_TypeError,
                        "expected C-contiguous float32 (n, dim) buffer");
        return -1;
    }
    *n_out = view->len / (dim * 4);
    return 0;
}

PyObject* py_hnsw_add(PyObject*, PyObject* args) {
    // (capsule, float32 (n, dim) buffer) -> list of assigned slots
    PyObject *cap, *buf;
    if (!PyArg_ParseTuple(args, "OO", &cap, &buf)) return nullptr;
    HnswIndex* H = hnsw_from_capsule(cap);
    if (H == nullptr) return nullptr;
    Py_buffer view;
    Py_ssize_t n;
    if (hnsw_get_matrix(buf, H->dim, &view, &n) < 0) return nullptr;
    std::vector<uint32_t> slots((size_t)n);
    const float* data = static_cast<const float*>(view.buf);
    Py_BEGIN_ALLOW_THREADS;
    {
        std::lock_guard<std::mutex> lock(H->mu);
        for (Py_ssize_t i = 0; i < n; i++)
            slots[(size_t)i] = hnsw_insert(*H, data + (size_t)i * H->dim);
    }
    Py_END_ALLOW_THREADS;
    PyBuffer_Release(&view);
    PyObject* out = PyList_New(n);
    if (out == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* v = PyLong_FromUnsignedLong(slots[(size_t)i]);
        if (v == nullptr) {
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, v);
    }
    return out;
}

PyObject* py_hnsw_remove(PyObject*, PyObject* args) {
    // (capsule, iterable of slots) — tombstone + slot reuse
    PyObject *cap, *slots_obj;
    if (!PyArg_ParseTuple(args, "OO", &cap, &slots_obj)) return nullptr;
    HnswIndex* H = hnsw_from_capsule(cap);
    if (H == nullptr) return nullptr;
    PyObject* seq = PySequence_Fast(slots_obj, "hnsw_remove expects slots");
    if (seq == nullptr) return nullptr;
    {
        // serialize against GIL-released add/search; safe to hold with
        // the GIL because mutex holders never ACQUIRE the GIL themselves
        std::lock_guard<std::mutex> lock(H->mu);
        for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); i++) {
            long long s = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(seq, i));
            if (s == -1 && PyErr_Occurred()) {
                Py_DECREF(seq);
                return nullptr;
            }
            if (s < 0 || (size_t)s >= H->alive.size() || !H->alive[(size_t)s])
                continue;
            H->alive[(size_t)s] = 0;
            H->freelist.push_back((uint32_t)s);
            H->n_alive--;
        }
        if (H->n_alive == 0) {  // empty graph: full reset
            H->vecs.clear();
            H->levels.clear();
            H->links.clear();
            H->alive.clear();
            H->freelist.clear();
            H->entry = -1;
            H->max_level = -1;
        }
    }
    Py_DECREF(seq);
    Py_RETURN_NONE;
}

PyObject* py_hnsw_search(PyObject*, PyObject* args) {
    // (capsule, float32 (nq, dim) buffer, k, ef) -> list of
    // ([slots...], [dists...]) per query; tombstones excluded
    PyObject *cap, *buf;
    long long k, ef;
    if (!PyArg_ParseTuple(args, "OOLL", &cap, &buf, &k, &ef)) return nullptr;
    HnswIndex* H = hnsw_from_capsule(cap);
    if (H == nullptr) return nullptr;
    Py_buffer view;
    Py_ssize_t nq;
    if (hnsw_get_matrix(buf, H->dim, &view, &nq) < 0) return nullptr;
    const float* data = static_cast<const float*>(view.buf);
    int eff_ef = (int)std::max(ef, k);
    std::vector<std::vector<DistSlot>> results((size_t)nq);
    Py_BEGIN_ALLOW_THREADS;
    // inner scope: the mutex MUST release before Py_END reacquires the
    // GIL, or a GIL-holding caller blocked on the mutex deadlocks us
    {
    std::lock_guard<std::mutex> lock(H->mu);
    for (Py_ssize_t qi = 0; qi < nq; qi++) {
        if (H->entry < 0) continue;
        const float* q = data + (size_t)qi * H->dim;
        uint32_t cur = (uint32_t)H->entry;
        float dcur = H->dist(q, H->vec(cur));
        for (int l = H->max_level; l > 0; l--) {
            bool moved = true;
            while (moved) {
                moved = false;
                if ((int)H->links[cur].size() <= l) break;
                for (uint32_t nb : H->links[cur][l]) {
                    float d = H->dist(q, H->vec(nb));
                    if (d < dcur) {
                        dcur = d;
                        cur = nb;
                        moved = true;
                    }
                }
            }
        }
        std::vector<DistSlot> found;
        // tombstones participate in traversal but not in results; a
        // bounded slack absorbs light churn, and the Python wrapper
        // retries with a larger ef if survivors run short
        int fetch = eff_ef + std::min((int)(H->alive.size() - H->n_alive),
                                      eff_ef);
        if (fetch > (int)H->levels.size()) fetch = (int)H->levels.size();
        hnsw_search_layer(*H, q, cur, fetch, 0, found);
        auto& out = results[(size_t)qi];
        for (const auto& ds : found) {
            if (!H->alive[ds.second]) continue;
            out.push_back(ds);
            if ((int)out.size() >= k) break;
        }
    }
    }  // mutex released here, before the GIL reacquire below
    Py_END_ALLOW_THREADS;
    PyBuffer_Release(&view);
    PyObject* out = PyList_New(nq);
    if (out == nullptr) return nullptr;
    for (Py_ssize_t qi = 0; qi < nq; qi++) {
        const auto& r = results[(size_t)qi];
        PyObject* ids = PyList_New((Py_ssize_t)r.size());
        PyObject* ds = PyList_New((Py_ssize_t)r.size());
        PyObject* pair = (ids && ds) ? PyTuple_Pack(2, ids, ds) : nullptr;
        Py_XDECREF(ids);
        Py_XDECREF(ds);
        if (pair == nullptr) {
            Py_DECREF(out);
            return nullptr;
        }
        for (size_t j = 0; j < r.size(); j++) {
            PyObject* i_ = PyLong_FromUnsignedLong(r[j].second);
            PyObject* d_ = PyFloat_FromDouble((double)r[j].first);
            if (i_ == nullptr || d_ == nullptr) {
                Py_XDECREF(i_);
                Py_XDECREF(d_);
                Py_DECREF(pair);
                Py_DECREF(out);
                return nullptr;
            }
            PyList_SET_ITEM(ids, (Py_ssize_t)j, i_);
            PyList_SET_ITEM(ds, (Py_ssize_t)j, d_);
        }
        PyList_SET_ITEM(out, qi, pair);
    }
    return out;
}

PyObject* py_hnsw_len(PyObject*, PyObject* cap) {
    HnswIndex* H = hnsw_from_capsule(cap);
    if (H == nullptr) return nullptr;
    return PyLong_FromSize_t(H->n_alive);
}

// ---------------------------------------------------------------------------
// Binary update framing for the inter-process exchange.
//
// The reference exchanges rows between worker processes as typed binary
// frames (timely's exchange channels serialize records with abomonation,
// external/timely-dataflow/communication/); the first TPU-build cluster
// shipped pickled (key, values, diff) lists instead, which made the
// 2-process wordcount *slower* than 1 process: pickling a Pointer
// int-subclass goes through copyreg per object, and the receive side
// rebuilt Update/Pointer objects in a per-row Python loop.  pack_updates
// / unpack_updates replace that with a tagged-scalar wire format written
// and parsed entirely in C++: 16 bytes of key, a zigzag-varint diff, and
// one tag byte per value (int64 / double / utf8 / bytes / bool / None /
// Pointer / nested tuple); anything outside the tag set (datetime,
// ndarray, Json, wrapped objects) is embedded as a single-object pickle,
// so the frame is always complete.

PyObject* g_update_type = nullptr;   // engine.stream.Update (NamedTuple)
PyObject* g_pickle_dumps = nullptr;  // pickle.dumps / loads for the
PyObject* g_pickle_loads = nullptr;  // out-of-tag-set value fallback

PyObject* py_set_update_type(PyObject*, PyObject* cls) {
    Py_XDECREF(g_update_type);
    Py_INCREF(cls);
    g_update_type = cls;
    if (g_pickle_dumps == nullptr) {
        PyObject* pickle = PyImport_ImportModule("pickle");
        if (pickle == nullptr) return nullptr;
        g_pickle_dumps = PyObject_GetAttrString(pickle, "dumps");
        g_pickle_loads = PyObject_GetAttrString(pickle, "loads");
        Py_DECREF(pickle);
        if (g_pickle_dumps == nullptr || g_pickle_loads == nullptr)
            return nullptr;
    }
    Py_RETURN_NONE;
}

enum : uint8_t {
    WT_NONE = 0,
    WT_TRUE = 1,
    WT_FALSE = 2,
    WT_I64 = 3,     // 8 bytes LE
    WT_F64 = 4,     // 8 bytes LE
    WT_STR = 5,     // u32 len + utf8
    WT_BYTES = 6,   // u32 len + raw
    WT_POINTER = 7, // u8 len + unsigned LE
    WT_TUPLE = 8,   // u8 arity + nested values
    WT_PICKLE = 9,  // u32 len + pickle bytes
    WT_STRREF = 10, // varint index into the frame's string table
};

// Per-frame string interning: group/join key columns repeat a small
// vocabulary across millions of rows, so the second and later
// occurrences of a string in a frame encode as a 1-2 byte table ref and
// decode as an INCREF of the already-built object (no UTF-8 decode, no
// allocation).  The table is IMPLICIT: both sides append every WT_STR
// they see (short ones, while there is room), so the wire carries no
// table section and a frame without refs is byte-identical to the
// pre-STRREF format.  The persistence codec (pack_kv) packs with
// interning disabled — snapshot bytes stay stable — but its decoder
// shares this logic and accepts refs regardless.
constexpr size_t kWfInternCap = 1 << 16;
constexpr size_t kWfInternMaxLen = 255;  // intern short strings only

struct WfIntern {
    std::unordered_map<std::string, uint32_t> map;
};

inline void wf_put_u32(std::string& b, uint32_t v) {
    b.append(reinterpret_cast<const char*>(&v), 4);
}
inline void wf_put_u64(std::string& b, uint64_t v) {
    b.append(reinterpret_cast<const char*>(&v), 8);
}
inline void wf_put_varint(std::string& b, long long sv) {
    // zigzag + LEB128 (diffs are almost always ±1: one byte)
    unsigned long long v =
        (static_cast<unsigned long long>(sv) << 1) ^
        static_cast<unsigned long long>(sv >> 63);
    while (v >= 0x80) {
        b.push_back(static_cast<char>(v | 0x80));
        v >>= 7;
    }
    b.push_back(static_cast<char>(v));
}

bool wf_pack_value(std::string& buf, PyObject* v,
                   WfIntern* intern);  // fwd (tuples recurse)

// u32 length fields cap any single value at 4 GiB; bigger ones abort the
// pack (the cluster layer falls back to whole-frame pickle) instead of
// writing a silently corrupt frame
constexpr size_t kWfMaxLen = 0xFFFFFFFFu;

bool wf_pack_pickled(std::string& buf, PyObject* v) {
    if (g_pickle_dumps == nullptr) {
        PyErr_SetString(PyExc_RuntimeError,
                        "pack_updates: pickle fallback unregistered");
        return false;
    }
    PyObject* data = PyObject_CallFunctionObjArgs(g_pickle_dumps, v, nullptr);
    if (data == nullptr) return false;
    char* p;
    Py_ssize_t n;
    if (PyBytes_AsStringAndSize(data, &p, &n) < 0) {
        Py_DECREF(data);
        return false;
    }
    if (static_cast<size_t>(n) > kWfMaxLen) {
        Py_DECREF(data);
        PyErr_SetString(PyExc_ValueError, "value too large for update frame");
        return false;
    }
    buf.push_back(static_cast<char>(WT_PICKLE));
    wf_put_u32(buf, static_cast<uint32_t>(n));
    buf.append(p, static_cast<size_t>(n));
    Py_DECREF(data);
    return true;
}

bool wf_pack_value(std::string& buf, PyObject* v, WfIntern* intern) {
    if (v == Py_None) {
        buf.push_back(static_cast<char>(WT_NONE));
    } else if (v == Py_True) {
        buf.push_back(static_cast<char>(WT_TRUE));
    } else if (v == Py_False) {
        buf.push_back(static_cast<char>(WT_FALSE));
    } else if (g_pointer_type != nullptr &&
               PyObject_TypeCheck(
                   v, reinterpret_cast<PyTypeObject*>(g_pointer_type))) {
        uint8_t kb[16];
        if (pt_long_as_bytes_unsigned(v, kb, sizeof kb) < 0) {
            PyErr_Clear();
            return wf_pack_pickled(buf, v);
        }
        buf.push_back(static_cast<char>(WT_POINTER));
        buf.push_back(static_cast<char>(sizeof kb));
        buf.append(reinterpret_cast<const char*>(kb), sizeof kb);
    } else if (PyLong_CheckExact(v)) {
        int overflow = 0;
        long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow != 0 || (x == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            return wf_pack_pickled(buf, v);  // >64-bit int: rare
        }
        buf.push_back(static_cast<char>(WT_I64));
        wf_put_u64(buf, static_cast<uint64_t>(x));
    } else if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        buf.push_back(static_cast<char>(WT_F64));
        uint64_t bits;
        std::memcpy(&bits, &d, 8);
        wf_put_u64(buf, bits);
    } else if (PyUnicode_CheckExact(v)) {
        Py_ssize_t n;
        const char* s = PyUnicode_AsUTF8AndSize(v, &n);
        if (s == nullptr) return false;
        if (static_cast<size_t>(n) > kWfMaxLen) {
            PyErr_SetString(PyExc_ValueError,
                            "value too large for update frame");
            return false;
        }
        if (intern != nullptr && static_cast<size_t>(n) <= kWfInternMaxLen) {
            // the decoder appends the same strings to its table in the
            // same order, so the insert-on-first-sight protocol below
            // must stay byte-symmetric with the WT_STR decode path
            std::string k(s, static_cast<size_t>(n));
            auto it = intern->map.find(k);
            if (it != intern->map.end()) {
                buf.push_back(static_cast<char>(WT_STRREF));
                wf_put_varint(buf, it->second);
                return true;
            }
            if (intern->map.size() < kWfInternCap) {
                intern->map.emplace(
                    std::move(k),
                    static_cast<uint32_t>(intern->map.size()));
            }
        }
        buf.push_back(static_cast<char>(WT_STR));
        wf_put_u32(buf, static_cast<uint32_t>(n));
        buf.append(s, static_cast<size_t>(n));
    } else if (PyBytes_CheckExact(v)) {
        char* p;
        Py_ssize_t n;
        if (PyBytes_AsStringAndSize(v, &p, &n) < 0) return false;
        if (static_cast<size_t>(n) > kWfMaxLen) {
            PyErr_SetString(PyExc_ValueError,
                            "value too large for update frame");
            return false;
        }
        buf.push_back(static_cast<char>(WT_BYTES));
        wf_put_u32(buf, static_cast<uint32_t>(n));
        buf.append(p, static_cast<size_t>(n));
    } else if (PyTuple_CheckExact(v) && PyTuple_GET_SIZE(v) < 255) {
        buf.push_back(static_cast<char>(WT_TUPLE));
        buf.push_back(static_cast<char>(PyTuple_GET_SIZE(v)));
        for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(v); i++) {
            if (!wf_pack_value(buf, PyTuple_GET_ITEM(v, i), intern))
                return false;
        }
    } else {
        return wf_pack_pickled(buf, v);  // datetime/ndarray/Json/...
    }
    return true;
}

// shared row codec: 16-byte key + count byte + tagged values (0xFF =
// whole-values pickle).  Both frame formats (updates, kv pairs) are this
// row plus format-specific fields, so there is exactly ONE copy of the
// value-encoding logic.
bool wf_pack_row(std::string& buf, PyObject* key, PyObject* values,
                 WfIntern* intern) {
    uint8_t kb[16];
    if (pt_long_as_bytes_unsigned(key, kb, sizeof kb) < 0) {
        // 3.13+ reports too-large keys without raising; keys are 128-bit
        // by contract so surface a clean error either way
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_ValueError, "key does not fit 16 bytes");
        return false;
    }
    buf.append(reinterpret_cast<const char*>(kb), sizeof kb);
    if (PyTuple_CheckExact(values) && PyTuple_GET_SIZE(values) < 255) {
        buf.push_back(static_cast<char>(PyTuple_GET_SIZE(values)));
        for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(values); j++) {
            if (!wf_pack_value(buf, PyTuple_GET_ITEM(values, j), intern))
                return false;
        }
        return true;
    }
    buf.push_back(static_cast<char>(0xFF));
    return wf_pack_pickled(buf, values);
}


// shared frame encoder: appends [u32 count] rows to `buf`; false with
// exception set on failure (buf may hold a torn frame — callers discard)
bool wf_pack_updates_frame(std::string& buf, PyObject* batch,
                           WfIntern* intern) {
    PyObject* seq = PySequence_Fast(batch, "pack_updates expects a sequence");
    if (seq == nullptr) return false;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if (buf.capacity() - buf.size() < static_cast<size_t>(n) * 48 + 8)
        buf.reserve(buf.size() + static_cast<size_t>(n) * 48 + 8);
    wf_put_u32(buf, static_cast<uint32_t>(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            Py_DECREF(seq);
            return false;
        }
        if (!wf_pack_row(buf, PyTuple_GET_ITEM(u, 0),
                         PyTuple_GET_ITEM(u, 1), intern)) {
            Py_DECREF(seq);
            return false;
        }
        long long d = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
        if (d == -1 && PyErr_Occurred()) {
            Py_DECREF(seq);
            return false;
        }
        wf_put_varint(buf, d);
    }
    Py_DECREF(seq);
    return true;
}

PyObject* py_pack_updates(PyObject*, PyObject* batch) {
    std::string buf;
    WfIntern intern;
    if (!wf_pack_updates_frame(buf, batch, &intern)) return nullptr;
    return PyBytes_FromStringAndSize(buf.data(),
                                     static_cast<Py_ssize_t>(buf.size()));
}

PyObject* py_pack_updates_into(PyObject*, PyObject* args) {
    // pack_updates_into(batch, bytearray) -> appended byte count.  The
    // cluster sender threads build one coalesced transmission per peer by
    // appending frames straight into a reusable bytearray; the scratch
    // string is thread-local so its capacity persists across epochs (no
    // per-epoch allocation churn on the exchange hot path).
    PyObject* batch;
    PyObject* target;
    if (!PyArg_ParseTuple(args, "OO!:pack_updates_into", &batch,
                          &PyByteArray_Type, &target))
        return nullptr;
    static thread_local std::string buf;
    static thread_local WfIntern intern;
    buf.clear();
    // the string table is scoped to ONE frame (each frame in a coalesced
    // transmission decodes with its own fresh reader), so the map resets
    // per call even though its buckets persist for reuse
    intern.map.clear();
    if (!wf_pack_updates_frame(buf, batch, &intern)) return nullptr;
    Py_ssize_t at = PyByteArray_GET_SIZE(target);
    if (PyByteArray_Resize(target, at + static_cast<Py_ssize_t>(buf.size())) <
        0)
        return nullptr;
    std::memcpy(PyByteArray_AS_STRING(target) + at, buf.data(), buf.size());
    return PyLong_FromSsize_t(static_cast<Py_ssize_t>(buf.size()));
}

struct WfReader {
    const uint8_t* p;
    const uint8_t* end;
    bool fail = false;
    // frame string table: borrowed refs to strings decoded so far (the
    // built rows own them; decode errors abort the whole frame, so an
    // entry can never dangle while the reader is live).  Mirrors the
    // encoder's insert-on-first-sight protocol exactly.
    std::vector<PyObject*> strtab;

    bool need(size_t n) {
        // sticky: a failed length read must poison the zero-length
        // bytes() that follows it, or truncated frames decode as ''
        if (fail || static_cast<size_t>(end - p) < n) {
            fail = true;
            return false;
        }
        return true;
    }
    uint32_t u32() {
        if (!need(4)) return 0;
        uint32_t v;
        std::memcpy(&v, p, 4);
        p += 4;
        return v;
    }
    uint64_t u64() {
        if (!need(8)) return 0;
        uint64_t v;
        std::memcpy(&v, p, 8);
        p += 8;
        return v;
    }
    uint8_t u8() {
        if (!need(1)) return 0;
        return *p++;
    }
    long long varint() {
        unsigned long long v = 0;
        int shift = 0;
        while (true) {
            if (!need(1)) return 0;
            uint8_t b = *p++;
            v |= static_cast<unsigned long long>(b & 0x7F) << shift;
            if ((b & 0x80) == 0) break;
            shift += 7;
            if (shift > 63) {
                fail = true;
                return 0;
            }
        }
        return static_cast<long long>(v >> 1) ^
               -static_cast<long long>(v & 1);
    }
    const uint8_t* bytes(size_t n) {
        if (!need(n)) return nullptr;
        const uint8_t* q = p;
        p += n;
        return q;
    }
};

PyObject* wf_unpack_value(WfReader& r) {
    uint8_t tag = r.u8();
    if (r.fail) {
        PyErr_SetString(PyExc_ValueError, "truncated update frame");
        return nullptr;
    }
    switch (tag) {
        case WT_NONE:
            Py_RETURN_NONE;
        case WT_TRUE:
            Py_RETURN_TRUE;
        case WT_FALSE:
            Py_RETURN_FALSE;
        case WT_I64: {
            uint64_t v = r.u64();
            if (r.fail) break;
            return PyLong_FromLongLong(static_cast<long long>(v));
        }
        case WT_F64: {
            uint64_t bits = r.u64();
            if (r.fail) break;
            double d;
            std::memcpy(&d, &bits, 8);
            return PyFloat_FromDouble(d);
        }
        case WT_STR: {
            uint32_t n = r.u32();
            const uint8_t* s = r.bytes(n);
            if (s == nullptr) break;
            PyObject* str = PyUnicode_DecodeUTF8(
                reinterpret_cast<const char*>(s),
                static_cast<Py_ssize_t>(n), nullptr);
            // condition must match the encoder's intern gate exactly or
            // the two sides' table indices diverge silently
            if (str != nullptr && n <= kWfInternMaxLen &&
                r.strtab.size() < kWfInternCap)
                r.strtab.push_back(str);  // borrowed; rows own it
            return str;
        }
        case WT_STRREF: {
            uint64_t idx = r.varint();
            if (r.fail) break;
            if (idx >= r.strtab.size()) {
                PyErr_SetString(PyExc_ValueError,
                                "bad string ref in frame");
                return nullptr;
            }
            PyObject* str = r.strtab[static_cast<size_t>(idx)];
            Py_INCREF(str);
            return str;
        }
        case WT_BYTES: {
            uint32_t n = r.u32();
            const uint8_t* s = r.bytes(n);
            if (s == nullptr) break;
            return PyBytes_FromStringAndSize(
                reinterpret_cast<const char*>(s), static_cast<Py_ssize_t>(n));
        }
        case WT_POINTER: {
            uint8_t klen = r.u8();
            const uint8_t* kb = r.bytes(klen);
            if (kb == nullptr) break;
            PyObject* num = pt_long_from_bytes_unsigned(kb, klen);
            if (num == nullptr || g_pointer_type == nullptr) return num;
            return pointer_from_long(num);
        }
        case WT_TUPLE: {
            uint8_t arity = r.u8();
            if (r.fail) break;
            PyObject* t = PyTuple_New(arity);
            if (t == nullptr) return nullptr;
            for (uint8_t i = 0; i < arity; i++) {
                PyObject* item = wf_unpack_value(r);
                if (item == nullptr) {
                    Py_DECREF(t);
                    return nullptr;
                }
                PyTuple_SET_ITEM(t, i, item);
            }
            return t;
        }
        case WT_PICKLE: {
            uint32_t n = r.u32();
            const uint8_t* s = r.bytes(n);
            if (s == nullptr || g_pickle_loads == nullptr) break;
            PyObject* data = PyBytes_FromStringAndSize(
                reinterpret_cast<const char*>(s), static_cast<Py_ssize_t>(n));
            if (data == nullptr) return nullptr;
            PyObject* v =
                PyObject_CallFunctionObjArgs(g_pickle_loads, data, nullptr);
            Py_DECREF(data);
            return v;
        }
        default:
            PyErr_Format(PyExc_ValueError, "bad value tag %d in frame",
                         static_cast<int>(tag));
            return nullptr;
    }
    PyErr_SetString(PyExc_ValueError, "truncated update frame");
    return nullptr;
}

// returns new refs in *key_out / *values_out; false with exception set
bool wf_unpack_row(WfReader& r, PyObject** key_out, PyObject** values_out) {
    const uint8_t* kb = r.bytes(16);
    uint8_t nvals = r.u8();
    if (kb == nullptr || r.fail) {
        PyErr_SetString(PyExc_ValueError, "truncated row in frame");
        return false;
    }
    PyObject* values;
    if (nvals == 0xFF) {
        values = wf_unpack_value(r);  // whole-values pickle
    } else {
        values = PyTuple_New(nvals);
        for (uint8_t j = 0; values != nullptr && j < nvals; j++) {
            PyObject* v = wf_unpack_value(r);
            if (v == nullptr) {
                Py_DECREF(values);
                values = nullptr;
                break;
            }
            PyTuple_SET_ITEM(values, j, v);
        }
    }
    if (values == nullptr) return false;
    PyObject* num = pt_long_from_bytes_unsigned(kb, 16);
    if (num == nullptr) {
        Py_DECREF(values);
        return false;
    }
    PyObject* key = pointer_from_long(num);
    if (key == nullptr) {
        Py_DECREF(values);
        return false;
    }
    *key_out = key;
    *values_out = values;
    return true;
}

PyObject* py_unpack_updates(PyObject*, PyObject* arg) {
    // accepts any C-contiguous buffer (bytes, bytearray, memoryview): the
    // cluster reader threads decode frames from zero-copy slices of the
    // reusable receive buffer
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
    const char* data = static_cast<const char*>(view.buf);
    Py_ssize_t nbytes = view.len;
    if (g_update_type == nullptr || g_pointer_type == nullptr) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_RuntimeError,
                        "unpack_updates: Update/Pointer types unregistered");
        return nullptr;
    }
    WfReader r{reinterpret_cast<const uint8_t*>(data),
               reinterpret_cast<const uint8_t*>(data) + nbytes};
    uint32_t n = r.u32();
    if (r.fail) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "truncated update frame");
        return nullptr;
    }
    PyObject* out = PyList_New(static_cast<Py_ssize_t>(n));
    if (out == nullptr) {
        PyBuffer_Release(&view);
        return nullptr;
    }
    for (uint32_t i = 0; i < n; i++) {
        PyObject *key, *values;
        if (!wf_unpack_row(r, &key, &values)) goto fail;
        {
            long long diff = r.varint();
            if (r.fail) {
                Py_DECREF(key);
                Py_DECREF(values);
                PyErr_SetString(PyExc_ValueError, "truncated update frame");
                goto fail;
            }
            PyObject* dobj = PyLong_FromLongLong(diff);
            if (dobj == nullptr) {
                Py_DECREF(values);
                Py_DECREF(key);
                goto fail;
            }
            // Update is a NamedTuple whose generated __new__ is a Python
            // function — calling it per row costs more than the whole
            // parse.  It adds no state beyond the tuple items, so
            // allocate the tuple subclass directly (exactly what
            // tuple.__new__ does) and steal the refs.
            PyTypeObject* ut = reinterpret_cast<PyTypeObject*>(g_update_type);
            PyObject* u = ut->tp_alloc(ut, 3);
            if (u == nullptr) {
                Py_DECREF(values);
                Py_DECREF(key);
                Py_DECREF(dobj);
                goto fail;
            }
            PyTuple_SET_ITEM(u, 0, key);
            PyTuple_SET_ITEM(u, 1, values);
            PyTuple_SET_ITEM(u, 2, dobj);
            PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), u);
        }
    }
    PyBuffer_Release(&view);
    return out;
fail:
    PyBuffer_Release(&view);
    Py_DECREF(out);
    return nullptr;
}

PyObject* py_pack_kv(PyObject*, PyObject* rows) {
    // persistence "addmany" records: (key, values) pairs in the tagged
    // binary format (pickling 2M-row chunks costs a per-row listcomp +
    // int conversions; see persistence _RecordingEvents.add_many)
    PyObject* seq = PySequence_Fast(rows, "pack_kv expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    std::string buf;
    buf.reserve(static_cast<size_t>(n) * 40 + 8);
    wf_put_u32(buf, static_cast<uint32_t>(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* kv = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(kv) || PyTuple_GET_SIZE(kv) != 2) {
            PyErr_SetString(PyExc_TypeError, "rows must be (key, values)");
            Py_DECREF(seq);
            return nullptr;
        }
        // no interning: snapshot bytes must stay stable across releases
        // (the shared decoder accepts refs regardless)
        if (!wf_pack_row(buf, PyTuple_GET_ITEM(kv, 0),
                         PyTuple_GET_ITEM(kv, 1), nullptr)) {
            Py_DECREF(seq);
            return nullptr;
        }
    }
    Py_DECREF(seq);
    return PyBytes_FromStringAndSize(buf.data(),
                                     static_cast<Py_ssize_t>(buf.size()));
}

PyObject* py_unpack_kv(PyObject*, PyObject* arg) {
    char* data;
    Py_ssize_t nbytes;
    if (PyBytes_AsStringAndSize(arg, &data, &nbytes) < 0) return nullptr;
    if (g_pointer_type == nullptr) {
        PyErr_SetString(PyExc_RuntimeError, "Pointer type unregistered");
        return nullptr;
    }
    WfReader r{reinterpret_cast<const uint8_t*>(data),
               reinterpret_cast<const uint8_t*>(data) + nbytes};
    uint32_t n = r.u32();
    if (r.fail) {
        PyErr_SetString(PyExc_ValueError, "truncated kv frame");
        return nullptr;
    }
    PyObject* out = PyList_New(static_cast<Py_ssize_t>(n));
    if (out == nullptr) return nullptr;
    for (uint32_t i = 0; i < n; i++) {
        PyObject *key, *values;
        if (!wf_unpack_row(r, &key, &values)) goto fail;
        {
            PyObject* kv = PyTuple_New(2);
            if (kv == nullptr) {
                Py_DECREF(values);
                Py_DECREF(key);
                goto fail;
            }
            PyTuple_SET_ITEM(kv, 0, key);
            PyTuple_SET_ITEM(kv, 1, values);
            PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), kv);
        }
    }
    return out;
fail:
    Py_DECREF(out);
    return nullptr;
}

PyObject* py_capture_batch(PyObject*, PyObject* args) {
    // CaptureNode epoch pass: stream.append((key, values, time, diff))
    // and rows[key] = values / del rows[key] for every update, in one C
    // loop — the per-row Python version dominates capture-terminated
    // pipelines (the select+filter bench spent more time here than in
    // the expression VM).
    PyObject *stream, *rows, *batch, *time_obj;
    if (!PyArg_ParseTuple(args, "OOOO", &stream, &rows, &batch, &time_obj))
        return nullptr;
    if (!PyList_Check(stream) || !PyDict_Check(rows)) {
        PyErr_SetString(PyExc_TypeError, "capture state must be list+dict");
        return nullptr;
    }
    PyObject* seq = PySequence_Fast(batch, "capture expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            Py_DECREF(seq);
            return nullptr;
        }
        PyObject* key = PyTuple_GET_ITEM(u, 0);
        PyObject* values = PyTuple_GET_ITEM(u, 1);
        PyObject* diff = PyTuple_GET_ITEM(u, 2);
        PyObject* rec = PyTuple_Pack(4, key, values, time_obj, diff);
        if (rec == nullptr || PyList_Append(stream, rec) < 0) {
            Py_XDECREF(rec);
            Py_DECREF(seq);
            return nullptr;
        }
        Py_DECREF(rec);
        long long d = PyLong_AsLongLong(diff);
        if (d == -1 && PyErr_Occurred()) {
            Py_DECREF(seq);
            return nullptr;
        }
        if (d > 0) {
            if (PyDict_SetItem(rows, key, values) < 0) {
                Py_DECREF(seq);
                return nullptr;
            }
        } else {
            if (PyDict_DelItem(rows, key) < 0) PyErr_Clear();
        }
    }
    Py_DECREF(seq);
    Py_RETURN_NONE;
}

// ---- per-stage latency instrumentation -------------------------------
//
// Streaming-safe latency histograms for the event-driven scheduler:
// log-bucketed (8 sub-buckets per octave, ~12% resolution) so a
// long-running pipeline aggregates unbounded samples in fixed memory
// and p50/p95/p99 stay queryable at any moment.  Buckets are atomics:
// connector reader threads, worker threads and the monitoring server
// touch the same histogram concurrently.  The bucket function is
// mirrored by the Python fallback in internals/monitoring.py.

constexpr int kLatBuckets = 488;  // idx(2^62 ns) == 487

struct LatHist {
    std::atomic<uint64_t> buckets[kLatBuckets];
    std::atomic<uint64_t> count{0};
    std::atomic<int64_t> sum{0};
    std::atomic<int64_t> maxv{0};
    LatHist() {
        for (auto& b : buckets) b.store(0, std::memory_order_relaxed);
    }
};

inline int lat_bucket(int64_t ns) {
    if (ns < 16) return ns < 0 ? 0 : (int)ns;
    int msb = 63 - __builtin_clzll((uint64_t)ns);
    return 16 + (msb - 4) * 8 + (int)((ns >> (msb - 3)) & 7);
}

// geometric bucket midpoint (exact for the 16 unit buckets)
inline int64_t lat_bucket_rep(int idx) {
    if (idx < 16) return idx;
    int msb = 4 + (idx - 16) / 8;
    int sub = (idx - 16) % 8;
    int64_t lo = (1LL << msb) | ((int64_t)sub << (msb - 3));
    return lo + (1LL << (msb - 3)) / 2;
}

int64_t mono_ns_now() {
    return (int64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
}

void lat_hist_free(PyObject* cap) {
    delete static_cast<LatHist*>(
        PyCapsule_GetPointer(cap, "pathway_tpu.lathist"));
}

PyObject* py_monotonic_ns(PyObject*, PyObject*) {
    return PyLong_FromLongLong(mono_ns_now());
}

PyObject* py_hist_new(PyObject*, PyObject*) {
    return PyCapsule_New(new LatHist(), "pathway_tpu.lathist",
                         lat_hist_free);
}

inline LatHist* lat_hist_from(PyObject* cap) {
    return static_cast<LatHist*>(
        PyCapsule_GetPointer(cap, "pathway_tpu.lathist"));
}

PyObject* py_hist_record(PyObject*, PyObject* args) {
    PyObject* cap;
    long long ns;
    if (!PyArg_ParseTuple(args, "OL", &cap, &ns)) return nullptr;
    LatHist* h = lat_hist_from(cap);
    if (h == nullptr) return nullptr;
    if (ns < 0) ns = 0;
    h->buckets[lat_bucket(ns)].fetch_add(1, std::memory_order_relaxed);
    h->count.fetch_add(1, std::memory_order_relaxed);
    h->sum.fetch_add(ns, std::memory_order_relaxed);
    int64_t prev = h->maxv.load(std::memory_order_relaxed);
    while (ns > prev &&
           !h->maxv.compare_exchange_weak(prev, ns,
                                          std::memory_order_relaxed)) {
    }
    Py_RETURN_NONE;
}

PyObject* py_hist_snapshot(PyObject*, PyObject* args) {
    PyObject* cap;
    if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
    LatHist* h = lat_hist_from(cap);
    if (h == nullptr) return nullptr;
    uint64_t counts[kLatBuckets];
    uint64_t total = 0;
    for (int i = 0; i < kLatBuckets; i++) {
        counts[i] = h->buckets[i].load(std::memory_order_relaxed);
        total += counts[i];
    }
    int64_t sum = h->sum.load(std::memory_order_relaxed);
    int64_t maxv = h->maxv.load(std::memory_order_relaxed);
    const double qs[3] = {0.50, 0.95, 0.99};
    double out[3] = {0.0, 0.0, 0.0};
    if (total > 0) {
        for (int q = 0; q < 3; q++) {
            double target = qs[q] * (double)total;
            uint64_t cum = 0;
            for (int i = 0; i < kLatBuckets; i++) {
                cum += counts[i];
                if ((double)cum >= target && cum > 0) {
                    int64_t rep = lat_bucket_rep(i);
                    out[q] = (double)(rep < maxv ? rep : maxv);
                    break;
                }
            }
        }
    }
    return Py_BuildValue(
        "{s:K,s:L,s:L,s:d,s:d,s:d}", "count", (unsigned long long)total,
        "sum_ns", (long long)sum, "max_ns", (long long)maxv, "p50_ns",
        out[0], "p95_ns", out[1], "p99_ns", out[2]);
}

// --------------------------------------------------------------------------
// columnar epoch frames
//
// A Frame is one epoch delta held as contiguous typed columns plus an
// interned string pool — the role of the reference's batched
// arrangements (Rust differential operates on sorted (data, time, diff)
// batches, never on per-row boxed values).  Connectors build frames
// straight from the input bytes (frame_parse_jsonl), operators fold them
// with vectorized kernels (frame_groupby_partials, frame_route_split,
// frame_project, frame_filter), and the exchange layer ships the column
// buffers as one blob per (peer, slot) with a transmission-scoped string
// pool (frame_pack / frame_unpack).  Any value outside the typed set
// (nested tuples, ndarrays, ERROR sentinels, >64-bit ints) keeps the
// whole batch on the row-at-a-time path: frames are an optimization of
// REPRESENTATION only, every kernel is behaviour-identical to its row
// counterpart and Unsupported/None means "caller falls back".
//
// Keys carry a LAZY representation: connector rows are keyed as
// blake2b(prefix..., seq + offset) (see hash_prefix_ints), so a frame
// can hold just the prefix hash STATE plus the int64 seqs — 8 bytes a
// row instead of 16, and no per-row blake2b until something actually
// needs the digests (positional groupby/route never does).

enum FrameTag : uint8_t {
    CF_I64 = 1,
    CF_F64 = 2,
    CF_STR = 3,   // u32 index into the frame string pool
    CF_BOOL = 4,
};

struct FrameCol {
    uint8_t tag = 0;
    std::vector<int64_t> i64;
    std::vector<double> f64;
    std::vector<uint32_t> sidx;
    std::vector<uint8_t> b8;
    std::vector<uint8_t> valid;  // empty == every row valid (non-None)

    bool is_valid(size_t i) const { return valid.empty() || valid[i] != 0; }
    size_t length() const {
        switch (tag) {
            case CF_I64: return i64.size();
            case CF_F64: return f64.size();
            case CF_STR: return sidx.size();
            case CF_BOOL: return b8.size();
            default: return 0;
        }
    }
    void reserve(size_t n) {
        switch (tag) {
            case CF_I64: i64.reserve(n); break;
            case CF_F64: f64.reserve(n); break;
            case CF_STR: sidx.reserve(n); break;
            case CF_BOOL: b8.reserve(n); break;
            default: break;
        }
    }
    // append a None cell (data slot is a zero placeholder)
    void push_null() {
        size_t len = length();
        if (valid.empty()) valid.assign(len, 1);
        valid.push_back(0);
        switch (tag) {
            case CF_I64: i64.push_back(0); break;
            case CF_F64: f64.push_back(0.0); break;
            case CF_STR: sidx.push_back(0); break;
            case CF_BOOL: b8.push_back(0); break;
            default: break;
        }
    }
    void push_valid_mark() {
        if (!valid.empty()) valid.push_back(1);
    }
    void copy_cell_from(const FrameCol& src, size_t i) {
        if (!src.is_valid(i)) {
            push_null();
            return;
        }
        switch (tag) {
            case CF_I64: i64.push_back(src.i64[i]); break;
            case CF_F64: f64.push_back(src.f64[i]); break;
            case CF_STR: sidx.push_back(src.sidx[i]); break;
            case CF_BOOL: b8.push_back(src.b8[i]); break;
            default: break;
        }
        push_valid_mark();
    }
    size_t nbytes() const {
        return i64.size() * 8 + f64.size() * 8 + sidx.size() * 4 +
               b8.size() + valid.size();
    }
};

struct Frame {
    int64_t n_rows = 0;
    std::vector<FrameCol> cols;
    std::vector<PyObject*> pool;  // owned PyUnicode, deduplicated

    bool keys_lazy = false;
    std::vector<uint8_t> keyb;        // 16 * n_rows when !keys_lazy
    pwnative::Blake2bState key_base;  // salted + prefix-fed when keys_lazy
    int64_t key_offset = 0;
    std::vector<int64_t> key_seqs;    // n_rows when keys_lazy

    bool all_plus = true;
    std::vector<int8_t> diffs;  // n_rows when !all_plus

    ~Frame() {
        for (PyObject* s : pool) Py_XDECREF(s);
    }
    long long diff_at(size_t i) const {
        return all_plus ? 1 : (long long)diffs[i];
    }
    void key_digest(size_t i, uint8_t out[16]) const {
        if (!keys_lazy) {
            std::memcpy(out, keyb.data() + 16 * i, 16);
            return;
        }
        Hasher h;
        h.S = key_base;
        feed_small_int(h, key_seqs[(size_t)i] + key_offset);
        pwnative::blake2b_final(&h.S, out);
    }
    // force the digest representation (needed for key grouping/routing
    // and for ordering-independent consumers of int keys)
    void materialize_keys() {
        if (!keys_lazy) return;
        keyb.resize((size_t)n_rows * 16);
        for (int64_t i = 0; i < n_rows; i++) {
            Hasher h;
            h.S = key_base;
            feed_small_int(h, key_seqs[(size_t)i] + key_offset);
            pwnative::blake2b_final(&h.S, keyb.data() + 16 * (size_t)i);
        }
        keys_lazy = false;
        key_seqs.clear();
        key_seqs.shrink_to_fit();
    }
    size_t nbytes() const {
        size_t n = sizeof(Frame) + keyb.size() + key_seqs.size() * 8 +
                   diffs.size();
        for (const FrameCol& c : cols) n += c.nbytes();
        for (PyObject* s : pool) {
            Py_ssize_t sl;
            // utf8 cache is populated for pool strings (built from utf8)
            if (PyUnicode_AsUTF8AndSize(s, &sl) != nullptr)
                n += (size_t)sl + 8;
            else
                PyErr_Clear();
        }
        return n;
    }
    // new empty frame shaped like this one (shared pool, same col tags,
    // same key representation); used by slice/route_split/filter
    Frame* like(bool share_pool = true) const {
        Frame* f = new Frame();
        f->cols.resize(cols.size());
        for (size_t c = 0; c < cols.size(); c++) f->cols[c].tag = cols[c].tag;
        if (share_pool) {
            f->pool = pool;
            for (PyObject* s : f->pool) Py_INCREF(s);
        }
        f->keys_lazy = keys_lazy;
        f->key_base = key_base;
        f->key_offset = key_offset;
        f->all_plus = all_plus;
        return f;
    }
    void append_row_from(const Frame& src, size_t i) {
        for (size_t c = 0; c < cols.size(); c++)
            cols[c].copy_cell_from(src.cols[c], i);
        if (keys_lazy) {
            key_seqs.push_back(src.key_seqs[i]);
        } else {
            keyb.insert(keyb.end(), src.keyb.begin() + 16 * i,
                        src.keyb.begin() + 16 * (i + 1));
        }
        if (!all_plus) diffs.push_back(src.diffs[i]);
        n_rows++;
    }
    // new ref or nullptr; cell must be valid
    PyObject* cell_object(size_t c, size_t i) const {
        const FrameCol& col = cols[c];
        if (!col.is_valid(i)) Py_RETURN_NONE;
        switch (col.tag) {
            case CF_I64: return PyLong_FromLongLong(col.i64[i]);
            case CF_F64: return PyFloat_FromDouble(col.f64[i]);
            case CF_STR: {
                PyObject* s = pool[col.sidx[i]];
                Py_INCREF(s);
                return s;
            }
            case CF_BOOL: return PyBool_FromLong(col.b8[i]);
            default:
                PyErr_SetString(g_unsupported, "bad column tag");
                return nullptr;
        }
    }
};

const char kFrameCap[] = "pathway_tpu.frame";

void frame_cap_free(PyObject* cap) {
    delete static_cast<Frame*>(PyCapsule_GetPointer(cap, kFrameCap));
}

Frame* frame_arg(PyObject* cap) {
    return static_cast<Frame*>(PyCapsule_GetPointer(cap, kFrameCap));
}

PyObject* frame_to_capsule(Frame* f) {
    PyObject* cap = PyCapsule_New(f, kFrameCap, frame_cap_free);
    if (cap == nullptr) delete f;
    return cap;
}

// pool builder: dedup by utf8 bytes during frame construction
struct FramePoolBuilder {
    std::unordered_map<std::string, uint32_t> map;
    // takes a NEW reference to store (steals on success)
    int64_t intern(Frame* f, PyObject* str, const char* u8, size_t n) {
        auto it = map.find(std::string(u8, n));
        if (it != map.end()) {
            Py_DECREF(str);
            return (int64_t)it->second;
        }
        uint32_t idx = (uint32_t)f->pool.size();
        if (idx == UINT32_MAX) {
            Py_DECREF(str);
            return -1;
        }
        f->pool.push_back(str);
        map.emplace(std::string(u8, n), idx);
        return (int64_t)idx;
    }
};

PyObject* py_frame_len(PyObject*, PyObject* cap) {
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    return PyLong_FromLongLong(f->n_rows);
}

PyObject* py_frame_nbytes(PyObject*, PyObject* cap) {
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    return PyLong_FromSize_t(f->nbytes());
}

PyObject* py_frame_ncols(PyObject*, PyObject* cap) {
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    return PyLong_FromSize_t(f->cols.size());
}

PyObject* py_frame_all_plus(PyObject*, PyObject* cap) {
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    return PyBool_FromLong(f->all_plus ? 1 : 0);
}

PyObject* py_frame_from_updates(PyObject*, PyObject* batch) {
    // strict columnarization of an update list: every value must be in
    // the typed set and every column type-stable, else Unsupported (the
    // caller keeps the row representation — NEVER a lossy conversion)
    PyObject* seq =
        PySequence_Fast(batch, "frame_from_updates expects a sequence");
    if (seq == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    std::unique_ptr<Frame> f(new Frame());
    FramePoolBuilder pb;
    Py_ssize_t ncols = -1;
    bool unsupported = false;
    for (Py_ssize_t i = 0; i < n && !unsupported; i++) {
        PyObject* u = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(u) || PyTuple_GET_SIZE(u) != 3) {
            Py_DECREF(seq);
            PyErr_SetString(PyExc_TypeError, "updates must be 3-tuples");
            return nullptr;
        }
        PyObject* key = PyTuple_GET_ITEM(u, 0);
        PyObject* values = PyTuple_GET_ITEM(u, 1);
        if (!PyTuple_CheckExact(values)) {
            unsupported = true;
            break;
        }
        if (ncols == -1) {
            ncols = PyTuple_GET_SIZE(values);
            f->cols.resize((size_t)ncols);
            for (FrameCol& c : f->cols) c.reserve((size_t)n);
            f->keyb.reserve((size_t)n * 16);
        } else if (PyTuple_GET_SIZE(values) != ncols) {
            unsupported = true;
            break;
        }
        uint8_t kb[16];
        if (!PyLong_Check(key) || pt_long_as_bytes_unsigned(key, kb, 16) < 0) {
            PyErr_Clear();
            unsupported = true;  // negative / >128-bit / non-int key
            break;
        }
        long long d = PyLong_AsLongLong(PyTuple_GET_ITEM(u, 2));
        if (d == -1 && PyErr_Occurred()) {
            PyErr_Clear();
            unsupported = true;
            break;
        }
        if (d < INT8_MIN || d > INT8_MAX) {
            unsupported = true;
            break;
        }
        for (Py_ssize_t c = 0; c < ncols && !unsupported; c++) {
            FrameCol& col = f->cols[(size_t)c];
            PyObject* v = PyTuple_GET_ITEM(values, c);
            if (v == Py_None) {
                if (col.tag == 0) {
                    // type still unknown: count as null, backfilled when
                    // (if ever) the column discovers its type
                    size_t len = col.valid.size();
                    if (col.valid.empty() && i > 0)
                        col.valid.assign((size_t)i, 0), len = (size_t)i;
                    col.valid.push_back(0);
                    (void)len;
                    continue;
                }
                col.push_null();
                continue;
            }
            uint8_t want;
            if (PyBool_Check(v)) {
                want = CF_BOOL;
            } else if (g_pointer_type != nullptr &&
                       PyObject_TypeCheck(
                           v, reinterpret_cast<PyTypeObject*>(
                                  g_pointer_type))) {
                unsupported = true;  // Pointer cells lose identity
                break;
            } else if (PyLong_CheckExact(v)) {
                want = CF_I64;
            } else if (PyFloat_CheckExact(v)) {
                want = CF_F64;
            } else if (PyUnicode_CheckExact(v)) {
                want = CF_STR;
            } else {
                unsupported = true;  // tuple/bytes/ndarray/ERROR/...
                break;
            }
            if (col.tag == 0) {
                // column discovers its type: backfill earlier nulls
                col.tag = want;
                size_t nulls = col.valid.size();
                switch (want) {
                    case CF_I64: col.i64.assign(nulls, 0); break;
                    case CF_F64: col.f64.assign(nulls, 0.0); break;
                    case CF_STR: col.sidx.assign(nulls, 0); break;
                    case CF_BOOL: col.b8.assign(nulls, 0); break;
                }
            } else if (col.tag != want) {
                unsupported = true;  // mixed column
                break;
            }
            switch (want) {
                case CF_I64: {
                    int overflow = 0;
                    long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
                    if (overflow != 0 || (x == -1 && PyErr_Occurred())) {
                        PyErr_Clear();
                        unsupported = true;
                        break;
                    }
                    col.i64.push_back(x);
                    break;
                }
                case CF_F64:
                    col.f64.push_back(PyFloat_AS_DOUBLE(v));
                    break;
                case CF_STR: {
                    Py_ssize_t sl;
                    const char* s = PyUnicode_AsUTF8AndSize(v, &sl);
                    if (s == nullptr) {
                        PyErr_Clear();
                        unsupported = true;
                        break;
                    }
                    Py_INCREF(v);
                    int64_t idx = pb.intern(f.get(), v, s, (size_t)sl);
                    if (idx < 0) {
                        unsupported = true;
                        break;
                    }
                    col.sidx.push_back((uint32_t)idx);
                    break;
                }
                case CF_BOOL:
                    col.b8.push_back(v == Py_True ? 1 : 0);
                    break;
            }
            if (!unsupported) col.push_valid_mark();
        }
        if (unsupported) break;
        f->keyb.insert(f->keyb.end(), kb, kb + 16);
        if (d != 1 && f->all_plus) {
            f->all_plus = false;
            f->diffs.assign((size_t)i, 1);
        }
        if (!f->all_plus) f->diffs.push_back((int8_t)d);
        f->n_rows++;
    }
    Py_DECREF(seq);
    if (unsupported) {
        if (!PyErr_Occurred())
            PyErr_SetString(g_unsupported, "batch not columnarizable");
        return nullptr;
    }
    if (ncols == -1) f->cols.clear();  // empty batch: zero columns
    // columns that stayed all-None: give them a concrete tag so every
    // kernel can treat tag as trusted
    for (FrameCol& c : f->cols) {
        if (c.tag == 0) {
            c.tag = CF_I64;
            c.i64.assign(c.valid.size(), 0);
        }
    }
    return frame_to_capsule(f.release());
}

PyObject* py_frame_to_updates(PyObject*, PyObject* cap) {
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    if (g_update_type == nullptr || g_pointer_type == nullptr) {
        PyErr_SetString(PyExc_RuntimeError,
                        "frame_to_updates: Update/Pointer unregistered");
        return nullptr;
    }
    PyObject* out = PyList_New((Py_ssize_t)f->n_rows);
    if (out == nullptr) return nullptr;
    size_t ncols = f->cols.size();
    for (int64_t i = 0; i < f->n_rows; i++) {
        uint8_t kb[16];
        f->key_digest((size_t)i, kb);
        PyObject* num = pt_long_from_bytes_unsigned(kb, 16);
        PyObject* key = pointer_from_long(num);
        if (key == nullptr) goto fail;
        {
            PyObject* values = PyTuple_New((Py_ssize_t)ncols);
            if (values == nullptr) {
                Py_DECREF(key);
                goto fail;
            }
            for (size_t c = 0; c < ncols; c++) {
                PyObject* v = f->cell_object(c, (size_t)i);
                if (v == nullptr) {
                    Py_DECREF(values);
                    Py_DECREF(key);
                    goto fail;
                }
                PyTuple_SET_ITEM(values, (Py_ssize_t)c, v);
            }
            PyObject* u =
                make_update(g_update_type, key, values, f->diff_at((size_t)i));
            Py_DECREF(key);
            Py_DECREF(values);
            if (u == nullptr) goto fail;
            PyList_SET_ITEM(out, (Py_ssize_t)i, u);
        }
    }
    return out;
fail:
    Py_DECREF(out);
    return nullptr;
}

PyObject* py_frame_slice(PyObject*, PyObject* args) {
    PyObject* cap;
    long long start, stop;
    if (!PyArg_ParseTuple(args, "OLL", &cap, &start, &stop)) return nullptr;
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    if (start < 0) start = 0;
    if (stop > f->n_rows) stop = f->n_rows;
    if (stop < start) stop = start;
    std::unique_ptr<Frame> out(f->like());
    for (size_t c = 0; c < f->cols.size(); c++)
        out->cols[c].reserve((size_t)(stop - start));
    for (long long i = start; i < stop; i++)
        out->append_row_from(*f, (size_t)i);
    return frame_to_capsule(out.release());
}

// ---- JSONL -> frame parser -------------------------------------------
//
// frame_parse_jsonl(data, plan, prefix, seq_start, seq_step, key_offset)
// parses a block of complete JSONL object lines straight into a frame:
// one pass over the bytes, zero per-row Python objects, lazy keys
// carrying just (prefix-hash state, line seq).  Strictly conservative:
// ANY construct whose semantics could diverge from the
// json.loads + coerce_rows row path (escapes, nested values, big ints,
// type/plan mismatches, malformed lines) returns None and the caller
// re-parses the whole block on the existing path.  Behaviour parity is
// therefore exact by construction — this parser only accepts inputs
// where the two paths provably agree.

struct FrameDefCell {
    bool is_null = true;
    int64_t i = 0;
    double d = 0.0;
    uint32_t s = 0;
    uint8_t b = 0;
};

inline const char* fj_skip_ws(const char* p, const char* end) {
    while (p < end &&
           (*p == ' ' || *p == '\t' || *p == '\r')) p++;
    return p;
}

// strict JSON number grammar; returns past-the-end or nullptr
const char* fj_scan_number(const char* p, const char* end, bool* is_float) {
    *is_float = false;
    if (p < end && *p == '-') p++;
    if (p >= end || *p < '0' || *p > '9') return nullptr;
    if (*p == '0') {
        p++;
    } else {
        while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (p < end && *p == '.') {
        *is_float = true;
        p++;
        if (p >= end || *p < '0' || *p > '9') return nullptr;
        while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
        *is_float = true;
        p++;
        if (p < end && (*p == '+' || *p == '-')) p++;
        if (p >= end || *p < '0' || *p > '9') return nullptr;
        while (p < end && *p >= '0' && *p <= '9') p++;
    }
    return p;
}

// string body scan: [p, returned) is the content, quote consumed.
// Escapes and raw control bytes bail (nullptr) — json.loads handles
// them; this fast path only takes the overwhelmingly common clean case.
const char* fj_scan_string(const char* p, const char* end,
                           const char** content_end) {
    const char* s = p;
    while (p < end) {
        unsigned char c = (unsigned char)*p;
        if (c == '"') {
            *content_end = p;
            return p + 1;
        }
        if (c == '\\' || c < 0x20) return nullptr;
        p++;
    }
    (void)s;
    return nullptr;
}

PyObject* py_frame_parse_jsonl(PyObject*, PyObject* args) {
    PyObject *data_obj, *plan, *prefix;
    long long seq_start, seq_step, key_offset;
    if (!PyArg_ParseTuple(args, "OOO!LLL", &data_obj, &plan, &PyTuple_Type,
                          &prefix, &seq_start, &seq_step, &key_offset))
        return nullptr;
    char* data;
    Py_ssize_t nbytes;
    if (PyBytes_AsStringAndSize(data_obj, &data, &nbytes) < 0) return nullptr;

    // plan: (name, default, code) per column — same triples coerce_rows
    // takes, so defaults coerce identically
    PyObject* plan_seq = PySequence_Fast(plan, "plan must be a sequence");
    if (plan_seq == nullptr) return nullptr;
    Py_ssize_t ncols = PySequence_Fast_GET_SIZE(plan_seq);

    std::unique_ptr<Frame> f(new Frame());
    f->cols.resize((size_t)ncols);
    FramePoolBuilder pb;
    std::vector<std::string> names((size_t)ncols);
    std::vector<FrameDefCell> defaults((size_t)ncols);
    bool fallback = false;
    for (Py_ssize_t c = 0; c < ncols && !fallback; c++) {
        PyObject* item = PySequence_Fast_GET_ITEM(plan_seq, c);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3) {
            Py_DECREF(plan_seq);
            PyErr_SetString(PyExc_TypeError, "plan items must be 3-tuples");
            return nullptr;
        }
        PyObject* name = PyTuple_GET_ITEM(item, 0);
        PyObject* dflt = PyTuple_GET_ITEM(item, 1);
        long code = PyLong_AsLong(PyTuple_GET_ITEM(item, 2));
        if (code == -1 && PyErr_Occurred()) {
            Py_DECREF(plan_seq);
            return nullptr;
        }
        Py_ssize_t nl;
        const char* ns = PyUnicode_AsUTF8AndSize(name, &nl);
        if (ns == nullptr) {
            Py_DECREF(plan_seq);
            return nullptr;
        }
        names[(size_t)c].assign(ns, (size_t)nl);
        // key names containing quotes/backslashes would never byte-match
        // the escaped form in the JSON text
        if (names[(size_t)c].find('"') != std::string::npos ||
            names[(size_t)c].find('\\') != std::string::npos) {
            fallback = true;
            break;
        }
        uint8_t tag;
        switch (code) {
            case CO_INT: tag = CF_I64; break;
            case CO_FLOAT: tag = CF_F64; break;
            case CO_STR: tag = CF_STR; break;
            case CO_BOOL: tag = CF_BOOL; break;
            default:
                fallback = true;  // CO_ANY columns stay on the row path
                tag = 0;
                break;
        }
        if (fallback) break;
        f->cols[(size_t)c].tag = tag;
        FrameDefCell& dc = defaults[(size_t)c];
        if (dflt == Py_None) {
            dc.is_null = true;
        } else {
            // run the default through the exact coercer, then require the
            // result to be natively storable
            PyObject* cv = coerce_one(dflt, (int)code);
            if (cv == nullptr) {
                Py_DECREF(plan_seq);
                return nullptr;
            }
            dc.is_null = false;
            if (tag == CF_BOOL && PyBool_Check(cv)) {
                dc.b = cv == Py_True ? 1 : 0;
            } else if (tag == CF_I64 && PyLong_CheckExact(cv)) {
                int overflow = 0;
                dc.i = PyLong_AsLongLongAndOverflow(cv, &overflow);
                if (overflow != 0 || (dc.i == -1 && PyErr_Occurred())) {
                    PyErr_Clear();
                    fallback = true;
                }
            } else if (tag == CF_F64 && PyFloat_CheckExact(cv)) {
                dc.d = PyFloat_AS_DOUBLE(cv);
            } else if (tag == CF_STR && PyUnicode_CheckExact(cv)) {
                Py_ssize_t sl;
                const char* s = PyUnicode_AsUTF8AndSize(cv, &sl);
                if (s == nullptr) {
                    Py_DECREF(cv);
                    Py_DECREF(plan_seq);
                    return nullptr;
                }
                Py_INCREF(cv);
                int64_t idx = pb.intern(f.get(), cv, s, (size_t)sl);
                if (idx < 0)
                    fallback = true;
                else
                    dc.s = (uint32_t)idx;
            } else {
                fallback = true;  // coerced default escapes the typed set
            }
            Py_DECREF(cv);
        }
    }
    Py_DECREF(plan_seq);
    if (fallback) Py_RETURN_NONE;

    // key prefix hash state, computed once for the whole block
    Hasher base;
    for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(prefix); j++) {
        if (!feed(base, PyTuple_GET_ITEM(prefix, j))) {
            if (PyErr_Occurred()) return nullptr;
            Py_RETURN_NONE;  // exotic prefix type: row path keys
        }
    }
    f->keys_lazy = true;
    f->key_base = base.S;
    f->key_offset = key_offset;

    size_t est = (size_t)std::count(data, data + nbytes, '\n') + 1;
    for (FrameCol& c : f->cols) c.reserve(est);
    f->key_seqs.reserve(est);

    // per-row staging: duplicate keys overwrite (json.loads keeps the
    // last occurrence), so cells commit to the columns only at row end
    struct StageCell {
        int64_t i;
        double d;
        int64_t s;  // pool idx, or -1 null
        uint8_t b;
        uint8_t null;
    };
    std::vector<StageCell> stage((size_t)ncols);
    std::vector<int64_t> seen((size_t)ncols, -1);
    char numbuf[64];

    const char* p = data;
    const char* end = data + nbytes;
    int64_t row = 0;
    while (p < end && !fallback) {
        const char* line_end =
            static_cast<const char*>(memchr(p, '\n', (size_t)(end - p)));
        if (line_end == nullptr) line_end = end;
        const char* q = fj_skip_ws(p, line_end);
        if (q >= line_end) {
            fallback = true;  // blank/whitespace line: not one JSON object
            break;
        }
        if (*q != '{') {
            fallback = true;
            break;
        }
        q = fj_skip_ws(q + 1, line_end);
        bool first = true;
        while (!fallback) {
            if (q < line_end && *q == '}') {
                q++;
                break;
            }
            if (!first) {
                if (q >= line_end || *q != ',') {
                    fallback = true;
                    break;
                }
                q = fj_skip_ws(q + 1, line_end);
            }
            first = false;
            if (q >= line_end || *q != '"') {
                fallback = true;
                break;
            }
            const char* kend;
            const char* kq = fj_scan_string(q + 1, line_end, &kend);
            if (kq == nullptr) {
                fallback = true;
                break;
            }
            const char* kstart = q + 1;
            size_t klen = (size_t)(kend - kstart);
            q = fj_skip_ws(kq, line_end);
            if (q >= line_end || *q != ':') {
                fallback = true;
                break;
            }
            q = fj_skip_ws(q + 1, line_end);
            // match the key against the plan
            Py_ssize_t col = -1;
            for (Py_ssize_t c = 0; c < ncols; c++) {
                if (names[(size_t)c].size() == klen &&
                    std::memcmp(names[(size_t)c].data(), kstart, klen) == 0) {
                    col = c;
                    break;
                }
            }
            if (q >= line_end) {
                fallback = true;
                break;
            }
            uint8_t tag = col >= 0 ? f->cols[(size_t)col].tag : 0;
            StageCell cell{0, 0.0, -1, 0, 0};
            char vch = *q;
            if (vch == '"') {
                const char* vend;
                const char* vq = fj_scan_string(q + 1, line_end, &vend);
                if (vq == nullptr) {
                    fallback = true;
                    break;
                }
                if (col >= 0) {
                    if (tag != CF_STR) {
                        // string into a numeric/bool column: coerce_one
                        // would attempt parses — row path decides
                        fallback = true;
                        break;
                    }
                    PyObject* s = PyUnicode_DecodeUTF8(
                        q + 1, (Py_ssize_t)(vend - (q + 1)), nullptr);
                    if (s == nullptr) {
                        PyErr_Clear();
                        fallback = true;  // invalid utf-8
                        break;
                    }
                    int64_t idx =
                        pb.intern(f.get(), s, q + 1, (size_t)(vend - (q + 1)));
                    if (idx < 0) {
                        fallback = true;
                        break;
                    }
                    cell.s = idx;
                }
                q = vq;
            } else if (vch == 't' || vch == 'f') {
                const char* word = vch == 't' ? "true" : "false";
                size_t wl = vch == 't' ? 4 : 5;
                if ((size_t)(line_end - q) < wl ||
                    std::memcmp(q, word, wl) != 0) {
                    fallback = true;
                    break;
                }
                if (col >= 0) {
                    if (tag != CF_BOOL) {
                        fallback = true;  // bool survives CO_INT coercion
                        break;
                    }
                    cell.b = vch == 't' ? 1 : 0;
                }
                q += wl;
            } else if (vch == 'n') {
                if ((size_t)(line_end - q) < 4 ||
                    std::memcmp(q, "null", 4) != 0) {
                    fallback = true;
                    break;
                }
                // explicit null == missing: both take the default
                cell.null = 1;
                q += 4;
            } else if (vch == '-' || (vch >= '0' && vch <= '9')) {
                bool is_float;
                const char* nend = fj_scan_number(q, line_end, &is_float);
                if (nend == nullptr ||
                    (size_t)(nend - q) >= sizeof(numbuf)) {
                    fallback = true;
                    break;
                }
                if (col >= 0) {
                    std::memcpy(numbuf, q, (size_t)(nend - q));
                    numbuf[nend - q] = '\0';
                    if (!is_float) {
                        errno = 0;
                        char* ep = nullptr;
                        long long x = strtoll(numbuf, &ep, 10);
                        if (errno != 0 || ep != numbuf + (nend - q)) {
                            fallback = true;  // >64-bit int
                            break;
                        }
                        if (tag == CF_I64) {
                            cell.i = x;
                        } else if (tag == CF_F64) {
                            // PyNumber_Float(int64) and the C conversion
                            // both round to nearest-even
                            cell.d = (double)x;
                        } else {
                            fallback = true;
                            break;
                        }
                    } else {
                        if (tag != CF_F64) {
                            fallback = true;  // float into int col: row path
                            break;
                        }
                        // json.loads parses doubles with this exact
                        // function, so the bits match
                        char* ep = nullptr;
                        double d =
                            PyOS_string_to_double(numbuf, &ep, nullptr);
                        if (d == -1.0 && PyErr_Occurred()) {
                            PyErr_Clear();
                            fallback = true;
                            break;
                        }
                        if (ep != numbuf + (nend - q)) {
                            fallback = true;
                            break;
                        }
                        cell.d = d;
                    }
                }
                q = nend;
            } else {
                fallback = true;  // nested object/array or garbage
                break;
            }
            if (col >= 0) {
                stage[(size_t)col] = cell;
                seen[(size_t)col] = row;
            }
            q = fj_skip_ws(q, line_end);
        }
        if (fallback) break;
        q = fj_skip_ws(q, line_end);
        if (q != line_end) {
            fallback = true;  // trailing garbage after the object
            break;
        }
        // commit the staged row
        for (Py_ssize_t c = 0; c < ncols; c++) {
            FrameCol& colv = f->cols[(size_t)c];
            bool have = seen[(size_t)c] == row;
            const StageCell& cell = stage[(size_t)c];
            bool is_null = !have || cell.null ||
                           (colv.tag == CF_STR && have && !cell.null &&
                            cell.s < 0);
            if (is_null) {
                const FrameDefCell& dc = defaults[(size_t)c];
                if (dc.is_null) {
                    colv.push_null();
                } else {
                    switch (colv.tag) {
                        case CF_I64: colv.i64.push_back(dc.i); break;
                        case CF_F64: colv.f64.push_back(dc.d); break;
                        case CF_STR: colv.sidx.push_back(dc.s); break;
                        case CF_BOOL: colv.b8.push_back(dc.b); break;
                    }
                    colv.push_valid_mark();
                }
            } else {
                switch (colv.tag) {
                    case CF_I64: colv.i64.push_back(cell.i); break;
                    case CF_F64: colv.f64.push_back(cell.d); break;
                    case CF_STR:
                        colv.sidx.push_back((uint32_t)cell.s);
                        break;
                    case CF_BOOL: colv.b8.push_back(cell.b); break;
                }
                colv.push_valid_mark();
            }
        }
        f->key_seqs.push_back(seq_start + row * seq_step);
        f->n_rows++;
        row++;
        p = line_end < end ? line_end + 1 : end;
    }
    if (fallback) Py_RETURN_NONE;
    return frame_to_capsule(f.release());
}

// ---- frame groupby partials ------------------------------------------
//
// frame_groupby_partials(frame, group_idx, red_specs, error_obj)
// — byte-compatible output with groupby_partials ({gvals: (count,
// (partial, ...))}), computed from columns without building row
// objects.  The Python merge loop that folds partials into persistent
// accumulators is IDENTICAL for both entry points, so reducer semantics
// are shared by construction.  Frames cannot contain ERROR sentinels or
// exotic types (construction rejects them), which removes the poisoning
// scan the row path needs.

PyObject* py_frame_groupby_partials(PyObject*, PyObject* args) {
    PyObject *cap, *group_idx, *red_specs, *error_obj;
    if (!PyArg_ParseTuple(args, "OOOO", &cap, &group_idx, &red_specs,
                          &error_obj))
        return nullptr;
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    if (!PyTuple_Check(group_idx) || !PyTuple_Check(red_specs)) {
        PyErr_SetString(PyExc_TypeError, "group_idx/red_specs must be tuples");
        return nullptr;
    }
    Py_ssize_t ngroup = PyTuple_GET_SIZE(group_idx);
    std::vector<Py_ssize_t> gidx((size_t)ngroup);
    bool need_keys = false;
    for (Py_ssize_t i = 0; i < ngroup; i++) {
        gidx[(size_t)i] = PyLong_AsSsize_t(PyTuple_GET_ITEM(group_idx, i));
        if (gidx[(size_t)i] == -1 && PyErr_Occurred()) return nullptr;
        if (gidx[(size_t)i] < 0) need_keys = true;
        if (gidx[(size_t)i] >= (Py_ssize_t)f->cols.size()) {
            PyErr_SetString(g_unsupported, "group column out of range");
            return nullptr;
        }
    }
    Py_ssize_t nred = PyTuple_GET_SIZE(red_specs);
    std::vector<int> rcodes((size_t)nred);
    std::vector<std::vector<Py_ssize_t>> ridx((size_t)nred);
    for (Py_ssize_t r = 0; r < nred; r++) {
        PyObject* spec = PyTuple_GET_ITEM(red_specs, r);
        if (!PyTuple_Check(spec) || PyTuple_GET_SIZE(spec) != 2) {
            PyErr_SetString(PyExc_TypeError, "red_specs items must be pairs");
            return nullptr;
        }
        long code = PyLong_AsLong(PyTuple_GET_ITEM(spec, 0));
        if (code == -1 && PyErr_Occurred()) return nullptr;
        rcodes[(size_t)r] = (int)code;
        PyObject* idxs = PyTuple_GET_ITEM(spec, 1);
        if (!PyTuple_Check(idxs)) {
            PyErr_SetString(PyExc_TypeError, "red spec idx must be a tuple");
            return nullptr;
        }
        for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(idxs); j++) {
            Py_ssize_t v = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, j));
            if (v == -1 && PyErr_Occurred()) return nullptr;
            if (v >= (Py_ssize_t)f->cols.size()) {
                PyErr_SetString(g_unsupported, "reduce column out of range");
                return nullptr;
            }
            if (v < 0) need_keys = true;
            ridx[(size_t)r].push_back(v);
        }
        if (code == 1) {
            // sum-like native partial: the argument column must be
            // numeric (string "sums" concatenate — row path handles)
            uint8_t t = ridx[(size_t)r][0] < 0
                            ? (uint8_t)0
                            : f->cols[(size_t)ridx[(size_t)r][0]].tag;
            if (ridx[(size_t)r][0] < 0 || t == CF_STR) {
                PyErr_SetString(g_unsupported, "non-numeric sum column");
                return nullptr;
            }
        }
    }
    if (need_keys) f->materialize_keys();

    // staging table: group cells serialized to a byte key.  Single
    // string-column grouping (the dominant shape: wordcount, any
    // group-by-categorical) short-circuits through a pool-index table —
    // O(1) per row with zero hashing of string bytes.
    struct FPart {
        long long isum = 0;
        double dsum = 0.0;
        long long cnt = 0;
        bool seen = false;
        PyObject* msdict = nullptr;
        std::vector<MsItem> msitems;
    };
    struct FEntry {
        long long count = 0;
        int64_t first_row = 0;
        std::vector<FPart> parts;
    };
    std::vector<FEntry> entries;
    std::unordered_map<std::string, size_t> emap;
    std::vector<int64_t> ent_by_pool;
    int64_t ent_null = -1;
    bool single_str = ngroup == 1 && gidx[0] >= 0 &&
                      f->cols[(size_t)gidx[0]].tag == CF_STR;
    if (single_str) ent_by_pool.assign(f->pool.size(), -1);
    std::string gkey;
    bool fail = false;
    bool unsupported = false;

    for (int64_t i = 0; i < f->n_rows && !fail; i++) {
        long long diff = f->diff_at((size_t)i);
        size_t ei;
        if (single_str) {
            const FrameCol& gc = f->cols[(size_t)gidx[0]];
            int64_t* slot;
            if (gc.is_valid((size_t)i)) {
                slot = &ent_by_pool[gc.sidx[(size_t)i]];
            } else {
                slot = &ent_null;
            }
            if (*slot < 0) {
                *slot = (int64_t)entries.size();
                entries.emplace_back();
                entries.back().first_row = i;
                entries.back().parts.resize((size_t)nred);
            }
            ei = (size_t)*slot;
        } else {
            gkey.clear();
            for (Py_ssize_t j = 0; j < ngroup; j++) {
                Py_ssize_t ix = gidx[(size_t)j];
                if (ix < 0) {
                    gkey.push_back((char)0x10);
                    size_t at = gkey.size();
                    gkey.resize(at + 16);
                    f->key_digest((size_t)i, (uint8_t*)&gkey[at]);
                    continue;
                }
                const FrameCol& c = f->cols[(size_t)ix];
                if (!c.is_valid((size_t)i)) {
                    gkey.push_back((char)0x00);
                    continue;
                }
                switch (c.tag) {
                    case CF_I64: {
                        gkey.push_back((char)CF_I64);
                        int64_t v = c.i64[(size_t)i];
                        gkey.append((const char*)&v, 8);
                        break;
                    }
                    case CF_F64: {
                        gkey.push_back((char)CF_F64);
                        double v = c.f64[(size_t)i];
                        gkey.append((const char*)&v, 8);
                        break;
                    }
                    case CF_STR: {
                        gkey.push_back((char)CF_STR);
                        uint32_t v = c.sidx[(size_t)i];
                        gkey.append((const char*)&v, 4);
                        break;
                    }
                    case CF_BOOL:
                        gkey.push_back((char)CF_BOOL);
                        gkey.push_back((char)c.b8[(size_t)i]);
                        break;
                }
            }
            auto it = emap.find(gkey);
            if (it != emap.end()) {
                ei = it->second;
            } else {
                ei = entries.size();
                emap.emplace(gkey, ei);
                entries.emplace_back();
                entries.back().first_row = i;
                entries.back().parts.resize((size_t)nred);
            }
        }
        FEntry& ge = entries[ei];
        ge.count += diff;
        for (Py_ssize_t r = 0; r < nred && !fail; r++) {
            FPart& part = ge.parts[(size_t)r];
            int code = rcodes[(size_t)r];
            if (code == 0) continue;
            if (code == 1) {
                Py_ssize_t ix = ridx[(size_t)r][0];
                const FrameCol& c = f->cols[(size_t)ix];
                if (!c.is_valid((size_t)i)) continue;  // None: skipped
                if (c.tag == CF_F64) {
                    part.dsum += c.f64[(size_t)i] * (double)diff;
                } else {
                    long long v = c.tag == CF_I64 ? c.i64[(size_t)i]
                                                  : (long long)c.b8[(size_t)i];
                    long long term, nsum;
                    if (__builtin_mul_overflow(v, diff, &term) ||
                        __builtin_add_overflow(part.isum, term, &nsum)) {
                        unsupported = true;  // int64 overflow: row path
                        fail = true;
                        break;
                    }
                    part.isum = nsum;
                }
                part.cnt += diff;
                part.seen = true;
            } else if (code == 2) {
                // multiset partial: per-row arg tuples (scalar cells are
                // always hashable, so no hashable_fn detour)
                const std::vector<Py_ssize_t>& idxs = ridx[(size_t)r];
                PyObject* margs = PyTuple_New((Py_ssize_t)idxs.size());
                if (margs == nullptr) {
                    fail = true;
                    break;
                }
                bool cellfail = false;
                for (size_t j = 0; j < idxs.size(); j++) {
                    PyObject* cell;
                    if (idxs[j] < 0) {
                        uint8_t kb[16];
                        f->key_digest((size_t)i, kb);
                        cell = pointer_from_long(
                            pt_long_from_bytes_unsigned(kb, 16));
                    } else {
                        cell = f->cell_object((size_t)idxs[j], (size_t)i);
                    }
                    if (cell == nullptr) {
                        cellfail = true;
                        break;
                    }
                    PyTuple_SET_ITEM(margs, (Py_ssize_t)j, cell);
                }
                if (cellfail) {
                    Py_DECREF(margs);
                    fail = true;
                    break;
                }
                if (part.msdict == nullptr) {
                    part.msdict = PyDict_New();
                    if (part.msdict == nullptr) {
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                }
                PyObject* mf = PyDict_GetItemWithError(part.msdict, margs);
                if (mf == nullptr && PyErr_Occurred()) {
                    Py_DECREF(margs);
                    fail = true;
                    break;
                }
                if (mf != nullptr) {
                    size_t mi = (size_t)PyLong_AsSsize_t(mf);
                    part.msitems[mi].delta += diff;
                    Py_DECREF(margs);
                } else {
                    PyObject* mi =
                        PyLong_FromSsize_t((Py_ssize_t)part.msitems.size());
                    if (mi == nullptr ||
                        PyDict_SetItem(part.msdict, margs, mi) < 0) {
                        Py_XDECREF(mi);
                        Py_DECREF(margs);
                        fail = true;
                        break;
                    }
                    Py_DECREF(mi);
                    Py_INCREF(margs);  // msitems owns args AND h (same obj)
                    part.msitems.push_back({diff, margs, margs});
                }
            } else {
                unsupported = true;
                fail = true;
                break;
            }
        }
    }

    auto free_entries = [&entries]() {
        for (FEntry& e : entries) {
            for (FPart& p : e.parts) {
                Py_XDECREF(p.msdict);
                for (MsItem& it : p.msitems) {
                    Py_XDECREF(it.args);
                    Py_XDECREF(it.h);
                }
            }
        }
        entries.clear();
    };

    if (fail) {
        free_entries();
        if (unsupported && !PyErr_Occurred())
            PyErr_SetString(g_unsupported, "frame groupby not supported");
        return nullptr;
    }

    PyObject* out = PyDict_New();
    if (out == nullptr) {
        free_entries();
        return nullptr;
    }
    for (size_t ei = 0; ei < entries.size() && !fail; ei++) {
        FEntry& ge = entries[ei];
        // rebuild gvals from the entry's first row
        PyObject* gv = PyTuple_New(ngroup);
        if (gv == nullptr) {
            fail = true;
            break;
        }
        for (Py_ssize_t j = 0; j < ngroup && !fail; j++) {
            PyObject* cell;
            if (gidx[(size_t)j] < 0) {
                uint8_t kb[16];
                f->key_digest((size_t)ge.first_row, kb);
                cell = pointer_from_long(pt_long_from_bytes_unsigned(kb, 16));
            } else {
                cell = f->cell_object((size_t)gidx[(size_t)j],
                                      (size_t)ge.first_row);
            }
            if (cell == nullptr) {
                fail = true;
                break;
            }
            PyTuple_SET_ITEM(gv, j, cell);
        }
        if (fail) {
            Py_DECREF(gv);
            break;
        }
        PyObject* parts = PyTuple_New(nred);
        if (parts == nullptr) {
            Py_DECREF(gv);
            fail = true;
            break;
        }
        for (Py_ssize_t r = 0; r < nred && !fail; r++) {
            FPart& p = ge.parts[(size_t)r];
            PyObject* payload = nullptr;
            if (rcodes[(size_t)r] == 0) {
                payload = PyLong_FromLongLong(ge.count);
            } else if (rcodes[(size_t)r] == 1) {
                if (!p.seen) {
                    payload = Py_BuildValue("(OL)", Py_None, (long long)0);
                } else {
                    Py_ssize_t ix = ridx[(size_t)r][0];
                    PyObject* tot =
                        f->cols[(size_t)ix].tag == CF_F64
                            ? PyFloat_FromDouble(p.dsum)
                            : PyLong_FromLongLong(p.isum);
                    if (tot != nullptr) {
                        payload = Py_BuildValue("(NL)", tot, p.cnt);
                        if (payload == nullptr) Py_DECREF(tot);
                    }
                }
            } else {
                payload = PyDict_New();
                if (payload != nullptr) {
                    for (MsItem& it : p.msitems) {
                        PyObject* dv = Py_BuildValue("(LO)", it.delta,
                                                     it.args);
                        if (dv == nullptr ||
                            PyDict_SetItem(payload, it.h, dv) < 0) {
                            Py_XDECREF(dv);
                            Py_DECREF(payload);
                            payload = nullptr;
                            break;
                        }
                        Py_DECREF(dv);
                    }
                }
            }
            if (payload == nullptr) {
                Py_DECREF(parts);
                Py_DECREF(gv);
                fail = true;
                break;
            }
            PyTuple_SET_ITEM(parts, r, payload);
        }
        if (fail) break;
        PyObject* val = Py_BuildValue("(LO)", ge.count, parts);
        Py_DECREF(parts);
        if (val == nullptr || PyDict_SetItem(out, gv, val) < 0) {
            Py_XDECREF(val);
            Py_DECREF(gv);
            fail = true;
            break;
        }
        Py_DECREF(val);
        Py_DECREF(gv);
    }
    free_entries();
    if (fail) {
        Py_DECREF(out);
        return nullptr;
    }
    return out;
}

// ---- frame routing / projection / filtering --------------------------

template <typename Sink>
bool frame_feed_cell(Sink& sink, const Frame* f, Py_ssize_t ix, size_t i) {
    if (ix < 0) {
        uint8_t kb[16];
        f->key_digest(i, kb);
        sink.tag(0x07);
        sink.bytes(kb, 16);
        return true;
    }
    const FrameCol& c = f->cols[(size_t)ix];
    if (!c.is_valid(i)) {
        sink.tag(0x00);
        return true;
    }
    switch (c.tag) {
        case CF_I64:
            feed_small_int(sink, c.i64[i]);
            return true;
        case CF_F64: {
            double d = c.f64[i];
            sink.tag(0x03);
            sink.bytes(&d, 8);
            return true;
        }
        case CF_STR: {
            Py_ssize_t n;
            const char* s = PyUnicode_AsUTF8AndSize(f->pool[c.sidx[i]], &n);
            if (s == nullptr) return false;
            sink.tag(0x04);
            sink.u64le((uint64_t)n);
            sink.bytes(s, (size_t)n);
            return true;
        }
        case CF_BOOL:
            sink.tag(0x01);
            sink.tag(c.b8[i] ? 0x01 : 0x00);
            return true;
        default:
            return false;
    }
}

PyObject* py_frame_route_split(PyObject*, PyObject* args) {
    // frame_route_split(frame, idx_tuple, W) -> list of W frames.
    // Destinations are byte-identical to route_split on the materialized
    // rows: positional cells feed the same tagged stream into the same
    // digest memo; the empty tuple means int(key) % W.  Single
    // string-column routes memoize the destination per POOL INDEX, so a
    // million-row frame over a 1k vocabulary does ~1k digests.
    PyObject *cap, *idxs;
    long W;
    if (!PyArg_ParseTuple(args, "OOl", &cap, &idxs, &W)) return nullptr;
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    if (W <= 0 || !PyTuple_Check(idxs)) {
        PyErr_SetString(PyExc_ValueError, "bad frame_route_split arguments");
        return nullptr;
    }
    Py_ssize_t nidx = PyTuple_GET_SIZE(idxs);
    std::vector<Py_ssize_t> pos((size_t)nidx);
    for (Py_ssize_t i = 0; i < nidx; i++) {
        pos[(size_t)i] = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, i));
        if (pos[(size_t)i] == -1 && PyErr_Occurred()) return nullptr;
        if (pos[(size_t)i] >= (Py_ssize_t)f->cols.size()) {
            PyErr_SetString(PyExc_IndexError, "route column out of range");
            return nullptr;
        }
    }
    if (nidx == 0) f->materialize_keys();  // key routing needs digests

    std::vector<std::unique_ptr<Frame>> outs;
    outs.reserve((size_t)W);
    for (long w = 0; w < W; w++) outs.emplace_back(f->like());

    bool single_str = nidx == 1 && pos[0] >= 0 &&
                      f->cols[(size_t)pos[0]].tag == CF_STR;
    std::vector<long> dest_by_pool;
    long dest_null = -1;
    if (single_str) dest_by_pool.assign(f->pool.size(), -1);
    std::string cells;

    for (int64_t i = 0; i < f->n_rows; i++) {
        long dest;
        if (nidx == 0) {
            // int(key) % W on the 128-bit LE digest
            uint64_t lo, hi;
            std::memcpy(&lo, f->keyb.data() + 16 * (size_t)i, 8);
            std::memcpy(&hi, f->keyb.data() + 16 * (size_t)i + 8, 8);
            unsigned __int128 v =
                ((unsigned __int128)hi << 64) | (unsigned __int128)lo;
            dest = (long)(unsigned long long)(v % (unsigned long long)W);
        } else {
            long* slot = nullptr;
            if (single_str) {
                const FrameCol& c = f->cols[(size_t)pos[0]];
                slot = c.is_valid((size_t)i)
                           ? &dest_by_pool[c.sidx[(size_t)i]]
                           : &dest_null;
                if (*slot >= 0) {
                    outs[(size_t)*slot]->append_row_from(*f, (size_t)i);
                    continue;
                }
            }
            cells.clear();
            ByteSink sink{cells};
            bool ok = true;
            for (Py_ssize_t j = 0; j < nidx && ok; j++)
                ok = frame_feed_cell(sink, f, pos[(size_t)j], (size_t)i);
            if (!ok) {
                if (!PyErr_Occurred())
                    PyErr_SetString(g_unsupported, "unroutable cell");
                return nullptr;
            }
            uint8_t dg[16];
            route_digest(cells, dg);
            uint64_t lo, hi;
            std::memcpy(&lo, dg, 8);
            std::memcpy(&hi, dg + 8, 8);
            unsigned __int128 v =
                ((unsigned __int128)hi << 64) | (unsigned __int128)lo;
            dest = (long)(unsigned long long)(v % (unsigned long long)W);
            if (slot != nullptr) *slot = dest;
        }
        outs[(size_t)dest]->append_row_from(*f, (size_t)i);
    }
    PyObject* out = PyList_New(W);
    if (out == nullptr) return nullptr;
    for (long w = 0; w < W; w++) {
        PyObject* c = frame_to_capsule(outs[(size_t)w].release());
        if (c == nullptr) {
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, w, c);
    }
    return out;
}

PyObject* py_frame_project(PyObject*, PyObject* args) {
    // frame_project(frame, pos_tuple) -> frame with the selected value
    // columns (keys/diffs/pool preserved) — the columnar form of a
    // pure-projection rowwise node
    PyObject *cap, *idxs;
    if (!PyArg_ParseTuple(args, "OO!", &cap, &PyTuple_Type, &idxs))
        return nullptr;
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    std::unique_ptr<Frame> out(new Frame());
    out->n_rows = f->n_rows;
    out->pool = f->pool;
    for (PyObject* s : out->pool) Py_INCREF(s);
    out->keys_lazy = f->keys_lazy;
    out->key_base = f->key_base;
    out->key_offset = f->key_offset;
    out->key_seqs = f->key_seqs;
    out->keyb = f->keyb;
    out->all_plus = f->all_plus;
    out->diffs = f->diffs;
    Py_ssize_t nsel = PyTuple_GET_SIZE(idxs);
    out->cols.resize((size_t)nsel);
    for (Py_ssize_t j = 0; j < nsel; j++) {
        Py_ssize_t ix = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, j));
        if (ix == -1 && PyErr_Occurred()) return nullptr;
        if (ix < 0 || ix >= (Py_ssize_t)f->cols.size()) {
            PyErr_SetString(PyExc_IndexError, "project column out of range");
            return nullptr;
        }
        out->cols[(size_t)j] = f->cols[(size_t)ix];  // column copy
    }
    return frame_to_capsule(out.release());
}

enum FrameCmp {
    FC_EQ = 0,
    FC_NE = 1,
    FC_LT = 2,
    FC_LE = 3,
    FC_GT = 4,
    FC_GE = 5,
};

template <typename T>
inline bool frame_cmp(int op, T a, T b) {
    switch (op) {
        case FC_EQ: return a == b;
        case FC_NE: return a != b;
        case FC_LT: return a < b;
        case FC_LE: return a <= b;
        case FC_GT: return a > b;
        default: return a >= b;
    }
}

PyObject* py_frame_filter(PyObject*, PyObject* args) {
    // frame_filter(frame, pos, op, const) -> frame keeping rows where
    // column[pos] <op> const.  None cells follow Python comparison
    // semantics under FilterNode's drop rules: == is False (drop),
    // != is True (keep), ordering raises (drop).  Type pairings are
    // strict — any cross-type compare falls back to the row path so
    // exact-arithmetic parity (int64 vs float) is never at risk.
    PyObject *cap, *cobj;
    long long posl;
    int op;
    if (!PyArg_ParseTuple(args, "OLiO", &cap, &posl, &op, &cobj))
        return nullptr;
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    if (posl < 0 || posl >= (long long)f->cols.size() || op < 0 || op > 5) {
        PyErr_SetString(PyExc_ValueError, "bad frame_filter arguments");
        return nullptr;
    }
    const FrameCol& c = f->cols[(size_t)posl];
    long long ci = 0;
    double cd = 0.0;
    std::string cs;
    if (c.tag == CF_I64 && PyLong_CheckExact(cobj)) {
        int overflow = 0;
        ci = PyLong_AsLongLongAndOverflow(cobj, &overflow);
        if (overflow != 0 || (ci == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            PyErr_SetString(g_unsupported, "filter constant out of range");
            return nullptr;
        }
    } else if (c.tag == CF_F64 && PyFloat_CheckExact(cobj)) {
        cd = PyFloat_AS_DOUBLE(cobj);
    } else if (c.tag == CF_BOOL && PyBool_Check(cobj)) {
        ci = cobj == Py_True ? 1 : 0;
    } else if (c.tag == CF_STR && PyUnicode_CheckExact(cobj)) {
        Py_ssize_t n;
        const char* s = PyUnicode_AsUTF8AndSize(cobj, &n);
        if (s == nullptr) return nullptr;
        cs.assign(s, (size_t)n);
    } else {
        PyErr_SetString(g_unsupported, "filter type pairing not columnar");
        return nullptr;
    }
    std::unique_ptr<Frame> out(f->like());
    for (int64_t i = 0; i < f->n_rows; i++) {
        bool keep;
        if (!c.is_valid((size_t)i)) {
            keep = op == FC_NE;  // None != const is True; rest drop
        } else {
            switch (c.tag) {
                case CF_I64:
                    keep = frame_cmp(op, (long long)c.i64[(size_t)i], ci);
                    break;
                case CF_F64: keep = frame_cmp(op, c.f64[(size_t)i], cd); break;
                case CF_BOOL:
                    keep = frame_cmp(op, (long long)c.b8[(size_t)i], ci);
                    break;
                default: {
                    // UTF-8 byte order == code point order
                    Py_ssize_t n;
                    const char* s = PyUnicode_AsUTF8AndSize(
                        f->pool[c.sidx[(size_t)i]], &n);
                    if (s == nullptr) return nullptr;
                    int r = std::memcmp(
                        s, cs.data(),
                        std::min((size_t)n, cs.size()));
                    if (r == 0)
                        r = (size_t)n < cs.size() ? -1
                            : (size_t)n > cs.size() ? 1 : 0;
                    keep = frame_cmp(op, (long long)r, (long long)0);
                    break;
                }
            }
        }
        if (keep) out->append_row_from(*f, (size_t)i);
    }
    return frame_to_capsule(out.release());
}

// ---- frame wire codec -------------------------------------------------
//
// One blob per (peer, slot): fixed-width column buffers memcpy'd in and
// out, string pool shared across every frame of ONE transmission
// (tx/rx pool capsules), lazy keys shipped as (hash state, seqs) so the
// receiver inherits the 8-bytes-per-key representation.  Decode is
// bounds-checked everywhere — a truncated or corrupt frame raises
// ValueError, never reads past the buffer.

constexpr uint8_t kFrameMagic = 0xCF;
constexpr uint8_t kFrameVersion = 1;
constexpr size_t kFramePoolShareCap = 1 << 20;  // tx/rx symmetric cap

struct FrameTxPool {
    std::unordered_map<std::string, uint32_t> map;
    unsigned long long hits = 0;
    unsigned long long misses = 0;
};
const char kTxPoolCap[] = "pathway_tpu.frame_txpool";
void txpool_free(PyObject* cap) {
    delete static_cast<FrameTxPool*>(
        PyCapsule_GetPointer(cap, kTxPoolCap));
}

struct FrameRxPool {
    std::vector<PyObject*> strs;  // owned
    ~FrameRxPool() {
        for (PyObject* s : strs) Py_XDECREF(s);
    }
};
const char kRxPoolCap[] = "pathway_tpu.frame_rxpool";
void rxpool_free(PyObject* cap) {
    delete static_cast<FrameRxPool*>(
        PyCapsule_GetPointer(cap, kRxPoolCap));
}

PyObject* py_frame_txpool_new(PyObject*, PyObject*) {
    return PyCapsule_New(new FrameTxPool(), kTxPoolCap, txpool_free);
}

PyObject* py_frame_rxpool_new(PyObject*, PyObject*) {
    return PyCapsule_New(new FrameRxPool(), kRxPoolCap, rxpool_free);
}

PyObject* py_frame_txpool_stats(PyObject*, PyObject* cap) {
    FrameTxPool* tp =
        static_cast<FrameTxPool*>(PyCapsule_GetPointer(cap, kTxPoolCap));
    if (tp == nullptr) return nullptr;
    return Py_BuildValue("(KK)", tp->hits, tp->misses);
}

bool frame_pack_to(std::string& buf, Frame* f, FrameTxPool* tp) {
    buf.push_back((char)kFrameMagic);
    buf.push_back((char)kFrameVersion);
    uint8_t flags = (f->all_plus ? 1 : 0) | (f->keys_lazy ? 2 : 0);
    buf.push_back((char)flags);
    wf_put_u32(buf, (uint32_t)f->n_rows);
    uint16_t nc = (uint16_t)f->cols.size();
    buf.append((const char*)&nc, 2);
    wf_put_u32(buf, (uint32_t)f->pool.size());
    if (f->keys_lazy) {
        uint16_t ns = (uint16_t)sizeof(pwnative::Blake2bState);
        buf.append((const char*)&ns, 2);
        buf.append((const char*)&f->key_base, sizeof(pwnative::Blake2bState));
        wf_put_u64(buf, (uint64_t)f->key_offset);
        buf.append((const char*)f->key_seqs.data(), f->key_seqs.size() * 8);
    } else {
        buf.append((const char*)f->keyb.data(), f->keyb.size());
    }
    if (!f->all_plus)
        buf.append((const char*)f->diffs.data(), f->diffs.size());
    for (PyObject* s : f->pool) {
        Py_ssize_t n;
        const char* u8 = PyUnicode_AsUTF8AndSize(s, &n);
        if (u8 == nullptr) return false;
        if (tp != nullptr) {
            auto it = tp->map.find(std::string(u8, (size_t)n));
            if (it != tp->map.end()) {
                tp->hits++;
                buf.push_back((char)1);
                wf_put_u32(buf, it->second);
                continue;
            }
            tp->misses++;
            if (tp->map.size() < kFramePoolShareCap)
                tp->map.emplace(std::string(u8, (size_t)n),
                                (uint32_t)tp->map.size());
        }
        buf.push_back((char)0);
        wf_put_u32(buf, (uint32_t)n);
        buf.append(u8, (size_t)n);
    }
    for (const FrameCol& c : f->cols) {
        buf.push_back((char)c.tag);
        buf.push_back((char)(c.valid.empty() ? 0 : 1));
        switch (c.tag) {
            case CF_I64:
                buf.append((const char*)c.i64.data(), c.i64.size() * 8);
                break;
            case CF_F64:
                buf.append((const char*)c.f64.data(), c.f64.size() * 8);
                break;
            case CF_STR:
                buf.append((const char*)c.sidx.data(), c.sidx.size() * 4);
                break;
            case CF_BOOL:
                buf.append((const char*)c.b8.data(), c.b8.size());
                break;
            default:
                PyErr_SetString(PyExc_ValueError, "bad column tag");
                return false;
        }
        if (!c.valid.empty())
            buf.append((const char*)c.valid.data(), c.valid.size());
    }
    return true;
}

FrameTxPool* txpool_arg_opt(PyObject* obj) {
    if (obj == Py_None) return nullptr;
    return static_cast<FrameTxPool*>(PyCapsule_GetPointer(obj, kTxPoolCap));
}

PyObject* py_frame_pack(PyObject*, PyObject* args) {
    PyObject* cap;
    PyObject* tpobj = Py_None;
    if (!PyArg_ParseTuple(args, "O|O", &cap, &tpobj)) return nullptr;
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    FrameTxPool* tp = txpool_arg_opt(tpobj);
    if (tp == nullptr && tpobj != Py_None) return nullptr;
    std::string buf;
    buf.reserve(f->nbytes() + 64);
    if (!frame_pack_to(buf, f, tp)) return nullptr;
    return PyBytes_FromStringAndSize(buf.data(), (Py_ssize_t)buf.size());
}

PyObject* py_frame_pack_into(PyObject*, PyObject* args) {
    PyObject *cap, *target;
    PyObject* tpobj = Py_None;
    if (!PyArg_ParseTuple(args, "OO!|O", &cap, &PyByteArray_Type, &target,
                          &tpobj))
        return nullptr;
    Frame* f = frame_arg(cap);
    if (f == nullptr) return nullptr;
    FrameTxPool* tp = txpool_arg_opt(tpobj);
    if (tp == nullptr && tpobj != Py_None) return nullptr;
    static thread_local std::string buf;
    buf.clear();
    if (!frame_pack_to(buf, f, tp)) return nullptr;
    Py_ssize_t at = PyByteArray_GET_SIZE(target);
    if (PyByteArray_Resize(target, at + (Py_ssize_t)buf.size()) < 0)
        return nullptr;
    std::memcpy(PyByteArray_AS_STRING(target) + at, buf.data(), buf.size());
    return PyLong_FromSsize_t((Py_ssize_t)buf.size());
}

PyObject* py_frame_unpack(PyObject*, PyObject* args) {
    PyObject* src;
    PyObject* rpobj = Py_None;
    if (!PyArg_ParseTuple(args, "O|O", &src, &rpobj)) return nullptr;
    FrameRxPool* rp = nullptr;
    if (rpobj != Py_None) {
        rp = static_cast<FrameRxPool*>(
            PyCapsule_GetPointer(rpobj, kRxPoolCap));
        if (rp == nullptr) return nullptr;
    }
    Py_buffer view;
    if (PyObject_GetBuffer(src, &view, PyBUF_SIMPLE) < 0) return nullptr;
    const uint8_t* p = static_cast<const uint8_t*>(view.buf);
    const uint8_t* end = p + view.len;
    std::unique_ptr<Frame> f(new Frame());

    auto truncated = [&view]() -> PyObject* {
        PyBuffer_Release(&view);
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_ValueError, "truncated columnar frame");
        return nullptr;
    };
    auto need = [&p, end](size_t n) { return (size_t)(end - p) >= n; };

    if (!need(13)) return truncated();
    if (p[0] != kFrameMagic || p[1] != kFrameVersion) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "bad columnar frame header");
        return nullptr;
    }
    uint8_t flags = p[2];
    uint32_t n_rows;
    uint16_t n_cols;
    uint32_t n_pool;
    std::memcpy(&n_rows, p + 3, 4);
    std::memcpy(&n_cols, p + 7, 2);
    std::memcpy(&n_pool, p + 9, 4);
    p += 13;
    if (n_rows > (uint32_t)INT32_MAX) return truncated();
    f->n_rows = (int64_t)n_rows;
    f->all_plus = (flags & 1) != 0;
    f->keys_lazy = (flags & 2) != 0;
    if (f->keys_lazy) {
        if (!need(2)) return truncated();
        uint16_t ns;
        std::memcpy(&ns, p, 2);
        p += 2;
        if (ns != sizeof(pwnative::Blake2bState)) {
            PyBuffer_Release(&view);
            PyErr_SetString(PyExc_ValueError,
                            "columnar frame hash-state size mismatch");
            return nullptr;
        }
        if (!need(sizeof(pwnative::Blake2bState) + 8 + (size_t)n_rows * 8))
            return truncated();
        std::memcpy(&f->key_base, p, sizeof(pwnative::Blake2bState));
        p += sizeof(pwnative::Blake2bState);
        uint64_t off;
        std::memcpy(&off, p, 8);
        p += 8;
        f->key_offset = (int64_t)off;
        f->key_seqs.resize(n_rows);
        std::memcpy(f->key_seqs.data(), p, (size_t)n_rows * 8);
        p += (size_t)n_rows * 8;
    } else {
        if (!need((size_t)n_rows * 16)) return truncated();
        f->keyb.assign(p, p + (size_t)n_rows * 16);
        p += (size_t)n_rows * 16;
    }
    if (!f->all_plus) {
        if (!need(n_rows)) return truncated();
        f->diffs.resize(n_rows);
        std::memcpy(f->diffs.data(), p, n_rows);
        p += n_rows;
    }
    f->pool.reserve(n_pool);
    for (uint32_t s = 0; s < n_pool; s++) {
        if (!need(1)) return truncated();
        uint8_t kind = *p++;
        if (kind == 0) {
            if (!need(4)) return truncated();
            uint32_t len;
            std::memcpy(&len, p, 4);
            p += 4;
            if (!need(len)) return truncated();
            PyObject* str = PyUnicode_DecodeUTF8(
                reinterpret_cast<const char*>(p), (Py_ssize_t)len, nullptr);
            if (str == nullptr) return truncated();
            p += len;
            // rx-pool mirror of the encoder's insert-on-first-sight
            if (rp != nullptr && rp->strs.size() < kFramePoolShareCap) {
                Py_INCREF(str);
                rp->strs.push_back(str);
            }
            f->pool.push_back(str);
        } else if (kind == 1) {
            if (!need(4)) return truncated();
            uint32_t ref;
            std::memcpy(&ref, p, 4);
            p += 4;
            if (rp == nullptr || ref >= rp->strs.size()) {
                PyBuffer_Release(&view);
                PyErr_SetString(PyExc_ValueError,
                                "bad string pool ref in columnar frame");
                return nullptr;
            }
            PyObject* str = rp->strs[ref];
            Py_INCREF(str);
            f->pool.push_back(str);
        } else {
            PyBuffer_Release(&view);
            PyErr_SetString(PyExc_ValueError,
                            "bad pool entry kind in columnar frame");
            return nullptr;
        }
    }
    f->cols.resize(n_cols);
    for (uint16_t c = 0; c < n_cols; c++) {
        if (!need(2)) return truncated();
        uint8_t tag = p[0];
        uint8_t has_valid = p[1];
        p += 2;
        FrameCol& col = f->cols[c];
        col.tag = tag;
        switch (tag) {
            case CF_I64:
                if (!need((size_t)n_rows * 8)) return truncated();
                col.i64.resize(n_rows);
                std::memcpy(col.i64.data(), p, (size_t)n_rows * 8);
                p += (size_t)n_rows * 8;
                break;
            case CF_F64:
                if (!need((size_t)n_rows * 8)) return truncated();
                col.f64.resize(n_rows);
                std::memcpy(col.f64.data(), p, (size_t)n_rows * 8);
                p += (size_t)n_rows * 8;
                break;
            case CF_STR:
                if (!need((size_t)n_rows * 4)) return truncated();
                col.sidx.resize(n_rows);
                std::memcpy(col.sidx.data(), p, (size_t)n_rows * 4);
                p += (size_t)n_rows * 4;
                for (uint32_t v : col.sidx) {
                    if (v >= f->pool.size()) {
                        PyBuffer_Release(&view);
                        PyErr_SetString(
                            PyExc_ValueError,
                            "string index out of range in columnar frame");
                        return nullptr;
                    }
                }
                break;
            case CF_BOOL:
                if (!need(n_rows)) return truncated();
                col.b8.resize(n_rows);
                for (uint32_t i = 0; i < n_rows; i++)
                    col.b8[i] = p[i] ? 1 : 0;
                p += n_rows;
                break;
            default:
                PyBuffer_Release(&view);
                PyErr_SetString(PyExc_ValueError,
                                "bad column tag in columnar frame");
                return nullptr;
        }
        if (has_valid) {
            if (!need(n_rows)) return truncated();
            col.valid.resize(n_rows);
            for (uint32_t i = 0; i < n_rows; i++)
                col.valid[i] = p[i] ? 1 : 0;
            p += n_rows;
        }
    }
    if (p != end) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError,
                        "trailing bytes after columnar frame");
        return nullptr;
    }
    PyBuffer_Release(&view);
    return frame_to_capsule(f.release());
}

PyMethodDef kMethods[] = {
    {"frame_from_updates", py_frame_from_updates, METH_O,
     "columnarize an update batch into a frame capsule"},
    {"frame_to_updates", py_frame_to_updates, METH_O,
     "materialize a frame capsule back into a list of Updates"},
    {"frame_len", py_frame_len, METH_O, "row count of a frame"},
    {"frame_nbytes", py_frame_nbytes, METH_O,
     "approximate in-memory size of a frame"},
    {"frame_ncols", py_frame_ncols, METH_O, "value column count of a frame"},
    {"frame_all_plus", py_frame_all_plus, METH_O,
     "True iff every row diff in the frame is +1"},
    {"frame_slice", py_frame_slice, METH_VARARGS,
     "row-range copy of a frame (shared string pool)"},
    {"frame_parse_jsonl", py_frame_parse_jsonl, METH_VARARGS,
     "parse a block of JSONL lines directly into a frame (None = fallback)"},
    {"frame_groupby_partials", py_frame_groupby_partials, METH_VARARGS,
     "per-group partial aggregates of a frame (same output as "
     "groupby_partials)"},
    {"frame_route_split", py_frame_route_split, METH_VARARGS,
     "split a frame into W per-destination frames (route_split parity)"},
    {"frame_project", py_frame_project, METH_VARARGS,
     "select value columns of a frame by position"},
    {"frame_filter", py_frame_filter, METH_VARARGS,
     "keep frame rows where column <op> constant"},
    {"frame_pack", py_frame_pack, METH_VARARGS,
     "serialize a frame to wire bytes (optional tx string pool)"},
    {"frame_pack_into", py_frame_pack_into, METH_VARARGS,
     "append a frame's wire bytes to a bytearray, returning the length"},
    {"frame_unpack", py_frame_unpack, METH_VARARGS,
     "decode wire bytes into a frame (optional rx string pool)"},
    {"frame_txpool_new", py_frame_txpool_new, METH_NOARGS,
     "new per-transmission string pool for frame_pack"},
    {"frame_txpool_stats", py_frame_txpool_stats, METH_O,
     "(hits, misses) of a tx string pool"},
    {"frame_rxpool_new", py_frame_rxpool_new, METH_NOARGS,
     "new per-transmission string pool for frame_unpack"},
    {"ref_scalar", py_ref_scalar, METH_VARARGS,
     "128-bit key hash of the argument values"},
    {"hash_rows", py_hash_rows, METH_O,
     "batch 128-bit key hashes for a sequence of value tuples"},
    {"hash_prefix_ints", py_hash_prefix_ints, METH_VARARGS,
     "bulk Pointer keys for (prefix..., seq+offset) rows"},
    {"scan_lines", py_scan_lines, METH_O,
     "offsets of non-empty lines in a bytes buffer"},
    {"consolidate", py_consolidate, METH_VARARGS,
     "merge updates with equal (key, row), dropping zero-diff entries"},
    {"per_key_changes", py_per_key_changes, METH_O,
     "group a batch into per-key (removals, additions) lists"},
    {"build_adds", py_build_adds, METH_VARARGS,
     "bulk Update(key, values, +1) construction"},
    {"coerce_rows", py_coerce_rows, METH_VARARGS,
     "bulk schema coercion of row dicts into value tuples"},
    {"groupby_partials", py_groupby_partials, METH_VARARGS,
     "per-group partial aggregates of an update batch"},
    {"all_positive", py_all_positive, METH_O,
     "True iff every update diff is > 0"},
    {"all_dicts", py_all_dicts, METH_O,
     "True iff every element is a dict"},
    {"rowwise_map", py_rowwise_map, METH_VARARGS,
     "apply a row function across a batch, containing row errors"},
    {"route_split", py_route_split, METH_VARARGS,
     "split an update batch into per-worker outboxes by route-cell hash"},
    {"wp_build", py_wp_build, METH_VARARGS,
     "build a WordPiece vocab handle from a token->id dict"},
    {"wp_encode", py_wp_encode, METH_VARARGS,
     "BERT-tokenize a batch of ASCII texts (None marks python fallback)"},
    {"filter_batch", py_filter_batch, METH_VARARGS,
     "keep updates whose (key, values) satisfy the predicate"},
    {"rows_with_error", py_rows_with_error, METH_VARARGS,
     "select updates whose values contain the sentinel (identity compare)"},
    {"set_pointer_type", py_set_pointer_type, METH_O,
     "register the Pointer class for type-tagged hashing"},
    {"set_json_type", py_set_json_type, METH_O,
     "register the Json class for VM convert/get semantics"},
    {"set_update_type", py_set_update_type, METH_O,
     "register the Update class for binary exchange frames"},
    {"pack_updates", py_pack_updates, METH_O,
     "serialize an update batch to a tagged binary frame"},
    {"pack_updates_into", py_pack_updates_into, METH_VARARGS,
     "append an update frame to a bytearray; returns appended byte count"},
    {"capture_batch", py_capture_batch, METH_VARARGS,
     "apply an update batch to capture state (stream list + rows dict)"},
    {"pack_kv", py_pack_kv, METH_O,
     "serialize (key, values) pairs to a tagged binary frame"},
    {"unpack_kv", py_unpack_kv, METH_O,
     "parse a tagged binary kv frame back into (Pointer, values) pairs"},
    {"unpack_updates", py_unpack_updates, METH_O,
     "parse a tagged binary frame back into Update objects"},
    {"vm_compile", py_vm_compile, METH_VARARGS,
     "compile an expression bytecode program to a capsule"},
    {"vm_eval_batch", py_vm_eval_batch, METH_VARARGS,
     "evaluate per-column VM programs across an update batch"},
    {"vm_filter_batch", py_vm_filter_batch, METH_VARARGS,
     "keep updates whose VM predicate result is truthy"},
    {"join_process", py_join_process, METH_VARARGS,
     "full incremental equi-join epoch pass over dict arrangements"},
    {"hnsw_new", py_hnsw_new, METH_VARARGS,
     "create an HNSW graph ANN index (dim, M, ef_construction, metric)"},
    {"hnsw_add", py_hnsw_add, METH_VARARGS,
     "bulk-insert float32 rows; returns assigned slots"},
    {"hnsw_remove", py_hnsw_remove, METH_VARARGS,
     "tombstone slots (freed for reuse)"},
    {"hnsw_search", py_hnsw_search, METH_VARARGS,
     "batch ANN search: (slots, distances) per query"},
    {"hnsw_len", py_hnsw_len, METH_O, "live item count"},
    {"monotonic_ns", py_monotonic_ns, METH_NOARGS,
     "steady-clock nanoseconds (latency probe timestamps)"},
    {"hist_new", py_hist_new, METH_NOARGS,
     "new log-bucketed concurrent latency histogram"},
    {"hist_record", py_hist_record, METH_VARARGS,
     "record a nanosecond sample into a histogram"},
    {"hist_snapshot", py_hist_snapshot, METH_VARARGS,
     "count/sum/max and p50/p95/p99 of a histogram"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "pathway_native",
                       "pathway_tpu C++ host hot paths", -1, kMethods};

}  // namespace

PyMODINIT_FUNC PyInit_pathway_native(void) {
    PyDateTime_IMPORT;  // .dt namespace methods use the C datetime API
    if (PyDateTimeAPI == nullptr) return nullptr;
    PyObject* m = PyModule_Create(&kModule);
    if (m == nullptr) return nullptr;
    g_unsupported =
        PyErr_NewException("pathway_native.Unsupported", nullptr, nullptr);
    Py_INCREF(g_unsupported);
    PyModule_AddObject(m, "Unsupported", g_unsupported);
    return m;
}
