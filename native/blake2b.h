// Compact BLAKE2b (RFC 7693) — sequential mode, no key, for the key-hash
// fast path.  Byte-for-byte compatible with Python's hashlib.blake2b at
// any digest size.
#pragma once

#include <cstdint>
#include <cstring>

namespace pwnative {

struct Blake2bState {
    uint64_t h[8];
    uint64_t t[2];
    uint8_t buf[128];
    size_t buflen;
    size_t outlen;
};

static const uint64_t BLAKE2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static const uint8_t BLAKE2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static inline uint64_t rotr64(uint64_t x, unsigned c) {
    return (x >> c) | (x << (64 - c));
}

static inline uint64_t load64(const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    return v;  // little-endian hosts only (x86-64/aarch64)
}

inline void blake2b_compress(Blake2bState* S, const uint8_t block[128],
                             bool last) {
    uint64_t m[16], v[16];
    for (int i = 0; i < 16; i++) m[i] = load64(block + i * 8);
    for (int i = 0; i < 8; i++) v[i] = S->h[i];
    for (int i = 0; i < 8; i++) v[i + 8] = BLAKE2B_IV[i];
    v[12] ^= S->t[0];
    v[13] ^= S->t[1];
    if (last) v[14] = ~v[14];

#define G(r, i, a, b, c, d)                         \
    a = a + b + m[BLAKE2B_SIGMA[r][2 * i]];         \
    d = rotr64(d ^ a, 32);                          \
    c = c + d;                                      \
    b = rotr64(b ^ c, 24);                          \
    a = a + b + m[BLAKE2B_SIGMA[r][2 * i + 1]];     \
    d = rotr64(d ^ a, 16);                          \
    c = c + d;                                      \
    b = rotr64(b ^ c, 63);

    for (int r = 0; r < 12; r++) {
        G(r, 0, v[0], v[4], v[8], v[12]);
        G(r, 1, v[1], v[5], v[9], v[13]);
        G(r, 2, v[2], v[6], v[10], v[14]);
        G(r, 3, v[3], v[7], v[11], v[15]);
        G(r, 4, v[0], v[5], v[10], v[15]);
        G(r, 5, v[1], v[6], v[11], v[12]);
        G(r, 6, v[2], v[7], v[8], v[13]);
        G(r, 7, v[3], v[4], v[9], v[14]);
    }
#undef G
    for (int i = 0; i < 8; i++) S->h[i] ^= v[i] ^ v[i + 8];
}

inline void blake2b_init(Blake2bState* S, size_t outlen) {
    std::memset(S, 0, sizeof(*S));
    S->outlen = outlen;
    for (int i = 0; i < 8; i++) S->h[i] = BLAKE2B_IV[i];
    // param block: digest_length | key_length<<8 | fanout<<16 | depth<<24
    S->h[0] ^= 0x01010000ULL ^ (uint64_t)outlen;
}

inline void blake2b_update(Blake2bState* S, const uint8_t* in, size_t inlen) {
    while (inlen > 0) {
        if (S->buflen == 128) {
            S->t[0] += 128;
            if (S->t[0] < 128) S->t[1]++;
            blake2b_compress(S, S->buf, false);
            S->buflen = 0;
        }
        size_t take = 128 - S->buflen;
        if (take > inlen) take = inlen;
        std::memcpy(S->buf + S->buflen, in, take);
        S->buflen += take;
        in += take;
        inlen -= take;
    }
}

inline void blake2b_final(Blake2bState* S, uint8_t* out) {
    S->t[0] += S->buflen;
    if (S->t[0] < S->buflen) S->t[1]++;
    std::memset(S->buf + S->buflen, 0, 128 - S->buflen);
    blake2b_compress(S, S->buf, true);
    uint8_t full[64];
    for (int i = 0; i < 8; i++) std::memcpy(full + i * 8, &S->h[i], 8);
    std::memcpy(out, full, S->outlen);
}

}  // namespace pwnative
