"""Live vector index under churn: keyed upserts through a python
connector into a KNN data index, queried as-of-now.

The ingest stream re-upserts a key, exercising the delta-segment /
background-merge path (PR 9).  The connector is ``pw.io.python`` — a
single reader thread, so the keyed upsert is order-safe and the
distribution-safety pass (PW-X001) stays quiet; swap the feed for a
byte-range file source and it would not.  Lintable without running:
``python -m pathway_tpu.cli lint examples/index_churn.py`` (accepted
warnings in ``scripts/lint_baseline.json``: the embedding ``pw.apply``
is a Python fallback on the hot path, PW-P001; the KNN index is
deliberately a single unsharded owner — the point here is the
delta/merge path, not availability — so the single-owner-no-standby
warning PW-R002 is accepted rather than fixed with
``serving.PartitionedIndex``).
"""

import pathway_tpu as pw
from pathway_tpu.io.python import ConnectorSubject
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory


class DocSchema(pw.Schema):
    doc_id: str = pw.column_definition(primary_key=True)
    vx: float
    vy: float


class QuerySchema(pw.Schema):
    qid: str = pw.column_definition(primary_key=True)
    qx: float
    qy: float


class DocFeed(ConnectorSubject):
    def run(self):
        self.next(doc_id="a", vx=1.0, vy=0.0)
        self.next(doc_id="b", vx=0.0, vy=1.0)
        self.commit()
        # churn: the re-upsert lands in a delta segment and is merged
        self.next(doc_id="a", vx=0.5, vy=0.5)
        self.commit()


class QueryFeed(ConnectorSubject):
    def run(self):
        self.next(qid="q1", qx=1.0, qy=0.0)
        self.commit()


docs = pw.io.python.read(DocFeed("docs"), schema=DocSchema, name="docs")
docs = docs.select(
    doc_id=pw.this.doc_id,
    vec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.vx, pw.this.vy),
)
queries = pw.io.python.read(QueryFeed("queries"), schema=QuerySchema, name="queries")
queries = queries.select(
    qid=pw.this.qid,
    qvec=pw.apply(lambda x, y: (float(x), float(y)), pw.this.qx, pw.this.qy),
)

index = BruteForceKnnFactory(dimensions=2, reserved_space=16).build_data_index(
    docs.vec, docs
)
hits = index.query_as_of_now(queries.qvec, number_of_matches=2)


def on_change(key, row, time, is_addition):
    if is_addition:
        print(f"{row['qid']}: {row.get('_pw_index_reply')}")


pw.io.subscribe(hits, on_change=on_change)
pw.run()
