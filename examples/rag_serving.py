"""Multi-tenant RAG serving graph (build only).

Builds the :class:`RagServingApp` ingest dataflow — python-connector
doc feed → splitter → keyed upsert into a churn-safe SegmentedIndex —
into the global graph so ``pw.analyze()`` / ``cli lint`` can verify it:
the serving nodes carry ``meta["serving"]`` stage annotations and the
sink declares itself a keyed index upsert, which PW-X001 checks against
the (order-preserving, single-reader) feed.  The index is sharded
across two snapshot-backed owners (``shards=2``), so a dead owner
degrades answers (``partial: true``) instead of taking the query
surface down — which is also what keeps PW-R002 quiet.  Accepted
warnings live in ``scripts/lint_baseline.json`` (the splitter
``pw.apply`` is a Python fallback on the hot path, PW-P001).
"""

import pathway_tpu as pw  # noqa: F401  (pw.run is what the lint stubs)
from pathway_tpu.serving import RagServingApp, TenantPolicy

app = RagServingApp(
    {"demo": TenantPolicy("interactive", rate_per_s=50.0, burst=10, queue_cap=32)},
    embed_dim=16,
    delta_cap=32,
    auto_merge=False,
    shards=2,
)
app.build()
app.close()
