"""Streaming wordcount over a jsonlines directory.

The canonical demo graph (bench.py's wordcount, as a standalone
program).  Lintable without running: ``python -m pathway_tpu.cli lint
examples/wordcount.py``.  The analyzer's accepted warnings for it live
in ``scripts/lint_baseline.json``: a file source feeding a groupby is a
full exchange (PW-X002) and unwindowed state (PW-S001) — both are the
point of the demo, not bugs.
"""

import json
import os
import tempfile

import pathway_tpu as pw


class WordSchema(pw.Schema):
    word: str


data_dir = tempfile.mkdtemp(prefix="pw_wordcount_")
with open(os.path.join(data_dir, "words.jsonl"), "w", encoding="utf-8") as f:
    for w in ["to", "be", "or", "not", "to", "be"]:
        f.write(json.dumps({"word": w}) + "\n")

words = pw.io.jsonlines.read(data_dir, schema=WordSchema, mode="static")
counts = words.groupby(pw.this.word).reduce(
    pw.this.word, n=pw.reducers.count()
)


def on_change(key, row, time, is_addition):
    if is_addition:
        print(f"{row['word']}: {row['n']}")


pw.io.subscribe(counts, on_change=on_change)
pw.run()
