"""Static state-growth & memory-capacity estimation.

Abstract interpretation over the captured engine graph: every operator
gets a **state-growth class** from a four-point lattice

- ``O(1)``      — no retained state (or a constant amount)
- ``O(window)`` — retention bounded by a temporal behavior / window
- ``O(keys)``   — linear in the number of DISTINCT keys (upsert sources,
  fixed-accumulator groupbys, deduplicate, keyed indexes)
- ``O(stream)`` — linear in total rows ingested: the class that turns a
  long-running deployment into an OOM schedule

plus a bytes estimate: per-row widths come from the build-time dtype
annotations (fixed-width scalars are exact; str/bytes/ndarray are
parameterized — constant expressions are measured from their actual
value), retained cardinalities from :class:`GraphFacts` (streaming /
unbounded / append-only) and the numeric parameters of
:class:`EstimateParams`, and the per-worker split from the
``distribution.py`` placement lattice.

The estimator is **plan-aware**: :func:`estimate_memory` runs over the
``optimize_graph`` rewritten view, so dead-column elimination (nulled
``ConstExpression(None)`` select slots) and append-only reducer
specialization (``AppendOnly*`` accumulators replacing row-retaining
multisets) shrink the estimate exactly where they shrink runtime state.

Three registry codes ride on the same model (:func:`check_memory`, part
of ``ALL_PASSES``):

- **PW-M001** (error): ``O(stream)`` operator state on an unbounded
  streaming path that reaches a sink.
- **PW-M002** (warning): estimated footprint exceeds
  ``PATHWAY_MEMORY_BUDGET`` (bytes, or with K/M/G[i]B suffix), with a
  per-operator breakdown in ``details``.
- **PW-M003** (warning): checkpointed ``O(stream)`` state — snapshot
  bytes grow with stream length, eroding recovery-time targets.

Runtime cross-validation closes the loop: the scheduler samples measured
per-operator state bytes (``pathway_tpu_state_bytes{operator}``), and
``bench.py``'s ``bench_capacity`` records predicted-vs-measured ratios
in ``BENCH_capacity.json``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Any

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex

from pathway_tpu.analysis.diagnostics import SEV_ERROR, SEV_WARNING, Diagnostic
from pathway_tpu.analysis.graph_facts import GraphFacts

__all__ = [
    "G_CONSTANT",
    "G_BOUNDED",
    "G_KEYS",
    "G_STREAM",
    "growth_join",
    "dtype_width",
    "EstimateParams",
    "OperatorMemory",
    "MemoryReport",
    "estimate_memory",
    "check_memory",
    "parse_budget",
]

# ---------------------------------------------------------------------------
# the state-growth lattice

G_CONSTANT = "O(1)"
G_BOUNDED = "O(window)"
G_KEYS = "O(keys)"
G_STREAM = "O(stream)"

_G_ORDER = {G_CONSTANT: 0, G_BOUNDED: 1, G_KEYS: 2, G_STREAM: 3}


def growth_join(*growths: str) -> str:
    """Least upper bound on the growth lattice."""
    best = G_CONSTANT
    for g in growths:
        if _G_ORDER.get(g, 0) > _G_ORDER[best]:
            best = g
    return best


def growth_meet(*growths: str) -> str:
    """Greatest lower bound on the growth lattice."""
    best = G_STREAM
    for g in growths:
        if _G_ORDER.get(g, 3) < _G_ORDER[best]:
            best = g
    return best


# ---------------------------------------------------------------------------
# bytes-per-row from dtype annotations

#: exact CPython-object widths for fixed-size scalars (small ints/bools
#: are interned, floats/pointers/datetimes are one 8-byte payload each —
#: container overhead is charged separately per retained entry)
_FIXED_WIDTHS = {
    dt.INT: 8,
    dt.FLOAT: 8,
    dt.BOOL: 8,
    dt.POINTER: 8,
    dt.DURATION: 8,
    dt.DATE_TIME_NAIVE: 8,
    dt.DATE_TIME_UTC: 8,
    dt.NONE: 8,
}

#: per-retained-row container overhead: dict slot + key object + the
#: row tuple header.  Calibrated against ``approx_state_bytes`` samples
#: of the running engine (``bench.py bench_capacity`` cross-validates
#: the two within 3x) — CPython object headers cost real bytes and the
#: estimate must describe THIS engine, not a hypothetical packed one.
ENTRY_OVERHEAD = 300
#: per-group overhead of a groupby entry: the group dict itself plus
#: gvals / accs / count / last_out slots around the accumulators
#: (calibrated the same way; see ENTRY_OVERHEAD)
GROUP_OVERHEAD = 800
#: one fixed-size accumulator object (count/sum/avg/append-only extreme)
ACC_FIXED = 56


def dtype_width(
    d: Any, *, str_bytes: int = 32, array_bytes: int = 256
) -> int:
    """Estimated payload bytes for one value of dtype ``d``; fixed-width
    scalars are exact, str/bytes/ndarray use the parameterized sizes."""
    if isinstance(d, dt.DType):
        d = d.strip_optional()
    w = _FIXED_WIDTHS.get(d)
    if w is not None:
        return w
    if d in (dt.STR, dt.BYTES):
        return str_bytes
    if d == dt.JSON:
        return 4 * str_bytes
    if d == dt.ANY_ARRAY or "Array" in type(d).__name__:
        return array_bytes
    return 24  # ANY / unannotated: a small boxed object


def _expr_width(expr: Any, declared: Any, params: "EstimateParams") -> int:
    """Width of one select column: constant expressions are measured
    from the actual value (the VM program is LOAD_CONST), everything
    else falls back to the declared dtype."""
    if type(expr) is ex.ConstExpression:
        v = expr._value
        if isinstance(v, (str, bytes)):
            return 49 + len(v)  # CPython str/bytes header + payload
    return dtype_width(
        declared, str_bytes=params.str_bytes, array_bytes=params.array_bytes
    )


def _is_nulled(expr: Any) -> bool:
    """A select slot the plan compiler dead-column-eliminated: replaced
    by a constant-None expression that is never computed or retained."""
    return type(expr) is ex.ConstExpression and expr._value is None


# ---------------------------------------------------------------------------
# parameters

@dataclass(frozen=True)
class EstimateParams:
    """Numeric scenario the symbolic growth classes are evaluated at.

    ``rows`` is total stream length, ``distinct_keys`` the live key
    cardinality, ``window_rows`` the rows a behavior/window keeps live,
    ``static_rows`` the size assumed for static (batch) sources."""

    rows: int = 1_000_000
    distinct_keys: int = 10_000
    window_rows: int = 10_000
    static_rows: int = 10_000
    str_bytes: int = 32
    array_bytes: int = 256
    workers: int = 1

    @classmethod
    def from_env(cls, **overrides: Any) -> "EstimateParams":
        def _i(name: str, default: int) -> int:
            v = os.environ.get(name, "").strip()
            try:
                return int(v) if v else default
            except ValueError:
                return default

        base = cls(
            rows=_i("PATHWAY_MEMORY_ROWS", cls.rows),
            distinct_keys=_i("PATHWAY_MEMORY_KEYS", cls.distinct_keys),
            window_rows=_i("PATHWAY_MEMORY_WINDOW_ROWS", cls.window_rows),
            static_rows=_i("PATHWAY_MEMORY_STATIC_ROWS", cls.static_rows),
            str_bytes=_i("PATHWAY_MEMORY_STR_BYTES", cls.str_bytes),
            array_bytes=_i("PATHWAY_MEMORY_ARRAY_BYTES", cls.array_bytes),
            workers=_i("PATHWAY_MEMORY_WORKERS", cls.workers),
        )
        clean = {k: v for k, v in overrides.items() if v is not None}
        return replace(base, **clean) if clean else base

    def cardinality(self, growth: str) -> int:
        """Retained-entry count a growth class evaluates to here."""
        if growth == G_STREAM:
            return self.rows
        if growth == G_KEYS:
            return self.distinct_keys
        if growth == G_BOUNDED:
            return self.window_rows
        return 0


def parse_budget(s: "str | None") -> "int | None":
    """``PATHWAY_MEMORY_BUDGET`` value -> bytes: a plain integer or a
    K/M/G/T with optional i/iB/B suffix (decimal and binary both read as
    binary — capacity planning rounds the safe way)."""
    if not s:
        return None
    t = s.strip().upper().removesuffix("IB").removesuffix("B").removesuffix("I")
    mult = 1
    for suffix, m in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30), ("T", 1 << 40)):
        if t.endswith(suffix):
            t = t[: -len(suffix)]
            mult = m
            break
    try:
        return int(float(t) * mult)
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# the per-operator model

@dataclass(frozen=True)
class OperatorMemory:
    """One stateful operator's estimate."""

    node_id: int
    name: str
    kind: str
    growth: str
    total_bytes: int
    per_worker_bytes: int
    placement: str
    #: column names whose widths the estimate counted (from the nearest
    #: select upstream); plan-nulled dead columns are absent
    columns: tuple[str, ...]
    detail: str
    checkpointed: bool


@dataclass(frozen=True)
class MemoryReport:
    """The ``pw.estimate_memory()`` capacity report."""

    operators: tuple[OperatorMemory, ...]
    total_bytes: int
    max_worker_bytes: int
    workers: int
    level: int
    growth: str
    params: EstimateParams

    def by_id(self) -> dict[int, OperatorMemory]:
        return {o.node_id: o for o in self.operators}

    def format(self) -> str:
        lines = [
            f"memory capacity estimate (optimize={self.level}, "
            f"workers={self.workers}, rows={self.params.rows}, "
            f"keys={self.params.distinct_keys})",
            f"{'operator':<28} {'growth':<10} {'bytes':>12} "
            f"{'per-worker':>12}  detail",
            "-" * 88,
        ]
        for o in sorted(
            self.operators, key=lambda o: o.total_bytes, reverse=True
        ):
            cols = f" [{', '.join(o.columns)}]" if o.columns else ""
            lines.append(
                f"{o.name + '#' + str(o.node_id):<28} {o.growth:<10} "
                f"{o.total_bytes:>12} {o.per_worker_bytes:>12}  "
                f"{o.detail}{cols}"
            )
        lines.append("-" * 88)
        lines.append(
            f"{'TOTAL':<28} {self.growth:<10} {self.total_bytes:>12} "
            f"{self.max_worker_bytes:>12}  (per-worker = hottest rank)"
        )
        return "\n".join(lines)


#: reducer impl classes whose accumulator is a fixed-size object — the
#: append-only variants keep their user-facing ``.name`` (min/max/...),
#: so classification MUST look at the instance type, which is what the
#: plan compiler's ``specialize_append_only`` actually swaps
_FIXED_ACC_CLASSES = {"CountReducer", "SumReducer", "AvgReducer", "NpSumReducer"}

#: reducer NAMES with fixed accumulators — fallback when a node carries
#: only build-time meta (name-based: cannot see plan specialization)
_FIXED_ACC_NAMES = {"count", "sum", "avg", "npsum"}

#: node classes that retain one entry per live input row, keyed by row
#: key (set ops, cell/row patches, sort/ix neighborhood state, ...)
_ROW_RETAINERS = {
    "IntersectNode",
    "SubtractNode",
    "UpdateRowsNode",
    "UpdateCellsNode",
    "ZipNode",
    "SortNode",
    "IxNode",
    "GradualBroadcastNode",
}

#: temporal buffer nodes: retention bounded by the behavior itself
_BOUNDED_BUFFERS = {"TemporalBehaviorNode", "SessionAssignNode"}


def _retaining_reducers(n: eg.Node) -> tuple[int, int]:
    """(fixed_acc_count, row_retaining_count) for a groupby node,
    classified from the LIVE reducer instances when present (plan-aware:
    ``AppendOnly*`` swaps land there), meta names otherwise."""
    args = getattr(n, "reducer_args", None)
    if args:
        fixed = retaining = 0
        for impl, _arg_fn in args:
            cls = type(impl).__name__
            if cls in _FIXED_ACC_CLASSES or cls.startswith("AppendOnly"):
                fixed += 1
            else:
                retaining += 1
        return fixed, retaining
    names = n.meta.get("groupby", {}).get("reducers", ())
    fixed = sum(1 for nm in names if nm in _FIXED_ACC_NAMES)
    return fixed, max(0, len(names) - fixed)


class _Estimator:
    """One forward pass over the graph: output-cardinality growth per
    node, then per-class state models."""

    def __init__(
        self, graph: eg.EngineGraph, facts: GraphFacts, params: EstimateParams
    ):
        self.graph = graph
        self.facts = facts
        self.params = params
        #: growth class of each node's OUTPUT cardinality (live rows)
        self.out_growth: dict[int, str] = {}
        #: numeric evaluation of that cardinality under ``params``
        self.out_rows: dict[int, int] = {}
        self._layout_cache: dict[int, tuple[tuple[str, ...], int]] = {}
        for n in graph.nodes:
            self._forward(n)

    # -- output cardinality -------------------------------------------
    def _forward(self, n: eg.Node) -> None:
        p = self.params
        if isinstance(n, eg.InputNode):
            if n.subject is not None:
                if n.upsert:
                    g, r = G_KEYS, p.distinct_keys
                else:
                    g, r = G_STREAM, p.rows
            else:
                g, r = G_CONSTANT, p.static_rows
        elif isinstance(n, eg.GroupByNode):
            g, r = self._groups_of(n)
        elif isinstance(n, eg.DeduplicateNode):
            gi, ri = self._in_card(n)
            g = growth_meet(gi, G_KEYS)
            r = min(ri, p.distinct_keys)
        elif isinstance(n, eg.JoinNode):
            g, r = self._in_card(n)
        else:
            g, r = self._in_card(n)
        self.out_growth[n.id] = g
        self.out_rows[n.id] = r

    def _in_card(self, n: eg.Node) -> tuple[str, int]:
        if not n.inputs:
            return G_CONSTANT, 0
        g = growth_join(*(self.out_growth.get(i.id, G_CONSTANT) for i in n.inputs))
        r = max(self.out_rows.get(i.id, 0) for i in n.inputs)
        return g, r

    def _groups_of(self, n: eg.Node) -> tuple[str, int]:
        """Live-group cardinality of a groupby: distinct keys over an
        unbounded input, window-bounded under a behavior, input-bounded
        over static data."""
        p = self.params
        gi, ri = self._in_card(n)
        if any(i.id in self.facts.unbounded for i in n.inputs):
            return G_KEYS, p.distinct_keys
        if any(i.id in self.facts.streaming for i in n.inputs):
            # streaming but bounded upstream (window/behavior)
            return growth_meet(gi, G_BOUNDED), min(ri, p.window_rows)
        return growth_meet(gi, G_KEYS), min(ri, p.distinct_keys)

    # -- row layout ----------------------------------------------------
    def row_layout(self, node: eg.Node) -> tuple[tuple[str, ...], int]:
        """(counted column names, bytes/row) from the nearest select or
        source dtype annotation upstream; plan-nulled select slots are
        skipped — they carry a shared ``None``, not a value."""
        cached = self._layout_cache.get(node.id)
        if cached is not None:
            return cached
        p = self.params
        out: tuple[tuple[str, ...], int] = ((), 3 * 24)  # unannotated
        work = [node]
        seen: set[int] = set()
        while work:
            n = work.pop(0)
            if n.id in seen:
                continue
            seen.add(n.id)
            sel = n.meta.get("select")
            if sel and sel.get("dtypes"):
                names: list[str] = []
                width = 0
                exprs = sel.get("exprs", ())
                for i, (nm, d) in enumerate(
                    zip(sel.get("names", ()), sel["dtypes"])
                ):
                    e = exprs[i] if i < len(exprs) else None
                    if e is not None and _is_nulled(e):
                        continue
                    names.append(nm)
                    width += _expr_width(e, d, p)
                out = (tuple(names), max(width, 8))
                break
            src = n.meta.get("source", {})
            if src.get("dtypes"):
                width = sum(
                    dtype_width(
                        d, str_bytes=p.str_bytes, array_bytes=p.array_bytes
                    )
                    for d in src["dtypes"]
                )
                out = ((), max(width, 8))
                break
            work.extend(n.inputs)
        self._layout_cache[node.id] = out
        return out

    # -- per-node state model -----------------------------------------
    def estimate_node(
        self, n: eg.Node
    ) -> "tuple[str, int, tuple[str, ...], str] | None":
        """(growth, total bytes, counted columns, detail) for a stateful
        node; None for stateless operators."""
        p = self.params
        cls = type(n).__name__

        if isinstance(n, eg.InputNode):
            if not n.upsert:
                return None  # append sessions never populate state
            g, r = self.out_growth[n.id], self.out_rows[n.id]
            cols, w = self.row_layout(n)
            return (
                growth_meet(g, G_KEYS),
                r * (w + ENTRY_OVERHEAD),
                cols,
                f"upsert session: {r} keys x {w + ENTRY_OVERHEAD} B",
            )

        if isinstance(n, eg.GroupByNode):
            gg, groups = self._groups_of(n)
            fixed, retaining = _retaining_reducers(n)
            key_cols = tuple(n.meta.get("groupby", {}).get("grouping", ()))
            _in_cols, in_w = self.row_layout(n.inputs[0]) if n.inputs else ((), 24)
            out_cols, out_w = self.row_layout(n)
            per_group = GROUP_OVERHEAD + out_w + fixed * ACC_FIXED
            total = groups * per_group
            growth = gg
            detail = (
                f"{groups} groups x {per_group} B "
                f"({fixed} fixed acc{'s' if fixed != 1 else ''}"
            )
            if retaining:
                gi, ri = self._in_card(n)
                growth = growth_join(gg, gi)
                retained = max(ri, groups)
                total += retaining * retained * (in_w + ENTRY_OVERHEAD)
                detail += (
                    f", {retaining} row-retaining x {retained} rows"
                )
            detail += ")"
            return growth, total, out_cols or key_cols, detail

        if isinstance(n, eg.JoinNode):
            if n.meta.get("temporal", {}).get("bounded"):
                g = G_BOUNDED
                sides = [(G_BOUNDED, p.window_rows)] * 2
            else:
                sides = [
                    (
                        self.out_growth.get(i.id, G_CONSTANT),
                        self.out_rows.get(i.id, 0),
                    )
                    for i in n.inputs
                ]
                g = growth_join(*(sg for sg, _ in sides))
            total = 0
            for inp, (_sg, sr) in zip(n.inputs, sides):
                _c, w = self.row_layout(inp)
                total += sr * (w + ENTRY_OVERHEAD)
            cols, _w = self.row_layout(n)
            rows = " + ".join(str(sr) for _sg, sr in sides)
            return g, total, cols, f"join retains both sides: {rows} rows"

        if cls == "IntervalJoinNode":
            # both sides buffer only rows inside the time band: the
            # watermark evicts everything older, so retention is the
            # window, not the stream
            total = 0
            for inp in n.inputs:
                _c, w = self.row_layout(inp)
                total += p.window_rows * (w + ENTRY_OVERHEAD)
            cols, _w = self.row_layout(n)
            return (
                G_BOUNDED,
                total,
                cols,
                f"time-band buffer: {p.window_rows} rows/side",
            )

        if cls in ("AsofJoinNode", "AsofNowJoinNode"):
            # retains the live right-side history (sorted per key) plus
            # the per-left-row answer cache: entries track live input
            # rows, so growth follows the inputs — an append-only raw
            # stream makes this linear even though RESULTS are frozen
            total = 0
            rows: list[int] = []
            for inp in n.inputs:
                _c, w = self.row_layout(inp)
                r = self.out_rows.get(inp.id, 0)
                total += r * (w + ENTRY_OVERHEAD)
                rows.append(r)
            g = growth_join(
                *(self.out_growth.get(i.id, G_CONSTANT) for i in n.inputs)
            )
            cols, _w = self.row_layout(n)
            return (
                g,
                total,
                cols,
                "asof retains live inputs: "
                + " + ".join(str(r) for r in rows)
                + " rows",
            )

        if isinstance(n, eg.DeduplicateNode):
            g, r = self.out_growth[n.id], self.out_rows[n.id]
            cols, w = self.row_layout(n)
            return (
                growth_meet(g, G_KEYS),
                r * (w + ENTRY_OVERHEAD),
                cols,
                f"one kept row per instance: {r} x {w + ENTRY_OVERHEAD} B",
            )

        if cls in _ROW_RETAINERS:
            g, r = self._in_card(n)
            cols, w = self.row_layout(n)
            total = sum(
                self.out_rows.get(i.id, 0) * (w + ENTRY_OVERHEAD)
                for i in n.inputs
            )
            return g, total, cols, f"retains live input rows ({r} max/side)"

        if cls in _BOUNDED_BUFFERS:
            cols, w = self.row_layout(n)
            return (
                G_BOUNDED,
                p.window_rows * (w + ENTRY_OVERHEAD),
                cols,
                f"behavior buffer: {p.window_rows} rows",
            )

        if cls == "ExternalIndexNode":
            # keyed upsert into the index: one entry per live doc id
            g = growth_meet(
                self.out_growth.get(n.inputs[0].id, G_KEYS) if n.inputs else G_KEYS,
                G_KEYS,
            )
            r = min(
                self.out_rows.get(n.inputs[0].id, p.distinct_keys)
                if n.inputs
                else p.distinct_keys,
                p.distinct_keys,
            )
            cols, w = self.row_layout(n.inputs[0]) if n.inputs else ((), 24)
            per = w + p.array_bytes + ENTRY_OVERHEAD
            return g, r * per, cols, f"index: {r} docs x {per} B (payload+vector)"

        if isinstance(n, eg.CaptureNode):
            g, r = self._in_card(n)
            cols, w = self.row_layout(n)
            return g, r * (w + ENTRY_OVERHEAD), cols, f"captures {r} rows"

        return None


def _placement_of(dist: Any, nid: int) -> tuple:
    try:
        return dist.placement.get(nid, ("single",))
    except Exception:
        return ("single",)


def _split_bytes(placement: tuple, total: int, workers: int) -> int:
    """Bytes held by the hottest worker under the placement lattice."""
    if workers <= 1 or placement[0] in ("single", "repl"):
        return total
    return -(-total // workers)  # key/cols/byterange/rr: even split


def build_report(
    engine_graph: eg.EngineGraph,
    facts: "GraphFacts | None" = None,
    *,
    params: "EstimateParams | None" = None,
    level: int = 0,
) -> MemoryReport:
    """Estimate over the graph AS GIVEN (callers resolve plan views)."""
    if facts is None:
        facts = GraphFacts(engine_graph)
    if params is None:
        params = EstimateParams.from_env()
    est = _Estimator(engine_graph, facts, params)
    try:
        dist = facts.distribution
    except Exception:
        dist = None
    ops: list[OperatorMemory] = []
    worker0 = 0
    for n in engine_graph.nodes:
        got = est.estimate_node(n)
        if got is None:
            continue
        growth, total, cols, detail = got
        placement = _placement_of(dist, n.id) if dist is not None else ("single",)
        per_worker = _split_bytes(placement, total, params.workers)
        worker0 += per_worker
        ops.append(
            OperatorMemory(
                node_id=n.id,
                name=n.name,
                kind=type(n).__name__,
                growth=growth,
                total_bytes=total,
                per_worker_bytes=per_worker,
                placement=placement[0],
                columns=cols,
                detail=detail,
                checkpointed=True,  # ctx.states is snapshot territory
            )
        )
    total_bytes = sum(o.total_bytes for o in ops)
    return MemoryReport(
        operators=tuple(ops),
        total_bytes=total_bytes,
        max_worker_bytes=worker0,
        workers=params.workers,
        level=level,
        growth=growth_join(*(o.growth for o in ops)) if ops else G_CONSTANT,
        params=params,
    )


def estimate_memory(
    graph: Any = None,
    *,
    optimize: "int | None" = None,
    rows: "int | None" = None,
    distinct_keys: "int | None" = None,
    window_rows: "int | None" = None,
    static_rows: "int | None" = None,
    str_bytes: "int | None" = None,
    array_bytes: "int | None" = None,
    workers: "int | None" = None,
) -> MemoryReport:
    """Plan-aware capacity report for a captured graph (default: the
    global parse graph at the default/env optimization level, i.e. the
    view that actually runs).  ``optimize=0`` estimates the unrewritten
    graph."""
    if graph is None:
        from pathway_tpu.internals.parse_graph import G

        graph = G.engine_graph
    engine_graph = getattr(graph, "engine_graph", graph)
    from pathway_tpu.analysis.rewrite import optimize_graph, resolve_level

    level = resolve_level(optimize)
    if level > 0:
        engine_graph, _plan = optimize_graph(engine_graph, level)
    params = EstimateParams.from_env(
        rows=rows,
        distinct_keys=distinct_keys,
        window_rows=window_rows,
        static_rows=static_rows,
        str_bytes=str_bytes,
        array_bytes=array_bytes,
        workers=workers,
    )
    return build_report(engine_graph, params=params, level=level)


# ---------------------------------------------------------------------------
# the diagnostics pass (ALL_PASSES member)


def _diag(
    code: str, sev: str, msg: str, node: "eg.Node | None", **details: Any
) -> Diagnostic:
    return Diagnostic(
        code=code,
        severity=sev,
        message=msg,
        trace=getattr(node, "trace", "") or "" if node is not None else "",
        node_id=node.id if node is not None else None,
        node_name=node.name if node is not None else "",
        details=details,
    )


def check_memory(graph: eg.EngineGraph, facts: GraphFacts) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    params = EstimateParams.from_env()
    report = build_report(graph, facts, params=params)
    by_node = {n.id: n for n in graph.nodes}
    for op in report.operators:
        if op.growth != G_STREAM or op.node_id not in facts.streaming:
            continue
        n = by_node.get(op.node_id)
        if n is None:
            continue
        if op.node_id in facts.reaches_sink:
            out.append(
                _diag(
                    "PW-M001",
                    SEV_ERROR,
                    f"operator state is linear in the stream ({op.detail}): "
                    "every ingested row is retained forever on a path that "
                    "reaches a sink; bound it with a window/behavior, an "
                    "upsert-keyed source, or an append-only-safe reducer",
                    n,
                    growth=op.growth,
                    estimated_bytes=op.total_bytes,
                )
            )
        if op.checkpointed:
            out.append(
                _diag(
                    "PW-M003",
                    SEV_WARNING,
                    "checkpointed operator state grows with stream length "
                    f"({op.detail}): snapshot bytes and recovery time "
                    "degrade as the run ages; bound retention or exclude "
                    "the operator from persistence",
                    n,
                    growth=op.growth,
                    estimated_bytes=op.total_bytes,
                )
            )
    budget = parse_budget(os.environ.get("PATHWAY_MEMORY_BUDGET"))
    if budget is not None and report.max_worker_bytes > budget:
        breakdown = [
            (f"{o.name}#{o.node_id}", o.per_worker_bytes)
            for o in sorted(
                report.operators,
                key=lambda o: o.per_worker_bytes,
                reverse=True,
            )[:8]
        ]
        out.append(
            _diag(
                "PW-M002",
                SEV_WARNING,
                f"estimated per-worker footprint "
                f"{report.max_worker_bytes} B exceeds "
                f"PATHWAY_MEMORY_BUDGET={budget} B "
                f"(top: {', '.join(f'{n}={b}B' for n, b in breakdown[:3])})",
                None,
                budget_bytes=budget,
                estimated_bytes=report.max_worker_bytes,
                breakdown=breakdown,
            )
        )
    return out
