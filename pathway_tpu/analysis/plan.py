"""Inspectable execution plans for the graph-rewriting optimizer.

Every rewrite the optimizer (``analysis/rewrite.py``) applies is
recorded as a :class:`RewriteStep` inside an :class:`ExecutionPlan`.
The plan is the *audit trail* of the static half of columnar execution:
``pw.explain()`` returns one, ``cli lint --plan`` prints one, and the
textual format below is committed as golden files
(``tests/plans/*.txt``) so any plan change shows up as a reviewable
diff.

Format stability contract: node labels are ``{name}#{id}`` (ids are
creation-order per graph, deterministic for a deterministic build
script), steps are listed in application order, and detail strings are
built only from sorted/stable inputs.  Nothing in the format depends on
the native module being present — pass *decisions* are made on the
native-free lint lowering, native code generation is best-effort.
"""

from __future__ import annotations

from typing import Any

__all__ = ["RewriteStep", "ExecutionPlan"]


class RewriteStep:
    """One applied rewrite: which pass, which nodes, what changed."""

    __slots__ = ("pass_name", "nodes", "detail")

    def __init__(self, pass_name: str, nodes: list[str], detail: str = ""):
        self.pass_name = pass_name
        self.nodes = list(nodes)
        self.detail = detail

    def format(self) -> str:
        where = " + ".join(self.nodes)
        return f"{self.pass_name}: {where}" + (
            f" [{self.detail}]" if self.detail else ""
        )

    def __repr__(self) -> str:
        return f"RewriteStep({self.format()!r})"


class ExecutionPlan:
    """The optimizer's output: rewritten-graph summary + step log.

    ``counters()`` (rewrite count per pass) feeds ``/status`` →
    ``plan``, the ``pathway_tpu_plan_rewrites`` gauge on ``/metrics``,
    and the bench artifact.  ``format()`` is the golden-tested text.
    """

    def __init__(self, level: int):
        self.level = int(level)
        self.steps: list[RewriteStep] = []
        self.nodes_before = 0
        self.nodes_after = 0
        #: per-operator columnar decisions: (node_label, path, reason)
        #: where path is "columnar" or "row" and reason explains a row
        #: fallback (empty for columnar).  Golden-tested like steps.
        self.columnar: list[tuple[str, str, str]] = []

    def record(self, pass_name: str, nodes: list[Any], detail: str = "") -> None:
        """Append one step; ``nodes`` may be engine nodes (labelled
        ``{name}#{id}``) or pre-formatted strings."""
        labels = [
            n if isinstance(n, str) else f"{n.name}#{n.id}" for n in nodes
        ]
        self.steps.append(RewriteStep(pass_name, labels, detail))

    def record_columnar(self, node: Any, path: str, reason: str = "") -> None:
        """Record one operator's batch-execution decision ("columnar" =
        frame segments run native kernels; "row" = the operator
        materializes frames and runs row-at-a-time, with ``reason``)."""
        label = node if isinstance(node, str) else f"{node.name}#{node.id}"
        self.columnar.append((label, path, reason))

    def columnar_lines(self) -> list[str]:
        """The per-operator decision lines (shared by ``format()`` and
        the ``/status`` plan block)."""
        return [
            f"{label}: {path}" + (f" [{reason}]" if reason else "")
            for label, path, reason in self.columnar
        ]

    def counters(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for s in self.steps:
            out[s.pass_name] = out.get(s.pass_name, 0) + 1
        return out

    def count(self, pass_name: str) -> int:
        return self.counters().get(pass_name, 0)

    def format(self) -> str:
        lines = [
            f"== execution plan (optimize={self.level}) ==",
            f"nodes: {self.nodes_before} -> {self.nodes_after}",
        ]
        if not self.steps:
            lines.append("(no rewrites)")
        else:
            width = len(str(len(self.steps)))
            for i, s in enumerate(self.steps, 1):
                lines.append(f"{str(i).rjust(width)}. {s.format()}")
        counters = self.counters()
        if counters:
            lines.append(
                "counters: "
                + " ".join(f"{k}={v}" for k, v in sorted(counters.items()))
            )
        if self.columnar:
            lines.append("columnar:")
            lines.extend("  " + ln for ln in self.columnar_lines())
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.format()

    def __repr__(self) -> str:
        return (
            f"<ExecutionPlan level={self.level} steps={len(self.steps)} "
            f"nodes={self.nodes_before}->{self.nodes_after}>"
        )
