"""The plan compiler: analyzer-fact-driven graph rewriting.

``optimize_graph(graph, level)`` builds an *execution view* of the
captured dataflow graph — shallow node clones sharing the original ids
— and runs a deterministic pass pipeline over it, recording every
applied rewrite in an :class:`~pathway_tpu.analysis.plan.ExecutionPlan`.
The scheduler consumes the view transparently: it routes purely by
``node.id`` (consumers map, per-run states, exchange keys), so clones
with original ids slot in without any scheduler change, and the
original graph stays untouched for re-runs and for ``pw.explain()``.

Passes, by level:

- **1** — ``const_fold`` (evaluate constant subtrees at plan time),
  ``dead_column_elim`` (act on the PW-D001 fact: a column no consumer
  reads is replaced by a constant-``None`` slot at its producer, so the
  value is never computed and exchange frames carry a shared immutable
  ``None`` instead of real payloads; slot *positions* are preserved
  because consumers address columns positionally), ``select_fusion`` /
  ``filter_fusion`` (adjacent CALL_PY-free nodes collapse into one
  operator whose VM program is the bytecode splice of both —
  ``expr_vm.concat_programs``).
- **2** — additionally ``append_only_groupby`` (swap retraction-capable
  reducers for non-retracting ones when ``graph_facts`` proves the
  input append-only), ``pushdown_filter`` and ``pushdown_projection``
  (move predicates / column nulling across joins toward connectors).

Every *decision* is made on the native-free lint lowering
(``vm_abstract.lint_lower``), so plans are identical with or without
the native module; native code generation for rewritten programs is
best-effort and falls back to composed Python closures.
"""

from __future__ import annotations

import os
from typing import Any

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import api
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expr_vm as vm
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.table import _referenced_names, compile_exprs

from pathway_tpu.analysis import vm_abstract as va
from pathway_tpu.analysis.graph_facts import GraphFacts
from pathway_tpu.analysis.passes import _SINK_CLASSES, _consumer_usage
from pathway_tpu.analysis.plan import ExecutionPlan

__all__ = ["optimize_graph", "resolve_level", "DEFAULT_LEVEL"]

DEFAULT_LEVEL = 2


def resolve_level(optimize: "int | None" = None) -> int:
    """Effective optimization level: explicit ``run(optimize=)`` beats
    ``PATHWAY_OPTIMIZE`` beats the default (2).  Clamped to 0..2."""
    if optimize is None:
        env = os.environ.get("PATHWAY_OPTIMIZE", "")
        if env.strip():
            try:
                optimize = int(env)
            except ValueError:
                optimize = None
    if optimize is None:
        optimize = DEFAULT_LEVEL
    return max(0, min(2, int(optimize)))


# ---------------------------------------------------------------------------
# execution view


class _GraphView:
    """Mutable clone layer over an EngineGraph.  Clones share the
    original node ids (the scheduler's only addressing scheme); rewiring
    happens exclusively through the clones' ``inputs`` lists.  Nodes the
    rewriter inserts get fresh ids past the original range."""

    def __init__(self, graph: eg.EngineGraph):
        self.original = graph
        self.nodes: list[eg.Node] = [self._clone(n) for n in graph.nodes]
        self.by_id = {c.id: c for c in self.nodes}
        for c in self.nodes:
            if type(c).__name__ in _SINK_CLASSES:
                continue  # identity-kept: leave the original's wiring alone
            c.inputs = [self.by_id[i.id] for i in c.inputs]
        self._next_id = max(self.by_id, default=-1) + 1

    @staticmethod
    def _clone(n: eg.Node) -> eg.Node:
        # sinks are NOT cloned: ExportNode accumulates its update log and
        # closed-frontier on the node object itself, and user handles
        # (ExportedTable, capture contexts) hold the original — a clone
        # would absorb the run's state where nobody reads it.  No pass
        # rewrites a sink or repoints its inputs, and the scheduler
        # routes by input *id*, so sharing the object is safe.
        if type(n).__name__ in _SINK_CLASSES:
            return n
        c = object.__new__(type(n))
        c.__dict__ = dict(n.__dict__)
        # meta is edited per-clone (exprs swap on recompile); one level
        # of copy keeps the original graph's annotations pristine
        c.meta = dict(n.meta)
        return c

    def alloc_id(self) -> int:
        nid = self._next_id
        self._next_id += 1
        return nid

    def consumers(self) -> dict[int, list[eg.Node]]:
        out: dict[int, list[eg.Node]] = {n.id: [] for n in self.nodes}
        for n in self.nodes:
            for i in n.inputs:
                out.setdefault(i.id, []).append(n)
        return out

    def remove(self, node: eg.Node) -> None:
        self.nodes.remove(node)
        del self.by_id[node.id]

    def insert_before(self, anchor: eg.Node, node: eg.Node) -> None:
        self.nodes.insert(self.nodes.index(anchor), node)
        self.by_id[node.id] = node

    def finish(self) -> eg.EngineGraph:
        g = object.__new__(eg.EngineGraph)
        g.nodes = self.nodes
        # shared list: attach_prober() after optimization is still seen
        g.probers = self.original.probers
        for n in self.nodes:
            if type(n).__name__ in _SINK_CLASSES:
                continue  # identity-kept sink: don't touch the original
            n.graph = g
        return g


class _UsageFacts:
    """Minimal ``facts`` shim for :func:`passes._consumer_usage` over
    the current (possibly already rewritten) view topology."""

    def __init__(self, consumers: dict[int, list[eg.Node]]):
        self.consumers = consumers


# ---------------------------------------------------------------------------
# recompilation helpers (mirror the table-API build paths exactly)


def _recompile_select(
    n: eg.Node, sel: dict, new_exprs: list, relax: "tuple[int, ...]" = ()
) -> None:
    layout = sel["layout"]
    n.row_fn = compile_exprs(new_exprs, layout)
    if sel.get("kind") != "join_select":
        # join_select keeps the closure path it was built with
        n.programs = vm.lower_programs(new_exprs, layout)
    n.meta["select"] = {**sel, "exprs": list(new_exprs)}
    n.meta["used_cols"] = _referenced_names(new_exprs)
    if relax and n.typecheck_info is not None:
        names, dtypes = n.typecheck_info
        n.typecheck_info = (
            names,
            [dt.ANY if i in relax else d for i, d in enumerate(dtypes)],
        )
        n._checker = None


def _recompile_filter(n: eg.Node, flt: dict, e: Any) -> None:
    layout = flt["layout"]
    c = e._compile(layout.resolver)
    n.pred = lambda key, values, c=c: c((key, values))
    n.program = vm.lower_program(e, layout)
    n.meta["filter"] = {**flt, "exprs": [e]}
    n.meta["used_cols"] = _referenced_names([e])


def _lint_triple(e: Any, layout: Any) -> "tuple[list, list, list] | None":
    """CALL_PY-free raw (code, consts, pyfuncs) triple for one
    expression, or None — the native-independent fusion currency."""
    asm = va.lint_lower(e, layout)
    if asm is None or asm.pyfuncs:
        return None
    return (asm.code, asm.consts, [])


# ---------------------------------------------------------------------------
# constant folding

_FOLDABLE = (ex.BinaryExpression, ex.UnaryExpression, ex.IsNoneExpression)


def _no_resolver(ref: Any) -> Any:
    raise ValueError("constant subtree must not reference columns")


def _fold_expr(e: Any) -> tuple[Any, int]:
    """Bottom-up fold; returns (expression, number of collapsed
    subtrees).  A subtree folds when every leaf is already constant and
    evaluation neither raises nor yields the ERROR sentinel (those keep
    their per-row runtime semantics)."""
    kids = list(e._children())
    if not kids:
        return e, 0
    folded = 0
    new_kids = []
    changed = False
    for k in kids:
        nk, f = _fold_expr(k)
        folded += f
        changed = changed or nk is not k
        new_kids.append(nk)
    if changed:
        try:
            e = e._rebuild(new_kids)
        except Exception:
            return e, 0  # rebuild refused (dtype conflict): keep original
    if isinstance(e, _FOLDABLE) and all(
        type(k) is ex.ConstExpression for k in e._children()
    ):
        try:
            v = e._compile(_no_resolver)(None)
        except Exception:
            return e, folded
        if v is api.ERROR:
            return e, folded
        try:
            ne = ex.ConstExpression(v)
        except Exception:
            return e, folded
        ne._dtype = e._dtype
        return ne, folded + 1
    return e, folded


def _pass_const_fold(view: _GraphView, plan: ExecutionPlan) -> None:
    for n in view.nodes:
        sel = n.meta.get("select")
        if sel is not None and type(n) is eg.RowwiseNode:
            exprs, layout = sel.get("exprs"), sel.get("layout")
            if exprs is None or layout is None:
                continue
            total = 0
            new_exprs = []
            for e in exprs:
                try:
                    ne, k = _fold_expr(e)
                except Exception:
                    ne, k = e, 0
                total += k
                new_exprs.append(ne)
            if total:
                _recompile_select(n, sel, new_exprs)
                plan.record("const_fold", [n], f"subtrees={total}")
            continue
        flt = n.meta.get("filter")
        if flt is not None and type(n) is eg.FilterNode:
            exprs, layout = flt.get("exprs"), flt.get("layout")
            if not exprs or layout is None:
                continue
            try:
                ne, k = _fold_expr(exprs[0])
            except Exception:
                continue
            if k:
                _recompile_filter(n, flt, ne)
                plan.record("const_fold", [n], f"subtrees={k}")


# ---------------------------------------------------------------------------
# dead-column elimination (acts on the PW-D001 fact)


def _null_columns(
    n: eg.Node, sel: dict, dead: list[int]
) -> None:
    new_exprs = list(sel["exprs"])
    for i in dead:
        ne = ex.ConstExpression(None)
        new_exprs[i] = ne
    _recompile_select(n, sel, new_exprs, relax=tuple(dead))


def _pass_dead_columns(view: _GraphView, plan: ExecutionPlan) -> None:
    consumers = view.consumers()
    shim = _UsageFacts(consumers)
    # reverse topological order: nulling a consumer's dead columns
    # shrinks its used_cols, letting dead columns cascade upstream
    for n in reversed(view.nodes):
        sel = n.meta.get("select")
        if not sel or sel.get("kind") != "select" or type(n) is not eg.RowwiseNode:
            continue
        if not consumers.get(n.id):
            continue  # a table nobody consumes is the user's business
        used = _consumer_usage(n, shim)
        if used is None:
            continue
        names = sel.get("names", ())
        exprs = sel.get("exprs", ())
        dead = [
            i
            for i, name in enumerate(names)
            if not name.startswith("__")
            and name not in used
            and i < len(exprs)
            and type(exprs[i]) is not ex.ConstExpression
        ]
        if not dead:
            continue
        _null_columns(n, sel, dead)
        plan.record(
            "dead_column_elim",
            [n],
            "null=" + ",".join(names[i] for i in dead),
        )


# ---------------------------------------------------------------------------
# append-only specialization


def _pass_append_only(
    view: _GraphView, facts: GraphFacts, plan: ExecutionPlan
) -> None:
    for n in view.nodes:
        if type(n) is not eg.GroupByNode:
            continue
        inp = n.inputs[0] if n.inputs else None
        if inp is None or inp.id not in facts.append_only:
            continue
        # reducer_args is shared with the original node until the swap
        # copies it (specialize_append_only builds a fresh list)
        swapped = n.specialize_append_only()
        if swapped:
            plan.record(
                "append_only_groupby", [n], "reducers=" + ",".join(swapped)
            )


# ---------------------------------------------------------------------------
# select fusion


def _select_triples(n: eg.Node) -> "list | None":
    """Per-output-column raw program triples for a select-like rowwise
    node — from a previous fusion's stored triples, or freshly
    lint-lowered from the build-time meta.  None = not fusable."""
    pf = n.meta.get("plan_fused")
    if pf is not None:
        return pf["triples"]
    sel = n.meta.get("select")
    if not sel or sel.get("kind") not in ("select", "with_columns", "join_select"):
        return None
    exprs, layout = sel.get("exprs"), sel.get("layout")
    if exprs is None or layout is None:
        return None
    triples = []
    for e in exprs:
        t = _lint_triple(e, layout)
        if t is None:
            return None
        triples.append(t)
    return triples


def _compose_row_fns(fa: Any, fb: Any) -> Any:
    def fused(key: Any, values: tuple, fa=fa, fb=fb) -> tuple:
        return fb(key, fa(key, values))

    return fused


def _pass_fuse_selects(view: _GraphView, plan: ExecutionPlan) -> None:
    changed = True
    while changed:
        changed = False
        consumers = view.consumers()
        for b in list(view.nodes):
            if type(b) is not eg.RowwiseNode or len(b.inputs) != 1:
                continue
            a = b.inputs[0]
            if type(a) is not eg.RowwiseNode:
                continue
            if consumers.get(a.id) != [b]:
                continue
            b_triples = _select_triples(b)
            a_triples = _select_triples(a)
            if b_triples is None or a_triples is None:
                continue
            colmap = dict(enumerate(a_triples))
            try:
                fused_triples = [
                    vm.concat_programs(t, colmap) for t in b_triples
                ]
            except (KeyError, ValueError):
                continue
            b.inputs = [a.inputs[0]]
            b.row_fn = _compose_row_fns(a.row_fn, b.row_fn)
            capsules = [vm.compile_triple(t) for t in fused_triples]
            b.programs = (
                tuple(capsules) if all(c is not None for c in capsules) else None
            )
            b.meta.pop("select", None)
            b.meta["plan_fused"] = {"triples": fused_triples}
            a_used = a.meta.get("used_cols")
            if a_used is not None:
                b.meta["used_cols"] = list(a_used)
            else:
                b.meta.pop("used_cols", None)
            view.remove(a)
            plan.record(
                "select_fusion", [a, b], f"cols={len(fused_triples)}"
            )
            changed = True
            break


# ---------------------------------------------------------------------------
# filter fusion


def _filter_triple(n: eg.Node) -> "tuple | None":
    pf = n.meta.get("plan_fused_filter")
    if pf is not None:
        return pf["triple"]
    flt = n.meta.get("filter")
    if not flt:
        return None
    exprs, layout = flt.get("exprs"), flt.get("layout")
    if not exprs or layout is None:
        return None
    e = exprs[0]
    d = getattr(e, "_dtype", None)
    if not isinstance(d, dt.DType) or d.strip_optional() != dt.BOOL:
        return None  # non-bool truthiness diverges under fused AND
    return _lint_triple(e, layout)


def _fused_pred(pa: Any, pb: Any) -> Any:
    def fused(key: Any, values: tuple, pa=pa, pb=pb) -> Any:
        ka = pa(key, values)
        if ka is None or ka is api.ERROR or not ka:
            return False
        return pb(key, values)

    return fused


#: downstream pseudo-program `if col0 then col1 else False` — splicing
#: predicate A into slot 0 and predicate B into slot 1 yields the fused,
#: short-circuiting predicate bytecode (same shape _lower emits for
#: IfElseExpression, whose None/ERROR behaviour is differential-tested)
_AND_TEMPLATE = (
    [
        vm.OP_LOAD_COL, 0,
        vm.OP_BRANCH, 9, 11,
        vm.OP_LOAD_COL, 1,
        vm.OP_JUMP, 11,
        vm.OP_LOAD_CONST, 0,
    ],
    [False],
    [],
)


def _pass_fuse_filters(view: _GraphView, plan: ExecutionPlan) -> None:
    changed = True
    while changed:
        changed = False
        consumers = view.consumers()
        for b in list(view.nodes):
            if type(b) is not eg.FilterNode or len(b.inputs) != 1:
                continue
            a = b.inputs[0]
            if type(a) is not eg.FilterNode:
                continue
            if consumers.get(a.id) != [b]:
                continue
            ta = _filter_triple(a)
            tb = _filter_triple(b)
            if ta is None or tb is None:
                continue
            try:
                fused = vm.concat_programs(_AND_TEMPLATE, {0: ta, 1: tb})
            except (KeyError, ValueError):
                continue
            b.inputs = [a.inputs[0]]
            b.pred = _fused_pred(a.pred, b.pred)
            b.program = vm.compile_triple(fused)
            b.meta.pop("filter", None)
            b.meta["plan_fused_filter"] = {"triple": fused}
            ua, ub = a.meta.get("used_cols"), b.meta.get("used_cols")
            if ua is not None and ub is not None:
                b.meta["used_cols"] = sorted(set(ua) | set(ub))
            else:
                b.meta.pop("used_cols", None)
            view.remove(a)
            plan.record("filter_fusion", [a, b])
            changed = True
            break


# ---------------------------------------------------------------------------
# filter pushdown across joins


class _Bail(Exception):
    pass


def _substitute_refs(e: Any, layout: Any, repl: list) -> Any:
    """Rewrite a predicate over a join_select's *output* frame into one
    over the join frame by replacing each column reference with the
    select expression that defines it.  Bails on id/key references and
    anything the layout cannot resolve positionally."""
    if type(e) is ex.ColumnReference:
        pos = layout.resolve_pos(e)
        if pos is None or pos < 0 or pos >= len(repl):
            raise _Bail
        return repl[pos]
    kids = list(e._children())
    if not kids:
        return e
    new = [_substitute_refs(k, layout, repl) for k in kids]
    if all(a is b for a, b in zip(new, kids)):
        return e
    try:
        return e._rebuild(new)
    except Exception:
        raise _Bail from None


def _pred_over_join(f: eg.Node, join: eg.JoinNode) -> "tuple | None":
    """(expr, join_layout) for a filter's predicate expressed over the
    join output frame, or None."""
    flt = f.meta.get("filter")
    if not flt or not flt.get("exprs"):
        return None
    e = flt["exprs"][0]
    if f.meta.get("join_filter") is not None:
        return e, flt["layout"]  # already over the join frame
    # filter over a join_select's output: substitute the select exprs
    s = f.inputs[0]
    sel = s.meta.get("select")
    if not sel or sel.get("kind") != "join_select":
        return None
    try:
        e2 = _substitute_refs(e, flt["layout"], list(sel["exprs"]))
    except Exception:
        return None
    return e2, sel["layout"]


def _try_push_filter(
    view: _GraphView,
    plan: ExecutionPlan,
    f: eg.Node,
    join: eg.JoinNode,
    e: Any,
    join_layout: Any,
) -> bool:
    asm = va.lint_lower(e, join_layout)
    if asm is None or asm.pyfuncs:
        return False
    try:
        ops = list(va.iter_ops(asm.code))
    except Exception:
        return False
    if any(op == vm.OP_LOAD_KEY for _, op, _ in ops):
        return False  # join output keys don't exist below the join
    positions = [o[0] for _, op, o in ops if op == vm.OP_LOAD_COL]
    if not positions:
        return False
    ln, rn = join.left_ncols, join.right_ncols
    if all(p < ln for p in positions):
        side, kinds = 0, ("inner", "left")
    elif all(ln <= p < ln + rn for p in positions):
        side, kinds = 1, ("inner", "right")
    else:
        return False  # mixed-side or id-slot predicate stays above
    if join.kind not in kinds:
        # on the side a join preserves unmatched, pre-filtering would
        # also drop the null-padded survivors the retained filter keeps
        return False
    c = e._compile(join_layout.resolver)
    if side == 0:
        pred = lambda key, values, c=c: c((key, values))  # noqa: E731
        code = asm.code
    else:
        pad = (None,) * ln
        pred = (  # noqa: E731
            lambda key, values, c=c, pad=pad: c((key, pad + tuple(values)))
        )
        try:
            code = vm.renumber_columns(asm.code, lambda p: p - ln)
        except (KeyError, ValueError):
            return False
    program = vm.compile_triple((code, asm.consts, []))
    pushed = eg.FilterNode.detached(
        join.inputs[side],
        pred,
        node_id=view.alloc_id(),
        name="pushed_filter",
        program=program,
    )
    view.insert_before(join, pushed)
    join.inputs[side] = pushed
    plan.record(
        "pushdown_filter",
        [f, join],
        f"side={'left' if side == 0 else 'right'}",
    )
    return True


def _pass_pushdown_filters(view: _GraphView, plan: ExecutionPlan) -> None:
    consumers = view.consumers()
    for f in list(view.nodes):
        if type(f) is not eg.FilterNode or len(f.inputs) != 1:
            continue
        up = f.inputs[0]
        if type(up) is eg.JoinNode:
            join = up
            if consumers.get(join.id) != [f]:
                continue
        elif (
            type(up) is eg.RowwiseNode
            and len(up.inputs) == 1
            and type(up.inputs[0]) is eg.JoinNode
        ):
            join = up.inputs[0]
            # the join (and the select) must feed this filter only —
            # other consumers expect the unfiltered stream
            if consumers.get(join.id) != [up] or consumers.get(up.id) != [f]:
                continue
        else:
            continue
        res = _pred_over_join(f, join)
        if res is None:
            continue
        _try_push_filter(view, plan, f, join, res[0], res[1])


# ---------------------------------------------------------------------------
# projection pushdown across joins


def _pass_pushdown_projection(view: _GraphView, plan: ExecutionPlan) -> None:
    consumers = view.consumers()
    shim = _UsageFacts(consumers)
    for join in list(view.nodes):
        if type(join) is not eg.JoinNode:
            continue
        if not consumers.get(join.id):
            continue
        used = _consumer_usage(join, shim)
        if used is None:
            continue
        on = join.meta.get("join", {}).get("on")
        if on is None:
            continue
        key_names = ([p[0] for p in on], [p[2] for p in on])
        for side in (0, 1):
            if "<expr>" in key_names[side]:
                continue  # unknown key inputs: keep every side column
            p = join.inputs[side]
            if type(p) is not eg.RowwiseNode or consumers.get(p.id) != [join]:
                continue
            sel = p.meta.get("select")
            if not sel or sel.get("kind") not in ("select", "with_columns"):
                continue
            keep = set(used) | set(key_names[side])
            names = sel.get("names", ())
            exprs = sel.get("exprs", ())
            dead = [
                i
                for i, name in enumerate(names)
                if not name.startswith("__")
                and name not in keep
                and i < len(exprs)
                and type(exprs[i]) is not ex.ConstExpression
            ]
            if not dead:
                continue
            _null_columns(p, sel, dead)
            plan.record(
                "pushdown_projection",
                [p, join],
                f"side={'left' if side == 0 else 'right'} null="
                + ",".join(names[i] for i in dead),
            )


# ---------------------------------------------------------------------------
# columnar path annotation (the static half of frame execution)

#: FilterNode comparison ops with a native frame kernel, by expression
#: operator string -> native FrameCmp code (frame_filter's ``op``)
_FRAME_CMP_OPS = {"==": 0, "!=": 1, "<": 2, "<=": 3, ">": 4, ">=": 5}


def _columnar_select_spec(n: eg.Node) -> "tuple | str":
    """Positional projection tuple if every output column of a select is
    a plain column reference, else the row-fallback reason string."""
    if n.meta.get("plan_fused") is not None:
        return "fused program (expression VM)"
    sel = n.meta.get("select")
    if not sel or sel.get("kind") not in ("select", "with_columns"):
        return "non-select rowwise program"
    exprs, layout = sel.get("exprs"), sel.get("layout")
    if exprs is None or layout is None:
        return "no expression metadata"
    poses = []
    for e in exprs:
        if type(e) is not ex.ColumnReference:
            return f"computed column (expression VM): {type(e).__name__}"
        pos = layout.resolve_pos(e)
        if pos is None or pos < 0:
            return "key-derived column"
        poses.append(pos)
    return tuple(poses)


def _columnar_filter_spec(n: eg.Node) -> "tuple | str":
    """(pos, op, const) for a single col-cmp-const predicate, else the
    row-fallback reason string."""
    if n.meta.get("plan_fused") is not None:
        return "fused predicate (expression VM)"
    flt = n.meta.get("filter")
    if not flt:
        return "no predicate metadata (expression VM)"
    exprs, layout = flt.get("exprs"), flt.get("layout")
    if not exprs or layout is None:
        return "no predicate metadata"
    e = exprs[0]
    if (
        type(e) is not ex.BinaryExpression
        or e._op not in _FRAME_CMP_OPS
        or type(e._left) is not ex.ColumnReference
        or type(e._right) is not ex.ConstExpression
    ):
        return "predicate not col-cmp-const (expression VM)"
    pos = layout.resolve_pos(e._left)
    if pos is None or pos < 0:
        return "key-derived predicate column"
    return (pos, _FRAME_CMP_OPS[e._op], e._right._value)


def _pass_columnar(view: _GraphView, plan: ExecutionPlan) -> None:
    """Record every operator's batch-execution decision and arm the
    frame fast paths the kernels support: pure-projection selects
    (``frame_project``) and col-cmp-const filters (``frame_filter``).
    Input and groupby decisions were fixed at graph build time
    (``supports_columnar`` / ``fast_spec``); this pass makes them
    visible in the plan next to the ones it decides itself."""
    for n in view.nodes:
        t = type(n)
        if t is eg.InputNode:
            if n.supports_columnar:
                plan.record_columnar(n, "columnar")
            else:
                plan.record_columnar(
                    n, "row", "upsert stream keeps per-key state"
                )
        elif t is eg.GroupByNode:
            if n.fast_spec is not None:
                plan.record_columnar(n, "columnar")
            else:
                plan.record_columnar(
                    n, "row", "reducer or grouping not native-positional"
                )
        elif t is eg.RowwiseNode:
            spec = _columnar_select_spec(n)
            if isinstance(spec, tuple):
                n.frame_project = spec
                n.supports_columnar = True
                plan.record_columnar(n, "columnar")
            else:
                plan.record_columnar(n, "row", spec)
        elif t is eg.FilterNode:
            spec = _columnar_filter_spec(n)
            if isinstance(spec, tuple):
                n.frame_filter_spec = spec
                n.supports_columnar = True
                plan.record_columnar(n, "columnar")
            else:
                plan.record_columnar(n, "row", spec)


# ---------------------------------------------------------------------------
# pipeline


def optimize_graph(
    graph: eg.EngineGraph,
    level: int,
    facts: "GraphFacts | None" = None,
) -> tuple[eg.EngineGraph, ExecutionPlan]:
    """Rewrite ``graph`` at ``level`` (0..2); returns ``(exec_graph,
    plan)``.  Level 0 returns the original graph and an empty plan.  The
    input graph is never mutated — clones carry every change."""
    level = max(0, min(2, int(level)))
    plan = ExecutionPlan(level)
    plan.nodes_before = len(graph.nodes)
    if level <= 0 or not graph.nodes:
        plan.nodes_after = len(graph.nodes)
        return graph, plan
    if facts is None:
        facts = GraphFacts(graph)
    view = _GraphView(graph)
    _pass_const_fold(view, plan)
    _pass_dead_columns(view, plan)
    if level >= 2:
        _pass_append_only(view, facts, plan)
        # projection first: a pushed filter inserted between a select
        # and its join would hide the sole-consumer pattern
        _pass_pushdown_projection(view, plan)
        _pass_pushdown_filters(view, plan)
    _pass_fuse_selects(view, plan)
    _pass_fuse_filters(view, plan)
    # after all rewrites: decide + record the frame/row path per operator
    # on the FINAL shape of each node's program
    _pass_columnar(view, plan)
    exec_graph = view.finish()
    plan.nodes_after = len(exec_graph.nodes)
    return exec_graph, plan
