"""The diagnostic passes.

Each pass is a pure function ``(graph, facts) -> list[Diagnostic]`` over
the engine graph + the dataflow facts; ``analyze()`` in
``analysis/__init__`` runs them all.  Detection relies on the build-time
``Node.meta`` annotations the table API attaches (expression ASTs,
layouts, declared dtypes) — nodes built outside the table API simply
carry no meta and are skipped, never crashed on.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import dtype as dt

from pathway_tpu.analysis.diagnostics import (
    SEV_ERROR,
    SEV_WARNING,
    Diagnostic,
)
from pathway_tpu.analysis.graph_facts import GraphFacts
from pathway_tpu.analysis import vm_abstract as va

_SINK_CLASSES = {"OutputNode", "ExportNode", "CaptureNode"}


def _diag(
    code: str, sev: str, msg: str, node: eg.Node, **details: Any
) -> Diagnostic:
    return Diagnostic(
        code=code,
        severity=sev,
        message=msg,
        trace=getattr(node, "trace", "") or "",
        node_id=node.id,
        node_name=node.name,
        details=details,
    )


def _bases_compatible(a: dt.DType, b: dt.DType) -> bool:
    """Two dtypes can hold a common value (either direction of the
    lattice order after stripping Optional)."""
    ab, bb = a.strip_optional(), b.strip_optional()
    if ab == dt.ANY or bb == dt.ANY:
        return True
    return dt.is_subtype(ab, bb) or dt.is_subtype(bb, ab)


# ---------------------------------------------------------------------------
# PW-T001 / PW-N001: types and nullability


def check_types(graph: eg.EngineGraph, facts: GraphFacts) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for n in graph.nodes:
        join = n.meta.get("join")
        if join:
            for ln, ld, rn, rd in join.get("on", ()):
                if not (isinstance(ld, dt.DType) and isinstance(rd, dt.DType)):
                    continue
                if not _bases_compatible(ld, rd):
                    out.append(
                        _diag(
                            "PW-T001",
                            SEV_ERROR,
                            f"join key {ln!r} ({ld!r}) cannot match "
                            f"{rn!r} ({rd!r}): no value inhabits both",
                            n,
                            left=repr(ld),
                            right=repr(rd),
                        )
                    )
        concat = n.meta.get("concat")
        if concat:
            for col, dlist in concat.get("columns", {}).items():
                for i in range(1, len(dlist)):
                    if not _bases_compatible(dlist[0], dlist[i]):
                        out.append(
                            _diag(
                                "PW-T001",
                                SEV_ERROR,
                                f"concat column {col!r} mixes {dlist[0]!r} "
                                f"and {dlist[i]!r}",
                                n,
                                column=col,
                            )
                        )
                        break
        sel = n.meta.get("select")
        if sel:
            out.extend(_check_select_types(n, sel, facts))
    return out


def _check_select_types(
    n: eg.Node, sel: dict, facts: GraphFacts
) -> list[Diagnostic]:
    """Abstractly execute each output column's VM program and compare the
    inferred result dtype against the DECLARED one (``expr._dtype`` —
    which ``declare_type`` overrides without changing the bytecode)."""
    out: list[Diagnostic] = []
    layout = sel.get("layout")
    names = sel.get("names", ())
    exprs = sel.get("exprs", ())
    declared_list = sel.get("dtypes", ())
    for name, expr, declared in zip(names, exprs, declared_list):
        if not isinstance(declared, dt.DType):
            continue
        res = va.analyze_expression(expr, layout)
        if res is None:
            continue
        for op, l, r in res.type_conflicts:
            out.append(
                _diag(
                    "PW-T001",
                    SEV_ERROR,
                    f"column {name!r}: operator {op!r} is not defined on "
                    f"{l!r} and {r!r}",
                    n,
                    column=name,
                )
            )
        if not res.ok:
            continue
        inferred = res.result_dtype
        inf_b, dec_b = inferred.strip_optional(), declared.strip_optional()
        if dt.ANY in (inf_b, dec_b) or inferred == dt.NONE:
            continue
        if dt.is_subtype(inferred, declared):
            continue
        if _bases_compatible(inferred, declared):
            # base types agree (or one narrows the other — a legitimate
            # declare_type assertion); the residue is optionality
            if (
                (inferred.is_optional() or inferred == dt.NONE)
                and not declared.is_optional()
                and n.id in facts.reaches_sink
            ):
                out.append(
                    _diag(
                        "PW-N001",
                        SEV_WARNING,
                        f"column {name!r} declared {declared!r} but its "
                        f"program can produce None ({inferred!r}) and the "
                        "value reaches a sink; unwrap or coalesce it",
                        n,
                        column=name,
                        inferred=repr(inferred),
                        declared=repr(declared),
                    )
                )
        else:
            out.append(
                _diag(
                    "PW-T001",
                    SEV_ERROR,
                    f"column {name!r} declared {declared!r} but its program "
                    f"computes {inferred!r}",
                    n,
                    column=name,
                    inferred=repr(inferred),
                    declared=repr(declared),
                )
            )
    return out


# ---------------------------------------------------------------------------
# PW-P001: CALL_PY fallback on a streaming path


def check_call_py(graph: eg.EngineGraph, facts: GraphFacts) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for n in graph.nodes:
        if n.id not in facts.streaming:
            continue
        sel = n.meta.get("select")
        if sel:
            layout = sel.get("layout")
            for name, expr in zip(sel.get("names", ()), sel.get("exprs", ())):
                asm = va.lint_lower(expr, layout)
                if asm is None:
                    continue
                k = va.count_call_py(asm.code)
                if k:
                    out.append(
                        _diag(
                            "PW-P001",
                            SEV_WARNING,
                            f"column {name!r} drops to the Python fallback "
                            f"({k} CALL_PY op{'s' if k > 1 else ''}) on a "
                            "streaming path; every row pays the closure "
                            "call",
                            n,
                            column=name,
                            call_py=k,
                        )
                    )
        flt = n.meta.get("filter")
        if flt:
            layout = flt.get("layout")
            for expr in flt.get("exprs", ()):
                asm = va.lint_lower(expr, layout)
                if asm is None:
                    continue
                k = va.count_call_py(asm.code)
                if k:
                    out.append(
                        _diag(
                            "PW-P001",
                            SEV_WARNING,
                            f"filter predicate drops to the Python fallback "
                            f"({k} CALL_PY op{'s' if k > 1 else ''}) on a "
                            "streaming path",
                            n,
                            call_py=k,
                        )
                    )
    return out


# ---------------------------------------------------------------------------
# PW-S001: unbounded state


def check_unbounded_state(
    graph: eg.EngineGraph, facts: GraphFacts
) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for n in graph.nodes:
        if facts.is_stateful_unbounded(n):
            kind = "join" if isinstance(n, eg.JoinNode) else "groupby"
            out.append(
                _diag(
                    "PW-S001",
                    SEV_WARNING,
                    f"unwindowed {kind} over a streaming source: per-key "
                    "state grows without bound; window the input "
                    "(windowby/sessions) or bound it with a behavior",
                    n,
                )
            )
    return out


# ---------------------------------------------------------------------------
# PW-S002: append-only violations


def check_append_only(
    graph: eg.EngineGraph, facts: GraphFacts
) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for n in graph.nodes:
        if isinstance(n, eg.DeduplicateNode):
            inp = n.inputs[0] if n.inputs else None
            if inp is not None and inp.id not in facts.append_only:
                out.append(
                    _diag(
                        "PW-S002",
                        SEV_ERROR,
                        "deduplicate requires an append-only input, but "
                        f"upstream {inp.name}#{inp.id} can retract rows; "
                        "acceptor state would silently diverge",
                        n,
                        upstream=f"{inp.name}#{inp.id}",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# PW-D001: dead columns


_TRANSPARENT_FOR_USAGE = {
    "FilterNode",
    "IntersectNode",
    "SubtractNode",
    "ReindexNode",
    "DeduplicateNode",
}


def _consumer_usage(n: eg.Node, facts: GraphFacts) -> "set[str] | None":
    """Union of column names ``n``'s consumers read, following
    pass-through operators; None = not analyzable / reaches a consumer
    that needs every column (sinks included)."""
    used: set[str] = set()
    work = list(facts.consumers.get(n.id, ()))
    seen: set[int] = set()
    while work:
        c = work.pop()
        if c.id in seen:
            continue
        seen.add(c.id)
        cls = type(c).__name__
        if cls in _SINK_CLASSES:
            return None
        uc = c.meta.get("used_cols")
        if cls in _TRANSPARENT_FOR_USAGE:
            if uc:
                used.update(uc)
            nxt = facts.consumers.get(c.id, ())
            if not nxt:
                return None  # dangling pass-through: assume probed
            work.extend(nxt)
            continue
        if uc is None:
            return None
        used.update(uc)
    return used


def check_dead_columns(
    graph: eg.EngineGraph, facts: GraphFacts
) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for n in graph.nodes:
        sel = n.meta.get("select")
        if not sel or sel.get("kind") != "select":
            continue  # with_columns pass-through columns are deliberate
        consumers = facts.consumers.get(n.id, ())
        if not consumers:
            continue  # a table nobody consumes is the user's business
        used = _consumer_usage(n, facts)
        if used is None:
            continue
        for name in sel.get("names", ()):
            if name.startswith("__"):
                continue  # internal groupby slots
            if name not in used:
                out.append(
                    _diag(
                        "PW-D001",
                        SEV_WARNING,
                        f"column {name!r} is computed but never read by "
                        "any downstream operator; drop it from the select",
                        n,
                        column=name,
                    )
                )
    return out


from pathway_tpu.analysis.device import check_device  # noqa: E402
from pathway_tpu.analysis.distribution import check_distribution  # noqa: E402
from pathway_tpu.analysis.memory import check_memory  # noqa: E402

ALL_PASSES = (
    check_types,
    check_call_py,
    check_unbounded_state,
    check_append_only,
    check_dead_columns,
    check_distribution,
    check_memory,
    check_device,
)
