"""Distribution-safety pass: partition + order lattices over the graph.

Multi-worker execution is only correct when two properties line up with
what each operator assumes:

- **placement** — how a node's output rows are spread across workers:

  - ``("single",)``   one worker owns the whole stream
  - ``("repl",)``     replicated (static rows exist on every worker)
  - ``("key", None)`` partitioned by row-key hash
  - ``("cols", (c, ...))`` co-partitioned by the named columns
  - ``("byterange",)`` static files split by byte offset (PR 9)
  - ``("rr",)``       round-robin / unknown interleave

- **ordered** — whether per-key arrival order is preserved.  Byte-range
  file splits put two updates for the same key on different ranks, so
  the downstream exchange can deliver them in either order.

Sources declare both via ``node.meta["source"]`` (stamped by
``io/_connector.py`` from ``RowSource.partitioning`` /
``order_preserving``); exchanges (groupby/join/dedup routing, the
route-to-zero operators) transform them.  One forward pass computes the
fixpoint-free lattice (the graph is a DAG in topological order), then
four checks read it:

- PW-X001 (error): order-sensitive stateful operator (keyed upsert into
  an index, ``deduplicate``, asof join) fed by a non-order-preserving
  partitioned source.
- PW-X002 (warning): streaming join/groupby whose input is partitioned
  but not co-partitioned with its keys — a full exchange on the hot
  path, with estimated per-row exchange volume.
- PW-X003 (error): arrival-order-dependent reducer over an unordered
  stream feeding a sink — recovered runs are not byte-identical (PR 8).
- PW-R001 (error): node holding out-of-band state (adapter/writer) whose
  class overrides neither ``snapshot_state`` nor ``on_restore`` — a
  checkpoint-coverage hole that duplicates work on replay.
- PW-R002 (warning): single-owner stateful serving/index node with no
  snapshot-backed standby — correctness survives a crash (PW-R001's
  territory) but *availability* does not: every query against it fails
  until recovery completes.  Shard the index
  (:class:`~pathway_tpu.serving.failover.PartitionedIndex`) or stamp
  ``node.meta["failover"] = {"standby": True}`` once a snapshot-backed
  standby actually serves during recovery.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import graph as eg
from pathway_tpu.internals import dtype as dt

from pathway_tpu.analysis.diagnostics import SEV_ERROR, SEV_WARNING, Diagnostic
from pathway_tpu.analysis.graph_facts import GraphFacts

# placement lattice constants
SINGLE = ("single",)
REPL = ("repl",)
KEY = ("key", None)
BYTERANGE = ("byterange",)
RR = ("rr",)

#: placements under which no cross-worker hazard exists
_LOCAL = (SINGLE, REPL)

#: operators that collapse their output onto worker 0
_ROUTE_TO_ZERO = {
    "AsyncMapNode",
    "OutputNode",
    "ExportNode",
    "CaptureNode",
    "GradualBroadcastNode",
    "ExternalIndexNode",
}

#: reducer impl names whose result depends on per-key ARRIVAL ORDER
#: (pathway_tpu/reducers.py); sum/min/max/count/... are commutative,
#: sorted_tuple canonicalises, these do not
_ORDER_DEPENDENT_REDUCERS = {"any", "earliest", "latest", "tuple", "ndarray"}


def _reducer_order_dependent(name: str) -> bool:
    return (
        name in _ORDER_DEPENDENT_REDUCERS
        or name.startswith("stateful_")
        or name.startswith("udf_reducer_")
    )


def _source_placement(meta: dict) -> tuple:
    p = meta.get("partitioning", "single")
    if p == "static":
        return REPL
    if p == "byte-range":
        return BYTERANGE
    if p == "key":
        return KEY
    if p == "round-robin":
        return RR
    return SINGLE


class DistributionFacts:
    """Per-node placement + order facts (one forward pass, creation
    order is topological — ``EngineGraph.register``)."""

    def __init__(self, graph: eg.EngineGraph, facts: GraphFacts):
        self.graph = graph
        self.facts = facts
        self.placement: dict[int, tuple] = {}
        self.ordered: dict[int, bool] = {}
        #: node id of the first order-breaking source upstream (messages)
        self.order_breaker: dict[int, int | None] = {}

        for n in graph.nodes:
            cls = type(n).__name__
            ins = list(n.inputs)
            in_ordered = all(self.ordered.get(i.id, True) for i in ins)
            breaker = next(
                (
                    self.order_breaker.get(i.id)
                    for i in ins
                    if self.order_breaker.get(i.id) is not None
                ),
                None,
            )

            if isinstance(n, eg.InputNode):
                src = n.meta.get("source", {})
                self.placement[n.id] = _source_placement(src)
                ordered = bool(src.get("order_preserving", True))
                self.ordered[n.id] = ordered
                self.order_breaker[n.id] = None if ordered else n.id
                continue

            if isinstance(n, eg.GroupByNode):
                grouping = tuple(n.meta.get("groupby", {}).get("grouping", ()))
                # exchange by group key: one worker owns each group, and
                # its output per group is emitted in processing order
                place = ("cols", grouping) if grouping else SINGLE
            elif isinstance(n, eg.JoinNode):
                on = n.meta.get("join", {}).get("on", ())
                lcols = tuple(ln for ln, _ld, _rn, _rd in on)
                place = ("cols", lcols) if lcols and "<expr>" not in lcols else KEY
            elif isinstance(n, eg.DeduplicateNode):
                place = KEY  # exchanged by instance hash
            elif cls in _ROUTE_TO_ZERO:
                place = SINGLE
            else:
                places = {self.placement.get(i.id, SINGLE) for i in ins}
                if len(places) == 1:
                    place = places.pop()
                elif places <= set(_LOCAL):
                    place = RR if SINGLE not in places else SINGLE
                else:
                    place = RR
            self.placement[n.id] = place
            self.ordered[n.id] = in_ordered
            self.order_breaker[n.id] = breaker

    # ------------------------------------------------------------------
    def co_partitioned(self, node: eg.Node, keys: tuple) -> bool:
        """True when ``node``'s output needs no exchange to be grouped /
        joined by ``keys`` (already local, or already split by exactly
        those columns)."""
        p = self.placement.get(node.id, SINGLE)
        if p in _LOCAL:
            return True
        return p[0] == "cols" and tuple(p[1]) == tuple(keys)


_WIDTHS = {dt.INT: 8, dt.FLOAT: 8, dt.BOOL: 8, dt.POINTER: 8, dt.STR: 32}


def _row_width(node: eg.Node) -> int | None:
    """Estimated bytes/row of ``node``'s output, from the nearest
    build-time dtype annotation upstream; None when unannotated."""
    work = [node]
    seen: set[int] = set()
    while work:
        n = work.pop(0)
        if n.id in seen:
            continue
        seen.add(n.id)
        dtypes = n.meta.get("select", {}).get("dtypes") or n.meta.get(
            "source", {}
        ).get("dtypes")
        if dtypes:
            return sum(
                _WIDTHS.get(d.strip_optional() if isinstance(d, dt.DType) else d, 24)
                for d in dtypes
            )
        work.extend(n.inputs)
    return None


def _diag(code: str, sev: str, msg: str, node: eg.Node, **details: Any) -> Diagnostic:
    return Diagnostic(
        code=code,
        severity=sev,
        message=msg,
        trace=getattr(node, "trace", "") or "",
        node_id=node.id,
        node_name=node.name,
        details=details,
    )


def _breaker_desc(dist: DistributionFacts, nid: int | None) -> str:
    if nid is None:
        return "an unordered upstream"
    for n in dist.graph.nodes:
        if n.id == nid:
            src = n.meta.get("source", {})
            part = src.get("partitioning", "?")
            return f"source {n.name}#{n.id} ({part}-partitioned)"
    return f"node #{nid}"


# ---------------------------------------------------------------------------
# PW-X001: order-sensitive operator over an unordered partitioned stream


def _order_sensitive_inputs(n: eg.Node) -> "list[tuple[int, str]]":
    """(input index, what-it-is) pairs whose per-key arrival order this
    operator's semantics depend on; empty when order-insensitive."""
    meta = n.meta
    if isinstance(n, eg.DeduplicateNode) or meta.get("dedup", {}).get(
        "order_sensitive"
    ):
        return [(0, "deduplicate acceptor state")]
    if meta.get("index", {}).get("order_sensitive"):
        return [(0, "keyed upsert into the external index")]
    if meta.get("index_upsert"):
        return [(0, "keyed upsert into an index")]
    kind = meta.get("temporal", {}).get("kind", "")
    if "asof" in kind:
        return [(i, f"{kind} matching") for i in range(len(n.inputs))]
    return []


def check_distribution(
    graph: eg.EngineGraph, facts: GraphFacts
) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    dist = facts.distribution

    for n in graph.nodes:
        # X001 at the source itself: an upsert session dedups by key, so
        # the source IS the order-sensitive consumer of its own split
        if isinstance(n, eg.InputNode):
            src = n.meta.get("source", {})
            if (
                src.get("upsert")
                and not dist.ordered.get(n.id, True)
                and dist.placement.get(n.id) not in _LOCAL
            ):
                out.append(
                    _diag(
                        "PW-X001",
                        SEV_ERROR,
                        f"keyed upsert source {n.name!r} is "
                        f"{src.get('partitioning')}-partitioned and not "
                        "order-preserving: two updates for one key can land "
                        "on different ranks and apply out of order; use a "
                        "single-reader connector (pw.io.python) or an "
                        "order-preserving partitioning",
                        n,
                        partitioning=src.get("partitioning"),
                    )
                )
            continue

        for idx, what in _order_sensitive_inputs(n):
            if idx >= len(n.inputs):
                continue
            inp = n.inputs[idx]
            if dist.ordered.get(inp.id, True):
                continue
            breaker = dist.order_breaker.get(inp.id)
            out.append(
                _diag(
                    "PW-X001",
                    SEV_ERROR,
                    f"{what} depends on per-key arrival order, but its "
                    f"input comes from {_breaker_desc(dist, breaker)} which "
                    "does not preserve cross-rank per-key order in a "
                    "multi-worker run; feed it from an order-preserving "
                    "connector (pw.io.python) or key-partitioned source",
                    n,
                    input=f"{inp.name}#{inp.id}",
                    breaker=breaker,
                )
            )

        # X002: streaming groupby/join not co-partitioned with its keys
        if n.id in facts.streaming:
            if isinstance(n, eg.GroupByNode):
                grouping = tuple(n.meta.get("groupby", {}).get("grouping", ()))
                inp = n.inputs[0] if n.inputs else None
                if inp is not None and not dist.co_partitioned(inp, grouping):
                    out.append(_x002(n, inp, "groupby", grouping, dist))
            elif isinstance(n, eg.JoinNode):
                on = n.meta.get("join", {}).get("on", ())
                lcols = tuple(ln for ln, _ld, _rn, _rd in on)
                rcols = tuple(rn for _ln, _ld, rn, _rd in on)
                for side, inp, cols in (
                    ("left", n.inputs[0] if n.inputs else None, lcols),
                    ("right", n.inputs[1] if len(n.inputs) > 1 else None, rcols),
                ):
                    if inp is not None and not dist.co_partitioned(inp, cols):
                        out.append(_x002(n, inp, f"join ({side} side)", cols, dist))

        # X003: order-dependent reducer over an unordered stream -> sink
        if isinstance(n, eg.GroupByNode) and n.id in facts.reaches_sink:
            inp = n.inputs[0] if n.inputs else None
            if inp is not None and not dist.ordered.get(inp.id, True):
                bad = [
                    r
                    for r in n.meta.get("groupby", {}).get("reducers", ())
                    if _reducer_order_dependent(r)
                ]
                if bad:
                    breaker = dist.order_breaker.get(inp.id)
                    out.append(
                        _diag(
                            "PW-X003",
                            SEV_ERROR,
                            f"reducer(s) {', '.join(sorted(set(bad)))} depend "
                            "on per-key arrival order, but the input stream "
                            f"comes from {_breaker_desc(dist, breaker)}; the "
                            "result reaches a sink, so a recovered run can "
                            "emit different bytes (breaks byte-identical "
                            "recovery) — use a commutative reducer "
                            "(sorted_tuple, min/max/sum) or an "
                            "order-preserving source",
                            n,
                            reducers=sorted(set(bad)),
                            breaker=breaker,
                        )
                    )

    out.extend(_check_recovery_coverage(graph, facts))
    out.extend(_check_failover_coverage(graph, facts))
    return out


def _x002(
    n: eg.Node, inp: eg.Node, kind: str, keys: tuple, dist: DistributionFacts
) -> Diagnostic:
    p = dist.placement.get(inp.id, SINGLE)
    width = _row_width(inp)
    vol = (
        f"; estimated exchange volume ~{width} bytes/row"
        if width is not None
        else ""
    )
    keys_s = ", ".join(keys) if keys else "<row key>"
    return _diag(
        "PW-X002",
        SEV_WARNING,
        f"streaming {kind} keyed on ({keys_s}) is fed by a "
        f"{p[0]}-partitioned input, so every row is exchanged across "
        f"workers on the hot path{vol}; pre-partition the source by the "
        "key or reuse an upstream groupby's partitioning",
        n,
        placement=p[0],
        keys=list(keys),
        row_width=width,
    )


# ---------------------------------------------------------------------------
# PW-R001: checkpoint-coverage holes


def _check_recovery_coverage(
    graph: eg.EngineGraph, facts: GraphFacts
) -> list[Diagnostic]:
    """Out-of-band state (an external adapter or writer handle) is only
    recovered when the node class overrides ``snapshot_state`` /
    ``on_restore`` (engine/scheduler.py ``_enriched_states`` /
    ``_restore_nodes``); plain ``ctx.states`` snapshotting cannot see it,
    so a hole here duplicates already-applied work on replay."""
    out: list[Diagnostic] = []
    for n in graph.nodes:
        if n.id not in facts.streaming:
            continue
        adapter = getattr(n, "adapter", None)
        writer = getattr(n, "writer", None)
        external = adapter is not None or writer is not None or bool(
            n.meta.get("external_state")
        )
        if not external:
            continue
        cls = type(n)
        has_snapshot = cls.snapshot_state is not eg.Node.snapshot_state
        has_restore = cls.on_restore is not eg.Node.on_restore
        if not has_snapshot and not has_restore:
            held = (
                "an external adapter"
                if adapter is not None
                else ("a writer handle" if writer is not None else "external state")
            )
            out.append(
                _diag(
                    "PW-R001",
                    SEV_ERROR,
                    f"{cls.__name__} holds {held} but overrides neither "
                    "snapshot_state nor on_restore: its state is invisible "
                    "to checkpoints, so a restored run replays input into "
                    "already-applied external effects (duplicates)",
                    n,
                )
            )
        elif adapter is not None and not (
            hasattr(adapter, "state_dict") and hasattr(adapter, "load_state_dict")
        ):
            out.append(
                _diag(
                    "PW-R001",
                    SEV_ERROR,
                    f"adapter {type(adapter).__name__} on {cls.__name__} has "
                    "no state_dict/load_state_dict, so snapshot_state cannot "
                    "capture it; the index rebuilt after restore diverges "
                    "from the checkpointed operator state",
                    n,
                    adapter=type(adapter).__name__,
                )
            )
    return out


# ---------------------------------------------------------------------------
# PW-R002: single-owner serving state with no standby


def _check_failover_coverage(
    graph: eg.EngineGraph, facts: GraphFacts
) -> list[Diagnostic]:
    """PW-R001 is about *correctness* after a crash; this is about
    *availability* during one.  A stateful serving/index node whose whole
    state lives on a single rank (SINGLE placement or a route-to-zero
    operator) is a query-surface single point of failure: per-rank
    failover restarts it, but every probe routed to it fails until the
    snapshot restore + tail replay finishes.  A snapshot-backed standby
    (or sharding the index across owners — ``PartitionedIndex``) keeps
    answers flowing, degraded, through that window; graphs that wired one
    up declare it via ``node.meta["failover"]["standby"]``."""
    dist = facts.distribution
    out: list[Diagnostic] = []
    for n in graph.nodes:
        if n.id not in facts.streaming:
            continue
        cls = type(n).__name__
        single_owner = (
            dist.placement.get(n.id, SINGLE) == SINGLE or cls in _ROUTE_TO_ZERO
        )
        if not single_owner:
            continue
        adapter = getattr(n, "adapter", None)
        stateful_serving = (
            bool(n.meta.get("index_upsert"))
            or bool(n.meta.get("index"))
            or (
                adapter is not None
                and hasattr(adapter, "state_dict")
                and hasattr(adapter, "load_state_dict")
            )
        )
        if not stateful_serving:
            continue
        if n.meta.get("failover", {}).get("standby"):
            continue  # a snapshot-backed standby covers the window
        out.append(
            _diag(
                "PW-R002",
                SEV_WARNING,
                f"{cls} holds the only copy of serving/index state on one "
                "rank with no snapshot-backed standby: if that rank dies, "
                "every query against it fails until restore + tail replay "
                "completes; shard it (serving.PartitionedIndex) or attach "
                'a standby and stamp meta["failover"]["standby"]',
                n,
                placement="single",
            )
        )
    return out
