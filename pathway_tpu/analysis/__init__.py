"""Pre-flight static analysis of the captured dataflow graph.

``analyze()`` walks the engine graph (``internals/parse_graph.G``) and
the expression-VM programs compiled for it, BEFORE execution, and
returns structured :class:`Diagnostic` findings — the build-time
equivalent of the checks the reference Rust engine does inside
``trait Graph`` (``src/engine/graph.rs``), plus perf, state-growth and
distribution-safety lints no runtime check can give you.

The code registry lives in ONE place —
:data:`pathway_tpu.analysis.diagnostics.CODE_INFO` — and that module's
docstring embeds the generated table (``render_code_table()``); codes
are never listed by hand anywhere else.

Three surfaces: ``pathway_tpu.analyze()``, the CLI ``pathway_tpu lint
program.py``, and strict mode (``pw.run(strict=True)`` /
``PATHWAY_STRICT=1``) which refuses to start connectors while
error-severity findings exist.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.analysis.diagnostics import (
    CODE_INFO,
    CODES,
    SEV_ERROR,
    SEV_INFO,
    SEV_WARNING,
    AnalysisError,
    Diagnostic,
    count_by_severity,
    format_diagnostics,
    render_code_table,
    sort_diagnostics,
)
from pathway_tpu.analysis.device import (
    DeviceReport,
    device_module_files,
    device_profile,
    scan_paths as scan_device,
)
from pathway_tpu.analysis.graph_facts import GraphFacts
from pathway_tpu.analysis.memory import (
    EstimateParams,
    MemoryReport,
    estimate_memory,
)
from pathway_tpu.analysis.passes import ALL_PASSES
from pathway_tpu.analysis.plan import ExecutionPlan
from pathway_tpu.analysis.rewrite import optimize_graph, resolve_level

__all__ = [
    "analyze",
    "explain",
    "lint_file",
    "estimate_memory",
    "EstimateParams",
    "MemoryReport",
    "DeviceReport",
    "scan_device",
    "device_profile",
    "device_module_files",
    "Diagnostic",
    "AnalysisError",
    "CODES",
    "CODE_INFO",
    "render_code_table",
    "SEV_ERROR",
    "SEV_WARNING",
    "SEV_INFO",
    "count_by_severity",
    "format_diagnostics",
    "GraphFacts",
    "ExecutionPlan",
    "optimize_graph",
    "resolve_level",
]


def analyze(graph: Any = None, optimize: "int | None" = None) -> list[Diagnostic]:
    """Statically analyze a captured graph (default: the global parse
    graph) and return sorted diagnostics.  Never raises on exotic
    graphs: a pass that cannot reason about a node skips it.

    ``optimize`` (plan-aware mode) runs every pass over the
    ``optimize_graph`` rewritten view at that level — what the scheduler
    will actually execute — so rewrites that remove work (dead columns,
    append-only reducer specialization) also remove the findings they
    cure.  ``None`` (the default) analyzes the captured graph as built."""
    if graph is None:
        from pathway_tpu.internals.parse_graph import G

        graph = G.engine_graph
    engine_graph = getattr(graph, "engine_graph", graph)
    if optimize is not None:
        level = resolve_level(optimize)
        if level > 0:
            engine_graph, _plan = optimize_graph(engine_graph, level)
    facts = GraphFacts(engine_graph)
    diags: list[Diagnostic] = []
    for p in ALL_PASSES:
        try:
            diags.extend(p(engine_graph, facts))
        except Exception:  # a broken pass must not block the run
            continue
    return sort_diagnostics(diags)


def explain(graph: Any = None, optimize: int | None = None) -> ExecutionPlan:
    """Compile (but do not run) the execution plan for a captured graph
    — default: the global parse graph at the default/env optimization
    level.  Returns the :class:`ExecutionPlan` audit trail; ``print()``
    it for the golden-tested textual form."""
    if graph is None:
        from pathway_tpu.internals.parse_graph import G

        graph = G.engine_graph
    engine_graph = getattr(graph, "engine_graph", graph)
    _, plan = optimize_graph(engine_graph, resolve_level(optimize))
    return plan


def lint_file(path: str) -> list[Diagnostic]:
    """Execute a pipeline script with ``pw.run``/``run_all`` stubbed to
    no-ops so the graph gets BUILT but never executed, then analyze it.
    Powers the CLI ``lint`` subcommand."""
    import runpy

    from pathway_tpu.internals import run as run_mod
    from pathway_tpu.internals.parse_graph import G

    saved_run, saved_run_all = run_mod.run, run_mod.run_all

    def _no_run(*a: Any, **k: Any) -> None:
        return None

    G.clear()
    run_mod.run = _no_run  # type: ignore[assignment]
    run_mod.run_all = _no_run  # type: ignore[assignment]
    import pathway_tpu as pw

    pw_run, pw_run_all = pw.run, pw.run_all
    pw.run = _no_run  # type: ignore[assignment]
    pw.run_all = _no_run  # type: ignore[assignment]
    try:
        runpy.run_path(path, run_name="__main__")
        return analyze()
    finally:
        run_mod.run = saved_run  # type: ignore[assignment]
        run_mod.run_all = saved_run_all  # type: ignore[assignment]
        pw.run = pw_run  # type: ignore[assignment]
        pw.run_all = pw_run_all  # type: ignore[assignment]
