"""Critical-path reconstruction and stage attribution over trace dumps.

Input is the Chrome-trace JSON the flight recorder emits
(:mod:`pathway_tpu.internals.tracing` — ``ph: "X"`` complete events
whose ``args`` carry ``trace_id``/``span_id``/``parent``).  This module
answers the question the aggregate histograms cannot: *which stage did
THIS slow request actually wait on?*

The model: within one trace, every span's **exclusive time** is its
duration minus the union of its children's intervals — the time the
request spent *in* that span and nowhere deeper.  Summed over a trace,
exclusive times partition the root span's wall time exactly, so the
per-category breakdown of a request always adds up to its end-to-end
latency.  Categories bucket the stage names recorded across the repo:

- ``queue_wait`` — admission + scheduler-lane queueing (``serve_sched``,
  generation-queue wait)
- ``exchange``  — cluster pack/send/unpack + per-peer status waits
- ``device``    — embed / search / generate / epoch compute
- ``merge``     — segment merge + sink/commit work
- ``lock``      — spans explicitly named as lock waits
- ``checkpoint``— snapshot serialization and writes
- ``other``     — everything else (including untraced gaps)

:func:`critical_path` additionally extracts the single deepest-wait
chain: walking from the root, at each level pick the child contributing
the most wall time, yielding the "admission → scheduler → dispatch →
collect" style path reports quote.  :func:`report` rolls per-trace
breakdowns into p50/p99 attribution; ``bench.py`` embeds its output in
``BENCH_trace.json``.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

__all__ = [
    "CATEGORY_OF",
    "attribute",
    "categorize",
    "connected_traces",
    "critical_path",
    "group_traces",
    "load_events",
    "report",
]

#: stage-name prefix → attribution category (first match wins; checked
#: in declaration order, most specific first)
CATEGORY_OF: tuple[tuple[str, str], ...] = (
    ("credit_wait", "exchange"),
    ("serve_sched", "queue_wait"),
    ("gen_queue", "queue_wait"),
    ("admit", "queue_wait"),
    ("status_wait", "exchange"),
    ("exchange", "exchange"),
    ("allgather", "exchange"),
    ("pack", "exchange"),
    ("unpack", "exchange"),
    ("send", "exchange"),
    ("recv", "exchange"),
    ("checkpoint", "checkpoint"),
    ("snapshot", "checkpoint"),
    ("merge", "merge"),
    ("pre_commit", "merge"),
    ("sink", "merge"),
    ("lock", "lock"),
    ("serve_embed", "device"),
    ("serve_generate", "device"),
    ("serve_retrieve", "device"),
    ("embed", "device"),
    ("generate", "device"),
    ("search", "device"),
    ("dispatch", "device"),
    ("collect", "device"),
    ("epoch", "device"),
    ("process", "device"),
    ("ingest", "device"),
    ("cut", "device"),
)

CATEGORIES = ("queue_wait", "exchange", "device", "merge", "lock",
              "checkpoint", "other")


def categorize(stage: str) -> str:
    for prefix, cat in CATEGORY_OF:
        if stage.startswith(prefix):
            return cat
    return "other"


def load_events(path: str) -> list[dict]:
    """Read one Chrome-trace JSON file's traceEvents."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare event-array form is also legal
        return doc
    return list(doc.get("traceEvents", ()))


def group_traces(events: Iterable[dict]) -> dict[int, list[dict]]:
    """Bucket events by args.trace_id, dropping context-free spans."""
    traces: dict[int, list[dict]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        tid = ev.get("args", {}).get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(ev)
    return traces


def _span_ids(spans: list[dict]) -> dict[int, dict]:
    return {
        s["args"]["span_id"]: s for s in spans if s["args"].get("span_id")
    }


def connected_traces(events: Iterable[dict]) -> dict[int, bool]:
    """For each trace: does every span's parent resolve inside the trace
    (parents equal to the trace id itself are the root hook)?  True means
    the causal chain stitches end to end with no orphaned fragments."""
    out: dict[int, bool] = {}
    for trace_id, spans in group_traces(events).items():
        ids = set(_span_ids(spans))
        ok = True
        for s in spans:
            parent = s["args"].get("parent", 0)
            if parent and parent != trace_id and parent not in ids:
                ok = False
                break
        out[trace_id] = ok
    return out


def _children(spans: list[dict]) -> dict[int, list[dict]]:
    kids: dict[int, list[dict]] = {}
    for s in spans:
        kids.setdefault(s["args"].get("parent", 0), []).append(s)
    for lst in kids.values():
        lst.sort(key=lambda s: s.get("ts", 0.0))
    return kids


def _union_ms(intervals: list[tuple[float, float]]) -> float:
    """Total length of the union of [t0, t1) intervals, in ms (inputs µs)."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur0, cur1 = intervals[0]
    for t0, t1 in intervals[1:]:
        if t0 > cur1:
            total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    total += cur1 - cur0
    return total / 1e3


def attribute(spans: list[dict]) -> dict[str, Any]:
    """One trace's breakdown: per-stage and per-category **exclusive**
    milliseconds, plus the trace's wall time (earliest start to latest
    end across all its spans, any rank)."""
    kids = _children(spans)
    by_stage: dict[str, float] = {}
    by_cat: dict[str, float] = {c: 0.0 for c in CATEGORIES}
    for s in spans:
        sid = s["args"].get("span_id")
        dur = float(s.get("dur", 0.0))
        t0 = float(s.get("ts", 0.0))
        covered = _union_ms(
            [
                (max(t0, float(c.get("ts", 0.0))),
                 min(t0 + dur,
                     float(c.get("ts", 0.0)) + float(c.get("dur", 0.0))))
                for c in kids.get(sid, ())
                if float(c.get("ts", 0.0)) < t0 + dur
                and float(c.get("ts", 0.0)) + float(c.get("dur", 0.0)) > t0
            ]
        )
        exclusive = max(dur / 1e3 - covered, 0.0)
        stage = s.get("name", "?")
        by_stage[stage] = by_stage.get(stage, 0.0) + exclusive
        by_cat[categorize(stage)] += exclusive
    t_lo = min(float(s.get("ts", 0.0)) for s in spans)
    t_hi = max(float(s.get("ts", 0.0)) + float(s.get("dur", 0.0))
               for s in spans)
    return {
        "wall_ms": (t_hi - t_lo) / 1e3,
        "spans": len(spans),
        "by_stage_ms": dict(
            sorted(by_stage.items(), key=lambda kv: -kv[1])
        ),
        "by_category_ms": {c: v for c, v in by_cat.items() if v > 0.0},
    }


def critical_path(spans: list[dict]) -> list[dict]:
    """The deepest-wait chain: from each root span (parent outside the
    trace), descend into the child contributing the most wall time.
    Returns ``[{stage, rank, ms, exclusive_ms}, ...]`` root-first."""
    ids = _span_ids(spans)
    kids = _children(spans)
    roots = [
        s for s in spans if s["args"].get("parent", 0) not in ids
    ]
    if not roots:
        return []
    root = max(roots, key=lambda s: float(s.get("dur", 0.0)))
    path: list[dict] = []
    node: dict | None = root
    seen: set[int] = set()
    while node is not None:
        sid = node["args"].get("span_id")
        if sid in seen:  # defensive: malformed parent loops
            break
        seen.add(sid)
        own_kids = kids.get(sid, [])
        covered = _union_ms(
            [(float(c.get("ts", 0.0)),
              float(c.get("ts", 0.0)) + float(c.get("dur", 0.0)))
             for c in own_kids]
        )
        path.append({
            "stage": node.get("name", "?"),
            "rank": node.get("pid", 0),
            "ms": float(node.get("dur", 0.0)) / 1e3,
            "exclusive_ms": max(
                float(node.get("dur", 0.0)) / 1e3 - covered, 0.0
            ),
        })
        node = max(
            own_kids, key=lambda c: float(c.get("dur", 0.0)), default=None
        )
    return path


def _quantile_trace(
    ranked: list[tuple[float, int]], q: float
) -> tuple[float, int]:
    i = min(len(ranked) - 1, max(0, int(round(q * (len(ranked) - 1)))))
    return ranked[i]


def report(events: Iterable[dict]) -> dict[str, Any]:
    """Roll every trace in ``events`` into a p50/p99 attribution block:
    which category held the median and the tail request, and the tail
    request's critical path."""
    traces = group_traces(events)
    if not traces:
        return {"requests": 0}
    per: dict[int, dict] = {tid: attribute(spans) for tid, spans in traces.items()}
    ranked = sorted(
        ((info["wall_ms"], tid) for tid, info in per.items())
    )
    mean_cat: dict[str, float] = {}
    for info in per.values():
        for cat, ms in info["by_category_ms"].items():
            mean_cat[cat] = mean_cat.get(cat, 0.0) + ms
    n = len(per)
    out: dict[str, Any] = {
        "requests": n,
        "mean_by_category_ms": {
            c: v / n for c, v in sorted(mean_cat.items(), key=lambda kv: -kv[1])
        },
    }
    for label, q in (("p50", 0.50), ("p99", 0.99)):
        wall, tid = _quantile_trace(ranked, q)
        info = per[tid]
        out[label] = {
            "trace_id": tid,
            "wall_ms": wall,
            "by_category_ms": info["by_category_ms"],
            "by_stage_ms": dict(
                list(info["by_stage_ms"].items())[:8]
            ),
        }
    _, tail_tid = ranked[-1]
    out["slowest"] = {
        "trace_id": tail_tid,
        "wall_ms": ranked[-1][0],
        "critical_path": critical_path(traces[tail_tid]),
    }
    return out
