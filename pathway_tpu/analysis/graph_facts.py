"""Per-operator dataflow facts over the engine graph.

One forward pass in topological order (node creation order — inputs are
always registered before consumers, ``EngineGraph.register``) derives,
per node:

- **streaming**: transitively fed by a live connector
  (``InputNode.subject is not None``) — the "hot path" predicate for
  PW-P001 and the precondition for PW-S001.
- **unbounded**: streaming AND no windowing construct upstream bounds
  the key space.  Window markers (``TemporalBehaviorNode``,
  ``SessionAssignNode``, the ``window_assign`` rowwise stage, a groupby
  keyed on ``_pw_window``) clear the flag; stateful consumers
  (groupby/join) re-clear it after being reported once so a single
  missing window doesn't cascade a diagnostic per downstream operator.
- **append_only**: the node's output stream provably carries no
  retractions (reference ``ColumnProperties.append_only``,
  ``src/engine/graph.rs:374``).

A backward pass marks **reaches_sink** (OutputNode / ExportNode /
CaptureNode) for the nullability lint.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import graph as eg

#: engine-graph classes recognised by *name* so this module never has to
#: import stdlib/temporal (which imports table, which imports half the
#: package) — markers that bound stateful operators' key space
_WINDOW_MARKERS = {"TemporalBehaviorNode", "SessionAssignNode"}

#: node classes whose output preserves append-only-ness of ALL inputs
_APPEND_PRESERVING = {
    "RowwiseNode",
    "FilterNode",
    "FlattenNode",
    "ReindexNode",
    "ConcatNode",
    "IntersectNode",
    "ZipNode",
    "AsyncMapNode",
}

#: node classes that can emit retractions even over append-only inputs
_RETRACTING = {
    "SubtractNode",
    "UpdateRowsNode",
    "UpdateCellsNode",
    "GroupByNode",
    "DeduplicateNode",
    "SortNode",
    "GradualBroadcastNode",
    "IxNode",
}

_SINKS = {"OutputNode", "ExportNode", "CaptureNode"}


class GraphFacts:
    def __init__(self, graph: eg.EngineGraph):
        self.graph = graph
        nodes = graph.nodes
        self.consumers: dict[int, list[eg.Node]] = {n.id: [] for n in nodes}
        for n in nodes:
            for inp in n.inputs:
                self.consumers.setdefault(inp.id, []).append(n)

        self.streaming: set[int] = set()
        self.unbounded: set[int] = set()
        self.append_only: set[int] = set()
        self.reaches_sink: set[int] = set()

        for n in nodes:
            cls = type(n).__name__
            in_streaming = any(i.id in self.streaming for i in n.inputs)
            in_unbounded = any(i.id in self.unbounded for i in n.inputs)
            in_append = all(i.id in self.append_only for i in n.inputs)

            if isinstance(n, eg.InputNode):
                live = n.subject is not None
                if live:
                    self.streaming.add(n.id)
                    self.unbounded.add(n.id)
                # upsert sessions overwrite by key -> retractions
                if not n.upsert:
                    self.append_only.add(n.id)
                continue

            if in_streaming:
                self.streaming.add(n.id)

            windowing = (
                cls in _WINDOW_MARKERS
                or n.name == "window_assign"
                # stdlib/temporal builders annotate their nodes with
                # meta["temporal"]["bounded"]: windowed/watermark-evicted
                # constructs (interval/asof joins, behaviors, window
                # assignment) bound downstream key spaces and must not
                # fall through analysis as opaque
                or bool(n.meta.get("temporal", {}).get("bounded"))
            )
            if isinstance(n, eg.GroupByNode):
                grouping = n.meta.get("groupby", {}).get("grouping", ())
                if "_pw_window" in grouping:
                    windowing = True
            if windowing:
                in_unbounded = False
            elif isinstance(n, (eg.GroupByNode, eg.JoinNode)):
                # stateful: the PW-S001 pass reports it when unbounded;
                # its (aggregated) output counts as accounted-for either
                # way, so one missing window yields ONE diagnostic
                in_unbounded = False
            if in_unbounded:
                self.unbounded.add(n.id)

            if isinstance(n, eg.JoinNode):
                if in_append and getattr(n, "kind", "inner") == "inner":
                    self.append_only.add(n.id)
            elif cls in _RETRACTING:
                pass
            elif cls in _APPEND_PRESERVING or cls in _SINKS:
                if in_append:
                    self.append_only.add(n.id)
            # unknown classes: conservatively not append-only

        # backward: which nodes can reach a sink
        work = [n for n in nodes if type(n).__name__ in _SINKS]
        seen = {n.id for n in work}
        while work:
            n = work.pop()
            self.reaches_sink.add(n.id)
            for inp in n.inputs:
                if inp.id not in seen:
                    seen.add(inp.id)
                    work.append(inp)

    @property
    def distribution(self):
        """Lazily-built partition/order facts (analysis/distribution.py),
        shared by every pass that consults them."""
        cached = getattr(self, "_distribution", None)
        if cached is None:
            from pathway_tpu.analysis.distribution import DistributionFacts

            cached = self._distribution = DistributionFacts(self.graph, self)
        return cached

    def is_stateful_unbounded(self, n: eg.Node) -> bool:
        """True when ``n`` is a groupby/join holding per-key state over a
        live source with nothing upstream bounding the key space."""
        if not isinstance(n, (eg.GroupByNode, eg.JoinNode)):
            return False
        if isinstance(n, eg.GroupByNode):
            grouping = n.meta.get("groupby", {}).get("grouping", ())
            if "_pw_window" in grouping:
                return False
        return any(i.id in self.unbounded for i in n.inputs)


def used_columns(node: eg.Node) -> "set[str] | None":
    """Input column names this consumer reads, from build-time meta;
    None when the consumer is not analyzable (treat as uses-everything)."""
    meta = node.meta
    if "used_cols" in meta:
        return set(meta["used_cols"])
    return None
