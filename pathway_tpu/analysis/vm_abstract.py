"""Abstract interpretation over expression-VM bytecode.

The analyzer reasons about the SAME flat postfix programs the native VM
executes (``internals/expr_vm.py``) instead of the expression AST, so
what gets linted is what actually runs: jump-lowered lazy constructs,
``CALL_PY`` fallback islands, cast/convert ops.  Because the native
module may be absent (or a subtree may not lower), lowering here uses
:class:`_LintAsm`, which records the *expression* for every fallback
instead of compiling its Python closure — the bytecode shape is
identical to what ``lower_program`` would produce, with no native
dependency and no closure-compilation cost.

The interpreter itself is a standard worklist fixpoint: abstract state =
the dtype stack at each pc, merged pointwise with ``dt.lub``.  Jump ops
refine the stack on their taken edge (``OP_JUMP_NOT_NONE`` strips
Optional; ``OP_REQUIRE`` injects NONE at the join), which is how
nullability facts flow — the same role ``Optional`` narrowing plays in
the reference type interpreter.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expr_vm as vm
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.type_interpreter import (
    TypeInterpreterError,
    binary_result_dtype,
    unary_result_dtype,
)

#: operand word count per opcode (code is a flat int list) — shared with
#: the program-rewriting helpers (concat/renumber) in expr_vm
_N_OPERANDS = vm.OPERAND_WIDTHS

_CAST_DTYPES = {0: dt.INT, 1: dt.FLOAT, 2: dt.BOOL, 3: dt.STR}


class _LintAsm(vm._Asm):
    """``_Asm`` that never touches the native module: fallbacks record
    the expression subtree itself (its ``_dtype`` is the abstract value
    ``OP_CALL_PY`` pushes), so lowering works for analysis even when
    ``native.load()`` would return None."""

    def fallback(self, e: ex.ColumnExpression) -> None:
        self.pyfuncs.append(e)
        self.emit(vm.OP_CALL_PY, len(self.pyfuncs) - 1)


def lint_lower(e: ex.ColumnExpression, layout: Any) -> "_LintAsm | None":
    """Lower one expression for analysis; None when lowering fails
    (analysis must never break on exotic expressions)."""
    asm = _LintAsm(layout)
    try:
        vm._lower(e, asm)
    except Exception:
        return None
    return asm


def iter_ops(code: list[int]):
    """Yield ``(pc, op, operands)`` walking the flat code list."""
    pc = 0
    n = len(code)
    while pc < n:
        op = code[pc]
        width = _N_OPERANDS.get(op)
        if width is None:
            return  # unknown opcode: stop rather than misparse
        yield pc, op, code[pc + 1 : pc + 1 + width]
        pc += 1 + width


def count_call_py(code: list[int]) -> int:
    return sum(1 for _, op, _ in iter_ops(code) if op == vm.OP_CALL_PY)


class AbstractResult:
    """Outcome of abstractly executing one program."""

    def __init__(self) -> None:
        self.result_dtype: dt.DType = dt.ANY
        self.call_py_count: int = 0
        #: ``(op, left, right)`` triples the type interpreter rejected
        self.type_conflicts: list[tuple[str, dt.DType, dt.DType]] = []
        self.ok: bool = False


def _const_dtype(v: Any) -> dt.DType:
    try:
        return dt.dtype_of_value(v)
    except Exception:
        return dt.ANY


def _expr_dtype(e: Any) -> dt.DType:
    d = getattr(e, "_dtype", None)
    return d if isinstance(d, dt.DType) else dt.ANY


def _merge(a: tuple, b: tuple) -> "tuple | None":
    if len(a) != len(b):
        return None
    return tuple(dt.lub(x, y) for x, y in zip(a, b))


def interpret(
    code: list[int],
    consts: list[Any],
    pyexprs: list[Any],
    col_dtypes: "dict[int, dt.DType] | None" = None,
) -> AbstractResult:
    """Run the worklist fixpoint; ``col_dtypes`` maps ``OP_LOAD_COL``
    positions to input dtypes (missing → ANY).  Bails out (``ok=False``)
    on stack-shape anomalies instead of guessing."""
    res = AbstractResult()
    res.call_py_count = count_call_py(code)
    cols = col_dtypes or {}
    widths = _N_OPERANDS

    # pc -> abstract stack (tuple of dtypes); END is pc == len(code)
    states: dict[int, tuple] = {0: ()}
    work = [0]
    end_state: "tuple | None" = None
    steps = 0

    def push_state(pc: int, stack: tuple) -> bool:
        nonlocal end_state
        if pc >= len(code):
            merged = stack if end_state is None else _merge(end_state, stack)
            if merged is None:
                return False
            end_state = merged
            return True
        old = states.get(pc)
        if old is None:
            states[pc] = stack
            work.append(pc)
            return True
        merged = _merge(old, stack)
        if merged is None:
            return False
        if merged != old:
            states[pc] = merged
            work.append(pc)
        return True

    while work:
        steps += 1
        if steps > 10_000:  # lattice has finite height; belt and braces
            return res
        pc = work.pop()
        stack = list(states.get(pc, ()))
        if pc >= len(code):
            continue
        op = code[pc]
        w = widths.get(op)
        if w is None:
            return res
        operands = code[pc + 1 : pc + 1 + w]
        nxt = pc + 1 + w
        try:
            if op == vm.OP_LOAD_COL:
                stack.append(cols.get(operands[0], dt.ANY))
            elif op == vm.OP_LOAD_KEY:
                stack.append(dt.POINTER)
            elif op == vm.OP_LOAD_CONST:
                stack.append(_const_dtype(consts[operands[0]]))
            elif op == vm.OP_CALL_PY:
                stack.append(_expr_dtype(pyexprs[operands[0]]))
            elif op == vm.OP_BIN:
                r, l = stack.pop(), stack.pop()
                opname = _BIN_NAMES.get(operands[0], "?")
                try:
                    stack.append(binary_result_dtype(opname, l, r))
                except TypeInterpreterError:
                    res.type_conflicts.append((opname, l, r))
                    stack.append(dt.ANY)
            elif op in (vm.OP_NEG, vm.OP_INV):
                t = stack.pop()
                opname = "-" if op == vm.OP_NEG else "~"
                try:
                    stack.append(unary_result_dtype(opname, t))
                except TypeInterpreterError:
                    res.type_conflicts.append((opname, t, t))
                    stack.append(dt.ANY)
            elif op == vm.OP_IS_NONE:
                stack.pop()
                stack.append(dt.BOOL)
            elif op == vm.OP_BRANCH:
                stack.pop()  # condition
                if not push_state(nxt, tuple(stack)):
                    return res
                if not push_state(operands[0], tuple(stack)):
                    return res
                continue
            elif op == vm.OP_JUMP:
                if not push_state(operands[0], tuple(stack)):
                    return res
                continue
            elif op == vm.OP_JUMP_NOT_NONE:
                t = stack.pop()
                # taken edge: value proven non-None
                if not push_state(
                    operands[0], tuple(stack + [t.strip_optional()])
                ):
                    return res
                # fall-through keeps the (possibly None) value for OP_POP
                if not push_state(nxt, tuple(stack + [t])):
                    return res
                continue
            elif op == vm.OP_POP:
                stack.pop()
            elif op == vm.OP_REQUIRE:
                stack.pop()  # the dep
                # dep-is-None edge: the program's RESULT becomes None
                if not push_state(operands[0], tuple(stack + [dt.NONE])):
                    return res
                if not push_state(nxt, tuple(stack)):
                    return res
                continue
            elif op == vm.OP_UNWRAP:
                t = stack.pop()
                if t == dt.NONE:
                    # unwrap(None) errors at runtime — no value flows
                    # on, so the path dies instead of leaking NONE into
                    # the end-state merge
                    continue
                stack.append(t.strip_optional())
            elif op == vm.OP_FILL_JUMP:
                t = stack.pop()
                # no-error edge jumps past the replacement, value kept
                if not push_state(operands[0], tuple(stack + [t])):
                    return res
                if not push_state(nxt, tuple(stack + [t])):
                    return res
                continue
            elif op == vm.OP_CAST:
                t = stack.pop()
                target = _CAST_DTYPES.get(operands[0], dt.ANY)
                stack.append(
                    dt.Optional(target) if t.is_optional() or t == dt.NONE
                    else target
                )
            elif op == vm.OP_CONVERT:
                t = stack.pop()
                target = _CAST_DTYPES.get(operands[0], dt.ANY)
                unwrap = bool(operands[1])
                stack.append(target if unwrap else dt.Optional(target))
            elif op == vm.OP_MAKE_TUPLE:
                n = operands[0]
                elems = stack[len(stack) - n :] if n else []
                del stack[len(stack) - n :]
                stack.append(dt.Tuple(*elems))
            elif op == vm.OP_GET:
                stack.pop()
                stack.pop()
                # hit edge jumps to end_t with the extracted value
                if not push_state(operands[1], tuple(stack + [dt.ANY])):
                    return res
                # miss edge falls through into the lowered default
                if not push_state(nxt, tuple(stack)):
                    return res
                continue
            elif op == vm.OP_POINTER:
                n = operands[0]
                if n:
                    del stack[len(stack) - n :]
                ptr = dt.POINTER
                stack.append(dt.Optional(ptr) if operands[1] else ptr)
            elif op == vm.OP_METHOD:
                n = operands[1]
                del stack[len(stack) - n :]
                stack.append(dt.ANY)
            else:
                return res
        except IndexError:
            return res  # stack underflow: malformed program, bail
        if not push_state(nxt, tuple(stack)):
            return res

    if end_state is not None and len(end_state) == 1:
        res.result_dtype = end_state[0]
        res.ok = True
    return res


_BIN_NAMES = {v: k for k, v in vm.BIN_IDS.items()}


def layout_col_dtypes(layout: Any) -> dict[int, dt.DType]:
    """pos -> input dtype, recovered from a ``_Layout``'s entries
    (``(table, {name: pos}, id_pos)`` triples)."""
    out: dict[int, dt.DType] = {}
    for entry in getattr(layout, "entries", ()):
        try:
            table, name_pos = entry[0], entry[1]
            dtypes = getattr(table, "_dtypes", {})
            for name, pos in name_pos.items():
                if pos is None or pos < 0:
                    continue
                d = dtypes.get(name)
                if isinstance(d, dt.DType):
                    out[pos] = d
        except Exception:
            continue
    return out


def analyze_expression(
    e: ex.ColumnExpression, layout: Any
) -> "AbstractResult | None":
    """Lower + interpret one expression against its layout."""
    asm = lint_lower(e, layout)
    if asm is None:
        return None
    return interpret(
        asm.code, asm.consts, asm.pyfuncs, layout_col_dtypes(layout)
    )
