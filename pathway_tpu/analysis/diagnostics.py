"""Diagnostic records for the pre-flight static analyzer.

Each finding carries a STABLE code (``PW-Xnnn``) so CI gates, dashboards
and strict mode can match on it without parsing prose.

This module is the SINGLE SOURCE OF TRUTH for the code registry:
:data:`CODE_INFO` maps every code to its fixed severity and one-line
description, :data:`CODES` is derived from it, and
:func:`render_code_table` generates the human-readable table that the
module docstring (below) and any docs embed — so the registry and the
prose can never drift apart.  ``tests/test_static_analysis.py`` checks
that every registered code also appears in the README table.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"

_SEV_ORDER = {SEV_ERROR: 0, SEV_WARNING: 1, SEV_INFO: 2}

#: every code the analyzer can emit: code -> (fixed severity, description)
CODE_INFO: dict[str, tuple[str, str]] = {
    "PW-T001": (
        SEV_ERROR,
        "type mismatch (join keys, concat columns, or a declared column "
        "dtype the bytecode contradicts)",
    ),
    "PW-P001": (
        SEV_WARNING,
        "CALL_PY fallback in a program on a streaming (hot) path — the "
        "row loop drops off the native VM",
    ),
    "PW-S001": (
        SEV_WARNING,
        "unwindowed join/groupby over a streaming source: operator state "
        "grows without bound",
    ),
    "PW-S002": (
        SEV_ERROR,
        "append-only violation: an operator that requires append-only "
        "input is fed retractions",
    ),
    "PW-D001": (
        SEV_WARNING,
        "dead column: computed by a select but never read by any "
        "downstream consumer",
    ),
    "PW-N001": (
        SEV_WARNING,
        "nullability leak: an optionally-None value flows into a column "
        "declared non-optional at a sink-reaching select",
    ),
    "PW-X001": (
        SEV_ERROR,
        "order-sensitive stateful operator (keyed upsert into an index, "
        "deduplicate, asof join) fed by a partitioned source that does "
        "not preserve cross-rank per-key arrival order",
    ),
    "PW-X002": (
        SEV_WARNING,
        "join/groupby whose inputs are not co-partitioned with its keys: "
        "a full exchange of the hot streaming path",
    ),
    "PW-X003": (
        SEV_ERROR,
        "arrival-order-dependent reducer over a non-deterministically "
        "ordered stream feeding a sink: recovered runs are not "
        "byte-identical",
    ),
    "PW-R001": (
        SEV_ERROR,
        "stateful operator with out-of-band state but no "
        "snapshot_state/on_restore coverage: a checkpoint-coverage hole "
        "that duplicates work on replay",
    ),
    "PW-R002": (
        SEV_WARNING,
        "single-owner stateful serving/index node with no snapshot-backed "
        "standby: one rank's death takes the whole query surface down "
        "until recovery completes (an availability hole degraded serving "
        "cannot cover)",
    ),
    "PW-M001": (
        SEV_ERROR,
        "linear-in-stream operator state on an unbounded streaming path "
        "that reaches a sink: memory use grows with every row ingested, "
        "so the deployment dies by OOM schedule, not by load",
    ),
    "PW-M002": (
        SEV_WARNING,
        "estimated steady-state footprint exceeds PATHWAY_MEMORY_BUDGET "
        "(per-operator breakdown in details): provision more memory, "
        "shard wider, or bound retention",
    ),
    "PW-M003": (
        SEV_WARNING,
        "checkpoint bytes grow with stream length (stream-linear state is "
        "snapshotted): recovery-time targets degrade as the run ages",
    ),
    "PW-J001": (
        SEV_ERROR,
        "unbounded jit-signature space on a hot path: a jitted callable's "
        "traced shapes derive from unpadded batch/corpus sizes, so every "
        "new size recompiles (pad to power-of-two buckets like "
        "JittedEncoder._pad_batch)",
    ),
    "PW-J002": (
        SEV_WARNING,
        "host<->device transfer (device_put, implicit np->jnp coercion, "
        ".item()/device_get readback) inside a per-query or per-epoch "
        "loop: the hot path stalls on PCIe/ICI every iteration",
    ),
    "PW-J003": (
        SEV_WARNING,
        "in-place device-buffer update without donate_argnums: the "
        "non-donated jit keeps input and output alive together, doubling "
        "HBM peak vs the donated scatter updates sharded_knn uses",
    ),
    "PW-J004": (
        SEV_ERROR,
        "collective divergence: a shard_map/collective region is "
        "reachable under rank-data-dependent Python control flow, so "
        "chips disagree about entering the collective and the mesh "
        "deadlocks",
    ),
    "PW-J005": (
        SEV_WARNING,
        "blocking device sync (block_until_ready, device-array readback) "
        "inside an SLO scheduler lane or while holding an index lock: "
        "one device round-trip serializes every waiter behind it",
    ),
}

#: every code the analyzer can emit, with its fixed severity (derived —
#: do not edit; add codes to CODE_INFO above)
CODES: dict[str, str] = {code: sev for code, (sev, _) in CODE_INFO.items()}


def render_code_table() -> str:
    """The registry as an aligned text table — generated, never
    hand-maintained.  Docs and docstrings embed this."""
    rows = [(code, sev, desc) for code, (sev, desc) in CODE_INFO.items()]
    lines = ["code        severity  meaning", "-" * 72]
    for code, sev, desc in rows:
        lines.append(f"{code:<11} {sev:<9} {desc}")
    return "\n".join(lines)


# the docstring advertises the registry it documents
__doc__ = (__doc__ or "") + "\n\n" + render_code_table() + "\n"


@dataclass(frozen=True)
class Diagnostic:
    """One static-analysis finding (reference: the Rust engine's
    build-time ``DataError``/trace plumbing, surfaced here as data
    instead of an exception so callers can batch and filter)."""

    code: str
    severity: str
    message: str
    #: user file:line that created the offending operator (Node.trace)
    trace: str = ""
    node_id: int | None = None
    node_name: str = ""
    #: free-form extras (column name, dtypes involved, ...)
    details: dict[str, Any] = field(default_factory=dict)

    def format(self) -> str:
        loc = f" at {self.trace}" if self.trace else ""
        op = f" [{self.node_name}#{self.node_id}]" if self.node_id is not None else ""
        return f"{self.code} {self.severity}: {self.message}{op}{loc}"


class AnalysisError(RuntimeError):
    """Raised by ``run(strict=True)`` when error-severity findings exist.

    Carries the full diagnostic list in ``.diagnostics``."""

    def __init__(self, diagnostics: list[Diagnostic]):
        self.diagnostics = list(diagnostics)
        errors = [d for d in self.diagnostics if d.severity == SEV_ERROR]
        lines = "\n".join("  " + d.format() for d in errors)
        super().__init__(
            f"static analysis found {len(errors)} error-severity "
            f"finding(s); refusing to run (strict mode):\n{lines}"
        )


def sort_diagnostics(diags: list[Diagnostic]) -> list[Diagnostic]:
    """Severity-major, then graph order — stable for tests and CLI."""
    return sorted(
        diags,
        key=lambda d: (
            _SEV_ORDER.get(d.severity, 9),
            d.code,
            d.node_id if d.node_id is not None else 1 << 30,
        ),
    )


def format_diagnostics(diags: list[Diagnostic]) -> str:
    return "\n".join(d.format() for d in diags)


def count_by_severity(diags: list[Diagnostic]) -> dict[str, int]:
    out = {SEV_ERROR: 0, SEV_WARNING: 0, SEV_INFO: 0}
    for d in diags:
        out[d.severity] = out.get(d.severity, 0) + 1
    return out
