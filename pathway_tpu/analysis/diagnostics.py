"""Diagnostic records for the pre-flight static analyzer.

Each finding carries a STABLE code (``PW-Xnnn``) so CI gates, dashboards
and strict mode can match on it without parsing prose.  Codes:

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
PW-T001     error     type mismatch (join keys, concat columns, or a
                      declared column dtype the bytecode contradicts)
PW-P001     warning   CALL_PY fallback in a program on a streaming (hot)
                      path — the row loop drops off the native VM
PW-S001     warning   unwindowed join/groupby over a streaming source:
                      operator state grows without bound
PW-S002     error     append-only violation: an operator that requires
                      append-only input is fed retractions
PW-D001     warning   dead column: computed by a select but never read by
                      any downstream consumer
PW-N001     warning   nullability leak: an optionally-None value flows
                      into a column declared non-optional at a sink-reaching
                      select
==========  ========  =====================================================
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"

_SEV_ORDER = {SEV_ERROR: 0, SEV_WARNING: 1, SEV_INFO: 2}

#: every code the analyzer can emit, with its fixed severity
CODES: dict[str, str] = {
    "PW-T001": SEV_ERROR,
    "PW-P001": SEV_WARNING,
    "PW-S001": SEV_WARNING,
    "PW-S002": SEV_ERROR,
    "PW-D001": SEV_WARNING,
    "PW-N001": SEV_WARNING,
}


@dataclass(frozen=True)
class Diagnostic:
    """One static-analysis finding (reference: the Rust engine's
    build-time ``DataError``/trace plumbing, surfaced here as data
    instead of an exception so callers can batch and filter)."""

    code: str
    severity: str
    message: str
    #: user file:line that created the offending operator (Node.trace)
    trace: str = ""
    node_id: int | None = None
    node_name: str = ""
    #: free-form extras (column name, dtypes involved, ...)
    details: dict[str, Any] = field(default_factory=dict)

    def format(self) -> str:
        loc = f" at {self.trace}" if self.trace else ""
        op = f" [{self.node_name}#{self.node_id}]" if self.node_id is not None else ""
        return f"{self.code} {self.severity}: {self.message}{op}{loc}"


class AnalysisError(RuntimeError):
    """Raised by ``run(strict=True)`` when error-severity findings exist.

    Carries the full diagnostic list in ``.diagnostics``."""

    def __init__(self, diagnostics: list[Diagnostic]):
        self.diagnostics = list(diagnostics)
        errors = [d for d in self.diagnostics if d.severity == SEV_ERROR]
        lines = "\n".join("  " + d.format() for d in errors)
        super().__init__(
            f"static analysis found {len(errors)} error-severity "
            f"finding(s); refusing to run (strict mode):\n{lines}"
        )


def sort_diagnostics(diags: list[Diagnostic]) -> list[Diagnostic]:
    """Severity-major, then graph order — stable for tests and CLI."""
    return sorted(
        diags,
        key=lambda d: (
            _SEV_ORDER.get(d.severity, 9),
            d.code,
            d.node_id if d.node_id is not None else 1 << 30,
        ),
    )


def format_diagnostics(diags: list[Diagnostic]) -> str:
    return "\n".join(d.format() for d in diags)


def count_by_severity(diags: list[Diagnostic]) -> dict[str, int]:
    out = {SEV_ERROR: 0, SEV_WARNING: 0, SEV_INFO: 0}
    for d in diags:
        out[d.severity] = out.get(d.severity, 0) + 1
    return out
