"""Device-safety pass: jit/transfer/collective hazards priced pre-run.

The fourth analyzer.  PW-T reasons about types, PW-X about placement,
PW-M about bytes; none of them see a single ``jax.jit``, ``device_put``
or ``shard_map`` — yet TPU serving lives or dies on shape-stable
compilation, padding discipline and disciplined host<->device traffic.
This pass walks the *source* of the device modules (an AST
abstract-interpretation, not the dataflow graph: jit boundaries are a
Python-level construct the engine graph cannot represent) and emits
registry-backed codes through the same surfaces as every other pass:

- PW-J001 (error): a hot-path call into a jitted callable whose traced
  shapes derive from unpadded batch sizes — recompile-per-shape.  Two
  concrete shapes: no padding at all between a host batch parameter and
  the jit boundary, or ceil-div *multiple-of-block* padding
  (``((n + b - 1) // b) * b``) whose signature count is still linear in
  the batch range.  The fix is power-of-two bucketing
  (``ops.bucketing.bucket_size`` / ``JittedEncoder._pad_batch``), which
  bounds the variant count logarithmically.
- PW-J002 (warning): host<->device transfer (``device_put``, implicit
  np->jnp coercion, ``.item()``/``device_get`` readback) lexically
  inside a per-query/per-epoch loop of a hot function.  Functions using
  the pipelined-readback idiom (``copy_to_host_async`` then one
  ``device_get``) are exempt — that is the cure, not the disease.
- PW-J003 (warning): a jitted in-place device-buffer update
  (``x.at[...].set(...)`` on an argument, result returned) without
  ``donate_argnums`` — input and output stay live together, doubling
  HBM peak.  A non-donating ``*_safe`` twin of a donated scatter (the
  deliberate concurrent-dispatch escape hatch ``sharded_knn`` uses) is
  exempt.
- PW-J004 (error): a ``shard_map``/collective region reachable under
  rank-data-dependent Python control flow (``process_index``, env rank
  ids, ``*rank*`` names): chips disagree about entering the collective
  and the mesh deadlocks.  Branching on static config (``if self.mesh
  is not None``) is fine — every process computes the same truth value.
- PW-J005 (warning): a blocking device sync (``block_until_ready``,
  ``device_get``, ``.item()``) while holding a lock or inside an SLO
  lane body — one device round-trip serializes every waiter behind it.

Heuristics are precise-by-default (bias toward missed findings, like
the lock lints): cold paths — train/grow/init/restore/checkpoint/... —
are never flagged, and a ``# pw-j:`` (or code-specific ``# pw-j001:``)
comment on the offending line waives a finding with an audit trail.

``check_device`` bridges the file analysis into ``pw.analyze()``: it
scans the modules *reachable from the graph* (index adapters' defining
modules; the whole device surface when a ``Node.meta["serving"]`` stage
annotation says the graph serves), attributing findings to the
annotated nodes, and prices per-chip HBM against
``PATHWAY_DEVICE_BUDGET_BYTES`` (PW-M002 with a device scope) so the
PR 15 budget story works per chip, not just per host.  The live
counterpart of the static prediction lives in
``internals/device_counters.py`` — jit-compile and transfer counters
joined against this pass's output on ``/status``.
"""

from __future__ import annotations

import ast
import dataclasses
import glob
import os
import sys
from dataclasses import dataclass
from typing import Any, Iterable

from pathway_tpu.analysis.diagnostics import SEV_ERROR, SEV_WARNING, Diagnostic
from pathway_tpu.analysis.graph_facts import GraphFacts

__all__ = [
    "DeviceReport",
    "scan_source",
    "scan_file",
    "scan_paths",
    "device_module_files",
    "device_profile",
    "check_device",
]

#: substrings that mark a function as cold-path (one-time / amortized):
#: recompiles and transfers there are expected and irrelevant
_COLD_TOKENS = (
    "train",
    "kmeans",
    "grow",
    "init",
    "restore",
    "state",  # state_dict / load_state_dict
    "convert",
    "checkpoint",
    "snapshot",
    "warm",
    "load",
    "setup",
    "save",
    "rebuild",
    "close",
    "shutdown",
    "teardown",
)

#: function-body tokens that prove padding discipline at the jit boundary
_PAD_TOKENS = ("bucket_size", "_pad_batch", "pad_to_bucket")

#: cross-chip collective primitives (jax.lax)
_COLLECTIVES = {
    "all_gather",
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "ppermute",
    "pshuffle",
    "all_to_all",
    "psum_scatter",
    "axis_index",
}

#: identity tokens whose appearance in a branch condition makes control
#: flow rank-data-dependent (lowercase substring match)
_RANK_TOKENS = ("rank", "process_index", "process_id", "proc_id")

#: blocking sync calls for PW-J005 (attribute / dotted forms)
_BLOCKING_ATTRS = {"block_until_ready", "item"}


def _fname(node: ast.AST) -> str:
    """Final identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_jit_expr(node: ast.AST) -> "tuple[bool, bool]":
    """(is a jit wrapper expression, donates buffers).

    Matches ``jax.jit`` / ``jit`` / ``partial(jax.jit, ...)`` /
    ``functools.partial(jax.jit, ...)`` and ``jax.jit(f, ...)`` calls.
    """
    if _fname(node) == "jit":
        return True, False
    if isinstance(node, ast.Call):
        donate = any(
            kw.arg in ("donate_argnums", "donate_argnames")
            for kw in node.keywords
            if kw.arg
        )
        if _fname(node.func) == "jit":
            # jax.jit(f, donate_argnums=...) or @jax.jit(...)
            for a in node.args:
                sub, sub_donate = _is_jit_expr(a)
                donate = donate or sub_donate
            return True, donate
        if _fname(node.func) == "partial":
            for a in node.args:
                jit, sub_donate = _is_jit_expr(a)
                if jit:
                    return True, donate or sub_donate
    return False, False


def _cold(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _COLD_TOKENS)


def _rank_dependent(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        ident = ""
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            ident = sub.value
        if ident and any(tok in ident.lower() for tok in _RANK_TOKENS):
            return True
    return False


def _waived(lines: "list[str]", lineno: int, code: str) -> bool:
    if not (1 <= lineno <= len(lines)):
        return False
    src = lines[lineno - 1].lower()
    return "pw-j:" in src or f"pw-j{code[-3:]}:" in src


@dataclass
class _Jitted:
    name: str
    donate: bool
    fn: "ast.FunctionDef | ast.AsyncFunctionDef | None" = None


class _ModuleIndex:
    """Module-level facts: which names are jitted, which functions
    contain collectives, what jnp is called."""

    def __init__(self, tree: ast.Module):
        self.jitted: dict[str, _Jitted] = {}
        self.collective_fns: set[str] = set()
        self.jnp_aliases: set[str] = {"jnp"}
        self.has_jax = False

        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "jax":
                        self.has_jax = True
                    if alias.name == "jax.numpy":
                        self.jnp_aliases.add(alias.asname or "jax")
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "jax":
                    self.has_jax = True
                    for alias in node.names:
                        if alias.name == "numpy":
                            self.jnp_aliases.add(alias.asname or "numpy")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                donate = False
                jit = False
                for dec in node.decorator_list:
                    d_jit, d_donate = _is_jit_expr(dec)
                    jit = jit or d_jit
                    donate = donate or d_donate
                if jit:
                    self.jitted[node.name] = _Jitted(node.name, donate, node)
                if any(
                    isinstance(sub, ast.Call)
                    and (
                        (
                            isinstance(sub.func, ast.Attribute)
                            and sub.func.attr in _COLLECTIVES
                        )
                        or _fname(sub.func) == "shard_map"
                    )
                    for sub in ast.walk(node)
                ):
                    self.collective_fns.add(node.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = _fname(node.targets[0])
                if not target:
                    continue
                value = node.value
                if isinstance(value, ast.Call):
                    jit, donate = _is_jit_expr(value)
                    if jit:
                        fn = None
                        for a in value.args:
                            if isinstance(a, ast.Name):
                                fn = a.id
                        self.jitted[target] = _Jitted(target, donate, None)
                        if fn:
                            self.jitted.setdefault(
                                fn, _Jitted(fn, donate, None)
                            )
                    elif "shard_map" in _fname(value.func):
                        self.collective_fns.add(target)

    def is_jitted_name(self, name: str) -> bool:
        return name in self.jitted


def _resolve_jit_call(call: ast.Call, idx: _ModuleIndex, local_jitted: set) -> bool:
    """Is this Call a dispatch into a jitted callable?"""
    func = call.func
    name = _fname(func)
    if name and (name in local_jitted or idx.is_jitted_name(name)):
        return True
    # curried dispatch: self._search_jit(k)(args...) — the inner call's
    # callee NAMES the jit factory
    if isinstance(func, ast.Call) and "jit" in _fname(func.func).lower():
        return True
    return False


def _upload_of_param(arg: ast.AST, params: set, jnp_aliases: set) -> bool:
    """arg is a fresh host->device upload of an (unpadded) parameter:
    jnp.asarray(p) / jnp.array(p) / jax.device_put(p)."""
    if not isinstance(arg, ast.Call):
        return False
    func = arg.func
    is_upload = False
    if isinstance(func, ast.Attribute) and func.attr in ("asarray", "array"):
        is_upload = isinstance(func.value, ast.Name) and func.value.id in jnp_aliases
    if _fname(func) == "device_put":
        is_upload = True
    if not is_upload:
        return False
    return any(
        isinstance(sub, ast.Name) and sub.id in params for sub in ast.walk(arg)
    )


def _has_ceil_div_mult(fn: ast.AST) -> bool:
    """Detect ``((n + b - 1) // b) * b``: multiple-of-block padding whose
    distinct-shape count is linear in the batch range."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult)):
            continue
        for left, right in ((node.left, node.right), (node.right, node.left)):
            if (
                isinstance(left, ast.BinOp)
                and isinstance(left.op, ast.FloorDiv)
                and ast.dump(left.right) == ast.dump(right)
            ):
                return True
    return False


def _transfer_call(call: ast.Call, idx: _ModuleIndex) -> "str | None":
    """Name of the host<->device transfer primitive this Call is, if any."""
    func = call.func
    name = _fname(func)
    if name in ("device_put", "device_get"):
        return name
    if (
        isinstance(func, ast.Attribute)
        and func.attr in ("asarray", "array")
        and isinstance(func.value, ast.Name)
        and func.value.id in idx.jnp_aliases
    ):
        return f"{func.value.id}.{func.attr}"
    if (
        isinstance(func, ast.Attribute)
        and func.attr == "item"
        and not call.args
        and idx.has_jax
    ):
        return ".item()"
    return None


def _blocking_call(call: ast.Call) -> "str | None":
    func = call.func
    name = _fname(func)
    if name == "block_until_ready":
        return "block_until_ready"
    if name == "device_get":
        return "device_get"
    if (
        isinstance(func, ast.Attribute)
        and func.attr == "item"
        and not call.args
    ):
        return ".item()"
    return None


def _locky(expr: ast.AST) -> bool:
    name = _fname(expr).lower()
    if isinstance(expr, ast.Call):
        name = _fname(expr.func).lower()
    return any(tok in name for tok in ("lock", "mutex", "_mu", "cond", "cv"))


def _inplace_on_param(fn: ast.AST, params: set) -> "int | None":
    """Line of an ``p.at[...].set(...)``-style in-place update of a
    parameter, if the function performs one."""
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("set", "add", "mul", "multiply", "min", "max")
        ):
            continue
        target = node.func.value
        if (
            isinstance(target, ast.Subscript)
            and isinstance(target.value, ast.Attribute)
            and target.value.attr == "at"
            and isinstance(target.value.value, ast.Name)
            and target.value.value.id in params
        ):
            return node.lineno
    return None


def _arg_names(fn: "ast.FunctionDef | ast.AsyncFunctionDef") -> set:
    a = fn.args
    names = [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


class _FunctionScan:
    """One hot/cold-classified function body walked with loop / branch /
    lock context stacks."""

    def __init__(
        self,
        fn: "ast.FunctionDef | ast.AsyncFunctionDef",
        qualname: str,
        cold: bool,
        idx: _ModuleIndex,
        lines: "list[str]",
        filename: str,
        serving_lane: bool,
    ):
        self.fn = fn
        self.qualname = qualname
        self.cold = cold
        self.idx = idx
        self.lines = lines
        self.filename = filename
        self.serving_lane = serving_lane
        self.diags: list[Diagnostic] = []
        self.params = _arg_names(fn)
        end = getattr(fn, "end_lineno", None) or fn.lineno
        self.text = "\n".join(lines[fn.lineno - 1 : end])
        self.padded = any(tok in self.text for tok in _PAD_TOKENS)
        self.pipelined = "copy_to_host_async" in self.text
        self.ceil_pad = _has_ceil_div_mult(fn)
        self.local_jitted: set = set()
        self.is_jitted_def = fn.name in idx.jitted and idx.jitted[fn.name].fn is fn
        self._collect_local_jitted()

    def _collect_local_jitted(self) -> None:
        for node in ast.walk(self.fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            value = node.value
            candidates: "list[ast.AST]" = [value]
            if isinstance(value, ast.IfExp):
                candidates = [value.body, value.orelse]
            for cand in candidates:
                if isinstance(cand, ast.Call):
                    jit, _don = _is_jit_expr(cand)
                    if jit or "jit" in _fname(cand.func).lower():
                        self.local_jitted.add(target.id)
                elif _fname(cand) in self.idx.jitted:
                    self.local_jitted.add(target.id)

    def _emit(self, code: str, sev: str, lineno: int, message: str, **details: Any) -> None:
        if _waived(self.lines, lineno, code):
            return
        self.diags.append(
            Diagnostic(
                code=code,
                severity=sev,
                message=message,
                trace=f"{self.filename}:{lineno}",
                node_name=self.qualname,
                details=dict(details, file=self.filename, line=lineno),
            )
        )

    def run(self) -> "list[Diagnostic]":
        self._visit(self.fn, loop=0, conds=(), locks=0)
        return self.diags

    # ------------------------------------------------------------------
    def _visit(self, node: ast.AST, loop: int, conds: tuple, locks: int) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs get their own scan
            c_loop, c_conds, c_locks = loop, conds, locks
            if isinstance(child, (ast.For, ast.AsyncFor)):
                c_loop = loop + 1
            elif isinstance(child, ast.While):
                c_loop = loop + 1
                c_conds = conds + (child.test,)
            elif isinstance(child, ast.If):
                c_conds = conds + (child.test,)
            elif isinstance(child, (ast.With, ast.AsyncWith)):
                if any(_locky(item.context_expr) for item in child.items):
                    c_locks = locks + 1
            if isinstance(child, ast.Call):
                self._check_call(child, c_loop, c_conds, c_locks)
            self._visit(child, c_loop, c_conds, c_locks)

    def _check_call(self, call: ast.Call, loop: int, conds: tuple, locks: int) -> None:
        idx = self.idx
        # PW-J004: collectives under rank-dependent control flow (checked
        # even on cold paths — a deadlock at init hangs the mesh too)
        name = _fname(call.func)
        is_collective = (
            (isinstance(call.func, ast.Attribute) and call.func.attr in _COLLECTIVES)
            or name == "shard_map"
            or name in idx.collective_fns
        )
        if is_collective and any(_rank_dependent(t) for t in conds):
            self._emit(
                "PW-J004",
                SEV_ERROR,
                call.lineno,
                f"collective/shard_map region ({name}) reachable under "
                "rank-data-dependent control flow: ranks can disagree "
                "about entering the collective and the mesh deadlocks — "
                "hoist the branch out or make it rank-invariant",
                collective=name,
                function=self.qualname,
            )

        if self.is_jitted_def:
            return  # inside a traced body: coercions/calls are free

        # PW-J005: blocking sync while holding a lock / in an SLO lane
        blocking = _blocking_call(call)
        if blocking and (locks > 0 or self.serving_lane):
            where = "while holding a lock" if locks > 0 else "inside an SLO serving lane"
            self._emit(
                "PW-J005",
                SEV_WARNING,
                call.lineno,
                f"blocking device sync ({blocking}) {where}: every "
                "waiter serializes behind one device round-trip — move "
                "the sync outside the critical section or pipeline with "
                "copy_to_host_async",
                sync=blocking,
                function=self.qualname,
            )

        if self.cold:
            return

        # PW-J002: transfer inside a hot loop (pipelined readback exempt)
        if loop > 0 and not self.pipelined:
            transfer = _transfer_call(call, idx)
            if transfer:
                self._emit(
                    "PW-J002",
                    SEV_WARNING,
                    call.lineno,
                    f"host<->device transfer ({transfer}) inside a "
                    "per-iteration loop of a hot function: the loop "
                    "stalls on the host link every pass — batch the "
                    "transfer outside the loop or pipeline it with "
                    "copy_to_host_async",
                    transfer=transfer,
                    function=self.qualname,
                )

        # PW-J001: unpadded shapes crossing a jit boundary
        if _resolve_jit_call(call, idx, self.local_jitted):
            if self.ceil_pad and "bucket_size" not in self.text:
                self._emit(
                    "PW-J001",
                    SEV_ERROR,
                    call.lineno,
                    "jitted call padded to a multiple of a block size "
                    "(ceil-div pattern): the signature count is still "
                    "linear in the batch range, so every new size "
                    "compiles a fresh program — round the BLOCK COUNT to "
                    "a power of two (ops.bucketing.bucket_size) like "
                    "JittedEncoder._pad_batch",
                    function=self.qualname,
                    pattern="ceil_div_multiple",
                )
            elif not self.padded:
                for arg in list(call.args) + [kw.value for kw in call.keywords]:
                    if _upload_of_param(arg, self.params, idx.jnp_aliases):
                        self._emit(
                            "PW-J001",
                            SEV_ERROR,
                            call.lineno,
                            "unpadded host batch uploaded straight into a "
                            "jitted callable: every distinct batch size "
                            "traces and compiles a new program — pad to a "
                            "power-of-two bucket (ops.bucketing."
                            "bucket_size) before the jit boundary",
                            function=self.qualname,
                            pattern="unpadded_param",
                        )
                        break


def _iter_functions(
    tree: ast.Module,
) -> "Iterable[tuple[ast.FunctionDef | ast.AsyncFunctionDef, str, bool]]":
    """(fn, qualname, cold) for every def, nested defs inheriting the
    enclosing function's coldness (a hot helper inside _kmeans is cold)."""

    def walk(body, prefix, inherited_cold):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}" if prefix else node.name
                cold = inherited_cold or _cold(node.name)
                yield node, qual, cold
                yield from walk(node.body, qual + ".", cold)
            elif isinstance(node, ast.ClassDef):
                yield from walk(node.body, f"{prefix}{node.name}.", inherited_cold)

    yield from walk(tree.body, "", False)


def scan_source(source: str, filename: str = "<string>") -> "list[Diagnostic]":
    """Run all PW-J checks over one module's source.  Returns findings;
    raises nothing (a syntax error yields no findings — the module will
    fail louder elsewhere)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    idx = _ModuleIndex(tree)
    lines = source.splitlines()
    serving_mod = f"{os.sep}serving{os.sep}" in filename or filename.startswith(
        "serving"
    )
    out: list[Diagnostic] = []
    jitted_defs_seen: set = set()
    for fn, qual, cold in _iter_functions(tree):
        lane = serving_mod and "lane" in fn.name.lower()
        scan = _FunctionScan(fn, qual, cold, idx, lines, filename, lane)
        out.extend(scan.run())
        if scan.is_jitted_def:
            jitted_defs_seen.add(fn.name)

    # PW-J003: non-donated in-place jitted updates (module-wide so the
    # donated-twin suppression can see every sibling)
    for jname, j in idx.jitted.items():
        if j.fn is None or j.donate:
            continue
        if jname.endswith("_safe"):
            base = idx.jitted.get(jname[: -len("_safe")])
            if base is not None and base.donate:
                continue  # deliberate non-donating twin of a donated scatter
        lineno = _inplace_on_param(j.fn, _arg_names(j.fn))
        if lineno is None or _waived(lines, lineno, "PW-J003"):
            continue
        out.append(
            Diagnostic(
                code="PW-J003",
                severity=SEV_WARNING,
                message=(
                    f"jitted function {jname!r} updates a device buffer "
                    "in place (.at[...].set) without donate_argnums: the "
                    "old and new buffer are live together, doubling HBM "
                    "peak — donate the updated operands (or add a "
                    "donated twin and keep this as the *_safe variant "
                    "for concurrent-dispatch windows)"
                ),
                trace=f"{filename}:{lineno}",
                node_name=jname,
                details={"file": filename, "line": lineno, "function": jname},
            )
        )
    return out


#: memoized per-file scans: path -> (mtime, size, findings)
_file_cache: dict[str, tuple[float, int, "list[Diagnostic]"]] = {}


def scan_file(path: str) -> "list[Diagnostic]":
    path = os.path.abspath(path)
    try:
        st = os.stat(path)
    except OSError:
        return []
    cached = _file_cache.get(path)
    if cached is not None and cached[0] == st.st_mtime and cached[1] == st.st_size:
        return list(cached[2])
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError:
        return []
    rel = path
    for root in (os.getcwd(), os.path.dirname(os.path.dirname(os.path.dirname(path)))):
        if root and path.startswith(root + os.sep):
            rel = os.path.relpath(path, root)
            break
    findings = scan_source(source, rel)
    _file_cache[path] = (st.st_mtime, st.st_size, findings)
    return list(findings)


@dataclass(frozen=True)
class DeviceReport:
    """One device-safety sweep: files scanned + findings + the static
    prediction the live counters are joined against."""

    files: tuple
    diagnostics: tuple

    @property
    def by_code(self) -> dict:
        out: dict[str, int] = {}
        for d in self.diagnostics:
            out[d.code] = out.get(d.code, 0) + 1
        return out

    @property
    def predicted_recompile_sites(self) -> int:
        return self.by_code.get("PW-J001", 0)

    @property
    def errors(self) -> int:
        return sum(1 for d in self.diagnostics if d.severity == SEV_ERROR)


def scan_paths(paths: "Iterable[str]") -> DeviceReport:
    files = []
    diags: list[Diagnostic] = []
    for p in paths:
        p = os.path.abspath(p)
        if p in files:
            continue
        files.append(p)
        diags.extend(scan_file(p))
    return DeviceReport(files=tuple(files), diagnostics=tuple(diags))


def device_module_files() -> "list[str]":
    """The repo's device surface: parallel/, ops/ and serving/ modules."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: list[str] = []
    for sub in ("parallel", "ops", "serving"):
        out.extend(sorted(glob.glob(os.path.join(pkg, sub, "*.py"))))
    return out


_profile_cache: "dict | None" = None


def device_profile(refresh: bool = False) -> dict:
    """Static prediction for the /status join: scan the device surface
    once per process and summarize.  ``predicted_recompile_sites == 0``
    is the invariant the live jit-compile counter is checked against —
    with no PW-J001 sites, a warmed serving loop must hold
    ``pathway_tpu_jit_compiles_total`` flat."""
    global _profile_cache
    if _profile_cache is not None and not refresh:
        return dict(_profile_cache)
    report = scan_paths(device_module_files())
    _profile_cache = {
        "files_scanned": len(report.files),
        "findings": sum(report.by_code.values()),
        "errors": report.errors,
        "by_code": report.by_code,
        "predicted_recompile_sites": report.predicted_recompile_sites,
    }
    return dict(_profile_cache)


# ----------------------------------------------------------------------
# graph pass


def _module_file(obj: Any) -> "str | None":
    mod = sys.modules.get(type(obj).__module__)
    f = getattr(mod, "__file__", None)
    if not f:
        return None
    f = os.path.abspath(f)
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return f if f.startswith(pkg + os.sep) else None


def _check_device_budget(graph: Any, facts: GraphFacts) -> "list[Diagnostic]":
    """PW-M002 with a per-chip scope: the device-resident share of the
    estimated state, split across PATHWAY_DEVICE_CHIPS (default: the
    local jax device count), must fit PATHWAY_DEVICE_BUDGET_BYTES."""
    from pathway_tpu.analysis.memory import (
        EstimateParams,
        build_report,
        parse_budget,
    )

    budget = parse_budget(os.environ.get("PATHWAY_DEVICE_BUDGET_BYTES"))
    if budget is None:
        return []
    chips = int(os.environ.get("PATHWAY_DEVICE_CHIPS", "0") or 0)
    if chips <= 0:
        try:
            import jax

            chips = max(1, jax.device_count())
        except Exception:
            chips = 1
    report = build_report(graph, facts, params=EstimateParams.from_env())
    by_node = {n.id: n for n in graph.nodes}
    device_ops = []
    for op in report.operators:
        n = by_node.get(op.node_id)
        if n is None:
            continue
        meta = getattr(n, "meta", None) or {}
        devicey = bool(meta.get("index_upsert"))
        adapter = getattr(n, "adapter", None)
        if adapter is not None:
            mod = type(adapter).__module__
            if mod.startswith("pathway_tpu.parallel") or ".indexing" in mod:
                devicey = True
        if devicey:
            device_ops.append(op)
    if not device_ops:
        return []
    dev_bytes = sum(op.per_worker_bytes for op in device_ops)
    per_chip = dev_bytes // chips
    if per_chip <= budget:
        return []
    breakdown = [
        (f"{op.name}#{op.node_id}", op.per_worker_bytes)
        for op in sorted(device_ops, key=lambda o: o.per_worker_bytes, reverse=True)[:8]
    ]
    return [
        Diagnostic(
            code="PW-M002",
            severity=SEV_WARNING,
            message=(
                f"estimated device-resident state {per_chip} B/chip "
                f"(total {dev_bytes} B across {chips} chip(s)) exceeds "
                f"PATHWAY_DEVICE_BUDGET_BYTES={budget} B: shard the "
                "index wider or spill cold cells to host"
            ),
            details={
                "scope": "device-per-chip",
                "budget_bytes": budget,
                "estimated_bytes": per_chip,
                "chips": chips,
                "breakdown": breakdown,
            },
        )
    ]


def check_device(graph: Any, facts: GraphFacts) -> "list[Diagnostic]":
    """The ``pw.analyze()`` bridge: scan the device modules reachable
    from this graph and attribute findings to the nodes that pull them
    in.  Host-only graphs (no index adapters, no serving stage
    annotations) scan nothing and return fast."""
    out: list[Diagnostic] = []
    try:
        out.extend(_check_device_budget(graph, facts))
    except Exception:
        pass  # budget pricing must never mask the source scan

    files: dict[str, tuple] = {}
    serving_anchor = None
    for n in graph.nodes:
        meta = getattr(n, "meta", None) or {}
        adapter = getattr(n, "adapter", None)
        if adapter is not None:
            f = _module_file(adapter)
            if f:
                files.setdefault(f, (n.id, type(adapter).__name__))
        if serving_anchor is None and (
            meta.get("serving") or meta.get("index_upsert")
        ):
            serving_anchor = n
    if serving_anchor is not None:
        # a serving graph executes the whole device surface (encoder,
        # index, lanes); scan all of it, anchored to the annotated node
        anchor = (serving_anchor.id, type(serving_anchor).__name__)
        for f in device_module_files():
            files.setdefault(os.path.abspath(f), anchor)

    for f in sorted(files):
        node_id, node_name = files[f]
        for d in scan_file(f):
            out.append(
                dataclasses.replace(d, node_id=node_id, node_name=node_name)
            )
    return out
