"""Device-resident sharded brute-force KNN index.

TPU re-design of the reference's Rust BruteForce KNN
(``src/external_integration/brute_force_knn_integration.rs:22-120``):
instead of a host ``Array2<f64>`` with scalar distance loops, the corpus
lives in TPU HBM as a fixed-capacity slab sharded row-wise over the mesh
``"data"`` axis.  Live upserts never recompile:

- slots are assigned host-side (freelist); updates are jitted donated
  scatters with the update batch padded to a power-of-two bucket and
  out-of-range pad slots dropped (``mode="drop"``);
- capacity grows 2x like the reference (``:115-119``) — a rare,
  amortized host-side realloc;
- queries: one ``[nq, d] @ [d, cap/shard]`` MXU matmul per shard +
  local top-k, then a k-sized ``all_gather`` over ICI and a final
  top-k — the network moves ``O(shards * k)`` per query, never the
  score matrix.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from pathway_tpu.internals import device_counters as _devctr
from pathway_tpu.ops.bucketing import bucket_size, pad_rows
from pathway_tpu.ops.distances import dot_scores, l2sq_distances, normalize
from pathway_tpu.ops.shard_map_compat import shard_map
from pathway_tpu.ops.topk import NEG_INF

__all__ = ["ShardedKnnIndex"]

_MIN_SHARD_ROWS = 128  # one MXU tile of rows per shard minimum


class ShardedKnnIndex:
    """Incremental vector index with add/remove/search.

    metric: "cos" (cosine over L2-normalized vectors), "dot", or "l2sq".
    Keys are arbitrary hashable host objects; the device only sees slots.
    """

    # segment merges mutate the slab in place (remove+upsert scatters)
    merge_strategy = "inplace"

    def __init__(
        self,
        dim: int,
        *,
        metric: str = "cos",
        capacity: int = 1024,
        mesh: Mesh | None = None,
        data_axis: str = "data",
        dtype: Any = jnp.float32,
    ):
        if metric not in ("cos", "dot", "l2sq"):
            raise ValueError(f"unknown metric {metric!r}")
        self.dim = dim
        self.metric = metric
        self.mesh = mesh
        self.data_axis = data_axis
        self.dtype = dtype
        self.shards = mesh.shape[data_axis] if mesh is not None else 1
        self.capacity = self._round_capacity(capacity)

        self._vec_sharding = (
            NamedSharding(mesh, P(data_axis, None)) if mesh is not None else None
        )
        self._valid_sharding = (
            NamedSharding(mesh, P(data_axis)) if mesh is not None else None
        )
        self._vectors = self._device_zeros((self.capacity, dim), dtype, self._vec_sharding)
        self._valid = self._device_zeros((self.capacity,), jnp.float32, self._valid_sharding)

        self._slot_of: dict[Any, int] = {}
        self._key_of: dict[int, Any] = {}
        self._free: list[int] = []
        self._cursor = 0  # next never-used slot
        self._search_cache: dict[tuple[int, int], Callable] = {}
        # freed slots are quarantined while dispatch handles are in flight,
        # so collect() never resolves a reused slot to the wrong key
        self._inflight = 0
        self._quarantine: list[int] = []
        # buffer generation: bumped on every realloc (_grow and
        # load_state_dict).  collect() branches on the generation in the
        # handle: anything at or past _reset_version decodes against the
        # live map (slot numbering is append-only across grows and freed
        # slots are quarantined), while a handle from before the last
        # load_state_dict is rejected — the slot->key map was replaced
        # wholesale, so decoding it would silently return wrong keys.
        self._version = 0
        self._reset_version = 0

    # ------------------------------------------------------------------
    def _round_capacity(self, cap: int) -> int:
        unit = self.shards * _MIN_SHARD_ROWS
        return max(unit, ((cap + unit - 1) // unit) * unit)

    @staticmethod
    def _device_zeros(shape, dtype, sharding):
        if sharding is None:
            return jnp.zeros(shape, dtype)
        return jax.device_put(np.zeros(shape, np.dtype(dtype)), sharding)

    def __len__(self) -> int:
        return len(self._slot_of)

    def __contains__(self, key: Any) -> bool:
        return key in self._slot_of

    @property
    def keys(self) -> list:
        return list(self._slot_of)

    # ------------------------------------------------------------------
    # updates

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def _scatter_set(vectors, valid, slots, vals):
        vectors = vectors.at[slots].set(vals, mode="drop")
        valid = valid.at[slots].set(1.0, mode="drop")
        return vectors, valid

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _scatter_clear(valid, slots):
        return valid.at[slots].set(0.0, mode="drop")

    # non-donating twins: used whenever a dispatch handle is in flight —
    # donating would hand the searched buffers' memory to the scatter
    # output while the async search may still read them (satellite fix:
    # growth/updates under concurrent dispatch)
    @staticmethod
    @jax.jit
    def _scatter_set_safe(vectors, valid, slots, vals):
        vectors = vectors.at[slots].set(vals, mode="drop")
        valid = valid.at[slots].set(1.0, mode="drop")
        return vectors, valid

    @staticmethod
    @jax.jit
    def _scatter_clear_safe(valid, slots):
        return valid.at[slots].set(0.0, mode="drop")

    @staticmethod
    @functools.partial(jax.jit, static_argnums=(4,))
    def _scatter_set_device_safe(vectors, valid, slots, vals, normalize):
        vals = vals.astype(jnp.float32)
        if normalize:
            n = jnp.linalg.norm(vals, axis=1, keepdims=True)
            vals = vals / jnp.maximum(n, 1e-30)
        vals = vals.astype(vectors.dtype)
        vectors = vectors.at[slots].set(vals, mode="drop")
        valid = valid.at[slots].set(1.0, mode="drop")
        return vectors, valid

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, 1), static_argnums=(4,))
    def _scatter_set_device(vectors, valid, slots, vals, normalize):
        # normalize/cast on device: the device-resident ingest path never
        # moves the embeddings across the host link
        vals = vals.astype(jnp.float32)
        if normalize:
            n = jnp.linalg.norm(vals, axis=1, keepdims=True)
            vals = vals / jnp.maximum(n, 1e-30)
        vals = vals.astype(vectors.dtype)
        vectors = vectors.at[slots].set(vals, mode="drop")
        valid = valid.at[slots].set(1.0, mode="drop")
        return vectors, valid

    def _assign_slots(self, keys: Sequence[Any], pad_to: int) -> np.ndarray:
        """Slot per key (allocating new slots as needed, growing the slab
        when full); rows beyond ``len(keys)`` pad with ``capacity`` so the
        scatter's mode="drop" ignores them.  The ONE copy of the
        free-list/cursor bookkeeping, shared by the host and device
        ingest paths."""
        slot_of = self._slot_of
        n_new = sum(1 for key in keys if key not in slot_of)
        while len(slot_of) + n_new > self.capacity:
            self._grow()
        slots = np.full(pad_to, self.capacity, np.int32)
        key_of = self._key_of
        free = self._free
        for i, key in enumerate(keys):
            slot = slot_of.get(key)
            if slot is None:
                slot = free.pop() if free else self._cursor
                if slot == self._cursor:
                    self._cursor += 1
                slot_of[key] = slot
                key_of[slot] = key
            slots[i] = slot
        return slots

    def add(self, items: Sequence[tuple[Any, np.ndarray]]) -> None:
        """Upsert (key, vector) pairs; one donated scatter per epoch batch."""
        if not items:
            return
        keys = [key for key, _v in items]
        vecs = np.stack([np.asarray(v, np.float32).reshape(-1) for _k, v in items])
        self.add_batch(keys, vecs)

    def add_batch(self, keys: Sequence[Any], vectors: np.ndarray) -> None:
        """Columnar upsert: ``keys`` aligned with rows of ``vectors`` [n, dim].

        The fast ingest path — normalization/cast are whole-array numpy ops
        and slot assignment is the only per-row host work, so host prep no
        longer bounds bulk-load throughput (it did when ``add`` took per-row
        tuples).
        """
        vectors = np.ascontiguousarray(vectors, np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.dim:
            raise ValueError(f"vectors shape {vectors.shape} != (n, {self.dim})")
        n = len(keys)
        if n != vectors.shape[0]:
            raise ValueError(f"{n} keys vs {vectors.shape[0]} vectors")
        if n == 0:
            return
        b = bucket_size(n)
        slots = self._assign_slots(keys, pad_to=b)
        if self.metric == "cos":
            norms = np.linalg.norm(vectors, axis=1, keepdims=True)
            np.maximum(norms, 1e-30, out=norms)
            vectors = vectors / norms
        vals = vectors.astype(np.dtype(self.dtype), copy=False)
        vals = pad_rows(vals, b)
        _devctr.record_h2d(vals.nbytes + slots.nbytes)
        scatter = self._scatter_set if self._inflight == 0 else self._scatter_set_safe
        self._vectors, self._valid = scatter(
            self._vectors, self._valid, jnp.asarray(slots), jnp.asarray(vals)
        )

    def add_batch_device(
        self, keys: Sequence[Any], vectors: Any, n_valid: int | None = None
    ) -> None:
        """Upsert from a DEVICE array [b, dim] (an encoder's output)
        without reading the embeddings back to the host: slot assignment
        is the only host work; normalization, dtype cast and the scatter
        all run on device.  Rows at index >= len(keys) (encoder padding)
        scatter to an out-of-range slot and are dropped.

        The reference's embed+index pipeline round-trips every embedding
        through host memory (python/pathway/xpacks/llm/embedders.py:
        270-327 -> index add); on a TPU the vector store lives in the
        same HBM the encoder writes to, so the round trip is pure waste
        — and on a tunneled link it dominates the pipeline.
        """
        n = len(keys) if n_valid is None else n_valid
        b = int(vectors.shape[0])
        if int(vectors.shape[1]) != self.dim:
            raise ValueError(f"vectors dim {vectors.shape[1]} != {self.dim}")
        if n > b:
            raise ValueError(f"{n} keys but only {b} vector rows")
        slots = self._assign_slots(keys, pad_to=b)
        scatter = (
            self._scatter_set_device
            if self._inflight == 0
            else self._scatter_set_device_safe
        )
        self._vectors, self._valid = scatter(
            self._vectors,
            self._valid,
            jnp.asarray(slots),
            vectors,
            self.metric == "cos",
        )

    def remove(self, keys: Sequence[Any]) -> None:
        slots = []
        for key in keys:
            slot = self._slot_of.pop(key, None)
            if slot is not None:
                self._key_of.pop(slot, None)
                if self._inflight > 0:
                    self._quarantine.append(slot)
                else:
                    self._free.append(slot)
                slots.append(slot)
        if not slots:
            return
        arr = pad_rows(np.asarray(slots, np.int32), bucket_size(len(slots)), fill=self.capacity)
        clear = self._scatter_clear if self._inflight == 0 else self._scatter_clear_safe
        self._valid = clear(self._valid, jnp.asarray(arr))

    def _grow(self) -> None:
        """2x capacity realloc (host roundtrip; rare and amortized)."""
        new_cap = self._round_capacity(self.capacity * 2)
        host_vec = np.zeros((new_cap, self.dim), np.dtype(self.dtype))
        host_valid = np.zeros((new_cap,), np.float32)
        host_vec[: self.capacity] = np.asarray(self._vectors)
        host_valid[: self.capacity] = np.asarray(self._valid)
        self.capacity = new_cap
        # in-flight handles keep referencing the pre-grow buffers (their
        # computations captured them); bump the generation so they are
        # identifiable and never confused with the new slab
        self._version += 1
        self._vectors = (
            jax.device_put(host_vec, self._vec_sharding)
            if self._vec_sharding is not None
            else jnp.asarray(host_vec)
        )
        self._valid = (
            jax.device_put(host_valid, self._valid_sharding)
            if self._valid_sharding is not None
            else jnp.asarray(host_valid)
        )

    # ------------------------------------------------------------------
    # search

    def _score_fn(self) -> Callable:
        metric = self.metric
        if metric == "l2sq":
            return lambda q, v: -l2sq_distances(q, v)
        return dot_scores  # cos vectors are pre-normalized at add time

    def _search_jit(self, k: int):
        # keyed on (k, capacity): growth changes shard_rows baked into the
        # sharded program
        cached = self._search_cache.get((k, self.capacity))
        if cached is not None:
            return cached
        score = self._score_fn()
        normalize_q = self.metric == "cos"

        if self.mesh is None:

            @jax.jit
            def run(q, vectors, valid):
                if normalize_q:
                    q = normalize(q)
                s = score(q.astype(vectors.dtype), vectors)
                s = jnp.where(valid.astype(bool)[None, :], s, NEG_INF)
                return jax.lax.top_k(s, k)

            self._search_cache[(k, self.capacity)] = run
            return run

        axis = self.data_axis
        mesh = self.mesh
        shard_rows = self.capacity // self.shards

        def local(q, vectors, valid):
            # per-shard block: vectors [cap/shards, d], valid [cap/shards]
            if normalize_q:
                q = normalize(q)
            s = score(q.astype(vectors.dtype), vectors)
            s = jnp.where(valid.astype(bool)[None, :], s, NEG_INF)
            kk = min(k, shard_rows)
            ls, li = jax.lax.top_k(s, kk)  # [nq, kk]
            li = li + jax.lax.axis_index(axis) * shard_rows
            gs = jax.lax.all_gather(ls, axis)  # [shards, nq, kk] over ICI
            gi = jax.lax.all_gather(li, axis)
            nq = q.shape[0]
            gs = jnp.transpose(gs, (1, 0, 2)).reshape(nq, -1)
            gi = jnp.transpose(gi, (1, 0, 2)).reshape(nq, -1)
            vals, pos = jax.lax.top_k(gs, k)
            return vals, jnp.take_along_axis(gi, pos, axis=1)

        shmapped = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(self.data_axis, None), P(self.data_axis)),
            out_specs=(P(), P()),
            check_vma=False,
        )
        run = jax.jit(shmapped)
        self._search_cache[(k, self.capacity)] = run
        return run

    def dispatch(self, queries: np.ndarray, k: int):
        """Asynchronously dispatch a search; returns an opaque handle.
        Dispatches pipeline on-device without host sync — a serving loop
        can keep several in flight and pay the host link latency once."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        nq = queries.shape[0]
        if nq == 0 or not self._slot_of:
            return (None, nq, k, self._version)
        k_eff = min(k, self.capacity)
        qb = pad_rows(queries, bucket_size(nq, min_bucket=1))
        _devctr.record_h2d(qb.nbytes)
        out = self._search_jit(k_eff)(jnp.asarray(qb), self._vectors, self._valid)
        # start the device->host copy NOW, without blocking: on remote/
        # tunneled backends the result transfer then overlaps later
        # dispatches, so a serving loop with several handles in flight
        # pays the link RTT once per pipeline fill, not once per query
        # (measured ~6x on a stream of batch=1 queries)
        for a in out:
            copy_async = getattr(a, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self._inflight += 1
        return (out, nq, k, self._version)

    def collect(self, handle) -> list[list[tuple[Any, float]]]:
        """Resolve a :meth:`dispatch` handle to [[(key, score), ...], ...].

        Valid across a ``_grow``: the handle's computation captured the
        dispatch-time buffers, slot numbering is grow-stable, and freed
        slots stay quarantined while any handle is outstanding — so a
        pre-grow handle decodes to exactly the keys that were live when
        it was dispatched.  NOT valid across ``load_state_dict``: that
        replaces the slot->key map wholesale, so the generation recorded
        in the handle gates the decode and a pre-restore handle raises
        instead of resolving to arbitrary wrong keys."""
        out, nq, k, version = handle
        if out is None:
            return [[] for _ in range(nq)]
        if version < self._reset_version:
            raise RuntimeError(
                "stale dispatch handle: the index was restored via "
                "load_state_dict after this dispatch; slot numbering is "
                "only stable across capacity grows, not restores"
            )
        self._inflight = max(0, self._inflight - 1)
        if self._inflight == 0 and self._quarantine:
            self._free.extend(self._quarantine)
            self._quarantine.clear()
        # one host readback for both arrays (each device_get is a full
        # host<->device round trip; they dominate single-query latency)
        vals, idx = jax.device_get(out)
        _devctr.record_d2h(vals.nbytes + idx.nbytes)
        vals = vals[:nq]
        idx = idx[:nq]
        rows: list[list[tuple[Any, float]]] = []
        for qi in range(nq):
            row = []
            for slot, score in zip(idx[qi], vals[qi]):
                if score <= float(NEG_INF) / 2:
                    continue
                key = self._key_of.get(int(slot))
                if key is not None:
                    row.append((key, float(score)))
            rows.append(row[:k])
        return rows

    def search(
        self, queries: np.ndarray, k: int
    ) -> list[list[tuple[Any, float]]]:
        """Top-k per query: [[(key, score), ...], ...].  Scores: higher =
        closer for cos/dot; for l2sq the NEGATED squared distance."""
        return self.collect(self.dispatch(queries, k))

    # ------------------------------------------------------------------
    # persistence support

    def state_dict(self) -> dict:
        return {
            "dim": self.dim,
            "metric": self.metric,
            "capacity": self.capacity,
            "vectors": np.asarray(self._vectors),
            "valid": np.asarray(self._valid),
            "slot_of": dict(self._slot_of),
            "cursor": self._cursor,
            "free": list(self._free) + list(self._quarantine),
        }

    def load_state_dict(self, state: dict) -> None:
        self.capacity = self._round_capacity(state["capacity"])
        vec = np.zeros((self.capacity, self.dim), np.dtype(self.dtype))
        val = np.zeros((self.capacity,), np.float32)
        vec[: state["vectors"].shape[0]] = state["vectors"]
        val[: state["valid"].shape[0]] = state["valid"]
        self._vectors = (
            jax.device_put(vec, self._vec_sharding)
            if self._vec_sharding is not None
            else jnp.asarray(vec)
        )
        self._valid = (
            jax.device_put(val, self._valid_sharding)
            if self._valid_sharding is not None
            else jnp.asarray(val)
        )
        self._slot_of = dict(state["slot_of"])
        self._key_of = {s: k for k, s in self._slot_of.items()}
        self._cursor = state["cursor"]
        self._free = list(state["free"])
        # outstanding handles reference the pre-restore slot space:
        # invalidate them (collect() rejects their generation) and reset
        # the in-flight bookkeeping they would otherwise leak into
        self._version += 1
        self._reset_version = self._version
        self._inflight = 0
        self._quarantine = []
