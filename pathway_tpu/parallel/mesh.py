"""Device-mesh construction helpers.

Conventions across the framework:

- axis ``"data"``: batch / corpus sharding (DP + index shards);
- axis ``"model"``: tensor parallelism inside encoders.

A mesh is always optional — every numeric-plane component has a
single-device fast path.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "best_mesh", "mesh_axis_size"]


def make_mesh(
    axes: dict[str, int] | None = None, devices: list | None = None
) -> Mesh:
    """Build a Mesh from {axis: size}; sizes must multiply to len(devices).
    Default: 1-D ``("data",)`` over all devices."""
    devs = devices if devices is not None else jax.devices()
    if axes is None:
        axes = {"data": len(devs)}
    shape = tuple(axes.values())
    if int(np.prod(shape)) != len(devs):
        raise ValueError(
            f"mesh axes {axes} need {int(np.prod(shape))} devices, have {len(devs)}"
        )
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, tuple(axes.keys()))


def best_mesh(model_parallel: int = 1, devices: list | None = None) -> Mesh:
    """2-D ("data", "model") mesh with the requested TP degree; TP is
    clamped to a divisor of the device count."""
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    mp = max(1, model_parallel)
    while n % mp != 0:
        mp -= 1
    return make_mesh({"data": n // mp, "model": mp}, devs)


def mesh_axis_size(mesh: Mesh | None, axis: str) -> int:
    if mesh is None or axis not in mesh.shape:
        return 1
    return mesh.shape[axis]
