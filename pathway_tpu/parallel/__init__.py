"""Distributed plane: device meshes, sharded indexes, batched executors.

The reference scales by key-sharding rows over timely workers connected
by TCP (``src/engine/dataflow.rs:1068-1072``, SURVEY.md §2.8).  The TPU
build splits the two planes:

- host plane: epoch-synchronous engine + connectors (see
  :mod:`pathway_tpu.engine`), shardable across processes;
- numeric plane: jit/shard_map programs over a ``jax.sharding.Mesh`` —
  XLA collectives over ICI/DCN replace NCCL/MPI-style transports.
"""

from pathway_tpu.parallel.mesh import best_mesh, make_mesh, mesh_axis_size
from pathway_tpu.parallel.executor import JittedEncoder
from pathway_tpu.parallel.ivf_knn import IvfKnnIndex
from pathway_tpu.parallel.sharded_knn import ShardedKnnIndex

__all__ = [
    "make_mesh",
    "best_mesh",
    "mesh_axis_size",
    "JittedEncoder",
    "IvfKnnIndex",
    "ShardedKnnIndex",
]
