"""IVF-flat approximate KNN, TPU-shaped.

A real ANN structure behind the ``UsearchKnn`` API (reference HNSW:
``src/external_integration/usearch_integration.rs:1-163``).  HNSW's
pointer-chasing graph walk is hostile to XLA (dynamic, scalar, branchy),
so the TPU re-design is an inverted-file index instead — the classic
matmul-friendly ANN:

- ``nlist`` k-means centroids live in HBM; assignment of a vector (or a
  query) to cells is one ``[n, d] @ [d, nlist]`` MXU matmul.
- vectors are stored GROUPED BY CELL in a static ``[nlist, cell_cap, d]``
  slab — static shapes, no recompilation on upserts; per-cell freelists
  are host-side.
- a query scans only its ``nprobe`` closest cells: ``jnp.take`` gathers
  those cells' rows (reads ``nprobe/nlist`` of the corpus from HBM
  instead of all of it — the whole point of IVF at 10M+ scale), then one
  einsum + top-k.  Queries are processed in fixed sub-batches via
  ``lax.map`` so the gather buffer stays bounded.
- cell overflow grows ``cell_cap`` 2x (amortized, like the reference's
  2x index growth); k-means (re)training is a few jitted Lloyd
  iterations on a sample.

Exactness contract: approximate — recall depends on nprobe/nlist and how
clustered the data is (tests assert recall@10 >= 0.95 on mixture data
with the defaults).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pathway_tpu.internals import device_counters as _devctr
from pathway_tpu.ops.bucketing import bucket_size, pad_rows
from pathway_tpu.ops.topk import NEG_INF

__all__ = ["IvfKnnIndex"]


@jax.jit
def _assign_ip(x, c):
    """Nearest centroid by inner product: [n, d] x [nlist, d] -> [n]."""
    return jnp.argmax(x @ c.T, axis=1)


def _kmeans(
    data: np.ndarray, nlist: int, iters: int = 8, seed: int = 0
) -> np.ndarray:
    """A few Lloyd iterations, assignment on device (one matmul/iter)."""
    rng = np.random.default_rng(seed)
    n = data.shape[0]
    cents = data[rng.choice(n, size=min(nlist, n), replace=False)].copy()
    if cents.shape[0] < nlist:  # degenerate: fewer points than cells
        cents = np.concatenate(
            [cents, rng.normal(size=(nlist - cents.shape[0], data.shape[1]))]
        ).astype(np.float32)

    @jax.jit
    def assign(x, c):
        # nearest centroid by L2 == max (c.x - |c|^2/2)
        scores = x @ c.T - 0.5 * jnp.sum(c * c, axis=1)[None, :]
        return jnp.argmax(scores, axis=1)

    xd = jnp.asarray(data)
    for _ in range(iters):
        a = np.asarray(assign(xd, jnp.asarray(cents)))
        for ci in range(nlist):
            members = data[a == ci]
            if len(members):
                cents[ci] = members.mean(axis=0)
            else:  # dead cell: re-seed on a random point
                cents[ci] = data[rng.integers(n)]
    return cents.astype(np.float32)


class IvfKnnIndex:
    """Incremental IVF-flat index with add/remove/search.

    metric: "cos" (vectors L2-normalized at add time) or "dot".
    Keys are arbitrary hashable host objects; the device sees (cell, slot).
    """

    # segment merges mutate the cell slabs in place (remove+upsert)
    merge_strategy = "inplace"

    def __init__(
        self,
        dim: int,
        *,
        metric: str = "cos",
        capacity: int = 1024,
        nlist: int | None = None,
        nprobe: int | None = None,
        train_size: int = 50_000,
        query_block: int = 8,
        dtype: Any = jnp.bfloat16,
        seed: int = 0,
    ):
        if metric not in ("cos", "dot"):
            raise ValueError(f"unsupported IVF metric {metric!r}")
        self.dim = dim
        self.metric = metric
        self.dtype = dtype
        self.seed = seed
        self.train_size = train_size
        self.query_block = query_block
        self.nlist = nlist or max(16, 1 << int(np.log2(max(capacity, 2) ** 0.5)))
        self.nprobe = nprobe or max(1, self.nlist // 8)
        self.cell_cap = max(
            64, bucket_size(4 * max(1, capacity // self.nlist))
        )

        self._centroids: Any = None  # [nlist, d] device
        self._cells = jnp.zeros((self.nlist, self.cell_cap, dim), dtype)
        self._valid = jnp.zeros((self.nlist, self.cell_cap), jnp.float32)
        # host bookkeeping
        self._slot_of: dict[Any, tuple[int, int]] = {}  # key -> (cell, slot)
        self._key_of: dict[tuple[int, int], Any] = {}
        self._free: list[list[int]] = [[] for _ in range(self.nlist)]
        self._cursor = np.zeros(self.nlist, np.int64)  # next fresh slot per cell
        self._pending: list[tuple[Any, np.ndarray]] = []  # rows awaiting training
        self._search_cache: dict[tuple, Callable] = {}

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._slot_of) + len(self._pending)

    def __contains__(self, key: Any) -> bool:
        return key in self._slot_of or any(k == key for k, _v in self._pending)

    def keys(self) -> list:
        seen = list(self._slot_of)
        seen.extend(k for k, _v in self._pending if k not in self._slot_of)
        return seen

    @property
    def trained(self) -> bool:
        return self._centroids is not None

    def _normalize(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.ascontiguousarray(vectors, np.float32)
        if self.metric == "cos":
            norms = np.linalg.norm(vectors, axis=1, keepdims=True)
            np.maximum(norms, 1e-30, out=norms)
            vectors = vectors / norms
        return vectors

    def train(self, sample: np.ndarray | None = None) -> None:
        """Fit centroids; flushes any rows buffered before training.

        Re-training a populated index re-inserts every stored vector, so
        cell placement always matches the centroids used for probing —
        refitting without re-assigning would silently collapse recall."""
        if sample is None:
            if not self._pending:
                raise ValueError("nothing to train on")
            sample = np.stack([v for _k, v in self._pending])
        sample = self._normalize(sample)
        if sample.shape[0] > self.train_size:
            rng = np.random.default_rng(self.seed)
            sample = sample[
                rng.choice(sample.shape[0], size=self.train_size, replace=False)
            ]
        stored: list[tuple[Any, np.ndarray]] = []
        if self._slot_of:
            host_cells = np.asarray(self._cells, np.float32)
            for key, (ci, slot) in self._slot_of.items():
                stored.append((key, host_cells[ci, slot]))
            self._cells = jnp.zeros_like(self._cells)
            self._valid = jnp.zeros_like(self._valid)
            self._slot_of.clear()
            self._key_of.clear()
            self._free = [[] for _ in range(self.nlist)]
            self._cursor[:] = 0
        self._centroids = jnp.asarray(_kmeans(sample, self.nlist, seed=self.seed))
        pending, self._pending = self._pending, []
        for keys_vecs in (stored, pending):
            if keys_vecs:
                self.add_batch(
                    [k for k, _ in keys_vecs],
                    np.stack([v for _, v in keys_vecs]),
                )

    # ------------------------------------------------------------------
    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def _scatter_set(cells, valid, cell_idx, slot_idx, vals):
        cells = cells.at[cell_idx, slot_idx].set(vals, mode="drop")
        valid = valid.at[cell_idx, slot_idx].set(1.0, mode="drop")
        return cells, valid

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _scatter_clear(valid, cell_idx, slot_idx):
        return valid.at[cell_idx, slot_idx].set(0.0, mode="drop")

    def _assign_cells(self, vectors: np.ndarray) -> np.ndarray:
        # cos/dot: nearest centroid by inner product (centroids come from
        # normalized data for cos).  Rows pad to a power-of-two bucket so
        # arbitrary batch sizes reuse a logarithmic set of compiled
        # programs (pad rows are zeros; their assignment is sliced off)
        n = vectors.shape[0]
        vpad = pad_rows(np.ascontiguousarray(vectors, np.float32), bucket_size(n))
        return np.asarray(_assign_ip(jnp.asarray(vpad), self._centroids))[:n]

    def add(self, items: Sequence[tuple[Any, np.ndarray]]) -> None:
        if not items:
            return
        keys = [k for k, _v in items]
        vecs = np.stack([np.asarray(v, np.float32).reshape(-1) for _k, v in items])
        self.add_batch(keys, vecs)

    def add_batch(self, keys: Sequence[Any], vectors: np.ndarray) -> None:
        vectors = self._normalize(vectors)
        if vectors.ndim != 2 or vectors.shape[1] != self.dim:
            raise ValueError(f"vectors shape {vectors.shape} != (n, {self.dim})")
        keys = list(keys)
        if len(keys) != vectors.shape[0]:
            raise ValueError(f"{len(keys)} keys vs {vectors.shape[0]} vectors")
        # duplicate keys within one batch: keep the LAST occurrence only
        # (upsert semantics) — otherwise two live slots map to one key and
        # remove() would leave an orphan forever searchable
        last = {key: i for i, key in enumerate(keys)}
        if len(last) != len(keys):
            sel = sorted(last.values())
            keys = [keys[i] for i in sel]
            vectors = vectors[sel]
        if self._centroids is None:
            # buffer until trained; auto-train once the buffer is useful
            self._pending.extend(zip(keys, vectors))
            if len(self._pending) >= max(self.nlist * 8, 1024):
                self.train()
            return
        # upserts: drop existing placements first (cell may change)
        existing = [k for k in keys if k in self._slot_of]
        if existing:
            self.remove(existing)
        cells = self._assign_cells(vectors)
        # overflow check (host counts; grow doubles cell_cap for all cells)
        counts = np.bincount(cells, minlength=self.nlist)
        for ci in np.nonzero(counts)[0]:
            while (
                self._cursor[ci] - len(self._free[ci]) + counts[ci] > self.cell_cap
            ):
                self._grow()
        slots = np.empty(len(keys), np.int32)
        for i, (key, ci) in enumerate(zip(keys, cells)):
            ci = int(ci)
            free = self._free[ci]
            slot = free.pop() if free else int(self._cursor[ci])
            if slot == self._cursor[ci]:
                self._cursor[ci] += 1
            slots[i] = slot
            self._slot_of[key] = (ci, slot)
            self._key_of[(ci, slot)] = key
        b = bucket_size(len(keys))
        cell_idx = pad_rows(cells.astype(np.int32), b, fill=self.nlist)  # dropped
        slot_idx = pad_rows(slots, b, fill=self.cell_cap)
        vals = pad_rows(vectors.astype(np.dtype(self.dtype), copy=False), b)
        self._cells, self._valid = self._scatter_set(
            self._cells,
            self._valid,
            jnp.asarray(cell_idx),
            jnp.asarray(slot_idx),
            jnp.asarray(vals),
        )

    def remove(self, keys: Sequence[Any]) -> None:
        cs, ss = [], []
        for key in keys:
            place = self._slot_of.pop(key, None)
            if place is None:
                # may still be sitting in the pre-training buffer
                self._pending = [(k, v) for k, v in self._pending if k != key]
                continue
            ci, slot = place
            self._key_of.pop(place, None)
            self._free[ci].append(slot)
            cs.append(ci)
            ss.append(slot)
        if not cs:
            return
        b = bucket_size(len(cs))
        cell_idx = pad_rows(np.asarray(cs, np.int32), b, fill=self.nlist)
        slot_idx = pad_rows(np.asarray(ss, np.int32), b, fill=self.cell_cap)
        self._valid = self._scatter_clear(
            self._valid, jnp.asarray(cell_idx), jnp.asarray(slot_idx)
        )

    def _grow(self) -> None:
        """Double cell_cap (host roundtrip; rare and amortized)."""
        new_cap = self.cell_cap * 2
        host_cells = np.zeros((self.nlist, new_cap, self.dim), np.dtype(self.dtype))
        host_valid = np.zeros((self.nlist, new_cap), np.float32)
        host_cells[:, : self.cell_cap] = np.asarray(self._cells)
        host_valid[:, : self.cell_cap] = np.asarray(self._valid)
        self.cell_cap = new_cap
        self._cells = jnp.asarray(host_cells)
        self._valid = jnp.asarray(host_valid)
        self._search_cache.clear()

    # ------------------------------------------------------------------
    def _search_jit(self, k: int, nprobe: int):
        sig = (k, nprobe, self.cell_cap, self.query_block)
        cached = self._search_cache.get(sig)
        if cached is not None:
            return cached
        qb = self.query_block
        cell_cap = self.cell_cap

        @jax.jit
        def run(queries, cents, cells, valid):
            # queries pre-padded to a multiple of qb: [nq, d]
            def block(qblk):
                # [qb, d] -> probe cells -> gather -> score -> top-k
                cscore = qblk @ cents.T  # [qb, nlist]
                _, probe = jax.lax.top_k(cscore, nprobe)  # [qb, nprobe]
                sub = jnp.take(cells, probe, axis=0)  # [qb, nprobe, cap, d]
                subv = jnp.take(valid, probe, axis=0)  # [qb, nprobe, cap]
                s = jnp.einsum(
                    "qd,qpcd->qpc",
                    qblk.astype(sub.dtype),
                    sub,
                    preferred_element_type=jnp.float32,
                )
                s = jnp.where(subv.astype(bool), s, NEG_INF)
                s = s.reshape(qb, nprobe * cell_cap)
                vals, pos = jax.lax.top_k(s, k)
                # flat slab id = cell * cell_cap + slot
                flat = (
                    probe[:, :, None] * cell_cap
                    + jnp.arange(cell_cap)[None, None, :]
                ).reshape(qb, nprobe * cell_cap)
                ids = jnp.take_along_axis(flat, pos, axis=1)
                return vals, ids

            blocks = queries.reshape(-1, qb, queries.shape[-1])
            vals, ids = jax.lax.map(block, blocks)
            return vals.reshape(-1, k), ids.reshape(-1, k)

        self._search_cache[sig] = run
        return run

    def search(
        self, queries: np.ndarray, k: int, *, nprobe: int | None = None
    ) -> list[list[tuple[Any, float]]]:
        """Top-k per query: [[(key, score), ...], ...] (higher = closer)."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        nq = queries.shape[0]
        if nq == 0:
            return []
        if self._centroids is None:
            if self._pending:
                self.train()
            else:
                return [[] for _ in range(nq)]
        if self.metric == "cos":
            queries = self._normalize(queries)
        nprobe = min(nprobe or self.nprobe, self.nlist)
        k_eff = min(k, nprobe * self.cell_cap)
        # pad the BLOCK COUNT to a power of two, not just the row count to
        # a multiple of query_block: multiple-of-block padding still
        # compiles one program per distinct block count (linear in the
        # query-batch range), which is a recompile storm under mixed
        # serving batch sizes
        pad_q = self.query_block * bucket_size(
            -(-nq // self.query_block), min_bucket=1
        )
        qpad = pad_rows(queries, pad_q)
        _devctr.record_h2d(qpad.nbytes)
        run = self._search_jit(k_eff, nprobe)
        out = run(jnp.asarray(qpad), self._centroids, self._cells, self._valid)
        for a in out:
            copy_async = getattr(a, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        vals, ids = jax.device_get(out)
        _devctr.record_d2h(vals.nbytes + ids.nbytes)
        rows: list[list[tuple[Any, float]]] = []
        for qi in range(nq):
            row = []
            for flat, score in zip(ids[qi], vals[qi]):
                if score <= float(NEG_INF) / 2:
                    continue
                place = (int(flat) // self.cell_cap, int(flat) % self.cell_cap)
                key = self._key_of.get(place)
                if key is not None:
                    row.append((key, float(score)))
            rows.append(row[:k])
        return rows

    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "dim": self.dim,
            "metric": self.metric,
            "nlist": self.nlist,
            "cell_cap": self.cell_cap,
            "centroids": (
                np.asarray(self._centroids) if self._centroids is not None else None
            ),
            "cells": np.asarray(self._cells),
            "valid": np.asarray(self._valid),
            "slot_of": dict(self._slot_of),
            "cursor": self._cursor.copy(),
            "free": [list(f) for f in self._free],
            "pending": [(k, np.asarray(v)) for k, v in self._pending],
        }

    def load_state_dict(self, state: dict) -> None:
        self.nlist = state["nlist"]
        self.cell_cap = state["cell_cap"]
        self._centroids = (
            jnp.asarray(state["centroids"]) if state["centroids"] is not None else None
        )
        self._cells = jnp.asarray(state["cells"])
        self._valid = jnp.asarray(state["valid"])
        self._slot_of = dict(state["slot_of"])
        self._key_of = {p: k for k, p in self._slot_of.items()}
        self._cursor = np.asarray(state["cursor"]).copy()
        self._free = [list(f) for f in state["free"]]
        self._pending = [(k, np.asarray(v)) for k, v in state["pending"]]
        self._search_cache.clear()
