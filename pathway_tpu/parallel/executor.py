"""Batched jitted model executor: the TPU replacement for per-row torch.

The reference embeds/reranks one row at a time inside a torch UDF
(``xpacks/llm/embedders.py:270-327``, ``rerankers.py:186-235``).  Here a
whole epoch's rows are tokenized into one bucketed batch and pushed
through a single jit-compiled flax program; with a mesh, the batch is
data-parallel over ``"data"`` and the params tensor-parallel over
``"model"`` (see :func:`pathway_tpu.models.encoder_param_specs`).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from pathway_tpu.models.encoder import (
    CrossEncoderModel,
    EncoderConfig,
    TextEncoderModel,
    encoder_param_specs,
)
from pathway_tpu.internals import device_counters as _devctr
from pathway_tpu.models.tokenizer import Tokenizer, get_tokenizer
from pathway_tpu.ops.bucketing import bucket_size

__all__ = ["JittedEncoder"]


class JittedEncoder:
    """Holds (possibly sharded) params + compiled apply fns per shape bucket.

    cross=False: ``encode(texts) -> [n, hidden] float32`` embeddings.
    cross=True:  ``score_pairs(queries, docs) -> [n] float32`` logits.
    """

    def __init__(
        self,
        config: EncoderConfig | None,
        *,
        cross: bool = False,
        tokenizer: Tokenizer | None = None,
        model_name: str | None = None,
        mesh: Mesh | None = None,
        data_axis: str = "data",
        model_axis: str = "model",
        max_batch: int = 1024,
        max_len: int | None = None,
        seed: int = 0,
        params: Any = None,
        checkpoint_dir: str | None = None,
        pipeline_depth: int = 2,
        sequence_axis: str | None = None,
    ):
        #: sequence_axis: shard the SEQUENCE dimension over this mesh
        #: axis and run ring attention inside every layer — the
        #: long-document path: max_len may exceed one device's attention
        #: memory (it must divide by the axis size).  Mutually exclusive
        #: with sharding the batch over the same axis.
        #: chunks kept in flight before collecting a readback.  2 keeps
        #: the historical device-memory footprint (one computing + one
        #: draining); raise on high-RTT links to hide the round trip at
        #: the cost of one more resident batch per extra slot.
        self.pipeline_depth = max(1, pipeline_depth)
        if checkpoint_dir is not None:
            # real pretrained weights: config/params/vocab all from the
            # local HF checkpoint directory (models/convert.py).  Pass
            # config=None to let config.json decide pooling (BGE -> cls);
            # an explicit config only overrides pool/dtype here.
            import dataclasses as _dc

            from pathway_tpu.models import convert as _convert
            from pathway_tpu.models.wordpiece import WordPieceTokenizer
            import os as _os

            if params is not None:
                raise ValueError(
                    "pass either params= or checkpoint_dir=, not both — "
                    "explicit params would be silently replaced"
                )
            user_cfg = config
            config = _convert.config_from_hf(
                checkpoint_dir,
                pool=user_cfg.pool if user_cfg is not None else None,
                num_labels=1 if cross else 0,
            )
            config = _dc.replace(config, normalize=not cross)
            if user_cfg is not None:
                config = _dc.replace(config, dtype=user_cfg.dtype)
            params = _convert.convert_bert_checkpoint(
                _convert.load_state_dict(checkpoint_dir), config
            )
            vocab = _os.path.join(checkpoint_dir, "vocab.txt")
            if tokenizer is None and _os.path.exists(vocab):
                tokenizer = WordPieceTokenizer(vocab)
        elif config is None:
            raise ValueError("config is required without checkpoint_dir")
        self.sequence_axis = sequence_axis
        if sequence_axis is not None:
            import dataclasses as _dc

            if mesh is None or sequence_axis not in mesh.shape:
                raise ValueError(
                    "sequence_axis requires a mesh containing that axis"
                )
            config = _dc.replace(
                config, seq_mesh=mesh, seq_axis=sequence_axis
            )
        self.config = config
        self.cross = cross
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.max_batch = max_batch
        self.max_len = max_len or config.max_len
        if sequence_axis is not None:
            n_seq = mesh.shape[sequence_axis]
            if self.max_len % n_seq != 0:
                raise ValueError(
                    f"max_len {self.max_len} must divide the "
                    f"{sequence_axis!r} axis size {n_seq}"
                )
        self.tokenizer = tokenizer or get_tokenizer(model_name, config.vocab_size)
        self.model = (CrossEncoderModel if cross else TextEncoderModel)(config)

        if params is None:
            rng = jax.random.PRNGKey(seed)
            dummy = jnp.zeros((1, 8), jnp.int32)
            init_model = self.model
            if sequence_axis is not None:
                # init with the local-attention twin: identical params
                # (ring attention adds no parameters), no shard_map at
                # init time
                import dataclasses as _dc

                init_model = (CrossEncoderModel if cross else TextEncoderModel)(
                    _dc.replace(config, seq_mesh=None)
                )
            params = init_model.init(rng, dummy, jnp.ones((1, 8), jnp.int32))
        # batch layout: DP shards rows over data_axis; the SP long-doc
        # path instead shards the SEQUENCE dimension over sequence_axis
        in_spec = (
            P(None, sequence_axis)
            if sequence_axis is not None
            else P(data_axis, None)
        )
        if mesh is not None and model_axis in mesh.shape:
            specs = encoder_param_specs(params, model_axis)
            shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
            params = jax.device_put(params, shardings)
            self._in_batch_sharding = NamedSharding(mesh, in_spec)
            self._out_sharding = NamedSharding(mesh, P())
        elif mesh is not None:
            params = jax.device_put(
                params, jax.tree.map(lambda _: NamedSharding(mesh, P()), params)
            )
            self._in_batch_sharding = NamedSharding(mesh, in_spec)
            self._out_sharding = NamedSharding(mesh, P())
        else:
            self._in_batch_sharding = None
            self._out_sharding = None
        self.params = params
        # token ids upload as int16 when the vocab permits (mask/type as
        # uint8): 3x less host->device traffic per chunk, which is what
        # bounds steady-state throughput on remote/tunneled backends; the
        # cast back to int32 is fused into the compiled apply
        self._narrow_ids = config.vocab_size < 2**15

        def _apply_cast(params, ids, mask, tps):
            return self.model.apply(
                params,
                ids.astype(jnp.int32),
                mask.astype(jnp.int32),
                tps.astype(jnp.int32),
            )

        self._apply = jax.jit(_apply_cast, out_shardings=self._out_sharding)
        self._dp = 1 if mesh is None else mesh.shape.get(data_axis, 1)

    # ------------------------------------------------------------------
    def _pad_batch(self, ids: np.ndarray, mask: np.ndarray, tps: np.ndarray):
        """Round the batch up so it divides the data-parallel degree."""
        n = ids.shape[0]
        b = bucket_size(n, min_bucket=max(8, self._dp))
        b = ((b + self._dp - 1) // self._dp) * self._dp
        if b > n:
            pad = ((0, b - n), (0, 0))
            ids = np.pad(ids, pad)
            mask = np.pad(mask, pad)
            tps = np.pad(tps, pad)
        # padded rows must still be valid encoder input: one non-masked token
        mask[n:, 0] = 1
        return ids, mask, tps, n

    def _dispatch(
        self,
        ids: np.ndarray,
        mask: np.ndarray,
        tps: np.ndarray,
        start_host_copy: bool = True,
    ):
        """Enqueue one padded chunk; returns (device_out, n_real_rows).
        The device->host copy is started immediately (non-blocking), so on
        remote/tunneled backends the transfer of chunk i overlaps the
        tokenize+compute of chunk i+1.  ``start_host_copy=False`` for
        consumers that keep the output on device (``encode_into``)."""
        ids, mask, tps, n = self._pad_batch(ids, mask, tps)
        if self.sequence_axis is not None and ids.shape[1] < self.max_len:
            # SP shards the sequence dimension: pad to the full max_len so
            # every device holds an equal block
            pad = ((0, 0), (0, self.max_len - ids.shape[1]))
            ids = np.pad(ids, pad)
            mask = np.pad(mask, pad)
            tps = np.pad(tps, pad)
        if self._narrow_ids:
            ids = ids.astype(np.int16, copy=False)
            mask = mask.astype(np.uint8, copy=False)
            tps = tps.astype(np.uint8, copy=False)
        _devctr.record_h2d(ids.nbytes + mask.nbytes + tps.nbytes)
        args = [jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(tps)]
        if self._in_batch_sharding is not None:
            args = [jax.device_put(a, self._in_batch_sharding) for a in args]
        out = self._apply(self.params, *args)
        if start_host_copy:
            copy_async = getattr(out, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        return out, n

    def _run(self, ids: np.ndarray, mask: np.ndarray, tps: np.ndarray) -> np.ndarray:
        out, n = self._dispatch(ids, mask, tps)
        return self._readback(out)[:n]

    @staticmethod
    def _readback(out: Any) -> np.ndarray:
        host = np.asarray(out)
        _devctr.record_d2h(host.nbytes)
        return host

    def _chunks(self, texts: Sequence[str], pair: Sequence[str] | None):
        for i in range(0, len(texts), self.max_batch):
            sl = slice(i, i + self.max_batch)
            yield texts[sl], None if pair is None else pair[sl]

    def _run_pipelined(
        self, texts: list, pair: "list | None"
    ) -> list[np.ndarray]:
        """Tokenize/dispatch up to ``self.pipeline_depth`` chunks before
        collecting the oldest readback, so tokenize + device compute +
        host transfer of different chunks all overlap."""
        from collections import deque

        outs: list[np.ndarray] = []
        inflight: deque = deque()
        for chunk, pchunk in self._chunks(texts, pair):
            ids, mask, tps = self.tokenizer.encode_batch(
                chunk, pair=pchunk, max_len=self.max_len
            )
            inflight.append(self._dispatch(ids, mask, tps))
            if len(inflight) >= self.pipeline_depth:
                out, nrows = inflight.popleft()
                outs.append(self._readback(out)[:nrows])
        while inflight:
            out, nrows = inflight.popleft()
            outs.append(self._readback(out)[:nrows])
        return outs

    # ------------------------------------------------------------------
    def encode(self, texts: Sequence[str]) -> np.ndarray:
        """Embed a list of texts -> [n, hidden] float32."""
        if self.cross:
            raise TypeError("cross-encoder executor: use score_pairs()")
        if not texts:
            return np.zeros((0, self.config.hidden), np.float32)
        return np.concatenate(self._run_pipelined(list(texts), None), axis=0)

    def encode_into(self, index: Any, keys: Sequence[Any], texts: Sequence[str]) -> int:
        """Embed ``texts`` and upsert the embeddings into ``index``
        (``ShardedKnnIndex.add_batch_device``) entirely on device — no
        embedding ever crosses the host link.  The reference embedder
        reads every vector back through host memory before indexing
        (python/pathway/xpacks/llm/embedders.py:270-327); on TPU the
        index slab lives in the same HBM, so the chunk pipeline here
        only ships token ids up and nothing down.  Returns the number of
        rows indexed."""
        if self.cross:
            raise TypeError("cross-encoder executor: use score_pairs()")
        texts = list(texts)
        keys = list(keys)
        if len(keys) != len(texts):
            raise ValueError("keys and texts must align")
        if not texts:
            return 0
        from collections import deque

        inflight: deque = deque()
        pos = 0
        for chunk, _p in self._chunks(texts, None):
            ids, mask, tps = self.tokenizer.encode_batch(
                chunk, max_len=self.max_len
            )
            out, n = self._dispatch(ids, mask, tps, start_host_copy=False)
            inflight.append((out, n, keys[pos : pos + n]))
            pos += n
            if len(inflight) >= self.pipeline_depth:
                out, n, kchunk = inflight.popleft()
                index.add_batch_device(kchunk, out, n_valid=n)
        while inflight:
            out, n, kchunk = inflight.popleft()
            index.add_batch_device(kchunk, out, n_valid=n)
        return pos

    def score_pairs(self, queries: Sequence[str], docs: Sequence[str]) -> np.ndarray:
        """Cross-encoder scores for aligned (query, doc) pairs -> [n]."""
        if not self.cross:
            raise TypeError("bi-encoder executor: use encode()")
        if len(queries) != len(docs):
            raise ValueError("queries and docs must align")
        if not queries:
            return np.zeros((0,), np.float32)
        return np.concatenate(
            self._run_pipelined(list(queries), list(docs)), axis=0
        )
