"""``pathway_tpu`` CLI (reference ``python/pathway/cli.py:53-319``):
``spawn`` runs a program under N processes x M threads;
``spawn-from-env`` reads the command from PATHWAY_SPAWN_ARGS.

Process topology env contract matches the reference
(``src/engine/dataflow/config.rs:86-120``): PATHWAY_THREADS,
PATHWAY_PROCESSES, PATHWAY_PROCESS_ID, PATHWAY_FIRST_PORT.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

__all__ = ["main", "spawn", "lint"]


def spawn(
    threads: int,
    processes: int,
    first_port: int,
    program: str,
    arguments: list[str],
    record: bool = False,
    record_path: str | None = None,
) -> int:
    env_base = dict(os.environ)
    env_base["PATHWAY_THREADS"] = str(threads)
    env_base["PATHWAY_PROCESSES"] = str(processes)
    env_base["PATHWAY_FIRST_PORT"] = str(first_port)
    if record:
        env_base["PATHWAY_PERSISTENT_STORAGE"] = record_path or "./record"
        env_base["PATHWAY_PERSISTENCE_MODE"] = "persisting"
    if processes <= 1:
        env_base["PATHWAY_PROCESS_ID"] = "0"
        return subprocess.call([program, *arguments], env=env_base)
    procs = []
    for pid in range(processes):
        env = dict(env_base)
        env["PATHWAY_PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen([program, *arguments], env=env))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def lint(
    program: str,
    *,
    werror: bool = False,
    plan: bool = False,
    memory: bool = False,
    device: bool = False,
    baseline: str | None = None,
) -> int:
    """Build ``program``'s dataflow graph without running it and print
    the pre-flight analyzer's findings (``pathway_tpu/analysis/``).
    With ``plan=True`` also print the optimizer's execution plan for the
    built graph (``pw.explain()`` textual form, at the PATHWAY_OPTIMIZE
    level); with ``memory=True`` also print the plan-aware capacity
    report (``pw.estimate_memory()``; scenario and budget come from the
    PATHWAY_MEMORY_* environment — a blown PATHWAY_MEMORY_BUDGET
    surfaces as a PW-M002 finding above, not a separate exit path);
    with ``device=True`` additionally sweep the program file AND the
    repo's whole device surface (``parallel/``, ``ops/``, ``serving/``)
    through the PW-J device-safety analyzer, whether or not the built
    graph reaches it — the self-lint mode ``scripts/lint_repo.sh
    --device`` runs over ``examples/``.  ``baseline`` names a JSON file mapping program basenames to
    ACCEPTED warning codes: baselined warnings are still printed but do
    not fail ``--werror`` (errors are never baselined — an accepted
    hazard belongs in the config, not silenced in code).  Exit 1 on
    error-severity diagnostics (or any unbaselined finding with
    ``--werror``), 0 on a clean graph."""
    import json
    import os.path

    from pathway_tpu.analysis import SEV_ERROR, format_diagnostics, lint_file

    accepted: set[str] = set()
    if baseline is not None:
        with open(baseline, encoding="utf-8") as fh:
            table = json.load(fh)
        accepted = set(table.get(os.path.basename(program), ()))

    diags = lint_file(program)
    if device:
        # file-level sweep: program source + the repo device modules,
        # deduplicated against findings the graph pass already raised
        from pathway_tpu.analysis import scan_device, device_module_files

        seen = {(d.code, d.trace) for d in diags}
        report = scan_device([program, *device_module_files()])
        diags = list(diags) + [
            d for d in report.diagnostics if (d.code, d.trace) not in seen
        ]
    if diags:
        print(format_diagnostics(diags))
    if plan:
        # lint_file leaves the built graph in place; compile its plan
        from pathway_tpu.analysis import explain

        print(explain().format())
    if memory:
        # same built graph: the plan-aware capacity report
        from pathway_tpu.analysis import estimate_memory

        print(estimate_memory().format())
    errors = sum(1 for d in diags if d.severity == SEV_ERROR)
    warnings = len(diags) - errors
    gating = [
        d for d in diags if d.severity == SEV_ERROR or d.code not in accepted
    ]
    baselined = len(diags) - len(gating)
    suffix = f", {baselined} baselined" if baselined else ""
    print(
        f"{program}: {errors} error(s), {warnings} warning(s){suffix}",
        file=sys.stderr,
    )
    if errors or (werror and gating):
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="pathway_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("spawn", help="run a pipeline with worker topology")
    sp.add_argument("--threads", "-t", type=int, default=1)
    sp.add_argument("--processes", "-n", type=int, default=1)
    sp.add_argument("--first-port", type=int, default=10000)
    sp.add_argument("--record", action="store_true")
    sp.add_argument("--record-path", default=None)
    sp.add_argument("program")
    sp.add_argument("arguments", nargs=argparse.REMAINDER)

    se = sub.add_parser("spawn-from-env", help="spawn using $PATHWAY_SPAWN_ARGS")

    lp = sub.add_parser(
        "lint",
        help="statically analyze a pipeline's graph without running it",
    )
    lp.add_argument("program", help="Python file that builds the graph")
    lp.add_argument(
        "--werror",
        action="store_true",
        help="exit non-zero on warnings too",
    )
    lp.add_argument(
        "--plan",
        action="store_true",
        help="also print the optimizer's execution plan",
    )
    lp.add_argument(
        "--memory",
        action="store_true",
        help="also print the plan-aware memory capacity report",
    )
    lp.add_argument(
        "--device",
        action="store_true",
        help="also sweep the program and the repo device modules "
        "through the PW-J device-safety analyzer",
    )
    lp.add_argument(
        "--baseline",
        default=None,
        help="JSON file of accepted warning codes per program basename",
    )

    args = parser.parse_args(argv)
    if args.command == "spawn":
        return spawn(
            args.threads,
            args.processes,
            args.first_port,
            args.program,
            args.arguments,
            record=args.record,
            record_path=args.record_path,
        )
    if args.command == "spawn-from-env":
        spawn_args = os.environ.get("PATHWAY_SPAWN_ARGS", "").split()
        return main(["spawn", *spawn_args])
    if args.command == "lint":
        return lint(
            args.program,
            werror=args.werror,
            plan=args.plan,
            memory=args.memory,
            device=args.device,
            baseline=args.baseline,
        )
    return 2


if __name__ == "__main__":
    sys.exit(main())
