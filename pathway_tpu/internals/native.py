"""Loader for the C++ host-runtime extension (``native/``).

Compiles ``native/pathway_native.cpp`` with g++ on first use (cached
under ``native/build/``) and exposes it; every caller has a Python
fallback, and ``PATHWAY_DISABLE_NATIVE=1`` forces it.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sysconfig
import threading
from typing import Any

_logger = logging.getLogger("pathway_tpu.native")
_lock = threading.Lock()
_module: Any = None
_tried = False

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "pathway_native.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")


def _compile() -> str | None:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(_BUILD_DIR, "pathway_native.so")
    # cross-PROCESS build lock + atomic rename: spawned cluster workers
    # all race through here on a cold cache; without it two g++ runs write
    # the same .so and a third process dlopens the torn file
    lock_path = so_path + ".lock"
    import contextlib

    @contextlib.contextmanager
    def _build_lock():
        try:
            import fcntl

            with open(lock_path, "w") as lf:
                fcntl.flock(lf, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lf, fcntl.LOCK_UN)
        except ImportError:  # non-POSIX: best effort, rename is still atomic
            yield

    with _build_lock():
        if os.path.exists(so_path) and os.path.getmtime(so_path) >= os.path.getmtime(_SRC):
            return so_path
        include = sysconfig.get_paths()["include"]
        tmp_path = f"{so_path}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O3", "-march=native", "-funroll-loops", "-shared", "-fPIC",
            "-std=c++17", f"-I{include}", _SRC, "-o", tmp_path,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, so_path)
        except Exception as e:  # noqa: BLE001
            _logger.info("native build skipped: %r", e)
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
            return None
        return so_path


def load() -> Any:
    """The compiled module, or None (fallback to Python paths)."""
    global _module, _tried
    if _module is not None or _tried:
        return _module
    with _lock:
        if _module is not None or _tried:
            return _module
        _tried = True
        if os.environ.get("PATHWAY_DISABLE_NATIVE") == "1":
            return None
        if not os.path.exists(_SRC):
            return None
        # PATHWAY_NATIVE_SO points at a prebuilt extension (the sanitizer
        # harness builds an ASan/UBSan-instrumented .so out of tree)
        so_path = os.environ.get("PATHWAY_NATIVE_SO") or _compile()
        if so_path is None or not os.path.exists(so_path):
            return None
        try:
            spec = importlib.util.spec_from_file_location("pathway_native", so_path)
            assert spec is not None and spec.loader is not None
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as e:  # noqa: BLE001
            _logger.info("native load failed: %r", e)
            return None
        # register the value classes the VM needs for type-tagged
        # hashing (Pointer) and Json get/convert semantics.  Local
        # imports: keys/json import this module at top level.
        try:
            from pathway_tpu.internals.json import Json
            from pathway_tpu.internals.keys import Pointer

            mod.set_pointer_type(Pointer)
            mod.set_json_type(Json)
            from pathway_tpu.engine.stream import Update

            mod.set_update_type(Update)
            mod._json_registered = True
        except Exception:  # registration failure only disables fast paths
            mod._json_registered = False
        _module = mod
        return mod
