"""Universe solver — key-set relation registry (reference
``internals/universe_solver.py:1-178``: can two tables share keys?).

Universes here are structural layout tokens; the solver tracks the
DECLARED relations between them (``promise_is_subset_of`` etc.) and
answers reflexive-transitive subset queries.  ``with_universe_of``
consults it: rebinding a table whose universe has NO known relation to
the target logs a warning (the reference raises unless provable).

Storage is weak: tokens are plain sentinels owned by their tables, so
registered relations vanish with the tables — a long-lived process that
keeps building graphs does not accumulate entries.
"""

from __future__ import annotations

import weakref
from typing import Any

__all__ = ["UniverseSolver", "UniverseToken", "solver"]


class UniverseToken:
    """Weakref-able universe sentinel (plain ``object()`` instances do not
    support weak references)."""

    __slots__ = ("__weakref__",)


class UniverseSolver:
    def __init__(self) -> None:
        #: token -> set of tokens it is declared a subset of (direct edges)
        self._subset_of: "weakref.WeakKeyDictionary[Any, weakref.WeakSet]" = (
            weakref.WeakKeyDictionary()
        )
        #: equivalence: token -> representative
        self._equal: "weakref.WeakKeyDictionary[Any, Any]" = (
            weakref.WeakKeyDictionary()
        )

    # -- registration ---------------------------------------------------
    def register_as_subset(self, sub: Any, sup: Any) -> None:
        rep_sub = self._rep(sub)
        edges = self._subset_of.get(rep_sub)
        if edges is None:
            edges = weakref.WeakSet()
            self._subset_of[rep_sub] = edges
        edges.add(self._rep(sup))

    def register_as_equal(self, a: Any, b: Any) -> None:
        ra, rb = self._rep(a), self._rep(b)
        if ra is not rb:
            self._equal[rb] = ra
            edges = self._subset_of.pop(rb, None)
            if edges:
                target = self._subset_of.get(ra)
                if target is None:
                    target = weakref.WeakSet()
                    self._subset_of[ra] = target
                for e in edges:
                    target.add(e)

    # -- queries --------------------------------------------------------
    def _rep(self, token: Any) -> Any:
        seen = []
        while token in self._equal:
            seen.append(token)
            token = self._equal[token]
        for t in seen:  # path compression
            self._equal[t] = token
        return token

    def query_is_subset_of(self, sub: Any, sup: Any) -> bool:
        """Reflexive-transitive closure over declared subset edges."""
        sub, sup = self._rep(sub), self._rep(sup)
        if sub is sup:
            return True
        frontier = [sub]
        visited = {id(sub)}
        while frontier:
            t = frontier.pop()
            for nxt in tuple(self._subset_of.get(t, ())):
                nxt = self._rep(nxt)
                if nxt is sup:
                    return True
                if id(nxt) not in visited:
                    visited.add(id(nxt))
                    frontier.append(nxt)
        return False

    def query_are_equal(self, a: Any, b: Any) -> bool:
        return self._rep(a) is self._rep(b)

    def query_related(self, a: Any, b: Any) -> bool:
        """Any declared relation path between the two universes."""
        return (
            self.query_are_equal(a, b)
            or self.query_is_subset_of(a, b)
            or self.query_is_subset_of(b, a)
        )

    def clear(self) -> None:
        self._subset_of.clear()
        self._equal.clear()


#: process-global solver; weak storage means entries die with their tables
solver = UniverseSolver()
