"""Static type interpretation of expressions.

Capability parity with the reference type interpreter
(``python/pathway/internals/type_interpreter.py``, 686 LoC, and the typed
expression enums in ``src/engine/expression.rs:26-340``): every binary /
unary operator application is checked against an operator table at graph
**build** time, so ``t.name + t.age`` on STR/INT columns raises immediately
with the offending types named, instead of producing ERROR values at run
time.  Columns typed ``ANY`` (or dynamic containers) bypass the check —
exactly the reference's escape hatch for untyped data.

The runtime half (``PATHWAY_RUNTIME_TYPECHECKING``) lives in
:func:`make_runtime_checker`: a per-schema validator used by ``select`` to
assert produced values actually inhabit the declared dtypes.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import api
from pathway_tpu.internals import dtype as dt


class TypeInterpreterError(TypeError):
    """Incompatible operand types detected at graph-build time."""


class RuntimeTypeError(api.FatalEngineError, TypeError):
    """Declared-dtype violation under PATHWAY_RUNTIME_TYPECHECKING —
    unrecoverable: the scheduler re-raises it instead of containing."""


#: scalar dtypes that participate in strict checking; anything else
#: (ANY/JSON/containers/callables) falls back to dynamic typing
_STRICT = (
    dt.BOOL,
    dt.INT,
    dt.FLOAT,
    dt.STR,
    dt.BYTES,
    dt.DATE_TIME_NAIVE,
    dt.DATE_TIME_UTC,
    dt.DURATION,
    dt.POINTER,
)

_NUMERIC = (dt.BOOL, dt.INT, dt.FLOAT)
_ARITH = ("+", "-", "*", "//", "%", "**")
_CMP = ("==", "!=", "<", "<=", ">", ">=")
_BITWISE = ("&", "|", "^")

#: (op, left, right) -> result for the non-numeric special forms
#: (mirrors the reference's DateTimeNaive/Utc/Duration expression enums)
_TABLE: dict[tuple[str, dt.DType, dt.DType], dt.DType] = {}


def _fill_table() -> None:
    for dtn in (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC):
        _TABLE[("-", dtn, dtn)] = dt.DURATION
        _TABLE[("+", dtn, dt.DURATION)] = dtn
        _TABLE[("+", dt.DURATION, dtn)] = dtn
        _TABLE[("-", dtn, dt.DURATION)] = dtn
    _TABLE[("+", dt.DURATION, dt.DURATION)] = dt.DURATION
    _TABLE[("-", dt.DURATION, dt.DURATION)] = dt.DURATION
    _TABLE[("*", dt.DURATION, dt.INT)] = dt.DURATION
    _TABLE[("*", dt.INT, dt.DURATION)] = dt.DURATION
    _TABLE[("*", dt.DURATION, dt.FLOAT)] = dt.DURATION
    _TABLE[("*", dt.FLOAT, dt.DURATION)] = dt.DURATION
    _TABLE[("/", dt.DURATION, dt.INT)] = dt.DURATION
    _TABLE[("/", dt.DURATION, dt.FLOAT)] = dt.DURATION
    _TABLE[("/", dt.DURATION, dt.DURATION)] = dt.FLOAT
    _TABLE[("//", dt.DURATION, dt.DURATION)] = dt.INT
    _TABLE[("%", dt.DURATION, dt.DURATION)] = dt.DURATION
    _TABLE[("+", dt.STR, dt.STR)] = dt.STR
    _TABLE[("*", dt.STR, dt.INT)] = dt.STR
    _TABLE[("*", dt.INT, dt.STR)] = dt.STR
    _TABLE[("+", dt.BYTES, dt.BYTES)] = dt.BYTES


_fill_table()


def _is_strict(d: dt.DType) -> bool:
    return any(d == s for s in _STRICT)


def binary_result_dtype(op: str, left: dt.DType, right: dt.DType) -> dt.DType:
    """Result dtype of ``left <op> right``; raises
    :class:`TypeInterpreterError` when both operands are strict scalars and
    no typing rule accepts the pair (reference
    ``type_interpreter.py`` eval_binary_op)."""
    optional = left.is_optional() or right.is_optional()
    l, r = left.strip_optional(), right.strip_optional()

    def wrap(res: dt.DType) -> dt.DType:
        return dt.Optional(res) if optional and res != dt.ANY else res

    # dynamic escape hatch: ANY / JSON / containers never raise
    if not (_is_strict(l) and _is_strict(r)):
        if op in _CMP:
            return wrap(dt.BOOL)
        if op == "/":
            return wrap(dt.FLOAT) if l in _NUMERIC and r in _NUMERIC else dt.ANY
        return dt.lub(l, r) if op not in _BITWISE else dt.ANY

    # equality is total across strict scalars (keys, mixed columns)
    if op in ("==", "!="):
        return wrap(dt.BOOL)
    if op in _CMP:
        if (l in _NUMERIC and r in _NUMERIC) or l == r:
            return wrap(dt.BOOL)
        raise TypeInterpreterError(
            f"Cannot compare {l!r} with {r!r} using {op!r}"
        )
    special = _TABLE.get((op, l, r))
    if special is not None:
        return wrap(special)
    if op in _BITWISE:
        if l == dt.BOOL and r == dt.BOOL:
            return wrap(dt.BOOL)
        if l in (dt.BOOL, dt.INT) and r in (dt.BOOL, dt.INT):
            return wrap(dt.INT)
        raise TypeInterpreterError(
            f"Binary operator {op!r} is not defined on {l!r} and {r!r}; "
            "boolean columns combine with & | ^"
        )
    if op == "/":
        if l in _NUMERIC and r in _NUMERIC:
            return wrap(dt.FLOAT)
        raise TypeInterpreterError(f"Cannot divide {l!r} by {r!r}")
    if op in _ARITH:
        if l in _NUMERIC and r in _NUMERIC:
            if l == dt.FLOAT or r == dt.FLOAT:
                return wrap(dt.FLOAT)
            return wrap(dt.INT)
        raise TypeInterpreterError(
            f"Binary operator {op!r} is not defined on {l!r} and {r!r} "
            "(cast one side, e.g. pw.cast(str, ...) or .str namespace)"
        )
    if op == "@":
        raise TypeInterpreterError(
            f"Matrix multiplication needs array operands, got {l!r} and {r!r}"
        )
    return dt.ANY


def unary_result_dtype(op: str, operand: dt.DType) -> dt.DType:
    optional = operand.is_optional()
    o = operand.strip_optional()

    def wrap(res: dt.DType) -> dt.DType:
        return dt.Optional(res) if optional else res

    if not _is_strict(o):
        return operand if op == "-" else dt.ANY
    if op == "-":
        if o in _NUMERIC:
            return wrap(dt.INT if o == dt.BOOL else o)
        if o == dt.DURATION:
            return wrap(dt.DURATION)
        raise TypeInterpreterError(f"Unary - is not defined on {o!r}")
    if op == "~":
        if o == dt.BOOL:
            return wrap(dt.BOOL)
        if o == dt.INT:
            return wrap(dt.INT)
        raise TypeInterpreterError(f"Unary ~ is not defined on {o!r}")
    return dt.ANY


# ---------------------------------------------------------------------------
# runtime typechecking (PATHWAY_RUNTIME_TYPECHECKING)


def make_runtime_checker(
    names: list[str], dtypes: list[dt.DType], where: str
) -> Any:
    """A validator ``(values_tuple) -> None`` raising
    :class:`RuntimeTypeError` when a produced value does not inhabit its
    declared dtype (reference runtime typechecking mode).  ERROR/None
    propagation is always allowed."""
    checks = [
        (i, n, d)
        for i, (n, d) in enumerate(zip(names, dtypes))
        if d != dt.ANY
    ]

    def check(values: tuple) -> None:
        for i, name, d in checks:
            v = values[i]
            if v is api.ERROR or (v is None and (d.is_optional() or d == dt.NONE)):
                continue
            if not d.is_value_compatible(v):
                raise RuntimeTypeError(
                    f"{where}: column {name!r} declared {d!r} but produced "
                    f"{type(v).__name__} value {v!r}"
                )

    return check
