"""``pw.iterate`` — fixed-point iteration.

Reference: ``pw.iterate`` builds a differential-dataflow subscope with
feedback variables (``Graph::iterate`` ``src/engine/graph.rs:941-949``,
``complex_columns.rs``).  Here the body is built into a SUBGRAPH whose
input placeholders are re-fed with the body's outputs until the row sets
stabilize (or ``iteration_limit`` is hit); the solve re-runs per epoch
when the outer inputs change — same externally observable fixpoint,
batch-style inner loop instead of differential nesting.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import graph as eg
from pathway_tpu.engine.stream import Update, consolidate
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

__all__ = ["iterate", "iterate_universe"]


class IterateNode(eg.Node):
    """inputs = outer nodes (ordered as ``names``).  Emits rows tagged
    with their output-table index: values = (out_idx,) + inner_values."""

    def __init__(
        self,
        graph: eg.EngineGraph,
        outer_inputs: list[eg.Node],
        names: list[str],
        subgraph: eg.EngineGraph,
        placeholders: dict[str, eg.Node],
        captures: dict[str, eg.CaptureNode],
        out_names: list[str],
        iteration_limit: int | None,
        name: str = "iterate",
    ):
        super().__init__(graph, outer_inputs, name)
        self.names = names
        self.subgraph = subgraph
        self.placeholders = placeholders
        self.captures = captures
        self.out_names = out_names
        self.iteration_limit = iteration_limit

    def make_state(self):
        return {
            "in": [dict() for _ in self.inputs],
            "last": {n: {} for n in self.out_names},
        }

    def exchange_routes(self):
        # the fixpoint solve is self-contained: centralize on worker 0
        from pathway_tpu.engine import cluster as cl

        return cl.route_all_to_zero(self)

    def _solve(self, st) -> dict[str, dict]:
        from pathway_tpu.engine.scheduler import Scheduler

        current: dict[str, dict] = {
            n: dict(st["in"][i]) for i, n in enumerate(self.names)
        }
        limit = self.iteration_limit if self.iteration_limit is not None else 1000
        outputs: dict[str, dict] = {n: {} for n in self.out_names}
        for _ in range(max(1, limit)):
            sched = Scheduler(self.subgraph)
            inject = {
                self.placeholders[n].id: [
                    Update(k, v, 1) for k, v in current[n].items()
                ]
                for n in self.names
            }
            sched.run_epoch(0, inject)
            outputs = {
                n: dict(sched.ctx.state(self.captures[n])["rows"])
                for n in self.out_names
            }
            next_state = {
                n: outputs.get(n, current[n]) for n in self.names
            }
            if next_state == current:
                break
            current = next_state
        return outputs

    def process(self, ctx, time, inbatches):
        st = ctx.state(self)
        changed = False
        for i, batch in enumerate(inbatches):
            for u in consolidate(batch):
                changed = True
                if u.diff > 0:
                    st["in"][i][u.key] = u.values
                else:
                    st["in"][i].pop(u.key, None)
        if not changed:
            return []
        outputs = self._solve(st)
        out: list[Update] = []
        for oi, n in enumerate(self.out_names):
            new_rows = outputs.get(n, {})
            old_rows = st["last"][n]
            for k, v in old_rows.items():
                if new_rows.get(k) != v:
                    out.append(Update(k, (oi,) + v, -1))
            for k, v in new_rows.items():
                if old_rows.get(k) != v:
                    out.append(Update(k, (oi,) + v, 1))
            st["last"][n] = new_rows
        return consolidate(out)


def iterate(
    func: Callable[..., Any],
    iteration_limit: int | None = None,
    **kwargs: Table,
) -> Any:
    """Iterate ``func`` to a fixed point.  ``func`` receives tables (by
    the kwarg names) and returns a table / dict / namedtuple of tables;
    returned tables matching input names feed back into the next
    iteration (reference ``pw.iterate`` semantics)."""
    names = list(kwargs.keys())
    outer_tables = [kwargs[n] for n in names]

    sub = eg.EngineGraph()
    placeholders: dict[str, eg.Node] = {}
    subtables: dict[str, Table] = {}
    outer_graph = G.engine_graph
    G.engine_graph = sub
    try:
        for n in names:
            t = kwargs[n]
            node = eg.InputNode(sub, n_cols=len(t._column_names), name=f"iter_{n}")
            placeholders[n] = node
            subtables[n] = Table(
                node, t._column_names, t._dtypes, name=f"iterate.{n}"
            )
        result = func(**subtables)
    finally:
        G.engine_graph = outer_graph

    if isinstance(result, Table):
        # a single returned table feeds back into the FIRST input; other
        # inputs are read-only context for the body
        out_map = {names[0]: result}
        single = result
    elif isinstance(result, dict):
        out_map = dict(result)
        single = None
    elif hasattr(result, "_asdict"):
        out_map = dict(result._asdict())
        single = None
    else:
        raise TypeError("iterate body must return a Table, dict, or namedtuple")

    captures: dict[str, eg.CaptureNode] = {}
    saved = G.engine_graph
    G.engine_graph = sub
    try:
        for n, t in out_map.items():
            captures[n] = eg.CaptureNode(sub, t._node, name=f"iter_cap_{n}")
    finally:
        G.engine_graph = saved

    out_names = list(out_map.keys())
    node = IterateNode(
        G.engine_graph,
        [t._node for t in outer_tables],
        names,
        sub,
        placeholders,
        captures,
        out_names,
        iteration_limit,
    )

    results: dict[str, Table] = {}
    for oi, n in enumerate(out_names):
        t = out_map[n]
        fnode = eg.FilterNode(
            G.engine_graph, node, lambda k, v, oi=oi: v[0] == oi, name=f"iter_out_{n}"
        )
        snode = eg.RowwiseNode(
            G.engine_graph, fnode, lambda k, v: v[1:], name=f"iter_strip_{n}"
        )
        results[n] = Table(snode, t._column_names, t._dtypes, name=f"iterate.{n}")

    if single is not None:
        return results[out_names[0]]
    if hasattr(result, "_asdict"):
        return type(result)(**results)
    return results


def iterate_universe(func: Callable[..., Any], **kwargs: Table) -> Any:
    """Reference ``pw.iterate_universe`` — iterate where the universe may
    change between steps (our iterate already allows that)."""
    return iterate(func, **kwargs)
