"""The ``pw.Table`` user API.

Capability parity with reference ``python/pathway/internals/table.py`` (2675
LoC): lazily-built keyed tables with select/filter/groupby/reduce/join/
concat/update/ix/flatten/... methods.  Construction is eager *graph
building* (engine nodes are created immediately); execution happens at
``pw.run()``/``pw.debug.compute_and_print`` via the epoch scheduler.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Mapping

from pathway_tpu.internals import api
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expr_vm as _vm
from pathway_tpu.internals import keys as K
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    ColumnExpression,
    ColumnReference,
    ConstExpression,
    PointerExpression,
    ReducerExpression,
    _wrap,
    smart_name,
)
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.thisclass import ThisMetaclass, left as LEFT, right as RIGHT, this as THIS
from pathway_tpu.engine import graph as eg


def _referenced_names(exprs: Iterable[ColumnExpression]) -> list[str]:
    """Input column names an expression list reads — build-time metadata
    for the static analyzer's dead-column pass (``analysis/passes.py``)."""
    names: set[str] = set()
    for e in exprs:
        try:
            for r in e._references():
                names.add(r._name)
        except Exception:
            pass
    names.discard("id")
    return sorted(names)


class _Layout:
    """Maps column references to accessors over engine row tuples.

    Matching is two-pass: exact table identity first, then "family" — the set
    of layout-preserving ancestor nodes (filter/intersect/difference/...) that
    share both universe and column layout, so a reference to the parent table
    resolves positionally on the derived one."""

    def __init__(self) -> None:
        # entries: (table, name->pos mapping, id_accessor_pos or None)
        self.entries: list[tuple[Any, dict[str, int | None], int | None]] = []

    def add(self, table: Any, mapping: dict[str, int | None], id_pos: int | None = None) -> None:
        self.entries.append((table, mapping, id_pos))

    @staticmethod
    def _family_match(entry_table: Any, t: Any) -> bool:
        fam = getattr(entry_table, "_family", None)
        node = getattr(t, "_node", None)
        return fam is not None and node is not None and node.id in fam

    def _build(self, ref: ColumnReference, mapping: dict, id_pos: int | None) -> Callable[[tuple], Any]:
        if ref._name == "id":
            if id_pos is None:
                return lambda kv: kv[0]
            pos = id_pos
            return lambda kv, pos=pos: kv[1][pos]
        if ref._name in mapping:
            pos = mapping[ref._name]
            if pos is None:
                raise ValueError(
                    f"Column {ref._name!r} is ambiguous here; qualify it "
                    "with pw.left / pw.right"
                )
            return lambda kv, pos=pos: kv[1][pos]
        raise KeyError(
            f"Table has no column {ref._name!r}; available: {list(mapping)}"
        )

    def resolver(self, ref: ColumnReference) -> Callable[[tuple], Any]:
        t = ref._table
        for table, mapping, id_pos in self.entries:
            if table is t:
                return self._build(ref, mapping, id_pos)
        for table, mapping, id_pos in self.entries:
            if self._family_match(table, t):
                return self._build(ref, mapping, id_pos)
        raise ValueError(
            f"Expression references table {getattr(t, '_name', t)!r} that is not part "
            "of this operation (universes must match)"
        )

    def resolve_pos(self, ref: ColumnReference) -> int | None:
        """Positional resolution for native fast paths: the value-tuple
        index, ``-1`` for the row key, or None when the reference isn't a
        plain positional column of this layout."""
        t = ref._table
        entry = None
        for table, mapping, id_pos in self.entries:
            if table is t:
                entry = (mapping, id_pos)
                break
        if entry is None:
            for table, mapping, id_pos in self.entries:
                if self._family_match(table, t):
                    entry = (mapping, id_pos)
                    break
        if entry is None:
            return None
        mapping, id_pos = entry
        if ref._name == "id":
            return -1 if id_pos is None else id_pos
        return mapping.get(ref._name)


def compile_exprs(
    exprs: list[ColumnExpression], layout: _Layout
) -> Callable[[Any, tuple], tuple]:
    compiled = [e._compile(layout.resolver) for e in exprs]

    if len(compiled) == 1:
        c0 = compiled[0]

        def row_fn(key: Any, values: tuple) -> tuple:
            return (c0((key, values)),)

    elif len(compiled) == 2:
        ca, cb = compiled

        def row_fn(key: Any, values: tuple) -> tuple:
            kv = (key, values)
            return (ca(kv), cb(kv))

    else:

        def row_fn(key: Any, values: tuple) -> tuple:
            kv = (key, values)
            return tuple(c(kv) for c in compiled)

    return row_fn


class TableSlice:
    """An ordered {output name -> column reference} view of a table
    (reference ``internals/table_slice.py``).  Iterating yields the
    references; passing the slice to ``select``/``with_columns`` keeps
    its renames."""

    def __init__(self, table: Any, mapping: "dict[str, ColumnReference]"):
        self._table = table
        self._mapping = dict(mapping)

    def __iter__(self):
        return iter(self._mapping.values())

    def keys(self) -> list[str]:
        return list(self._mapping)

    def __repr__(self) -> str:
        return f"TableSlice({list(self._mapping)})"

    def _name_of(self, col: Any) -> str:
        if isinstance(col, ColumnReference):
            if col._table is not self._table:
                raise ValueError(
                    f"column reference {col!r} belongs to a different table "
                    "than this slice"
                )
            name = col._name
        else:
            name = col
        if name not in self._mapping:
            raise KeyError(
                f"slice has no column {name!r}; available: {list(self._mapping)}"
            )
        return name

    def __getitem__(self, arg: Any):
        if isinstance(arg, (list, tuple)):
            return TableSlice(
                self._table,
                {self._name_of(c): self._mapping[self._name_of(c)] for c in arg},
            )
        return self._mapping[self._name_of(arg)]

    def without(self, *cols: Any) -> "TableSlice":
        drop = {self._name_of(c) for c in cols}
        return TableSlice(
            self._table,
            {n: r for n, r in self._mapping.items() if n not in drop},
        )

    def rename(self, mapping: "dict[Any, str]") -> "TableSlice":
        renames = {self._name_of(k): v for k, v in mapping.items()}
        out: dict[str, ColumnReference] = {}
        for n, r in self._mapping.items():
            target = renames.get(n, n)
            if target in out or (
                target != n and target in self._mapping and target not in renames
            ):
                # a collision would silently drop a column's data
                raise ValueError(
                    f"rename target {target!r} collides with an existing "
                    "column; rename or drop the other column first"
                )
            out[target] = r
        return TableSlice(self._table, out)

    def with_prefix(self, prefix: str) -> "TableSlice":
        return TableSlice(
            self._table, {prefix + n: r for n, r in self._mapping.items()}
        )

    def with_suffix(self, suffix: str) -> "TableSlice":
        return TableSlice(
            self._table, {n + suffix: r for n, r in self._mapping.items()}
        )


def _contains_async(expr: ColumnExpression) -> bool:
    stack = [expr]
    while stack:
        e = stack.pop()
        if isinstance(e, AsyncApplyExpression):
            return True
        stack.extend(e._children())
    return False


class Table:
    def __init__(
        self,
        node: eg.Node,
        column_names: list[str],
        dtypes: Mapping[str, dt.DType] | None = None,
        name: str = "table",
        layout_token: Any = None,
        id_dtype: dt.DType = dt.POINTER,
        family: frozenset | None = None,
    ):
        self._node = node
        self._column_names = list(column_names)
        self._dtypes = dict(dtypes) if dtypes else {c: dt.ANY for c in column_names}
        for c in column_names:
            self._dtypes.setdefault(c, dt.ANY)
        self._name = name
        from pathway_tpu.internals.universe_solver import UniverseToken

        self._layout_token = (
            layout_token if layout_token is not None else UniverseToken()
        )
        self._id_dtype = id_dtype
        #: node ids sharing this table's (universe, column layout) — a
        #: reference to any of them resolves positionally on this table
        self._family: frozenset = (family or frozenset()) | {node.id}

    # -- introspection ------------------------------------------------------
    @property
    def schema(self) -> sch.SchemaMetaclass:
        return sch.schema_from_columns(
            {
                c: sch.ColumnDefinition(dtype=self._dtypes[c], name=c)
                for c in self._column_names
            },
            name=f"Schema_{self._name}",
        )

    def column_names(self) -> list[str]:
        return list(self._column_names)

    def keys(self) -> list[str]:
        return self.column_names()

    def typehints(self) -> dict[str, Any]:
        return {c: self._dtypes[c] for c in self._column_names}

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(self, "id")

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._column_names:
            raise AttributeError(
                f"Table has no column {name!r}; available: {self._column_names}"
            )
        return ColumnReference(self, name)

    def __getitem__(self, arg: Any) -> Any:
        if isinstance(arg, str):
            if arg == "id":
                return self.id
            if arg not in self._column_names:
                raise KeyError(arg)
            return ColumnReference(self, arg)
        if isinstance(arg, ColumnReference):
            return self[arg._name]
        if isinstance(arg, (list, tuple)):
            return self.select(*[self[c] for c in arg])
        raise TypeError(f"Cannot index Table with {arg!r}")

    @property
    def slice(self) -> "TableSlice":
        """Lazy column-set helper (reference ``TableSlice``,
        ``internals/table_slice.py``): ``t.select(t.slice.without("a"))``,
        ``t.slice.with_prefix("l_")`` etc."""
        return TableSlice(
            self, {c: ColumnReference(self, c) for c in self._column_names}
        )

    def __iter__(self) -> Iterable[ColumnReference]:
        return iter([self[c] for c in self._column_names])

    def __repr__(self) -> str:
        cols = ", ".join(f"{c}: {self._dtypes[c]!r}" for c in self._column_names)
        return f"<pw.Table {self._name}({cols})>"

    def _layout(self) -> _Layout:
        layout = _Layout()
        layout.add(self, {c: i for i, c in enumerate(self._column_names)})
        return layout

    def _prepare(self, exprs: list[ColumnExpression]) -> tuple[_Layout, eg.Node]:
        """Layout + engine node for rowwise evaluation of ``exprs``.

        References to other same-universe tables (same layout token but
        layout-incompatible, e.g. an ``ix`` result) are satisfied by zipping
        those tables' nodes by key."""
        zip_tables: list[Table] = []
        for e in exprs:
            for r in e._references():
                t = r._table
                if t is self or _Layout._family_match(self, t):
                    continue
                if any(t is z or _Layout._family_match(z, t) for z in zip_tables):
                    continue
                if getattr(t, "_layout_token", None) is self._layout_token:
                    zip_tables.append(t)
                # else: leave it to the resolver to raise a clear error
        if not zip_tables:
            return self._layout(), self._node
        widths = [len(self._column_names)] + [len(t._column_names) for t in zip_tables]
        node = eg.ZipNode(
            G.engine_graph,
            [self._node] + [t._node for t in zip_tables],
            widths,
        )
        layout = _Layout()
        layout.add(self, {c: i for i, c in enumerate(self._column_names)})
        offset = len(self._column_names)
        for t in zip_tables:
            layout.add(t, {c: offset + i for i, c in enumerate(t._column_names)})
            offset += len(t._column_names)
        return layout, node

    def _subst(self, expr: Any) -> ColumnExpression:
        return _wrap(expr)._substitute({THIS: self})

    # -- row transforms -----------------------------------------------------
    def _gather_select(
        self, args: tuple, kwargs: dict
    ) -> tuple[list[str], list[ColumnExpression]]:
        names: list[str] = []
        exprs: list[ColumnExpression] = []
        for a in args:
            if isinstance(a, ThisMetaclass):
                # pw.this splat: all columns
                for c in self._column_names:
                    names.append(c)
                    exprs.append(ColumnReference(self, c))
                continue
            if isinstance(a, TableSlice):
                # t.select(*...) also works, but passing the slice itself
                # keeps its renames: select(t.slice.with_prefix("l_"))
                for n, ref in a._mapping.items():
                    names.append(n)
                    exprs.append(ref)
                continue
            e = self._subst(a)
            n = smart_name(e)
            if n is None:
                raise ValueError(
                    "Positional select() arguments must be column references; "
                    "use keyword arguments for computed columns"
                )
            names.append(n)
            exprs.append(e)
        for n, a in kwargs.items():
            names.append(n)
            exprs.append(self._subst(a))
        return names, exprs

    def select(self, *args: Any, **kwargs: Any) -> "Table":
        """Compute a new column set per row (reference ``Table.select``).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... a | b
        ... 3 | foo
        ... 5 | bar
        ... ''')
        >>> out = t.select(t.a, double=t.a * 2, upper=t.b.str.upper())
        >>> pw.debug.compute_and_print(out, include_id=False)
        a | double | upper
        3 | 6      | 'FOO'
        5 | 10     | 'BAR'
        """
        names, exprs = self._gather_select(args, kwargs)
        seen: dict[str, int] = {}
        for i, n in enumerate(names):
            seen[n] = i  # later wins
        order = sorted(seen.values())
        names = [names[i] for i in order]
        exprs = [exprs[i] for i in order]
        layout, in_node = self._prepare(exprs)
        async_idx = [i for i, e in enumerate(exprs) if _contains_async(e)]
        dtypes = {n: e._dtype for n, e in zip(names, exprs)}
        if async_idx:
            return self._select_async(names, exprs, layout, dtypes, in_node)
        row_fn = compile_exprs(exprs, layout)
        node = eg.RowwiseNode(
            G.engine_graph, in_node, row_fn, name="select",
            typecheck_info=(names, [dtypes[n] for n in names]),
            programs=_vm.lower_programs(exprs, layout),
        )
        node.meta["select"] = {
            "kind": "select",
            "names": list(names),
            "exprs": list(exprs),
            "layout": layout,
            "dtypes": [dtypes[n] for n in names],
        }
        node.meta["used_cols"] = _referenced_names(exprs)
        # select keeps row keys -> same universe token; new layout family
        return Table(
            node, names, dtypes, name=f"{self._name}.select",
            layout_token=self._layout_token,
        )

    def _select_async(
        self,
        names: list[str],
        exprs: list[ColumnExpression],
        layout: _Layout,
        dtypes: dict[str, dt.DType],
        in_node: eg.Node | None = None,
    ) -> "Table":
        """Async apply columns: batch all rows of the epoch through the async
        executor (reference ``map_named_async`` micro-batching)."""
        from pathway_tpu.internals.udfs import run_async_batch

        from pathway_tpu.internals.expression import BatchApplyExpression

        async_exprs = [(i, e) for i, e in enumerate(exprs) if _contains_async(e)]
        sync_exprs = [(i, e) for i, e in enumerate(exprs) if not _contains_async(e)]
        sync_fns = [(i, e._compile(layout.resolver)) for i, e in sync_exprs]
        async_plans = []
        for i, e in async_exprs:
            assert isinstance(e, AsyncApplyExpression)
            arg_fns = [a._compile(layout.resolver) for a in e._args]
            kw_fns = {k: v._compile(layout.resolver) for k, v in e._kwargs.items()}
            async_plans.append(
                (
                    i,
                    e._fun,
                    arg_fns,
                    kw_fns,
                    isinstance(e, BatchApplyExpression),
                    e._propagate_none,
                )
            )

        if in_node is None:
            in_node = self._node
        n_in = (
            sum(in_node.widths) if isinstance(in_node, eg.ZipNode) else len(self._column_names)
        )

        def batch_fn(rows: list[tuple]) -> list[Any]:
            # rows are (original input values + hidden key at end)? we receive raw values
            kvs = [((r[-1]), r[:-1]) for r in rows]
            results: list[list[Any]] = [[None] * len(exprs) for _ in rows]
            for i, fn in sync_fns:
                for j, kv in enumerate(kvs):
                    results[j][i] = fn(kv)
            for i, fun, arg_fns, kw_fns, is_batch, prop_none in async_plans:
                if is_batch:
                    # one call with per-argument LISTS (jitted TPU batch).
                    # Rows with ERROR (or None under propagate_none) inputs
                    # are screened out so one bad row can't poison the batch.
                    all_args = [[f(kv) for f in arg_fns] for kv in kvs]
                    all_kw = [{k: f(kv) for k, f in kw_fns.items()} for kv in kvs]

                    def _bad(vals: Iterable) -> Any:
                        for v in vals:
                            if v is api.ERROR:
                                return api.ERROR
                            if v is None and prop_none:
                                return None
                        return False

                    sentinel = [
                        _bad(list(a) + list(k.values()))
                        for a, k in zip(all_args, all_kw)
                    ]
                    clean = [j for j, s in enumerate(sentinel) if s is False]
                    outs_clean: list[Any] = []
                    if clean:
                        arg_lists = [
                            [all_args[j][ai] for j in clean]
                            for ai in range(len(arg_fns))
                        ]
                        kw_lists = {
                            k: [all_kw[j][k] for j in clean] for k in kw_fns
                        }
                        outs_clean = list(fun(*arg_lists, **kw_lists))
                        if len(outs_clean) != len(clean):
                            raise ValueError(
                                f"batch UDF returned {len(outs_clean)} results "
                                f"for {len(clean)} rows"
                            )
                    outs = list(sentinel)
                    for j, o in zip(clean, outs_clean):
                        outs[j] = o
                else:
                    calls = []
                    for kv in kvs:
                        calls.append(
                            (
                                [f(kv) for f in arg_fns],
                                {k: f(kv) for k, f in kw_fns.items()},
                            )
                        )
                    outs = run_async_batch(fun, calls)
                for j, o in enumerate(outs):
                    results[j][i] = o
            return [tuple(r) for r in results]

        # append key as a hidden column so batch_fn can resolve `id` refs
        key_node = eg.RowwiseNode(
            G.engine_graph,
            in_node,
            lambda key, values: values + (key,),
            name="attach_key",
        )
        anode = eg.AsyncMapNode(
            G.engine_graph,
            key_node,
            batch_fn,
            name="async_select",
            # device-batched UDFs need the whole epoch batch on the TPU
            # host (worker 0); pure async-IO UDFs shard across workers
            distributed=not any(plan[4] for plan in async_plans),
        )
        # AsyncMapNode emits values + (result,); extract the result tuple
        unpack = eg.RowwiseNode(
            G.engine_graph,
            anode,
            lambda key, values: tuple(values[n_in + 1]),
            name="unpack_async",
        )
        return Table(
            unpack, names, dtypes, name=f"{self._name}.select",
            layout_token=self._layout_token,
        )

    def _gradual_broadcast(
        self,
        threshold_table: "Table",
        lower_column: Any,
        value_column: Any,
        upper_column: Any,
    ) -> "Table":
        """Append an ``apx_value`` column broadcast from a (usually 1-row)
        threshold table's ``(lower, value, upper)`` approximation triplet;
        rows only re-emit when their held value leaves the new window
        (reference ``Table._gradual_broadcast``, ``internals/table.py:631``
        over ``src/engine/dataflow/operators/gradual_broadcast.rs``)."""
        exprs = [
            threshold_table._subst(e)
            for e in (lower_column, value_column, upper_column)
        ]
        tlayout = threshold_table._layout()
        triplet_fn = compile_exprs(exprs, tlayout)
        node = eg.GradualBroadcastNode(
            G.engine_graph, self._node, threshold_table._node, triplet_fn
        )
        cols = self._column_names + ["apx_value"]
        dtypes = dict(self._dtypes)
        dtypes["apx_value"] = dt.Optional(dt.FLOAT)
        return Table(node, cols, dtypes, name=f"{self._name}.gradual_broadcast")

    def filter(self, expr: Any) -> "Table":
        """Keep rows where ``expr`` is truthy.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... a
        ... 1
        ... 4
        ... 7
        ... ''')
        >>> pw.debug.compute_and_print(t.filter(t.a > 2), include_id=False)
        a
        4
        7
        """
        e = self._subst(expr)
        layout, in_node = self._prepare([e])
        c = e._compile(layout.resolver)
        node: eg.Node = eg.FilterNode(
            G.engine_graph, in_node, lambda key, values: c((key, values)),
            program=_vm.lower_program(e, layout),
        )
        node.meta["filter"] = {"exprs": [e], "layout": layout}
        node.meta["used_cols"] = _referenced_names([e])
        if in_node is not self._node:
            # predicate needed zipped columns: project back to our layout
            n = len(self._column_names)
            node = eg.RowwiseNode(
                G.engine_graph, node, lambda key, values: values[:n], name="project",
                programs=_vm.project_program(list(range(n))),
            )
        return Table(
            node,
            self._column_names,
            self._dtypes,
            name=f"{self._name}.filter",
            layout_token=self._layout_token,
            family=self._family,
        )

    def with_columns(self, *args: Any, **kwargs: Any) -> "Table":
        """All existing columns plus the given new/overridden ones.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... a
        ... 1
        ... 2
        ... ''')
        >>> pw.debug.compute_and_print(t.with_columns(b=t.a + 10), include_id=False)
        a | b
        1 | 11
        2 | 12
        """
        names, exprs = self._gather_select(args, kwargs)
        all_names = list(self._column_names)
        all_exprs: list[ColumnExpression] = [
            ColumnReference(self, c) for c in self._column_names
        ]
        for n, e in zip(names, exprs):
            if n in all_names:
                all_exprs[all_names.index(n)] = e
            else:
                all_names.append(n)
                all_exprs.append(e)
        layout, in_node = self._prepare(all_exprs)
        dtypes = {n: e._dtype for n, e in zip(all_names, all_exprs)}
        if any(_contains_async(e) for e in all_exprs):
            return self._select_async(all_names, all_exprs, layout, dtypes, in_node)
        row_fn = compile_exprs(all_exprs, layout)
        node = eg.RowwiseNode(
            G.engine_graph, in_node, row_fn, name="with_columns",
            programs=_vm.lower_programs(all_exprs, layout),
        )
        node.meta["select"] = {
            "kind": "with_columns",  # pass-through columns exempt from PW-D001
            "names": list(all_names),
            "exprs": list(all_exprs),
            "layout": layout,
            "dtypes": [dtypes[n] for n in all_names],
        }
        node.meta["used_cols"] = _referenced_names(all_exprs)
        return Table(
            node, all_names, dtypes, name=f"{self._name}.with_columns",
            layout_token=self._layout_token,
        )

    def without(self, *columns: Any) -> "Table":
        drop = {c._name if isinstance(c, ColumnReference) else c for c in columns}
        keep = [c for c in self._column_names if c not in drop]
        return self.select(*[self[c] for c in keep])

    def rename(self, names_mapping: Mapping[Any, str] | None = None, **kwargs: str) -> "Table":
        mapping: dict[str, str] = {}
        if names_mapping:
            for k, v in names_mapping.items():
                mapping[k._name if isinstance(k, ColumnReference) else k] = v
        # kwargs: new_name=old_ref style (reference rename_columns(new=old))
        sel: dict[str, Any] = {}
        for c in self._column_names:
            if c in mapping:
                sel[mapping[c]] = self[c]
            else:
                sel[c] = self[c]
        for new, old in kwargs.items():
            old_name = old._name if isinstance(old, ColumnReference) else old
            sel.pop(old_name, None)
            sel[new] = self[old_name]
        return self.select(**sel)

    rename_columns = rename

    def rename_by_dict(self, names_mapping: Mapping[Any, str]) -> "Table":
        return self.rename(names_mapping)

    def with_suffix(self, suffix: str) -> "Table":
        return self.select(**{c + suffix: self[c] for c in self._column_names})

    def with_prefix(self, prefix: str) -> "Table":
        return self.select(**{prefix + c: self[c] for c in self._column_names})

    def cast_to_types(self, **kwargs: Any) -> "Table":
        from pathway_tpu.internals.expression import cast

        sel = {c: self[c] for c in self._column_names}
        for n, t in kwargs.items():
            sel[n] = cast(t, self[n])
        return self.select(**sel)

    def update_types(self, **kwargs: Any) -> "Table":
        out = self.copy()
        for n, t in kwargs.items():
            out._dtypes[n] = dt.wrap(t)
        return out

    def copy(self) -> "Table":
        return Table(
            self._node,
            self._column_names,
            self._dtypes,
            name=self._name,
            layout_token=self._layout_token,
            family=self._family,
        )

    def await_futures(self) -> "Table":
        """Reference ``Table.await_futures``: make async-UDF results
        concrete.  This engine resolves async UDFs WITHIN the epoch
        (AsyncMapNode batches the whole epoch through the event loop), so
        values are already concrete — only the Future dtypes unwrap."""
        out = self.copy()
        out._dtypes = {
            c: (d.wrapped if isinstance(d, dt.Future) else d)
            for c, d in self._dtypes.items()
        }
        return out

    # -- keys / pointers ----------------------------------------------------
    def pointer_from(self, *args: Any, optional: bool = False, instance: Any = None) -> ColumnExpression:
        # NOTE: `pw.this` in args stays unresolved — it refers to the table
        # the expression is *used* on, not to the pointer's target (self).
        return PointerExpression(self, *[_wrap(a) for a in args], optional=optional)

    def with_id_from(self, *args: Any, instance: Any = None) -> "Table":
        exprs = [self._subst(a) for a in args]
        layout = self._layout()
        cs = [e._compile(layout.resolver) for e in exprs]

        def key_fn(key: Any, values: tuple) -> K.Pointer:
            kv = (key, values)
            return K.ref_scalar(*[c(kv) for c in cs])

        node = eg.ReindexNode(G.engine_graph, self._node, key_fn, name="with_id_from")
        return Table(node, self._column_names, self._dtypes, name=f"{self._name}.with_id_from")

    def with_id(self, new_id: ColumnReference) -> "Table":
        e = self._subst(new_id)
        layout = self._layout()
        c = e._compile(layout.resolver)
        node = eg.ReindexNode(
            G.engine_graph, self._node, lambda key, values: c((key, values)), name="with_id"
        )
        return Table(node, self._column_names, self._dtypes, name=f"{self._name}.with_id")

    # -- set operations -----------------------------------------------------
    def concat(self, *others: "Table") -> "Table":
        tables = [self, *others]
        for t in tables[1:]:
            if t._column_names != self._column_names:
                raise ValueError(
                    f"concat: column mismatch {t._column_names} vs {self._column_names}"
                )
        node = eg.ConcatNode(G.engine_graph, [t._node for t in tables])
        node.meta["concat"] = {
            "columns": {
                c: [t._dtypes[c] for t in tables] for c in self._column_names
            }
        }
        dtypes = {
            c: dt.lub_many(*[t._dtypes[c] for t in tables]) for c in self._column_names
        }
        return Table(node, self._column_names, dtypes, name="concat")

    def concat_reindex(self, *others: "Table") -> "Table":
        """Union of same-schema tables under fresh row keys.

        Example:

        >>> import pathway_tpu as pw
        >>> a = pw.debug.table_from_markdown('''
        ... x
        ... 1
        ... ''')
        >>> b = pw.debug.table_from_markdown('''
        ... x
        ... 2
        ... ''')
        >>> pw.debug.compute_and_print(a.concat_reindex(b), include_id=False)
        x
        1
        2
        """
        tables = [self, *others]
        reindexed = []
        for i, t in enumerate(tables):
            node = eg.ReindexNode(
                G.engine_graph,
                t._node,
                lambda key, values, i=i: K.derive(key, "concat", i),
                name="concat_reindex",
            )
            reindexed.append(
                Table(node, t._column_names, t._dtypes, name=f"reindex{i}")
            )
        return reindexed[0].concat(*reindexed[1:])

    def update_rows(self, other: "Table") -> "Table":
        """Per key, rows of ``other`` override rows of ``self``.

        Example:

        >>> import pathway_tpu as pw
        >>> a = pw.debug.table_from_markdown('''
        ... id | v
        ... 1  | x
        ... 2  | y
        ... ''')
        >>> b = pw.debug.table_from_markdown('''
        ... id | v
        ... 2  | z
        ... ''')
        >>> pw.debug.compute_and_print(a.update_rows(b), include_id=False)
        v
        'x'
        'z'
        """
        if other._column_names != self._column_names:
            other = other.select(**{c: other[c] for c in self._column_names})
        node = eg.UpdateRowsNode(G.engine_graph, self._node, other._node)
        dtypes = {
            c: dt.lub(self._dtypes[c], other._dtypes[c]) for c in self._column_names
        }
        return Table(node, self._column_names, dtypes, name="update_rows")

    def update_cells(self, other: "Table") -> "Table":
        for c in other._column_names:
            if c not in self._column_names:
                raise ValueError(f"update_cells: unknown column {c!r}")
        col_map: list[tuple[int, int]] = []
        for i, c in enumerate(self._column_names):
            if c in other._column_names:
                col_map.append((1, other._column_names.index(c)))
            else:
                col_map.append((0, i))
        node = eg.UpdateCellsNode(G.engine_graph, self._node, other._node, col_map)
        dtypes = dict(self._dtypes)
        for c in other._column_names:
            dtypes[c] = dt.lub(dtypes[c], other._dtypes[c])
        return Table(node, self._column_names, dtypes, name="update_cells")

    def __lshift__(self, other: "Table") -> "Table":
        return self.update_cells(other)

    def intersect(self, *others: "Table") -> "Table":
        """Keep rows whose keys appear in every other table.

        Example:

        >>> import pathway_tpu as pw
        >>> a = pw.debug.table_from_markdown('''
        ... id | v
        ... 1  | x
        ... 2  | y
        ... ''')
        >>> b = pw.debug.table_from_markdown('''
        ... id | w
        ... 2  | q
        ... ''')
        >>> pw.debug.compute_and_print(a.intersect(b), include_id=False)
        v
        'y'
        """
        node = eg.IntersectNode(
            G.engine_graph, self._node, [t._node for t in others]
        )
        return Table(
            node,
            self._column_names,
            self._dtypes,
            name="intersect",
            layout_token=self._layout_token,
            family=self._family,
        )

    def difference(self, other: "Table") -> "Table":
        """Keep rows whose keys do NOT appear in ``other``.

        Example:

        >>> import pathway_tpu as pw
        >>> a = pw.debug.table_from_markdown('''
        ... id | v
        ... 1  | x
        ... 2  | y
        ... ''')
        >>> b = pw.debug.table_from_markdown('''
        ... id | w
        ... 2  | q
        ... ''')
        >>> pw.debug.compute_and_print(a.difference(b), include_id=False)
        v
        'x'
        """
        node = eg.SubtractNode(G.engine_graph, self._node, other._node)
        return Table(
            node,
            self._column_names,
            self._dtypes,
            name="difference",
            layout_token=self._layout_token,
            family=self._family,
        )

    def restrict(self, other: "Table") -> "Table":
        node = eg.IntersectNode(G.engine_graph, self._node, [other._node])
        return Table(
            node,
            self._column_names,
            self._dtypes,
            name="restrict",
            layout_token=self._layout_token,
            family=self._family,
        )

    def with_universe_of(self, other: "Table") -> "Table":
        from pathway_tpu.internals.universe_solver import solver

        # reference semantics: with_universe_of REQUIRES a provable key-set
        # relation.  Rebinding with NO declared relation is a correctness
        # smell (zips may silently drop/misalign rows) — warn, then record
        # the equality claim so later rebinding of the same pair is known.
        if (
            self._layout_token is not other._layout_token
            and not solver.query_related(self._layout_token, other._layout_token)
        ):
            from pathway_tpu.internals.parse_graph import logger

            logger.debug(
                "with_universe_of: no declared key-set relation between "
                "%r and %r (use pw.universes.promise_* to declare one)",
                self._name,
                other._name,
            )
        solver.register_as_equal(other._layout_token, self._layout_token)
        out = self.copy()
        out._layout_token = other._layout_token
        return out

    # -- flatten ------------------------------------------------------------
    def flatten(self, to_flatten: ColumnReference, **kwargs: Any) -> "Table":
        """Explode one sequence column into one row per element.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_rows(
        ...     pw.schema_from_types(xs=tuple), [((1, 2),), ((3,),)]
        ... )
        >>> pw.debug.compute_and_print(t.flatten(t.xs), include_id=False)
        xs
        1
        2
        3
        """
        e = self._subst(to_flatten)
        assert isinstance(e, ColumnReference)
        idx = self._column_names.index(e._name)
        node = eg.FlattenNode(G.engine_graph, self._node, idx)
        dtypes = dict(self._dtypes)
        inner = dtypes[e._name].strip_optional()
        if isinstance(inner, dt.List):
            dtypes[e._name] = inner.element_type
        elif inner == dt.STR:
            dtypes[e._name] = dt.STR
        else:
            dtypes[e._name] = dt.ANY
        return Table(node, self._column_names, dtypes, name=f"{self._name}.flatten")

    # -- groupby / reduce ---------------------------------------------------
    def groupby(self, *args: Any, id: Any = None, instance: Any = None, **kwargs: Any) -> "GroupedTable":
        """Group rows by expressions; follow with ``.reduce(...)``.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... word  | n
        ... apple | 2
        ... pear  | 1
        ... apple | 3
        ... ''')
        >>> res = t.groupby(t.word).reduce(t.word, total=pw.reducers.sum(t.n))
        >>> pw.debug.compute_and_print(res, include_id=False)
        word    | total
        'apple' | 5
        'pear'  | 1
        """
        from pathway_tpu.internals.groupbys import GroupedTable

        grouping = [self._subst(a) for a in args]
        if instance is not None:
            grouping.append(self._subst(instance))
        return GroupedTable(self, grouping, set_id=id is not None)

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        from pathway_tpu.internals.groupbys import GroupedTable

        return GroupedTable(self, []).reduce(*args, **kwargs)

    def deduplicate(
        self,
        *,
        value: Any,
        instance: Any = None,
        acceptor: Callable[[Any, Any], bool],
        name: str | None = None,
    ) -> "Table":
        """Stateful deduplicate (reference ``stdlib/stateful/deduplicate.py:9``)."""
        value_e = self._subst(value)
        layout = self._layout()
        vc = value_e._compile(layout.resolver)
        if instance is not None:
            ic = self._subst(instance)._compile(layout.resolver)
        else:
            ic = lambda kv: ()
        val_idx: dict[str, int] = {c: i for i, c in enumerate(self._column_names)}

        def acceptor_rows(new_vals: tuple, old_vals: tuple | None) -> bool:
            new_v = vc((None, new_vals))
            if old_vals is None:
                return True
            old_v = vc((None, old_vals))
            return acceptor(new_v, old_v)

        node = eg.DeduplicateNode(
            G.engine_graph,
            self._node,
            lambda key, values: ic((key, values)),
            acceptor_rows,
        )
        dedup_refs = [value_e]
        if instance is not None:
            dedup_refs.append(self._subst(instance))
        node.meta["used_cols"] = _referenced_names(dedup_refs)
        # the acceptor compares each row against the PREVIOUS accepted one,
        # so the result depends on per-instance arrival order (PW-X001)
        node.meta["dedup"] = {"order_sensitive": True}
        return Table(node, self._column_names, self._dtypes, name="deduplicate")

    # -- joins ---------------------------------------------------------------
    def join(self, other: "Table", *on: Any, id: Any = None, how: Any = None, **kwargs: Any) -> Any:
        """Equi-join on ``left.col == right.col`` conditions.

        Example:

        >>> import pathway_tpu as pw
        >>> left = pw.debug.table_from_markdown('''
        ... k | v
        ... 1 | a
        ... 2 | b
        ... ''')
        >>> right = pw.debug.table_from_markdown('''
        ... k | w
        ... 1 | x
        ... 2 | y
        ... ''')
        >>> out = left.join(right, left.k == right.k).select(left.v, right.w)
        >>> pw.debug.compute_and_print(out, include_id=False)
        v   | w
        'a' | 'x'
        'b' | 'y'
        """
        from pathway_tpu.internals.joins import JoinKind, JoinResult

        kind = how if how is not None else JoinKind.INNER
        return JoinResult(self, other, list(on), kind, assign_id=id)

    def join_inner(self, other: "Table", *on: Any, **kw: Any) -> Any:
        from pathway_tpu.internals.joins import JoinKind, JoinResult

        return JoinResult(self, other, list(on), JoinKind.INNER, assign_id=kw.get("id"))

    def join_left(self, other: "Table", *on: Any, **kw: Any) -> Any:
        from pathway_tpu.internals.joins import JoinKind, JoinResult

        return JoinResult(self, other, list(on), JoinKind.LEFT, assign_id=kw.get("id"))

    def join_right(self, other: "Table", *on: Any, **kw: Any) -> Any:
        from pathway_tpu.internals.joins import JoinKind, JoinResult

        return JoinResult(self, other, list(on), JoinKind.RIGHT, assign_id=kw.get("id"))

    def join_outer(self, other: "Table", *on: Any, **kw: Any) -> Any:
        from pathway_tpu.internals.joins import JoinKind, JoinResult

        return JoinResult(self, other, list(on), JoinKind.OUTER, assign_id=kw.get("id"))

    # -- ix -------------------------------------------------------------------
    def ix(self, expression: Any, *, optional: bool = False, context: "Table | None" = None) -> "Table":
        """Row lookup: ``target.ix(requests.ptr_col)`` → table with requests'
        universe holding target's columns (reference ``Table.ix``)."""
        e = _wrap(expression)
        if context is None:
            refs = e._references()
            tables = {
                r._table
                for r in refs
                if not isinstance(r._table, ThisMetaclass)
            }
            if len(tables) != 1:
                raise ValueError("ix: cannot infer request table; pass context=")
            context = tables.pop()
        e = e._substitute({THIS: context})
        layout = context._layout()
        c = e._compile(layout.resolver)
        node = eg.IxNode(
            G.engine_graph,
            self._node,
            context._node,
            lambda key, values: c((key, values)),
            target_ncols=len(self._column_names),
            optional=optional,
        )
        dtypes = (
            {c_: dt.Optional(self._dtypes[c_]) for c_ in self._column_names}
            if optional
            else dict(self._dtypes)
        )
        return Table(
            node,
            self._column_names,
            dtypes,
            name=f"{self._name}.ix",
            layout_token=context._layout_token,
        )

    def ix_ref(self, *args: Any, optional: bool = False, context: "Table | None" = None, instance: Any = None) -> "Table":
        from pathway_tpu.internals.expression import make_tuple

        if context is None:
            refs: set[ColumnReference] = set()
            for a in args:
                if isinstance(a, ColumnExpression):
                    refs |= a._references()
            tables = {r._table for r in refs if not isinstance(r._table, ThisMetaclass)}
            if len(tables) != 1:
                raise ValueError("ix_ref: cannot infer request table; pass context=")
            context = tables.pop()
        ptr = PointerExpression(self, *[_wrap(a) for a in args], optional=optional)
        return self.ix(ptr, optional=optional, context=context)

    def having(self, *indexers: ColumnReference) -> "Table":
        """Restrict to rows whose key appears among the pointer values of each
        indexer column (reference ``Table.having``)."""
        out = self
        for ix in indexers:
            if not isinstance(ix, ColumnReference):
                raise TypeError("having() arguments must be column references")
            src: Table = ix._table
            layout = src._layout()
            c = ix._compile(layout.resolver)
            keyset_node = eg.ReindexNode(
                G.engine_graph,
                src._node,
                lambda key, values, c=c: c((key, values)),
                name="having_keys",
            )
            keyset = Table(keyset_node, src._column_names, src._dtypes, name="having_keys")
            node = eg.IntersectNode(G.engine_graph, out._node, [keyset._node])
            out = Table(
                node,
                out._column_names,
                out._dtypes,
                name=f"{self._name}.having",
                layout_token=out._layout_token,
                family=out._family,
            )
        return out

    # -- temporal (reference exposes these as Table methods too) -------------
    def windowby(self, time_expr: Any, *, window: Any, behavior: Any = None, instance: Any = None, shard: Any = None) -> Any:
        """Assign rows to temporal windows; follow with ``.reduce(...)``.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... t  | v
        ... 1  | 10
        ... 3  | 20
        ... 12 | 30
        ... ''')
        >>> w = t.windowby(t.t, window=pw.temporal.tumbling(duration=10)).reduce(
        ...     start=pw.this._pw_window_start, s=pw.reducers.sum(pw.this.v)
        ... )
        >>> pw.debug.compute_and_print(w.select(w.start, w.s), include_id=False)
        start | s
        0     | 30
        10    | 30
        """
        from pathway_tpu.stdlib.temporal import windowby as _windowby

        return _windowby(self, time_expr, window=window, behavior=behavior, instance=instance, shard=shard)

    def interval_join(self, other: "Table", self_time: Any, other_time: Any, interval: Any, *on: Any, **kw: Any) -> Any:
        from pathway_tpu.stdlib.temporal import interval_join as _ij

        return _ij(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_inner(self, other, self_time, other_time, interval, *on, **kw):
        from pathway_tpu.stdlib.temporal import interval_join_inner as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_left(self, other, self_time, other_time, interval, *on, **kw):
        from pathway_tpu.stdlib.temporal import interval_join_left as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_right(self, other, self_time, other_time, interval, *on, **kw):
        from pathway_tpu.stdlib.temporal import interval_join_right as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_outer(self, other, self_time, other_time, interval, *on, **kw):
        from pathway_tpu.stdlib.temporal import interval_join_outer as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def asof_join(self, other, self_time, other_time, *on, **kw):
        """For each left row, the closest right row at or before its time.

        Example:

        >>> import pathway_tpu as pw
        >>> trades = pw.debug.table_from_markdown('''
        ... t | px
        ... 3 | 100
        ... 7 | 105
        ... ''')
        >>> quotes = pw.debug.table_from_markdown('''
        ... t | bid
        ... 1 | 99
        ... 5 | 103
        ... ''')
        >>> j = trades.asof_join(quotes, trades.t, quotes.t)
        >>> pw.debug.compute_and_print(
        ...     j.select(trades.px, quotes.bid), include_id=False
        ... )
        px  | bid
        100 | 99
        105 | 103
        """
        from pathway_tpu.stdlib.temporal import asof_join as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_join_left(self, other, self_time, other_time, *on, **kw):
        from pathway_tpu.stdlib.temporal import asof_join_left as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_join_right(self, other, self_time, other_time, *on, **kw):
        from pathway_tpu.stdlib.temporal import asof_join_right as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_join_outer(self, other, self_time, other_time, *on, **kw):
        from pathway_tpu.stdlib.temporal import asof_join_outer as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_now_join(self, other, *on, **kw):
        from pathway_tpu.stdlib.temporal import asof_now_join as _f

        return _f(self, other, *on, **kw)

    def asof_now_join_inner(self, other, *on, **kw):
        from pathway_tpu.stdlib.temporal import asof_now_join_inner as _f

        return _f(self, other, *on, **kw)

    def asof_now_join_left(self, other, *on, **kw):
        from pathway_tpu.stdlib.temporal import asof_now_join_left as _f

        return _f(self, other, *on, **kw)

    def window_join(self, other, self_time, other_time, window, *on, **kw):
        from pathway_tpu.stdlib.temporal import window_join as _f

        return _f(self, other, self_time, other_time, window, *on, **kw)

    def window_join_inner(self, other, self_time, other_time, window, *on):
        from pathway_tpu.stdlib.temporal import window_join_inner as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_left(self, other, self_time, other_time, window, *on):
        from pathway_tpu.stdlib.temporal import window_join_left as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_right(self, other, self_time, other_time, window, *on):
        from pathway_tpu.stdlib.temporal import window_join_right as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_outer(self, other, self_time, other_time, window, *on):
        from pathway_tpu.stdlib.temporal import window_join_outer as _f

        return _f(self, other, self_time, other_time, window, *on)

    # -- sorting / misc -------------------------------------------------------
    def sort(self, key: Any = None, instance: Any = None) -> "Table":
        from pathway_tpu.stdlib.ordered import sort as _sort

        return _sort(self, key=key, instance=instance)

    def diff(self, timestamp: Any, *values: Any) -> "Table":
        from pathway_tpu.stdlib.ordered import diff as _diff

        return _diff(self, timestamp, *values)

    # -- output helpers -------------------------------------------------------
    def _capture_node(self) -> eg.CaptureNode:
        node = eg.CaptureNode(G.engine_graph, self._node)
        node.meta["sink"] = {
            "names": list(self._column_names),
            "dtypes": dict(self._dtypes),
        }
        return node

    def _subscribe(self, on_change=None, on_time_end=None, on_end=None) -> eg.OutputNode:
        node = eg.OutputNode(
            G.engine_graph, self._node, on_change, on_time_end, on_end
        )
        node.meta["sink"] = {
            "names": list(self._column_names),
            "dtypes": dict(self._dtypes),
        }
        return node


def table_from_static_rows(
    rows: Iterable[tuple[Any, tuple]],
    column_names: list[str],
    dtypes: Mapping[str, dt.DType] | None = None,
    name: str = "static",
) -> Table:
    node = eg.InputNode(
        G.engine_graph, n_cols=len(column_names), static_rows=rows, name=name
    )
    return Table(node, column_names, dtypes, name=name)
