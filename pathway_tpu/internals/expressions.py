"""Expression method namespaces: ``.dt``, ``.str``, ``.num``.

Capability parity with reference ``python/pathway/internals/expressions/``
(datetime 1613 LoC, string 931, numerical 212) in a compact functional form:
each method builds a :class:`MethodCallExpression` over the wrapped
expression.
"""

from __future__ import annotations

import datetime as _dtm
import math
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    MethodCallExpression,
    _wrap,
)


class _Namespace:
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def _m(self, name: str, fun: Any, ret: Any, *extra: Any, propagate_none: bool = True) -> ColumnExpression:
        return MethodCallExpression(
            name, fun, ret, self._expr, *[_wrap(e) for e in extra], propagate_none=propagate_none
        )


class StringNamespace(_Namespace):
    """``expr.str`` methods (reference ``expressions/string.py``).

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... s
    ... Hello
    ... World
    ... ''')
    >>> out = t.select(up=t.s.str.upper(), n=t.s.str.len())
    >>> pw.debug.compute_and_print(out, include_id=False)
    up      | n
    'HELLO' | 5
    'WORLD' | 5
    """

    def lower(self) -> ColumnExpression:
        return self._m("str.lower", lambda s: s.lower(), dt.STR)

    def upper(self) -> ColumnExpression:
        return self._m("str.upper", lambda s: s.upper(), dt.STR)

    def reversed(self) -> ColumnExpression:
        return self._m("str.reversed", lambda s: s[::-1], dt.STR)

    def len(self) -> ColumnExpression:
        return self._m("str.len", len, dt.INT)

    # NOTE: optional arguments with a None default are baked into the lambda
    # instead of passed as operands — MethodCallExpression propagates None
    # operands to a None result, which would wipe out every row.
    def strip(self, chars: Any = None) -> ColumnExpression:
        if chars is None:
            return self._m("str.strip", lambda s: s.strip(), dt.STR)
        return self._m("str.strip", lambda s, c: s.strip(c), dt.STR, chars)

    def lstrip(self, chars: Any = None) -> ColumnExpression:
        if chars is None:
            return self._m("str.lstrip", lambda s: s.lstrip(), dt.STR)
        return self._m("str.lstrip", lambda s, c: s.lstrip(c), dt.STR, chars)

    def rstrip(self, chars: Any = None) -> ColumnExpression:
        if chars is None:
            return self._m("str.rstrip", lambda s: s.rstrip(), dt.STR)
        return self._m("str.rstrip", lambda s, c: s.rstrip(c), dt.STR, chars)

    def count(self, sub: Any) -> ColumnExpression:
        return self._m("str.count", lambda s, x: s.count(x), dt.INT, sub)

    def find(self, sub: Any, start: Any = 0, end: Any = None) -> ColumnExpression:
        if end is None:
            return self._m("str.find", lambda s, x, a: s.find(x, a), dt.INT, sub, start)
        return self._m("str.find", lambda s, x, a, b: s.find(x, a, b), dt.INT, sub, start, end)

    def rfind(self, sub: Any, start: Any = 0, end: Any = None) -> ColumnExpression:
        if end is None:
            return self._m("str.rfind", lambda s, x, a: s.rfind(x, a), dt.INT, sub, start)
        return self._m("str.rfind", lambda s, x, a, b: s.rfind(x, a, b), dt.INT, sub, start, end)

    def startswith(self, prefix: Any) -> ColumnExpression:
        return self._m("str.startswith", lambda s, p: s.startswith(p), dt.BOOL, prefix)

    def endswith(self, suffix: Any) -> ColumnExpression:
        return self._m("str.endswith", lambda s, p: s.endswith(p), dt.BOOL, suffix)

    def swapcase(self) -> ColumnExpression:
        return self._m("str.swapcase", lambda s: s.swapcase(), dt.STR)

    def title(self) -> ColumnExpression:
        return self._m("str.title", lambda s: s.title(), dt.STR)

    def replace(self, old: Any, new: Any, count: Any = -1) -> ColumnExpression:
        return self._m("str.replace", lambda s, o, n, c: s.replace(o, n, c), dt.STR, old, new, count)

    def split(self, sep: Any = None, maxsplit: Any = -1) -> ColumnExpression:
        if sep is None:
            return self._m(
                "str.split", lambda s, m: tuple(s.split(None, m)), dt.List(dt.STR), maxsplit
            )
        return self._m(
            "str.split", lambda s, p, m: tuple(s.split(p, m)), dt.List(dt.STR), sep, maxsplit
        )

    def slice(self, start: Any, end: Any) -> ColumnExpression:
        return self._m("str.slice", lambda s, a, b: s[a:b], dt.STR, start, end)

    # NOTE: the ``_opt`` method-name suffix and the extra const operands
    # (true/false value sets, datetime format, timestamp scale) exist so
    # the expression VM can lower these by (name, arity) — see
    # expr_vm._METHOD_IDS; the lambdas remain the semantic ground truth.
    def parse_int(self, optional: bool = False) -> ColumnExpression:
        def parse(s: str) -> int | None:
            try:
                return int(s)
            except ValueError:
                if optional:
                    return None
                raise

        name = "str.parse_int_opt" if optional else "str.parse_int"
        return self._m(name, parse, dt.Optional(dt.INT) if optional else dt.INT)

    def parse_float(self, optional: bool = False) -> ColumnExpression:
        def parse(s: str) -> float | None:
            try:
                return float(s)
            except ValueError:
                if optional:
                    return None
                raise

        name = "str.parse_float_opt" if optional else "str.parse_float"
        return self._m(name, parse, dt.Optional(dt.FLOAT) if optional else dt.FLOAT)

    def parse_bool(self, true_values: Any = ("on", "true", "yes", "1"), false_values: Any = ("off", "false", "no", "0"), optional: bool = False) -> ColumnExpression:
        tv = tuple(v.lower() for v in true_values)
        fv = tuple(v.lower() for v in false_values)

        def parse(s: str, tvs: tuple, fvs: tuple) -> bool | None:
            low = s.lower()
            if low in tvs:
                return True
            if low in fvs:
                return False
            if optional:
                return None
            raise ValueError(f"Cannot parse {s!r} as bool")

        name = "str.parse_bool_opt" if optional else "str.parse_bool"
        return self._m(name, parse, dt.Optional(dt.BOOL) if optional else dt.BOOL, tv, fv)

    def parse_datetime(self, fmt: str, contains_timezone: bool = False) -> ColumnExpression:
        return self._m(
            "str.parse_datetime",
            lambda s, f: _dtm.datetime.strptime(s, f),
            dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE,
            fmt,
        )


class NumericalNamespace(_Namespace):
    """``expr.num`` methods (reference ``expressions/numerical.py``).

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... x
    ... -3
    ... 2
    ... ''')
    >>> pw.debug.compute_and_print(t.select(a=t.x.num.abs()), include_id=False)
    a
    2
    3
    """

    def abs(self) -> ColumnExpression:
        return self._m("num.abs", abs, self._expr._dtype)

    def round(self, decimals: Any = 0) -> ColumnExpression:
        return self._m("num.round", lambda x, d: round(x, d), self._expr._dtype, decimals)

    def fill_na(self, default_value: Any) -> ColumnExpression:
        def fill(x: Any, d: Any) -> Any:
            if x is None:
                return d
            if isinstance(x, float) and math.isnan(x):
                return d
            return x

        return self._m("num.fill_na", fill, dt.unoptionalize(self._expr._dtype), default_value, propagate_none=False)


_UTC = _dtm.timezone.utc


class DateTimeNamespace(_Namespace):
    """``expr.dt`` methods over datetimes and durations (reference
    ``expressions/date_time.py``).

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... ts
    ... 2024-05-01T12:30:45
    ... ''')
    >>> d = t.select(d=t.ts.str.parse_datetime("%Y-%m-%dT%H:%M:%S"))
    >>> out = d.select(h=d.d.dt.hour(), dow=d.d.dt.day_of_week())
    >>> pw.debug.compute_and_print(out, include_id=False)
    h  | dow
    12 | 2
    """

    def nanosecond(self) -> ColumnExpression:
        return self._m("dt.nanosecond", lambda d: d.microsecond * 1000, dt.INT)

    def microsecond(self) -> ColumnExpression:
        return self._m("dt.microsecond", lambda d: d.microsecond, dt.INT)

    def millisecond(self) -> ColumnExpression:
        return self._m("dt.millisecond", lambda d: d.microsecond // 1000, dt.INT)

    def second(self) -> ColumnExpression:
        return self._m("dt.second", lambda d: d.second, dt.INT)

    def minute(self) -> ColumnExpression:
        return self._m("dt.minute", lambda d: d.minute, dt.INT)

    def hour(self) -> ColumnExpression:
        return self._m("dt.hour", lambda d: d.hour, dt.INT)

    def day(self) -> ColumnExpression:
        return self._m("dt.day", lambda d: d.day, dt.INT)

    def month(self) -> ColumnExpression:
        return self._m("dt.month", lambda d: d.month, dt.INT)

    def year(self) -> ColumnExpression:
        return self._m("dt.year", lambda d: d.year, dt.INT)

    def day_of_week(self) -> ColumnExpression:
        return self._m("dt.day_of_week", lambda d: d.weekday(), dt.INT)

    def day_of_year(self) -> ColumnExpression:
        return self._m("dt.day_of_year", lambda d: d.timetuple().tm_yday, dt.INT)

    def timestamp(self, unit: str = "s") -> ColumnExpression:
        scale = {"ns": 1e9, "us": 1e6, "ms": 1e3, "s": 1.0}[unit]

        def ts(d: _dtm.datetime, sc: float) -> float:
            if d.tzinfo is None:
                d = d.replace(tzinfo=_UTC)
            return d.timestamp() * sc

        return self._m("dt.timestamp", ts, dt.FLOAT, scale)

    def strftime(self, fmt: Any) -> ColumnExpression:
        return self._m("dt.strftime", lambda d, f: d.strftime(f), dt.STR, fmt)

    def strptime(self, fmt: Any, contains_timezone: bool = False) -> ColumnExpression:
        return self._m(
            "dt.strptime",
            lambda s, f: _dtm.datetime.strptime(s, f),
            dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE,
            fmt,
        )

    def to_utc(self, from_timezone: str) -> ColumnExpression:
        from zoneinfo import ZoneInfo

        from pathway_tpu.internals.tztable import build_tz_table

        # _tbl is the packed transition-table operand the native VM
        # converts with; the closure stays the semantic ground truth and
        # doubles as the native per-value fallback (called without _tbl)
        def conv(d: _dtm.datetime, _tbl: Any = None) -> _dtm.datetime:
            return d.replace(tzinfo=ZoneInfo(from_timezone)).astimezone(_UTC)

        return self._m(
            "dt.to_utc", conv, dt.DATE_TIME_UTC, build_tz_table(from_timezone, conv)
        )

    def to_naive_in_timezone(self, timezone: str) -> ColumnExpression:
        from zoneinfo import ZoneInfo

        from pathway_tpu.internals.tztable import build_tz_table

        def conv(d: _dtm.datetime, _tbl: Any = None) -> _dtm.datetime:
            return d.astimezone(ZoneInfo(timezone)).replace(tzinfo=None)

        return self._m(
            "dt.to_naive_in_timezone",
            conv,
            dt.DATE_TIME_NAIVE,
            build_tz_table(timezone, conv),
        )

    def round(self, duration: Any) -> ColumnExpression:
        return self._m("dt.round", _round_dt, self._expr._dtype, duration)

    def floor(self, duration: Any) -> ColumnExpression:
        return self._m("dt.floor", _floor_dt, self._expr._dtype, duration)

    # duration accessors
    def nanoseconds(self) -> ColumnExpression:
        return self._m("dt.nanoseconds", lambda d: int(d.total_seconds() * 1e9), dt.INT)

    def microseconds(self) -> ColumnExpression:
        return self._m("dt.microseconds", lambda d: int(d.total_seconds() * 1e6), dt.INT)

    def milliseconds(self) -> ColumnExpression:
        return self._m("dt.milliseconds", lambda d: int(d.total_seconds() * 1e3), dt.INT)

    def seconds(self) -> ColumnExpression:
        return self._m("dt.seconds", lambda d: int(d.total_seconds()), dt.INT)

    def minutes(self) -> ColumnExpression:
        return self._m("dt.minutes", lambda d: int(d.total_seconds() // 60), dt.INT)

    def hours(self) -> ColumnExpression:
        return self._m("dt.hours", lambda d: int(d.total_seconds() // 3600), dt.INT)

    def days(self) -> ColumnExpression:
        return self._m("dt.days", lambda d: d.days, dt.INT)

    def weeks(self) -> ColumnExpression:
        return self._m("dt.weeks", lambda d: d.days // 7, dt.INT)

    def from_timestamp(self, unit: str = "s") -> ColumnExpression:
        # scale rides along as a float operand so the VM lowers by
        # (name, arity), like timestamp() above
        scale = {"ns": 1e9, "us": 1e6, "ms": 1e3, "s": 1.0}[unit]
        return self._m(
            "dt.from_timestamp",
            lambda x, sc: _dtm.datetime.fromtimestamp(x / sc, tz=_UTC).replace(tzinfo=None),
            dt.DATE_TIME_NAIVE,
            scale,
        )

    def utc_from_timestamp(self, unit: str = "s") -> ColumnExpression:
        scale = {"ns": 1e9, "us": 1e6, "ms": 1e3, "s": 1.0}[unit]
        return self._m(
            "dt.utc_from_timestamp",
            lambda x, sc: _dtm.datetime.fromtimestamp(x / sc, tz=_UTC),
            dt.DATE_TIME_UTC,
            scale,
        )


def _floor_dt(d: _dtm.datetime, duration: _dtm.timedelta) -> _dtm.datetime:
    epoch = _dtm.datetime(1970, 1, 1, tzinfo=d.tzinfo)
    delta = (d - epoch).total_seconds()
    step = duration.total_seconds()
    return epoch + _dtm.timedelta(seconds=math.floor(delta / step) * step)


def _round_dt(d: _dtm.datetime, duration: _dtm.timedelta) -> _dtm.datetime:
    epoch = _dtm.datetime(1970, 1, 1, tzinfo=d.tzinfo)
    delta = (d - epoch).total_seconds()
    step = duration.total_seconds()
    return epoch + _dtm.timedelta(seconds=round(delta / step) * step)
