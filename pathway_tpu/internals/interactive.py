"""Interactive mode / LiveTable / cross-graph export-import.

Capability parity with reference ``internals/interactive.py:37-222`` +
the engine export machinery (``src/engine/dataflow/export.rs``,
``ExportedTable`` at ``src/engine/graph.rs:630``):

- ``enable_interactive_mode()`` marks the session interactive; ``live()``
  starts the graph once in a background thread.
- ``export_table(t)`` attaches an :class:`~pathway_tpu.engine.graph.
  ExportNode`: a thread-safe update log with a closed-epoch frontier,
  offset reads and replay-then-live subscriptions.
- ``import_table(exported)`` rebuilds the stream as an input of the
  CURRENT graph — a second, later graph continues from a finished (or
  still-running) first graph's table.
- :class:`LiveTable` is a continuously updated snapshot with blocking
  ``wait(epoch)`` / ``wait_closed()`` synchronisation and pandas export.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable

from pathway_tpu.engine.graph import ExportNode
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

__all__ = [
    "enable_interactive_mode",
    "LiveTable",
    "live",
    "export_table",
    "import_table",
    "ExportedTable",
]

_interactive: dict[str, Any] = {"enabled": False, "thread": None}


def enable_interactive_mode() -> None:
    """Mark the session interactive: ``live(table)`` snapshots run the
    graph in the background (reference ``enable_interactive_mode``)."""
    _interactive["enabled"] = True


class ExportedTable:
    """Handle over an engine export (reference ``ExportedTable``):
    column metadata + frontier/data_from_offset/subscribe."""

    def __init__(self, node: ExportNode, column_names: list[str], dtypes: dict):
        self._node = node
        self.column_names = list(column_names)
        self.dtypes = dict(dtypes)

    def frontier(self) -> int:
        """Last closed epoch exported so far."""
        return self._node.frontier()

    @property
    def closed(self) -> bool:
        """True once the producing run finished."""
        return self._node.closed

    def data_from_offset(self, offset: int):
        """(updates, next_offset, frontier, closed); updates are
        ``(time, key, values, diff)`` in epoch order."""
        return self._node.data_from_offset(offset)

    def subscribe(self, cb: Callable, replay: bool = True) -> None:
        """``cb(batch, frontier)`` on every exported epoch; ``replay``
        first delivers the full history atomically with registration."""
        self._node.subscribe(cb, replay=replay)

    def snapshot(self) -> dict[Any, tuple]:
        """Consolidated current rows (applies the whole log)."""
        rows: dict[Any, tuple] = {}
        batch, _, _, _ = self._node.data_from_offset(0)
        for _t, key, values, diff in batch:
            if diff > 0:
                rows[key] = values
            else:
                rows.pop(key, None)
        return rows


def export_table(table: Table) -> ExportedTable:
    """Attach an export to ``table`` (reference ``scope.export_table``).
    Must be called while building the producing graph."""
    node = ExportNode(G.engine_graph, table._node)
    return ExportedTable(node, table._column_names, table._dtypes)


class _ImportSubject:
    """RowSource bridging an ExportedTable into another graph's input:
    replays the committed history, then polls for new epochs until the
    producer closes (reference ``scope.import_table``)."""

    deterministic_replay = False

    def __init__(self, exported: ExportedTable, poll_s: float = 0.02):
        self._exported = exported
        self._poll_s = poll_s

    def run(self, events: Any) -> None:
        offset = 0
        while True:
            batch, offset, _frontier, closed = self._exported.data_from_offset(
                offset
            )
            for _t, key, values, diff in batch:
                if diff > 0:
                    events.add(key, values)
                else:
                    events.remove(key, values)
            if batch:
                events.commit()
            if closed and not batch:
                break
            if events.stopped:
                break
            if not batch:
                _time.sleep(self._poll_s)
        events.close()


def import_table(exported: ExportedTable) -> Table:
    """Rebuild an exported table as an input of the CURRENT graph,
    preserving row keys and dtypes (reference ``scope.import_table``)."""
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io._connector import input_table

    # dt.wrap passes DType instances through, so the exported dtypes
    # carry over verbatim
    schema = sch.schema_from_types(
        **{
            n: exported.dtypes.get(n) or object
            for n in exported.column_names
        }
    )
    t = input_table(
        _ImportSubject(exported), schema, name="import", upsert=False
    )
    t._dtypes.update(exported.dtypes)
    return t


class LiveTable:
    """A continuously updated snapshot of a table (reference ``LiveTable``),
    built on the export machinery: update history, epoch frontier, and
    blocking synchronisation."""

    def __init__(self, table: Table):
        self._columns = table._column_names
        self.rows: dict[Any, tuple] = {}
        self._lock = threading.Lock()
        self._changed = threading.Condition(self._lock)
        #: mirrored under OUR lock so wait() never touches the ExportNode
        #: lock (the engine thread holds that lock while delivering to
        #: _on_batch, which takes ours — reading through would AB-BA)
        self._frontier = -1
        self._exported = export_table(table)
        self._exported.subscribe(self._on_batch, replay=True)

    def _on_batch(self, batch: list, frontier: int) -> None:
        with self._changed:
            for _t, key, values, diff in batch:
                if diff > 0:
                    self.rows[key] = values
                else:
                    self.rows.pop(key, None)
            self._frontier = max(self._frontier, frontier)
            self._changed.notify_all()

    # -- synchronisation ------------------------------------------------
    def frontier(self) -> int:
        with self._lock:
            return self._frontier

    def wait(self, epoch: int, timeout: float = 30.0) -> bool:
        """Block until the exported frontier reaches ``epoch``."""
        deadline = _time.monotonic() + timeout
        with self._changed:
            while self._frontier < epoch:
                left = deadline - _time.monotonic()
                if left <= 0:
                    return False
                self._changed.wait(min(left, 0.5))
        return True

    def wait_closed(self, timeout: float = 30.0) -> bool:
        """Block until the producing run finishes."""
        deadline = _time.monotonic() + timeout
        while not self._exported.closed:
            if _time.monotonic() >= deadline:
                return False
            _time.sleep(0.02)
        return True

    # -- views ----------------------------------------------------------
    def snapshot(self) -> dict[Any, tuple]:
        with self._lock:
            return dict(self.rows)

    def update_history(self) -> list[tuple[int, Any, tuple, int]]:
        """The full (time, key, values, diff) update stream so far (read
        from the export log — not duplicated here)."""
        return self._exported.data_from_offset(0)[0]

    def to_pandas(self):
        import pandas as pd

        with self._lock:
            return pd.DataFrame.from_dict(
                self.rows, orient="index", columns=self._columns
            )

    def __len__(self) -> int:
        with self._lock:
            return len(self.rows)

    def __repr__(self) -> str:
        return f"<LiveTable {len(self.rows)} rows: {self._columns}>"


def live(table: Table, *, start: bool = True) -> LiveTable:
    """Create a LiveTable and (by default) start the run in the
    background if not already running."""
    lt = LiveTable(table)
    if start and _interactive["thread"] is None:
        import pathway_tpu as pw

        th = threading.Thread(target=pw.run, daemon=True, name="pw_interactive")
        th.start()
        _interactive["thread"] = th
    return lt
