"""Interactive mode / LiveTable (reference ``internals/interactive.py:37-222``:
``enable_interactive_mode`` runs the graph in a background thread and
exposes tables as live snapshots)."""

from __future__ import annotations

import threading
from typing import Any

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

__all__ = ["enable_interactive_mode", "LiveTable", "live"]

_interactive = {"enabled": False, "thread": None}


def enable_interactive_mode() -> None:
    """Mark the session interactive: ``live(table)`` snapshots run the
    graph in the background (reference ``enable_interactive_mode``)."""
    _interactive["enabled"] = True


class LiveTable:
    """A continuously updated snapshot of a table (reference
    ``LiveTable``: export/import through the engine; here a subscription
    feeding a dict)."""

    def __init__(self, table: Table):
        import pathway_tpu as pw

        self._columns = table._column_names
        self.rows: dict[Any, tuple] = {}
        self._lock = threading.Lock()

        def on_change(key, row, time, is_addition):
            with self._lock:
                if is_addition:
                    self.rows[key] = tuple(row.values())
                else:
                    self.rows.pop(key, None)

        pw.io.subscribe(table, on_change=on_change, name="live_table")

    def snapshot(self) -> dict[Any, tuple]:
        with self._lock:
            return dict(self.rows)

    def to_pandas(self):
        import pandas as pd

        with self._lock:
            return pd.DataFrame.from_dict(
                self.rows, orient="index", columns=self._columns
            )

    def __repr__(self) -> str:
        return f"<LiveTable {len(self.rows)} rows: {self._columns}>"


def live(table: Table, *, start: bool = True) -> LiveTable:
    """Create a LiveTable and (by default) start the run in the
    background if not already running."""
    lt = LiveTable(table)
    if start and _interactive["thread"] is None:
        import pathway_tpu as pw

        th = threading.Thread(target=pw.run, daemon=True, name="pw_interactive")
        th.start()
        _interactive["thread"] = th
    return lt
