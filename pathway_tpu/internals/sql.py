"""``pw.sql(query, **tables)`` — SQL over Tables (reference
``internals/sql.py``, 726 LoC, built on SQLGlot).

SQLGlot isn't available in this environment, so this is a hand-rolled
translator for the practical subset: SELECT (expressions, aliases, *),
FROM, INNER/LEFT JOIN ... ON equalities, WHERE, GROUP BY, HAVING, and
the SUM/COUNT/AVG/MIN/MAX aggregates.  Produces the same incremental
Table operations a hand-written pipeline would.
"""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.internals.expression import ColumnExpression, _wrap
from pathway_tpu.internals.table import Table

__all__ = ["sql"]

_TOKEN = re.compile(
    r"\s*(?:(?P<num>\d+\.\d+|\d+)"
    r"|(?P<str>'[^']*')"
    r'|(?P<qname>"[^"]*")'  # quoted identifier: never a keyword
    r"|(?P<name>[A-Za-z_][A-Za-z_0-9]*)"
    r"|(?P<op><=|>=|<>|!=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.))"
)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "as", "join",
    "inner", "left", "right", "outer", "on", "and", "or", "not", "union",
    "all", "distinct", "with", "in", "between", "like", "is", "null",
    "case", "when", "then", "else", "end", "true", "false", "intersect",
    "except",
}

_AGGS = {"sum", "count", "avg", "min", "max"}


def _tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            if src[pos:].strip() == "":
                break
            raise ValueError(f"SQL syntax error near: {src[pos:pos+30]!r}")
        pos = m.end()
        for kind in ("num", "str", "qname", "name", "op"):
            v = m.group(kind)
            if v is not None:
                if kind == "qname":
                    out.append(("name", v[1:-1]))  # "end" -> plain identifier
                elif kind == "name" and v.lower() in _KEYWORDS:
                    out.append(("kw", v.lower()))
                else:
                    out.append((kind, v))
                break
    out.append(("end", ""))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def at_kw(self, *kws: str) -> bool:
        k, v = self.peek()
        return k == "kw" and v in kws

    def eat(self, kind=None, value=None):
        k, v = self.toks[self.i]
        if (kind and k != kind) or (value and v != value):
            raise ValueError(f"unexpected {v!r} (wanted {value or kind})")
        self.i += 1
        return v

    # ---- expressions (AST: tuples) ----
    def expr(self):
        return self._or()

    def _or(self):
        left = self._and()
        while self.at_kw("or"):
            self.eat()
            left = ("or", left, self._and())
        return left

    def _and(self):
        left = self._not()
        while self.at_kw("and"):
            self.eat()
            left = ("and", left, self._not())
        return left

    def _not(self):
        if self.at_kw("not"):
            self.eat()
            return ("not", self._not())
        return self._cmp()

    def _cmp(self):
        left = self._add()
        k, v = self.peek()
        if k == "op" and v in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.eat()
            return ("cmp", v, left, self._add())
        negated = False
        if self.at_kw("not"):
            # NOT between a value and IN/BETWEEN/LIKE binds to the operator
            self.eat()
            negated = True
        if self.at_kw("in"):
            self.eat()
            self.eat("op", "(")
            if self.at_kw("select"):
                # IN (SELECT ...) — semi/anti-join subquery
                sub = self.select()
                self.eat("op", ")")
                return ("in_sub", left, sub, negated)
            vals = [self.expr()]
            while self.peek() == ("op", ","):
                self.eat()
                vals.append(self.expr())
            self.eat("op", ")")
            node = ("in", left, vals)
            return ("not", node) if negated else node
        if self.at_kw("between"):
            self.eat()
            lo = self._add()
            self.eat("kw", "and")
            hi = self._add()
            node = ("between", left, lo, hi)
            return ("not", node) if negated else node
        if self.at_kw("like"):
            self.eat()
            pat = self._add()
            node = ("like", left, pat)
            return ("not", node) if negated else node
        if negated:
            raise ValueError("NOT here must precede IN/BETWEEN/LIKE")
        if self.at_kw("is"):
            self.eat()
            neg = False
            if self.at_kw("not"):
                self.eat()
                neg = True
            self.eat("kw", "null")
            return ("isnull", left, neg)
        return left

    def _add(self):
        left = self._mul()
        while self.peek() == ("op", "+") or self.peek() == ("op", "-"):
            op = self.eat()
            left = ("bin", op, left, self._mul())
        return left

    def _mul(self):
        left = self._atom()
        while self.peek()[0] == "op" and self.peek()[1] in ("*", "/", "%"):
            op = self.eat()
            left = ("bin", op, left, self._atom())
        return left

    def _atom(self):
        k, v = self.peek()
        if k == "op" and v == "(":
            self.eat()
            e = self.expr()
            self.eat("op", ")")
            return e
        if k == "op" and v == "-":
            self.eat()
            return ("neg", self._atom())
        if k == "kw" and v == "null":
            self.eat()
            return ("lit", None)
        if k == "kw" and v in ("true", "false"):
            self.eat()
            return ("lit", v == "true")
        if k == "kw" and v == "case":
            self.eat()
            whens = []
            while self.at_kw("when"):
                self.eat()
                cond = self.expr()
                self.eat("kw", "then")
                whens.append((cond, self.expr()))
            default = ("lit", None)
            if self.at_kw("else"):
                self.eat()
                default = self.expr()
            self.eat("kw", "end")
            return ("case", whens, default)
        if k == "num":
            self.eat()
            return ("lit", float(v) if "." in v else int(v))
        if k == "str":
            self.eat()
            return ("lit", v[1:-1])
        if k == "op" and v == "*":
            self.eat()
            return ("star",)
        if k == "name":
            self.eat()
            name = v
            if self.peek() == ("op", "("):
                self.eat()
                if self.peek() == ("op", "*"):
                    self.eat()
                    args: list = [("star",)]
                elif self.peek() == ("op", ")"):
                    args = []
                else:
                    args = [self.expr()]
                    while self.peek() == ("op", ","):
                        self.eat()
                        args.append(self.expr())
                self.eat("op", ")")
                return ("call", name.lower(), args)
            if self.peek() == ("op", "."):
                self.eat()
                col = self.eat("name")
                return ("col", name, col)
            return ("col", None, name)
        raise ValueError(f"unexpected token {v!r} in expression")

    # ---- statement ----
    def statement(self) -> dict:
        """Full statement: [WITH ctes] select [UNION [ALL] select]..."""
        ctes = []
        if self.at_kw("with"):
            self.eat()
            while True:
                name = self.eat("name")
                self.eat("kw", "as")
                self.eat("op", "(")
                ctes.append((name, self.select()))
                self.eat("op", ")")
                if self.peek() == ("op", ","):
                    self.eat()
                    continue
                break
        # set-op chain; INTERSECT binds tighter than UNION/EXCEPT (SQL
        # standard precedence), so parse intersect-chains as units
        def intersect_chain() -> dict | tuple:
            node: dict | tuple = self.select()
            while self.at_kw("intersect"):
                self.eat()
                node = ("intersect", node, self.select())
            return node

        first = intersect_chain()
        setops = []
        while self.at_kw("union", "except"):
            op = self.eat()
            all_ = False
            if op == "union" and self.at_kw("all"):
                self.eat()
                all_ = True
            setops.append((op, all_, intersect_chain()))
        self.eat("end")
        return {"ctes": ctes, "select": first, "setops": setops}

    def select(self) -> dict:
        self.eat("kw", "select")
        distinct = False
        if self.at_kw("distinct"):
            self.eat()
            distinct = True
        items = []
        while True:
            e = self.expr()
            alias = None
            if self.at_kw("as"):
                self.eat()
                alias = self.eat("name")
            elif self.peek()[0] == "name":
                alias = self.eat("name")
            items.append((e, alias))
            if self.peek() == ("op", ","):
                self.eat()
                continue
            break
        self.eat("kw", "from")
        if self.peek() == ("op", "("):
            # derived table: FROM (SELECT ...) [AS] alias
            self.eat()
            sub = self.select()
            self.eat("op", ")")
            if self.at_kw("as"):
                self.eat()
            table = ("subquery", sub, self.eat("name"))
        else:
            table = self.eat("name")
        joins = []
        while self.at_kw("join", "inner", "left", "right", "outer"):
            how = "inner"
            while self.at_kw("inner", "left", "right", "outer"):
                how = self.eat()
            self.eat("kw", "join")
            jt = self.eat("name")
            self.eat("kw", "on")
            cond = self.expr()
            joins.append((how, jt, cond))
        where = None
        if self.at_kw("where"):
            self.eat()
            where = self.expr()
        group_by = []
        if self.at_kw("group"):
            self.eat()
            self.eat("kw", "by")
            group_by.append(self.expr())
            while self.peek() == ("op", ","):
                self.eat()
                group_by.append(self.expr())
        having = None
        if self.at_kw("having"):
            self.eat()
            having = self.expr()
        return {
            "items": items,
            "table": table,
            "joins": joins,
            "where": where,
            "group_by": group_by,
            "having": having,
            "distinct": distinct,
        }


def _has_agg(ast) -> bool:
    if not isinstance(ast, tuple):
        return False
    if ast[0] == "call" and ast[1] in _AGGS:
        return True
    return any(_has_agg(c) for c in ast[1:] if isinstance(c, (tuple, list)))


class _Translator:
    def __init__(self, tables: dict[str, Table]):
        self.tables = tables

    def column(self, table_hint: str | None, name: str, scope: Table) -> ColumnExpression:
        if table_hint is not None:
            t = self.tables.get(table_hint)
            if t is None:
                raise KeyError(f"unknown table {table_hint!r}")
            return t[name]
        return scope[name]

    def to_expr(self, ast, scope: Table) -> Any:
        import pathway_tpu as pw

        kind = ast[0]
        if kind == "lit":
            return ast[1]
        if kind == "col":
            return self.column(ast[1], ast[2], scope)
        if kind == "cmp":
            op, a, b = ast[1], self.to_expr(ast[2], scope), self.to_expr(ast[3], scope)
            a, b = _wrap(a), _wrap(b)
            return {
                "=": a == b, "!=": a != b, "<>": a != b,
                "<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b,
            }[op]
        if kind == "bin":
            op, a, b = ast[1], _wrap(self.to_expr(ast[2], scope)), _wrap(self.to_expr(ast[3], scope))
            return {"+": a + b, "-": a - b, "*": a * b, "/": a / b, "%": a % b}[op]
        if kind == "and":
            return _wrap(self.to_expr(ast[1], scope)) & _wrap(self.to_expr(ast[2], scope))
        if kind == "or":
            return _wrap(self.to_expr(ast[1], scope)) | _wrap(self.to_expr(ast[2], scope))
        if kind == "not":
            return ~_wrap(self.to_expr(ast[1], scope))
        if kind == "neg":
            return -_wrap(self.to_expr(ast[1], scope))
        if kind == "in":
            from pathway_tpu.internals.expression import if_else

            e = _wrap(self.to_expr(ast[1], scope))
            out = None
            for v_ast in ast[2]:
                test = e == _wrap(self.to_expr(v_ast, scope))
                out = test if out is None else (out | test)
            # SQL three-valued logic: NULL IN (...) is NULL, so NOT IN
            # keeps excluding NULL rows (None drops in filters either way)
            return if_else(e.is_none(), _wrap(None), _wrap(out))
        if kind == "between":
            e = _wrap(self.to_expr(ast[1], scope))
            lo = _wrap(self.to_expr(ast[2], scope))
            hi = _wrap(self.to_expr(ast[3], scope))
            return (e >= lo) & (e <= hi)
        if kind == "like":
            import re as _re

            pat_ast = ast[2]
            if pat_ast[0] != "lit" or not isinstance(pat_ast[1], str):
                raise ValueError("LIKE pattern must be a string literal")
            # SQL wildcards: % -> .*, _ -> . (everything else literal)
            rx = _re.compile(
                "^"
                + "".join(
                    ".*" if ch == "%" else "." if ch == "_" else _re.escape(ch)
                    for ch in pat_ast[1]
                )
                + "$"
            )
            from pathway_tpu.internals import dtype as dt
            from pathway_tpu.internals.expression import apply_with_type

            # NULL LIKE p is NULL (so NOT LIKE excludes NULL rows too);
            # declared Optional(BOOL) to match
            return apply_with_type(
                lambda s, rx=rx: None if s is None else rx.match(s) is not None,
                dt.Optional(dt.BOOL),
                _wrap(self.to_expr(ast[1], scope)),
            )
        if kind == "isnull":
            e = _wrap(self.to_expr(ast[1], scope))
            return e.is_not_none() if ast[2] else e.is_none()
        if kind == "case":
            from pathway_tpu.internals.expression import if_else

            out = self.to_expr(ast[2], scope)  # ELSE (default NULL)
            for cond_ast, then_ast in reversed(ast[1]):
                out = if_else(
                    _wrap(self.to_expr(cond_ast, scope)),
                    _wrap(self.to_expr(then_ast, scope)),
                    _wrap(out),
                )
            return out
        if kind == "call":
            name, args = ast[1], ast[2]
            if name in _AGGS:
                if name == "count":
                    return pw.reducers.count()
                red = {
                    "sum": pw.reducers.sum, "avg": pw.reducers.avg,
                    "min": pw.reducers.min, "max": pw.reducers.max,
                }[name]
                return red(self.to_expr(args[0], scope))
            raise ValueError(f"unsupported SQL function {name!r}")
        raise ValueError(f"cannot translate {ast!r}")

    def default_name(self, ast) -> str:
        if ast[0] == "col":
            return ast[2]
        if ast[0] == "call":
            return ast[1]
        return "expr"


def _distinct(table: Table) -> Table:
    """SELECT DISTINCT: one row per distinct value tuple."""
    cols = table._column_names
    return table.groupby(*[table[c] for c in cols]).reduce(
        *[table[c] for c in cols]
    )


def _positional_align(left: Table, right: Table) -> Table:
    """Rename ``right``'s columns to ``left``'s, positionally (set ops
    match columns by position, like the reference's SQLGlot translation)."""
    if len(right._column_names) != len(left._column_names):
        raise ValueError("set-operation arms must have the same column count")
    renames = {
        ln: right[rn]
        for ln, rn in zip(left._column_names, right._column_names)
    }
    return right.select(**renames)


def _setop(left: Table, right: Table, op: str) -> Table:
    """Value-based INTERSECT / EXCEPT with SQL set semantics.

    Implemented as a tagged concat + groupby over all columns rather than
    a join, so NULL cells compare equal (SQL set ops use IS NOT DISTINCT
    FROM semantics, unlike joins) and the result is deduplicated."""
    import pathway_tpu as pw

    right = _positional_align(left, right)
    cols = left._column_names
    a = left.select(*[left[c] for c in cols], _pw_l=1, _pw_r=0)
    b = right.select(*[right[c] for c in cols], _pw_l=0, _pw_r=1)
    u = a.concat_reindex(b)
    g = u.groupby(*[u[c] for c in cols]).reduce(
        *[u[c] for c in cols],
        _pw_l=pw.reducers.sum(u["_pw_l"]),
        _pw_r=pw.reducers.sum(u["_pw_r"]),
    )
    if op == "intersect":
        kept = g.filter((g["_pw_l"] > 0) & (g["_pw_r"] > 0))
    else:  # except
        kept = g.filter((g["_pw_l"] > 0) & (g["_pw_r"] == 0))
    return kept.select(**{c: kept[c] for c in cols})


def _split_conjuncts(ast) -> list:
    if isinstance(ast, tuple) and ast[0] == "and":
        return _split_conjuncts(ast[1]) + _split_conjuncts(ast[2])
    return [ast]


def _contains_in_sub(ast) -> bool:
    if not isinstance(ast, tuple):
        return False
    if ast[0] == "in_sub":
        return True
    return any(
        _contains_in_sub(c) for c in ast[1:] if isinstance(c, (tuple, list))
    )


def _apply_in_subquery(
    tr: "_Translator", scope: Table, node: tuple, tables: dict[str, Table]
) -> Table:
    """WHERE x [NOT] IN (SELECT c FROM ...) as a semi/anti-join.

    The subquery is deduplicated first, so the semi-join never duplicates
    scope rows.  NULL handling: a NULL probe value never matches (IN drops
    it; NOT IN drops it too, per SQL three-valued logic); NULL values
    *inside* the subquery are treated as non-matching values — stricter
    standard semantics would make NOT IN empty whenever the subquery
    contains a NULL, which is almost never what a query means."""
    import pathway_tpu as pw

    _tag, left_ast, sub_ast, negated = node
    sub = _translate_select(sub_ast, tables)
    if len(sub._column_names) != 1:
        raise ValueError("IN (SELECT ...) must select exactly one column")
    sc = sub._column_names[0]
    subd = _distinct(sub)
    marked = subd.select(_pw_in_val=subd[sc], _pw_m=1)
    lexpr = _wrap(tr.to_expr(left_ast, scope))
    cols = scope._column_names
    if negated:
        # NULL probes drop first (NULL NOT IN (...) is NULL in SQL);
        # the anti-join then keeps rows with no subquery match
        non_null = scope.filter(~lexpr.is_none())
        j = non_null.join_left(marked, lexpr == marked["_pw_in_val"])
        j2 = j.select(
            **{c: pw.left[c] for c in cols}, _pw_m=pw.right["_pw_m"]
        )
        kept = j2.filter(j2["_pw_m"].is_none())
    else:
        j = scope.join(marked, lexpr == marked["_pw_in_val"])
        kept = j.select(**{c: pw.left[c] for c in cols})
    return kept.select(**{c: kept[c] for c in cols})


def sql(query: str, **tables: Table) -> Table:
    """Run a SQL query against keyword-named tables::

        pw.sql("SELECT owner, SUM(pets) AS total FROM t GROUP BY owner", t=t)

    Supported: SELECT [DISTINCT] expressions/aliases/*, FROM (incl.
    derived-table subqueries), WITH ctes, INNER/LEFT/RIGHT/OUTER JOIN ON
    equality, WHERE (incl. ``[NOT] IN (SELECT ...)`` semi/anti-join
    conjuncts), GROUP BY, HAVING, UNION [ALL], INTERSECT, EXCEPT,
    IN / BETWEEN / LIKE / IS [NOT] NULL / CASE WHEN, and
    SUM/COUNT/AVG/MIN/MAX.
    

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... a | b
    ... 1 | 10
    ... 2 | 20
    ... ''')
    >>> res = pw.sql("SELECT a, b FROM tab WHERE b > 15", tab=t)
    >>> pw.debug.compute_and_print(res, include_id=False)
    a | b
    2 | 20
    """
    stmt = _Parser(_tokenize(query)).statement()
    env = dict(tables)
    for name, sub_ast in stmt["ctes"]:
        env[name] = _translate_select(sub_ast, env)
    result = _translate_set(stmt["select"], env)
    for op, all_, node in stmt["setops"]:
        other = _translate_set(node, env)
        if op == "union":
            result = result.concat_reindex(_positional_align(result, other))
            if not all_:
                result = _distinct(result)
        else:  # except
            result = _setop(result, other, "except")
    return result


def _translate_set(node: Any, tables: dict[str, Table]) -> Table:
    """An intersect-chain unit: a plain select dict or ("intersect", l, r)."""
    if isinstance(node, tuple) and node[0] == "intersect":
        return _setop(
            _translate_set(node[1], tables),
            _translate_set(node[2], tables),
            "intersect",
        )
    return _translate_select(node, tables)


def _translate_select(ast: dict, tables: dict[str, Table]) -> Table:
    tables = dict(tables)
    tr = _Translator(tables)
    if isinstance(ast["table"], tuple):  # ("subquery", sub_ast, alias)
        _tag, sub_ast, alias = ast["table"]
        base = _translate_select(sub_ast, tables)
        tables[alias] = base
        tr = _Translator(tables)
    else:
        base = tables.get(ast["table"])
    if base is None:
        raise KeyError(f"unknown table {ast['table']!r} (pass it as a kwarg)")

    scope = base
    for how, jt_name, cond in ast["joins"]:
        jt = tables.get(jt_name)
        if jt is None:
            raise KeyError(f"unknown table {jt_name!r}")
        if cond[0] != "cmp" or cond[1] != "=":
            raise ValueError("JOIN ON must be an equality")
        left_e = tr.to_expr(cond[2], scope)
        right_e = tr.to_expr(cond[3], scope)
        jr = {
            "inner": scope.join,
            "left": scope.join_left,
            "right": scope.join_right,
            "outer": scope.join_outer,
        }[how](jt, _wrap(left_e) == _wrap(right_e))
        import pathway_tpu as pw

        seen: dict[str, Any] = {}
        for c in scope._column_names:
            seen[c] = pw.left[c]
        for c in jt._column_names:
            if c not in seen:
                seen[c] = pw.right[c]
        scope = jr.select(**seen)

    if ast["where"] is not None:
        # [NOT] IN (SELECT ...) conjuncts become semi/anti-joins; the
        # remaining conjuncts recombine into one ordinary filter
        plain: list = []
        for conj in _split_conjuncts(ast["where"]):
            if isinstance(conj, tuple) and conj[0] == "in_sub":
                scope = _apply_in_subquery(tr, scope, conj, tables)
            elif _contains_in_sub(conj):
                raise ValueError(
                    "IN (SELECT ...) is only supported as a top-level "
                    "WHERE conjunct"
                )
            else:
                plain.append(conj)
        if plain:
            combined = plain[0]
            for conj in plain[1:]:
                combined = ("and", combined, conj)
            scope = scope.filter(_wrap(tr.to_expr(combined, scope)))

    items = ast["items"]
    if ast["group_by"]:
        group_exprs = [tr.to_expr(g, scope) for g in ast["group_by"]]
        grouped = scope.groupby(*group_exprs)
        outs: dict[str, Any] = {}
        for e_ast, alias in items:
            if e_ast == ("star",):
                raise ValueError("SELECT * with GROUP BY is not supported")
            name = alias or tr.default_name(e_ast)
            outs[name] = tr.to_expr(e_ast, scope)
        having_ast = ast["having"]
        hidden: list[str] = []
        if having_ast is not None:
            # HAVING may re-state aggregates (HAVING SUM(x) > 2): hoist
            # them into hidden reduce columns and reference those
            def hoist(node):
                if isinstance(node, tuple) and node[0] == "call" and node[1] in _AGGS:
                    name = f"_pw_having_{len(hidden)}"
                    hidden.append(name)
                    outs[name] = tr.to_expr(node, scope)
                    return ("col", None, name)
                if isinstance(node, tuple):
                    return tuple(
                        hoist(c) if isinstance(c, tuple) else c for c in node
                    )
                return node

            having_ast = hoist(having_ast)
        result = grouped.reduce(**outs)
        if having_ast is not None:
            result = result.filter(_wrap(tr.to_expr(having_ast, result)))
            if hidden:
                keep = [c for c in result._column_names if c not in hidden]
                result = result.select(**{c: result[c] for c in keep})
        return _distinct(result) if ast["distinct"] else result

    if any(_has_agg(e) for e, _ in items):
        outs = {}
        for e_ast, alias in items:
            name = alias or tr.default_name(e_ast)
            outs[name] = tr.to_expr(e_ast, scope)
        return scope.reduce(**outs)

    if len(items) == 1 and items[0][0] == ("star",):
        return _distinct(scope) if ast["distinct"] else scope
    outs = {}
    for e_ast, alias in items:
        if e_ast == ("star",):
            for c in scope._column_names:
                outs[c] = scope[c]
            continue
        name = alias or tr.default_name(e_ast)
        outs[name] = tr.to_expr(e_ast, scope)
    result = scope.select(**outs)
    return _distinct(result) if ast["distinct"] else result
