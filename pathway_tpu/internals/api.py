"""Engine-level sentinels and error values.

Mirrors the capability of reference ``Value::Error`` / ``Value::Pending``
(``src/engine/value.rs:207-231``): a poisoned cell value that propagates
through expressions without aborting the run, and a pending marker for async
results.
"""

from __future__ import annotations


class _Error:
    _instance: "_Error | None" = None

    def __new__(cls) -> "_Error":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"

    def __bool__(self) -> bool:
        raise ValueError("Cannot use pw Error value in a boolean context")


class _Pending:
    _instance: "_Pending | None" = None

    def __new__(cls) -> "_Pending":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Pending"


ERROR = _Error()
PENDING = _Pending()


def is_error(value: object) -> bool:
    return value is ERROR


class PyObjectWrapper:
    """Explicitly wraps an arbitrary Python object as an engine value
    (reference ``Value::PyObjectWrapper``, ``src/engine/value.rs:207-231``;
    Python shape ``engine.pyi:895``).

    The payload flows through tables untouched; equality/hashing delegate
    to the payload so wrapped values group and join naturally.  An
    optional serializer (``dumps``/``loads``, default pickle) controls
    how persistence snapshots the payload — set via
    :func:`wrap_py_object`.
    """

    __slots__ = ("value", "_serializer")

    def __init__(self, value: object, _serializer: object = None):
        self.value = value
        self._serializer = _serializer

    def __repr__(self) -> str:
        return f"PyObjectWrapper({self.value!r})"

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PyObjectWrapper):
            return self.value == other.value
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self.value)

    def __reduce__(self):
        ser = self._serializer
        if ser is not None:
            return (_unwrap_py_object, (ser.dumps(self.value), ser))
        return (PyObjectWrapper, (self.value,))


def _unwrap_py_object(data: bytes, serializer: object) -> PyObjectWrapper:
    return PyObjectWrapper(serializer.loads(data), serializer)  # type: ignore[attr-defined]


def wrap_py_object(object: object, *, serializer: object = None) -> PyObjectWrapper:
    """Wrap a Python object for the engine, optionally with a custom
    ``dumps``/``loads`` serializer used by persistence (reference
    ``api.wrap_py_object``; default pickle via ``__reduce__``)."""
    return PyObjectWrapper(object, serializer)


class EngineError(Exception):
    """Raised for engine failures; contained per-node by the scheduler
    (routed to the error log) unless it is a :class:`FatalEngineError`."""


class FatalEngineError(EngineError):
    """An engine failure that must abort the run instead of being
    contained (e.g. runtime typecheck violations)."""


class EngineErrorWithTrace(EngineError):
    def __init__(self, message: str, trace: str | None = None):
        super().__init__(message if trace is None else f"{message}\n{trace}")
        self.trace = trace
