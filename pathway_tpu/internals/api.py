"""Engine-level sentinels and error values.

Mirrors the capability of reference ``Value::Error`` / ``Value::Pending``
(``src/engine/value.rs:207-231``): a poisoned cell value that propagates
through expressions without aborting the run, and a pending marker for async
results.
"""

from __future__ import annotations


class _Error:
    _instance: "_Error | None" = None

    def __new__(cls) -> "_Error":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"

    def __bool__(self) -> bool:
        raise ValueError("Cannot use pw Error value in a boolean context")


class _Pending:
    _instance: "_Pending | None" = None

    def __new__(cls) -> "_Pending":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Pending"


ERROR = _Error()
PENDING = _Pending()


def is_error(value: object) -> bool:
    return value is ERROR


class EngineError(Exception):
    """Raised for engine failures; contained per-node by the scheduler
    (routed to the error log) unless it is a :class:`FatalEngineError`."""


class FatalEngineError(EngineError):
    """An engine failure that must abort the run instead of being
    contained (e.g. runtime typecheck violations)."""


class EngineErrorWithTrace(EngineError):
    def __init__(self, message: str, trace: str | None = None):
        super().__init__(message if trace is None else f"{message}\n{trace}")
        self.trace = trace
