"""``pw.Json`` value wrapper (reference ``python/pathway/internals/json.py``).

Wraps an arbitrary JSON-serialisable value so the type system can treat it as
one opaque dtype while still offering indexing and conversion accessors.
"""

from __future__ import annotations

import json as _json
from typing import Any


class Json:
    __slots__ = ("_value",)

    NULL: "Json"

    def __init__(self, value: Any = None):
        if isinstance(value, Json):
            value = value._value
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    def __getitem__(self, item: Any) -> "Json":
        v = self._value[item]
        return v if isinstance(v, Json) else Json(v)

    def get(self, item: Any, default: Any = None) -> Any:
        try:
            return self[item]
        except (KeyError, IndexError, TypeError):
            return default

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Json):
            return self._value == other._value
        return self._value == other

    def __hash__(self) -> int:
        try:
            return hash(_json.dumps(self._value, sort_keys=True, default=str))
        except TypeError:
            return hash(repr(self._value))

    def __repr__(self) -> str:
        return f"pw.Json({self._value!r})"

    def __str__(self) -> str:
        return _json.dumps(self._value, default=str)

    def __bool__(self) -> bool:
        return bool(self._value)

    def as_int(self) -> int | None:
        return int(self._value) if isinstance(self._value, (int, float)) and not isinstance(self._value, bool) else None

    def as_float(self) -> float | None:
        return float(self._value) if isinstance(self._value, (int, float)) and not isinstance(self._value, bool) else None

    def as_str(self) -> str | None:
        return self._value if isinstance(self._value, str) else None

    def as_bool(self) -> bool | None:
        return self._value if isinstance(self._value, bool) else None

    def as_list(self) -> list | None:
        return self._value if isinstance(self._value, list) else None

    def as_dict(self) -> dict | None:
        return self._value if isinstance(self._value, dict) else None

    @staticmethod
    def parse(text: str | bytes) -> "Json":
        return Json(_json.loads(text))

    @staticmethod
    def dumps(value: Any) -> str:
        if isinstance(value, Json):
            value = value.value
        return _json.dumps(value, default=str)


Json.NULL = Json(None)
