"""128-bit row keys ("pointers").

The reference engine identifies every row by a 128-bit ``Key`` produced by
hashing the values of the primary-key columns (``src/engine/value.rs`` ``Key``;
``shard_as_usize`` for worker sharding).  We reproduce the *capability* —
stable, collision-resistant, order-free row identity with derived-key
generation — with our own scheme: BLAKE2b-128 over a type-tagged
serialisation.  A thin C++ fast path may replace the hash loop later; the
Python fallback is authoritative for semantics.
"""

from __future__ import annotations

import datetime
import hashlib
import struct
from typing import Any, Iterable

import numpy as np

_SALT = b"pathway_tpu.key.v1"


class Pointer(int):
    """A row key: an int subclass so it hashes/sorts natively, prints short."""

    __slots__ = ()

    def __repr__(self) -> str:
        return f"^{self:032X}"[:12] + "…"

    def __str__(self) -> str:
        return repr(self)

    @property
    def value(self) -> int:
        return int(self)


def _feed(h: "hashlib._Hash", value: Any) -> None:
    if value is None:
        h.update(b"\x00")
    elif isinstance(value, bool):
        h.update(b"\x01" + (b"\x01" if value else b"\x00"))
    elif isinstance(value, Pointer):
        h.update(b"\x07" + int(value).to_bytes(16, "little"))
    elif isinstance(value, int):
        h.update(b"\x02" + value.to_bytes((value.bit_length() + 8) // 8 + 1, "little", signed=True))
    elif isinstance(value, float):
        h.update(b"\x03" + struct.pack("<d", value))
    elif isinstance(value, str):
        b = value.encode()
        h.update(b"\x04" + len(b).to_bytes(8, "little") + b)
    elif isinstance(value, bytes):
        h.update(b"\x05" + len(value).to_bytes(8, "little") + value)
    elif isinstance(value, tuple):
        h.update(b"\x06" + len(value).to_bytes(8, "little"))
        for v in value:
            _feed(h, v)
    elif isinstance(value, datetime.datetime):
        h.update(b"\x08" + struct.pack("<d", value.timestamp()))
    elif isinstance(value, datetime.timedelta):
        h.update(b"\x09" + struct.pack("<d", value.total_seconds()))
    elif isinstance(value, np.ndarray):
        h.update(b"\x0a" + value.tobytes())
    else:
        b = repr(value).encode()
        h.update(b"\x0b" + len(b).to_bytes(8, "little") + b)


def _py_ref_scalar(*args: Any) -> Pointer:
    h = hashlib.blake2b(_SALT, digest_size=16)
    for a in args:
        _feed(h, a)
    return Pointer(int.from_bytes(h.digest(), "little"))


def _load_native():
    """C++ fast path (native/pathway_native.cpp): byte-identical
    serialization+hash, so keys are stable across both paths."""
    from pathway_tpu.internals import native as _native_loader

    mod = _native_loader.load()
    if mod is not None:
        mod.set_pointer_type(Pointer)
    return mod


_native = None
_native_checked = False


def ref_scalar(*args: Any) -> Pointer:
    """Hash a tuple of values into a 128-bit Pointer (reference
    ``Key::for_values``)."""
    global _native, _native_checked
    if not _native_checked:
        _native_checked = True
        _native = _load_native()
    if _native is not None:
        try:
            return Pointer(_native.ref_scalar(*args))
        except _native.Unsupported:
            pass  # value type outside the C fast path
    return _py_ref_scalar(*args)


def sequential_key(seq: int) -> Pointer:
    """Key for auto-numbered rows (static tables / connectors without
    primary keys)."""
    return ref_scalar("__seq__", seq)


def derive(key: Pointer, *tags: Any) -> Pointer:
    """Derive a new key from an existing one (reindex/flatten/join rows)."""
    return ref_scalar(int(key), *tags)


def join_key(left: Pointer, right: Pointer | None) -> Pointer:
    return ref_scalar("__join__", int(left), int(right) if right is not None else None)


def shard_of(key: Pointer, n_shards: int) -> int:
    """Worker shard for a key (reference ``shard_as_usize() % worker_count``,
    ``src/engine/dataflow.rs:1068-1072``)."""
    return int(key) % n_shards


def unsafe_pointer(x: int) -> Pointer:
    return Pointer(x)


def keys_for_values(rows: Iterable[tuple[Any, ...]]) -> list[Pointer]:
    """Hash many key tuples in ONE native call (bulk ingest fast path),
    falling back to per-row ref_scalar when the native module is absent
    or a value type is outside its fast path."""
    global _native, _native_checked
    if not _native_checked:
        _native_checked = True
        _native = _load_native()
    rows = list(rows)
    if _native is not None:
        try:
            return [Pointer(k) for k in _native.hash_rows(rows)]
        except _native.Unsupported:
            pass
    return [ref_scalar(*r) for r in rows]
