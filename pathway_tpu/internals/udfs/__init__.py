"""UDF system: ``@pw.udf`` with sync/async executors, retries, caching.

Capability parity with reference ``python/pathway/internals/udfs/``
(executors sync/async/fully-async, caches, retries — ``executors.py:91-219``,
``caches.py``, ``retries.py``).  Async UDFs are micro-batched per epoch by
the engine's :class:`AsyncMapNode` — the whole epoch's rows are dispatched
concurrently on one event loop (the TPU-batched analogue of the reference's
``map_named_async`` FuturesUnordered block).
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import inspect
import os
import pickle
import random
import threading
import time
from typing import Any, Awaitable, Callable

from pathway_tpu.internals import api
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    ColumnExpression,
)

__all__ = [
    "udf",
    "UDF",
    "BatchUDF",
    "batch_udf",
    "async_executor",
    "sync_executor",
    "auto_executor",
    "fully_async_executor",
    "AsyncRetryStrategy",
    "ExponentialBackoffRetryStrategy",
    "FixedDelayRetryStrategy",
    "NoRetryStrategy",
    "CacheStrategy",
    "DefaultCache",
    "InMemoryCache",
    "DiskCache",
    "run_async_batch",
    "coerce_async",
    "with_capacity",
    "with_retry_strategy",
    "with_cache_strategy",
    "with_timeout",
]


# ---------------------------------------------------------------------------
# Retry strategies (reference internals/udfs/retries.py)


class AsyncRetryStrategy:
    async def invoke(self, fun: Callable[..., Awaitable[Any]], *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError


class NoRetryStrategy(AsyncRetryStrategy):
    async def invoke(self, fun, *args, **kwargs):
        return await fun(*args, **kwargs)


class FixedDelayRetryStrategy(AsyncRetryStrategy):
    def __init__(self, max_retries: int = 3, delay_ms: int = 1000):
        self._max_retries = max_retries
        self._delay = delay_ms / 1000

    def _next_delay(self, attempt: int) -> float:
        return self._delay

    def next_delay(self, attempt: int) -> float:
        """Delay in seconds before retry number ``attempt`` (0-based).
        Public: the connector supervisor reuses the same policy objects
        for its restart schedule."""
        return self._next_delay(attempt)

    async def invoke(self, fun, *args, **kwargs):
        last: Exception | None = None
        for attempt in range(self._max_retries + 1):
            try:
                return await fun(*args, **kwargs)
            except Exception as e:  # noqa: BLE001
                last = e
                if attempt < self._max_retries:
                    await asyncio.sleep(self._next_delay(attempt))
        assert last is not None
        raise last


class ExponentialBackoffRetryStrategy(FixedDelayRetryStrategy):
    """Exponential backoff with jitter.

    ``max_delay_ms`` caps every delay (with it unset, a long retry chain
    sleeps unboundedly: delay * factor**n).  ``full_jitter=True`` draws
    uniformly from ``[0, capped_base]`` (AWS full-jitter — decorrelates
    retry storms better than additive jitter); the default keeps the
    additive ``base + U(0, jitter_ms)`` behaviour.  ``seed`` makes the
    schedule deterministic (chaos tests, reproducible drills)."""

    def __init__(
        self,
        max_retries: int = 3,
        initial_delay: int = 1000,
        backoff_factor: float = 2.0,
        jitter_ms: int = 300,
        max_delay_ms: int | None = None,
        full_jitter: bool = False,
        seed: int | None = None,
    ):
        super().__init__(max_retries, initial_delay)
        self._backoff = backoff_factor
        self._jitter = jitter_ms / 1000
        self._max_delay = max_delay_ms / 1000 if max_delay_ms is not None else None
        self._full_jitter = full_jitter
        self._rng = random.Random(seed) if seed is not None else random

    def _next_delay(self, attempt: int) -> float:
        base = self._delay * (self._backoff**attempt)
        if self._max_delay is not None:
            base = min(base, self._max_delay)
        if self._full_jitter:
            return self._rng.uniform(0.0, base)
        delay = base + self._rng.random() * self._jitter
        if self._max_delay is not None:
            delay = min(delay, self._max_delay)
        return delay


# ---------------------------------------------------------------------------
# Cache strategies (reference internals/udfs/caches.py)


class CacheStrategy:
    def make_wrapper(self, fun: Callable[..., Awaitable[Any]]) -> Callable[..., Awaitable[Any]]:
        raise NotImplementedError


class InMemoryCache(CacheStrategy):
    def __init__(self) -> None:
        self._store: dict[bytes, Any] = {}
        self._lock = threading.Lock()

    def make_wrapper(self, fun):
        @functools.wraps(fun)
        async def wrapper(*args, **kwargs):
            key = _cache_key(fun, args, kwargs)
            with self._lock:
                if key in self._store:
                    return self._store[key]
            result = await fun(*args, **kwargs)
            with self._lock:
                self._store[key] = result
            return result

        return wrapper


class DiskCache(CacheStrategy):
    """Persists results under ``PATHWAY_PERSISTENT_STORAGE`` (reference
    UdfCaching persistence mode)."""

    def __init__(self, directory: str | None = None):
        self._dir = directory

    def _path(self, key: bytes) -> str:
        base = self._dir or os.environ.get(
            "PATHWAY_PERSISTENT_STORAGE", "/tmp/pathway_tpu_cache"
        )
        os.makedirs(base, exist_ok=True)
        return os.path.join(base, key.hex())

    def make_wrapper(self, fun):
        @functools.wraps(fun)
        async def wrapper(*args, **kwargs):
            key = _cache_key(fun, args, kwargs)
            path = self._path(key)
            if os.path.exists(path):
                try:
                    with open(path, "rb") as f:
                        return pickle.load(f)
                except Exception:
                    # torn/corrupt entry (crash mid-write before this
                    # cache used tmp+replace, disk corruption): a cache
                    # miss, not a permanent failure — drop it and recompute
                    try:
                        os.remove(path)
                    except OSError:
                        pass
            result = await fun(*args, **kwargs)
            # tmp + atomic rename: a crash mid-write must never leave a
            # half-written pickle under the final name (unique tmp per
            # writer — concurrent epochs may compute the same key)
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            try:
                with open(tmp, "wb") as f:
                    pickle.dump(result, f)
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
            return result

        return wrapper


DefaultCache = InMemoryCache


def _cache_key(fun: Callable, args: tuple, kwargs: dict) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(getattr(fun, "__qualname__", repr(fun)).encode())
    try:
        h.update(pickle.dumps((args, sorted(kwargs.items()))))
    except Exception:
        h.update(repr((args, kwargs)).encode())
    return h.digest()


# ---------------------------------------------------------------------------
# Composable async wrappers (reference internals/udfs/executors.py:286-326)


def coerce_async(fun: Callable) -> Callable[..., Awaitable[Any]]:
    if inspect.iscoroutinefunction(fun):
        return fun

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return fun(*args, **kwargs)

    return wrapper


def with_capacity(fun: Callable[..., Awaitable[Any]], capacity: int) -> Callable[..., Awaitable[Any]]:
    semaphores: dict[int, asyncio.Semaphore] = {}

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        loop_id = id(asyncio.get_running_loop())
        if loop_id not in semaphores:
            semaphores[loop_id] = asyncio.Semaphore(capacity)
        async with semaphores[loop_id]:
            return await fun(*args, **kwargs)

    return wrapper


def with_timeout(fun: Callable[..., Awaitable[Any]], timeout: float) -> Callable[..., Awaitable[Any]]:
    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return await asyncio.wait_for(fun(*args, **kwargs), timeout)

    return wrapper


def with_retry_strategy(
    fun: Callable[..., Awaitable[Any]], retry_strategy: AsyncRetryStrategy
) -> Callable[..., Awaitable[Any]]:
    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return await retry_strategy.invoke(fun, *args, **kwargs)

    return wrapper


def with_cache_strategy(
    fun: Callable[..., Awaitable[Any]], cache_strategy: CacheStrategy
) -> Callable[..., Awaitable[Any]]:
    return cache_strategy.make_wrapper(fun)


# ---------------------------------------------------------------------------
# Executors


class Executor:
    def wrap(self, fun: Callable) -> Callable:
        return fun

    is_async = False


class SyncExecutor(Executor):
    pass


class AsyncExecutor(Executor):
    is_async = True

    def __init__(
        self,
        *,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
    ):
        self.capacity = capacity
        self.timeout = timeout
        self.retry_strategy = retry_strategy
        self.cache_strategy = cache_strategy

    def wrap(self, fun: Callable) -> Callable:
        f = coerce_async(fun)
        if self.retry_strategy is not None:
            f = with_retry_strategy(f, self.retry_strategy)
        if self.timeout is not None:
            f = with_timeout(f, self.timeout)
        if self.cache_strategy is not None:
            f = with_cache_strategy(f, self.cache_strategy)
        if self.capacity is not None:
            f = with_capacity(f, self.capacity)
        return f


class FullyAsyncExecutor(AsyncExecutor):
    """Results arrive at later epochs (reference fully_async_executor).
    Currently mapped to the blocking batched executor; the decoupled
    AsyncTransformer path covers the fully-async capability."""


def sync_executor() -> Executor:
    return SyncExecutor()


def async_executor(
    *,
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
    cache_strategy: CacheStrategy | None = None,
) -> Executor:
    return AsyncExecutor(
        capacity=capacity,
        timeout=timeout,
        retry_strategy=retry_strategy,
        cache_strategy=cache_strategy,
    )


def fully_async_executor(**kwargs: Any) -> Executor:
    return FullyAsyncExecutor(**kwargs)


def auto_executor() -> Executor:
    return Executor()


# ---------------------------------------------------------------------------
# The @pw.udf decorator


class UDF:
    """Base class / wrapper for user-defined functions applied to columns
    (reference ``internals/udfs/__init__.py`` ``UDF``)."""

    def __init__(
        self,
        *,
        return_type: Any = None,
        deterministic: bool = False,
        propagate_none: bool = False,
        executor: Executor | None = None,
        cache_strategy: CacheStrategy | None = None,
        max_batch_size: int | None = None,
    ):
        self.return_type = return_type
        self.deterministic = deterministic
        self.propagate_none = propagate_none
        self.executor = executor
        self.cache_strategy = cache_strategy
        self.max_batch_size = max_batch_size
        self._wrapped: Callable | None = None

    # subclasses override ONE of these
    def __wrapped__(self, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError

    #: subclasses may instead define ``__batch__(self, xs: list, ...) ->
    #: list`` to run ONCE per epoch with per-argument lists (the jitted
    #: TPU executor contract; see ``BatchApplyExpression``)
    __batch__: Callable | None = None

    def _resolve_fun(self) -> tuple[Callable, bool]:
        fun = self._wrapped if self._wrapped is not None else self.__wrapped__
        executor = self.executor
        is_async = inspect.iscoroutinefunction(fun) or (
            executor is not None and executor.is_async
        )
        if executor is None and is_async:
            executor = AsyncExecutor(cache_strategy=self.cache_strategy)
        if executor is None:
            executor = SyncExecutor()
        if isinstance(executor, AsyncExecutor):
            if self.cache_strategy is not None and executor.cache_strategy is None:
                executor.cache_strategy = self.cache_strategy
            return executor.wrap(fun), True
        if self.cache_strategy is not None:
            f = coerce_async(fun)
            f = with_cache_strategy(f, self.cache_strategy)
            return f, True
        return fun, False

    def _return_dtype(self) -> Any:
        if self.return_type is not None:
            return self.return_type
        fun = self._wrapped if self._wrapped is not None else self.__wrapped__
        try:
            import typing

            return typing.get_type_hints(fun).get("return", dt.ANY)
        except Exception:
            return dt.ANY

    def __call__(self, *args: Any, **kwargs: Any) -> ColumnExpression:
        from pathway_tpu.internals.expression import BatchApplyExpression

        batch = getattr(self, "__batch__", None)
        if batch is not None:
            ret = self._return_dtype()
            fun = batch if not isinstance(batch, staticmethod) else batch.__func__
            if self.max_batch_size is not None:
                fun = _chunk_batches(fun, self.max_batch_size)
            return BatchApplyExpression(
                fun, ret, args, kwargs, propagate_none=self.propagate_none,
                deterministic=self.deterministic,
            )
        fun, is_async = self._resolve_fun()
        ret = self._return_dtype()
        if is_async:
            return AsyncApplyExpression(
                fun, ret, args, kwargs, propagate_none=self.propagate_none,
                deterministic=self.deterministic,
            )
        return ApplyExpression(
            fun, ret, args, kwargs, propagate_none=self.propagate_none,
            deterministic=self.deterministic,
        )


def _chunk_batches(fun: Callable, max_batch: int) -> Callable:
    """Split oversize epoch batches into chunks of ``max_batch`` rows."""

    @functools.wraps(fun)
    def wrapper(*arg_lists: list, **kw_lists: list) -> list:
        n = len(arg_lists[0]) if arg_lists else len(next(iter(kw_lists.values())))
        if n <= max_batch:
            return fun(*arg_lists, **kw_lists)
        out: list = []
        for s in range(0, n, max_batch):
            sl = slice(s, s + max_batch)
            out.extend(
                fun(
                    *[a[sl] for a in arg_lists],
                    **{k: v[sl] for k, v in kw_lists.items()},
                )
            )
        return out

    return wrapper


class BatchUDF(UDF):
    """UDF whose function takes per-argument LISTS covering the whole epoch
    (one jitted TPU call per epoch)."""

    def __init__(self, fun: Callable, **kwargs: Any):
        super().__init__(**kwargs)
        self.__batch__ = fun
        functools.update_wrapper(self, fun)


def batch_udf(
    fun: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    max_batch_size: int | None = None,
    **kwargs: Any,
) -> Any:
    """Decorator: epoch-batched UDF (``fun(list, ...) -> list``)."""

    def wrap(f: Callable) -> BatchUDF:
        return BatchUDF(
            f, return_type=return_type, max_batch_size=max_batch_size, **kwargs
        )

    return wrap(fun) if fun is not None else wrap


class _FunctionUDF(UDF):
    def __init__(self, fun: Callable, **kwargs: Any):
        super().__init__(**kwargs)
        self._wrapped = fun
        functools.update_wrapper(self, fun)

    @property
    def __wrapped_fun__(self) -> Callable:
        assert self._wrapped is not None
        return self._wrapped


def udf(
    fun: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    deterministic: bool = False,
    propagate_none: bool = False,
    executor: Executor | None = None,
    cache_strategy: CacheStrategy | None = None,
    max_batch_size: int | None = None,
) -> Any:
    """``@pw.udf`` — turn a Python function (sync or async) into a column
    operator."""

    def wrap(f: Callable) -> _FunctionUDF:
        return _FunctionUDF(
            f,
            return_type=return_type,
            deterministic=deterministic,
            propagate_none=propagate_none,
            executor=executor,
            cache_strategy=cache_strategy,
            max_batch_size=max_batch_size,
        )

    if fun is not None:
        return wrap(fun)
    return wrap


# ---------------------------------------------------------------------------
# Engine entry: run a whole epoch's calls on one event loop


_loop_holder: dict[str, Any] = {}
_loop_lock = threading.Lock()


def _get_loop() -> asyncio.AbstractEventLoop:
    with _loop_lock:
        loop = _loop_holder.get("loop")
        if loop is None or loop.is_closed():
            loop = asyncio.new_event_loop()
            t = threading.Thread(target=loop.run_forever, daemon=True)
            t.start()
            _loop_holder["loop"] = loop
            _loop_holder["thread"] = t
        return loop


def run_async_batch(
    fun: Callable[..., Awaitable[Any]], calls: list[tuple[list, dict]]
) -> list[Any]:
    """Run ``fun`` over every call in the batch concurrently; exceptions in
    individual calls become Error values (reference async-UDF semantics)."""
    afun = coerce_async(fun)

    async def one(args: list, kwargs: dict) -> Any:
        try:
            return await afun(*args, **kwargs)
        except Exception as e:  # noqa: BLE001
            from pathway_tpu.internals.parse_graph import G

            G.log_error(f"async UDF {getattr(fun, '__name__', fun)!r} failed: {e!r}")
            return api.ERROR

    async def gather() -> list[Any]:
        return await asyncio.gather(*[one(a, k) for a, k in calls])

    loop = _get_loop()
    fut = asyncio.run_coroutine_threadsafe(gather(), loop)
    return fut.result()
