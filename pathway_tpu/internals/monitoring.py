"""Monitoring dashboard (reference ``internals/monitoring.py:56-232``:
rich-based live TUI driven by ProberStats — connectors table, operator
latency table, recent errors)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

__all__ = ["MonitoringLevel", "ProberStats", "collect_stats", "start_dashboard"]


class MonitoringLevel:
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"
    AUTO = "auto"


@dataclass
class ProberStats:
    """Per-run stats snapshot (reference ``ProberStats``,
    ``src/engine/graph.rs:554-566``)."""

    epoch: int = 0
    operators: int = 0
    errors: int = 0
    input_rows: int = 0
    output_rows: int = 0
    latency_ms: float | None = None
    connectors: dict[str, dict] = field(default_factory=dict)
    operator_probes: dict[int, dict] = field(default_factory=dict)
    #: resilience counters (connector.restarts/failures/breaker_open/
    #: dlq_events) from the telemetry layer
    resilience: dict[str, int] = field(default_factory=dict)
    #: connector names whose source gave up under on_failure="degrade" —
    #: their downstream tables are stale, not complete
    stale_connectors: list[str] = field(default_factory=list)
    #: exchange-overhead probe from cluster runs: collective counts plus
    #: pack/send/unpack/wait milliseconds (empty for single-worker runs)
    exchange: dict[str, Any] = field(default_factory=dict)


def collect_stats(sched: Any) -> ProberStats:
    from pathway_tpu.internals.telemetry import get_telemetry

    ctx = sched.ctx
    # race-free copy: worker threads register connectors concurrently
    connectors = sched.snapshot_connector_stats()
    probes = {k: dict(v) for k, v in ctx.stats.get("operators", {}).items()}
    resilience = {
        name: v
        for name, v in get_telemetry().snapshot_counters().items()
        if name.startswith("connector.")
    }
    return ProberStats(
        epoch=ctx.time,
        operators=len(sched.graph.nodes),
        errors=len(ctx.error_log),
        input_rows=sum(c.get("rows", 0) for c in connectors.values()),
        output_rows=sum(
            # OutputNodes consume rows and emit none: rows_in IS the
            # number of updates written (matched by node TYPE — sink
            # names vary: "bigquery_out", "kafka_out", ...)
            p["rows_in"]
            for p in probes.values()
            if p.get("kind") == "OutputNode"
        ),
        connectors=connectors,
        operator_probes=probes,
        resilience=resilience,
        stale_connectors=sorted(
            name for name, c in connectors.items() if c.get("stale")
        ),
        exchange=_exchange_stats(sched, ctx),
    )


def _exchange_stats(sched: Any, ctx: Any) -> dict[str, Any]:
    """Live exchange probe while a cluster run is active; the final
    snapshot stashed on the context afterwards."""
    cluster = getattr(sched, "_active_cluster", None)
    if cluster is not None:
        try:
            return cluster.exchange_stats()
        except Exception:
            pass
    return dict(ctx.stats.get("exchange", {}))


def start_dashboard(
    sched: Any, refresh_per_second: float = 4.0, level: str = MonitoringLevel.ALL
) -> threading.Thread:
    """Live rich dashboard (call before ``sched.run``); sections mirror
    the reference TUI: connector counters, per-operator latency probes
    (``level=ALL``), recent errors."""
    from rich.console import Group
    from rich.live import Live
    from rich.table import Table as RichTable

    def render() -> Group:
        stats = collect_stats(sched)
        parts: list[Any] = []

        head = RichTable(title="pathway_tpu")
        head.add_column("epoch")
        head.add_column("operators")
        head.add_column("errors")
        head.add_row(str(stats.epoch), str(stats.operators), str(stats.errors))
        parts.append(head)

        if stats.connectors:
            ct = RichTable(title="connectors")
            for col in ("input", "rows", "retractions", "commits", "restarts", "state"):
                ct.add_column(col)
            for name, c in sorted(stats.connectors.items()):
                if c.get("stale"):
                    state = "degraded"
                elif c.get("state") in ("failed", "drop"):
                    state = "failed"
                elif c.get("closed"):
                    state = "closed"
                else:
                    state = "live"
                ct.add_row(
                    name,
                    str(c.get("rows", 0)),
                    str(c.get("retractions", 0)),
                    str(c.get("commits", 0)),
                    str(c.get("restarts", 0)),
                    state,
                )
            parts.append(ct)

        if level == MonitoringLevel.ALL and stats.operator_probes:
            ot = RichTable(title="operators (top by total latency)")
            for col in ("operator", "rows in", "rows out", "total ms", "max ms"):
                ot.add_column(col)
            top = sorted(
                stats.operator_probes.values(),
                key=lambda p: -p["total_ms"],
            )[:12]
            for p in top:
                ot.add_row(
                    p["name"],
                    str(p["rows_in"]),
                    str(p["rows_out"]),
                    f"{p['total_ms']:.1f}",
                    f"{p['max_ms']:.2f}",
                )
            parts.append(ot)

        if sched.ctx.error_log:
            et = RichTable(title="recent errors")
            et.add_column("message")
            for e in sched.ctx.error_log[-5:]:
                et.add_row(str(e)[:120])
            parts.append(et)
        return Group(*parts)

    def loop() -> None:
        with Live(render(), refresh_per_second=refresh_per_second) as live:
            while not sched._stop.is_set():
                time.sleep(1.0 / refresh_per_second)
                live.update(render())

    t = threading.Thread(target=loop, daemon=True, name="pw_dashboard")
    t.start()
    return t
