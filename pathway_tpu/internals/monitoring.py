"""Monitoring dashboard (reference ``internals/monitoring.py:56-232``:
rich-based live TUI driven by ProberStats — connectors table, operator
latency table, recent errors)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

__all__ = [
    "LabeledLatencyProbe",
    "LatencyProbe",
    "MonitoringLevel",
    "ProberStats",
    "SERVING_STAGES",
    "STAGES",
    "collect_stats",
    "index_stats",
    "start_dashboard",
]

#: pipeline stages instrumented by the scheduler (ISSUE 4 tentpole c):
#:   ingest   — connector enqueue -> scheduler drain (queue residency)
#:   cut      — first buffered arrival -> epoch cut decision (batching hold)
#:   process  — one epoch of operator propagation (run_epoch wall time)
#:   exchange — cluster mailbox wait for peer frames (recv side)
#:   sink     — epoch cut -> update delivered to an output node
#:   e2e      — earliest enqueue in the epoch -> sink delivery
STAGES = ("ingest", "cut", "process", "exchange", "sink", "e2e")

#: serving-layer stages instrumented per tenant class (ISSUE 10): the
#: SLO scheduler's queue wait, then the co-scheduled pipeline stages
#:   serve_sched    — submit -> lane dispatch (weighted-fair queue wait)
#:   serve_embed    — submit -> query embedding done
#:   serve_retrieve — embedding done -> index hits resolved
#:   serve_generate — hits resolved -> answer produced
#:   serve_e2e      — submit -> answer delivered
SERVING_STAGES = (
    "serve_sched",
    "serve_embed",
    "serve_retrieve",
    "serve_generate",
    "serve_e2e",
)

_LAT_BUCKETS = 488  # mirrors kLatBuckets in native/pathway_native.cpp


def _lat_bucket(ns: int) -> int:
    """Python mirror of the native ``lat_bucket``: 16 exact unit buckets,
    then 8 sub-buckets per octave (~12% relative resolution)."""
    if ns < 16:
        return ns if ns > 0 else 0
    msb = ns.bit_length() - 1
    idx = 16 + (msb - 4) * 8 + ((ns >> (msb - 3)) & 7)
    return idx if idx < _LAT_BUCKETS else _LAT_BUCKETS - 1


def _lat_rep(idx: int) -> int:
    """Representative (midpoint) nanosecond value of bucket ``idx``."""
    if idx < 16:
        return idx
    msb = (idx - 16) // 8 + 4
    sub = (idx - 16) % 8
    lo = (1 << msb) | (sub << (msb - 3))
    return lo + (1 << (msb - 3)) // 2


class _PyHist:
    """Fallback histogram when the native module is unavailable; same
    bucket layout and snapshot contract as the C++ ``LatHist``."""

    __slots__ = ("buckets", "count", "sum_ns", "max_ns", "_lock")

    def __init__(self) -> None:
        self.buckets = [0] * _LAT_BUCKETS
        self.count = 0
        self.sum_ns = 0
        self.max_ns = 0
        self._lock = threading.Lock()

    def record(self, ns: int) -> None:
        if ns < 0:
            ns = 0
        with self._lock:
            self.buckets[_lat_bucket(ns)] += 1
            self.count += 1
            self.sum_ns += ns
            if ns > self.max_ns:
                self.max_ns = ns

    def snapshot(self) -> dict:
        with self._lock:
            buckets = list(self.buckets)
            count, sum_ns, max_ns = self.count, self.sum_ns, self.max_ns

        def q(target: float) -> float:
            cum = 0
            for i, c in enumerate(buckets):
                if not c:
                    continue
                cum += c
                if cum >= target:
                    return float(min(_lat_rep(i), max_ns))
            return float(max_ns)

        return {
            "count": count,
            "sum_ns": sum_ns,
            "max_ns": max_ns,
            "p50_ns": q(0.50 * count) if count else 0.0,
            "p95_ns": q(0.95 * count) if count else 0.0,
            "p99_ns": q(0.99 * count) if count else 0.0,
        }


class LatencyProbe:
    """Per-stage latency histograms for the streaming hot path.

    Recording is one native call per sample (atomic log-bucket increment,
    no lock, safe from any thread); snapshots reduce the buckets to
    p50/p95/p99 without ever resetting them, so the probe is streaming-
    safe — concurrent recording during a snapshot at worst lands a sample
    in the next read."""

    def __init__(self) -> None:
        native = None
        try:
            from pathway_tpu.internals import native as _native_mod

            native = _native_mod.load()
        except Exception:
            native = None
        if native is not None and hasattr(native, "hist_new"):
            self._native = native
            self._h = {s: native.hist_new() for s in STAGES}
            self.now_ns = native.monotonic_ns
            self._record = native.hist_record
        else:
            self._native = None
            self._h = {s: _PyHist() for s in STAGES}
            self.now_ns = time.monotonic_ns
            self._record = lambda h, ns: h.record(ns)

    def record(self, stage: str, ns: int) -> None:
        self._record(self._h[stage], ns)

    def record_since(self, stage: str, t0_ns: int) -> None:
        self._record(self._h[stage], self.now_ns() - t0_ns)

    def snapshot(self) -> dict[str, dict]:
        """``{stage: {count, p50_ms, p95_ms, p99_ms, max_ms, mean_ms}}``
        for every stage that has recorded at least one sample."""
        out: dict[str, dict] = {}
        for s in STAGES:
            h = self._h[s]
            d = self._native.hist_snapshot(h) if self._native else h.snapshot()
            n = d["count"]
            if not n:
                continue
            out[s] = {
                "count": n,
                "p50_ms": d["p50_ns"] / 1e6,
                "p95_ms": d["p95_ns"] / 1e6,
                "p99_ms": d["p99_ns"] / 1e6,
                "max_ms": d["max_ns"] / 1e6,
                "mean_ms": d["sum_ns"] / n / 1e6,
                # cumulative sum: the Prometheus _sum companion, so
                # rate(sum)/rate(count) average math works downstream
                "sum_ms": d["sum_ns"] / 1e6,
            }
        return out


class LabeledLatencyProbe:
    """Latency histograms keyed by ``(stage, label)`` — the serving
    layer's per-tenant-class variant of :class:`LatencyProbe`.

    Histograms are created on first record per key (tenant classes are
    not known up front) and share the native/py histogram substrate:
    recording is one lock-free bucket increment, snapshots never reset,
    so concurrent recording at worst lands a sample in the next read."""

    def __init__(self, stages: tuple[str, ...] = SERVING_STAGES):
        self._stages = tuple(stages)
        native = None
        try:
            from pathway_tpu.internals import native as _native_mod

            native = _native_mod.load()
        except Exception:
            native = None
        if native is not None and hasattr(native, "hist_new"):
            self._native = native
            self._new = native.hist_new
            self.now_ns = native.monotonic_ns
            self._rec = native.hist_record
        else:
            self._native = None
            self._new = _PyHist
            self.now_ns = time.monotonic_ns
            self._rec = lambda h, ns: h.record(ns)
        self._h: dict[tuple[str, str], Any] = {}
        self._lock = threading.Lock()

    def _hist(self, stage: str, label: str) -> Any:
        key = (stage, label)
        h = self._h.get(key)
        if h is None:
            with self._lock:
                h = self._h.get(key)
                if h is None:
                    h = self._h[key] = self._new()
        return h

    def record(self, stage: str, label: str, ns: int) -> None:
        self._rec(self._hist(stage, label), ns)

    def record_since(self, stage: str, label: str, t0_ns: int) -> None:
        self._rec(self._hist(stage, label), self.now_ns() - t0_ns)

    def snapshot(self) -> dict[str, dict[str, dict]]:
        """``{stage: {label: {count, p50_ms, p95_ms, p99_ms, max_ms,
        mean_ms}}}`` for every key with at least one sample."""
        with self._lock:
            keys = list(self._h.items())
        out: dict[str, dict[str, dict]] = {}
        for (stage, label), h in keys:
            d = self._native.hist_snapshot(h) if self._native else h.snapshot()
            n = d["count"]
            if not n:
                continue
            out.setdefault(stage, {})[label] = {
                "count": n,
                "p50_ms": d["p50_ns"] / 1e6,
                "p95_ms": d["p95_ns"] / 1e6,
                "p99_ms": d["p99_ns"] / 1e6,
                "max_ms": d["max_ns"] / 1e6,
                "mean_ms": d["sum_ns"] / n / 1e6,
                "sum_ms": d["sum_ns"] / 1e6,
            }
        return out


class MonitoringLevel:
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"
    AUTO = "auto"


@dataclass
class ProberStats:
    """Per-run stats snapshot (reference ``ProberStats``,
    ``src/engine/graph.rs:554-566``)."""

    epoch: int = 0
    operators: int = 0
    errors: int = 0
    input_rows: int = 0
    output_rows: int = 0
    latency_ms: float | None = None
    connectors: dict[str, dict] = field(default_factory=dict)
    operator_probes: dict[int, dict] = field(default_factory=dict)
    #: resilience counters (connector.restarts/failures/breaker_open/
    #: dlq_events) from the telemetry layer
    resilience: dict[str, int] = field(default_factory=dict)
    #: connector names whose source gave up under on_failure="degrade" —
    #: their downstream tables are stale, not complete
    stale_connectors: list[str] = field(default_factory=list)
    #: exchange-overhead probe from cluster runs: collective counts plus
    #: pack/send/unpack/wait milliseconds (empty for single-worker runs)
    exchange: dict[str, Any] = field(default_factory=dict)
    #: per-stage streaming latency histogram snapshot
    #: ({stage: {count, p50_ms, p95_ms, p99_ms, max_ms, mean_ms}})
    latency: dict[str, Any] = field(default_factory=dict)
    #: pre-flight static-analyzer finding counts by severity
    #: ({"error": n, "warning": n, "info": n}) — what this deployed
    #: graph was warned about before it started
    analysis: dict[str, int] = field(default_factory=dict)
    #: coordinated-checkpoint snapshot ({epoch, age_seconds, bytes,
    #: count, wall_at}; empty when persistence is off) plus the cluster
    #: supervisor's restart generation under "worker_restarts"
    checkpoint: dict[str, Any] = field(default_factory=dict)
    #: serving-layer snapshot (pathway_tpu.serving.serving_snapshot():
    #: admission counters per tenant class, scheduler lane stats,
    #: co-scheduler overlap, per-(stage, tenant_class) latency); empty
    #: when no serving component is live in this process
    serving: dict[str, Any] = field(default_factory=dict)
    #: capacity cross-validation per stateful operator
    #: ({operator: {"estimated": bytes, "measured": bytes, "growth"}};
    #: estimated from analysis/memory.py over the executing plan view,
    #: measured sampled by the scheduler into the operator probes)
    memory: dict[str, Any] = field(default_factory=dict)
    #: backpressure snapshot ({"ingest": per-source buffer occupancy +
    #: shed counters, "exchange": per-peer credit backlog, "serving":
    #: brownout level + sheds}; sections empty where not applicable)
    pressure: dict[str, Any] = field(default_factory=dict)
    #: device-plane join: live jit-compile / H2D / D2H counters
    #: (internals/device_counters.py) next to the static device-safety
    #: prediction (analysis/device.py) — steady state must hold
    #: jit_compiles flat once predicted_recompile_sites == 0
    device: dict[str, Any] = field(default_factory=dict)


def memory_stats(sched: Any) -> dict[str, Any]:
    """Estimated vs measured state bytes, joined per operator label."""
    out: dict[str, Any] = {}
    est = getattr(sched, "memory_estimate", None)
    if est is not None and getattr(est, "operators", None):
        for o in est.operators:
            out[f"{o.name}#{o.node_id}"] = {
                "estimated": o.total_bytes,
                "growth": o.growth,
                "measured": 0,
            }
    try:
        probes = sched.snapshot_operator_probes()
    except Exception:
        probes = {}
    for p in probes.values():
        measured = p.get("state_bytes", 0)
        if not measured:
            continue
        entry = out.setdefault(
            p["name"], {"estimated": 0, "growth": None, "measured": 0}
        )
        entry["measured"] = measured
    return out


def collect_stats(sched: Any) -> ProberStats:
    from pathway_tpu.internals.telemetry import get_telemetry

    ctx = sched.ctx
    # race-free copy: worker threads register connectors concurrently
    connectors = sched.snapshot_connector_stats()
    probes = {k: dict(v) for k, v in ctx.stats.get("operators", {}).items()}
    resilience = {
        name: v
        for name, v in get_telemetry().snapshot_counters().items()
        if name.startswith("connector.")
    }
    return ProberStats(
        epoch=ctx.time,
        operators=len(sched.graph.nodes),
        errors=len(ctx.error_log),
        input_rows=sum(c.get("rows", 0) for c in connectors.values()),
        output_rows=sum(
            # OutputNodes consume rows and emit none: rows_in IS the
            # number of updates written (matched by node TYPE — sink
            # names vary: "bigquery_out", "kafka_out", ...)
            p["rows_in"]
            for p in probes.values()
            if p.get("kind") == "OutputNode"
        ),
        connectors=connectors,
        operator_probes=probes,
        resilience=resilience,
        stale_connectors=sorted(
            name for name, c in connectors.items() if c.get("stale")
        ),
        exchange=_exchange_stats(sched, ctx),
        latency=latency_stats(sched),
        analysis=dict(getattr(sched, "analysis_findings", {}) or {}),
        checkpoint=checkpoint_stats(sched),
        serving=serving_stats(),
        memory=memory_stats(sched),
        pressure=pressure_stats(sched),
        device=device_stats(),
    )


def pressure_stats(sched: Any) -> dict[str, Any]:
    """Backpressure snapshot across the three bounded hops: connector
    ingest buffer (per source), exchange credit windows (per peer), and
    serving brownout.  Every section degrades to absent/empty when the
    layer is not running — the schema is stable either way."""
    out: dict[str, Any] = {}
    ip = getattr(sched, "ingest_pressure", None)
    if ip is not None:
        try:
            out["ingest"] = ip()
        except Exception:
            pass
    cluster = getattr(sched, "_active_cluster", None)
    if cluster is not None:
        try:
            ex = cluster.exchange_pressure()
            if ex:
                out["exchange"] = ex
        except Exception:
            pass
    srv = serving_stats().get("admission")
    if srv:
        out["serving"] = {
            "pressure_level": srv.get("pressure_level", 0.0),
            "brownout_shed_total": srv.get("brownout_shed_total", {}),
            "shed_total": srv.get("shed_total", {}),
        }
    return out


def device_stats() -> dict[str, Any]:
    """Predicted-vs-observed device-plane join.  ``counters`` is the
    live side (jit compiles, H2D/D2H bytes — zeros until a device module
    runs); ``static`` is the analyzer's prediction over the device
    source.  Keyed off ``sys.modules`` like :func:`serving_stats`: a
    host-only process that never imported the device layer pays neither
    a jax import nor an AST sweep on every scrape."""
    import sys

    if sys.modules.get("pathway_tpu.internals.device_counters") is None:
        return {}
    out: dict[str, Any] = {}
    try:
        from pathway_tpu.internals import device_counters

        out["counters"] = device_counters.snapshot()
    except Exception:
        return {}
    try:
        from pathway_tpu.analysis.device import device_profile

        out["static"] = device_profile()
    except Exception:
        pass
    return out


def serving_stats() -> dict[str, Any]:
    """Process-wide serving-layer snapshot — admission/scheduler/latency
    aggregates from ``pathway_tpu.serving``, plus the ``"failover"``
    section (shard health, degraded-response counters, and the
    failover-seconds histogram) when a
    :class:`~pathway_tpu.serving.failover.PartitionedIndex` is live.
    Deliberately keyed off ``sys.modules`` so a process that never
    imported the serving layer pays nothing for this on every scrape."""
    import sys

    mod = sys.modules.get("pathway_tpu.serving")
    if mod is None:
        return {}
    try:
        return mod.serving_snapshot()
    except Exception:
        return {}


def checkpoint_stats(sched: Any) -> dict[str, Any]:
    """Coordinated-checkpoint health snapshot: last checkpointed epoch,
    its age, size, and the supervisor restart generation.  Empty dict
    when persistence is not attached (nothing to report)."""
    hooks = getattr(sched, "persistence", None)
    snap_fn = getattr(hooks, "checkpoint_snapshot", None)
    if snap_fn is None:
        return {}
    try:
        snap = dict(snap_fn())
    except Exception:
        return {}
    snap["worker_restarts"] = int(getattr(sched, "worker_restarts", 0) or 0)
    return snap


def index_stats(sched: Any) -> dict[str, Any]:
    """Live external-index maintenance snapshot, one entry per index
    operator: delta segment size, tombstones, merges, main-segment size
    (see ``stdlib/indexing/segments.py``).  Empty dict when the graph
    has no index operators (or their adapters predate ``stats()``)."""
    graph = getattr(sched, "graph", None)
    if graph is None:
        return {}
    out: dict[str, Any] = {}
    for node in getattr(graph, "nodes", []):
        stats_fn = getattr(getattr(node, "adapter", None), "stats", None)
        if stats_fn is None:
            continue
        try:
            out[f"{node.name}#{node.id}"] = dict(stats_fn())
        except Exception:
            continue
    return out


def latency_stats(sched: Any) -> dict[str, Any]:
    """Per-stage latency snapshot from the scheduler's probe (empty when
    the scheduler has not recorded any samples yet)."""
    probe = getattr(sched, "latency", None)
    if probe is None:
        return {}
    try:
        return probe.snapshot()
    except Exception:
        return {}


def _exchange_stats(sched: Any, ctx: Any) -> dict[str, Any]:
    """Live exchange probe while a cluster run is active; the final
    snapshot stashed on the context afterwards."""
    cluster = getattr(sched, "_active_cluster", None)
    if cluster is not None:
        try:
            return cluster.exchange_stats()
        except Exception:
            pass
    return dict(ctx.stats.get("exchange", {}))


def start_dashboard(
    sched: Any, refresh_per_second: float = 4.0, level: str = MonitoringLevel.ALL
) -> threading.Thread:
    """Live rich dashboard (call before ``sched.run``); sections mirror
    the reference TUI: connector counters, per-operator latency probes
    (``level=ALL``), recent errors."""
    from rich.console import Group
    from rich.live import Live
    from rich.table import Table as RichTable

    def render() -> Group:
        stats = collect_stats(sched)
        parts: list[Any] = []

        head = RichTable(title="pathway_tpu")
        head.add_column("epoch")
        head.add_column("operators")
        head.add_column("errors")
        head.add_row(str(stats.epoch), str(stats.operators), str(stats.errors))
        parts.append(head)

        if stats.connectors:
            ct = RichTable(title="connectors")
            for col in ("input", "rows", "retractions", "commits", "restarts", "state"):
                ct.add_column(col)
            for name, c in sorted(stats.connectors.items()):
                if c.get("stale"):
                    state = "degraded"
                elif c.get("state") in ("failed", "drop"):
                    state = "failed"
                elif c.get("closed"):
                    state = "closed"
                else:
                    state = "live"
                ct.add_row(
                    name,
                    str(c.get("rows", 0)),
                    str(c.get("retractions", 0)),
                    str(c.get("commits", 0)),
                    str(c.get("restarts", 0)),
                    state,
                )
            parts.append(ct)

        if level == MonitoringLevel.ALL and stats.operator_probes:
            ot = RichTable(title="operators (top by total latency)")
            for col in ("operator", "rows in", "rows out", "total ms", "max ms"):
                ot.add_column(col)
            top = sorted(
                stats.operator_probes.values(),
                key=lambda p: -p["total_ms"],
            )[:12]
            for p in top:
                ot.add_row(
                    p["name"],
                    str(p["rows_in"]),
                    str(p["rows_out"]),
                    f"{p['total_ms']:.1f}",
                    f"{p['max_ms']:.2f}",
                )
            parts.append(ot)

        if sched.ctx.error_log:
            et = RichTable(title="recent errors")
            et.add_column("message")
            for e in sched.ctx.error_log[-5:]:
                et.add_row(str(e)[:120])
            parts.append(et)
        return Group(*parts)

    def loop() -> None:
        with Live(render(), refresh_per_second=refresh_per_second) as live:
            while not sched._stop.is_set():
                time.sleep(1.0 / refresh_per_second)
                live.update(render())

    t = threading.Thread(target=loop, daemon=True, name="pw_dashboard")
    t.start()
    return t
