"""Monitoring dashboard (reference ``internals/monitoring.py:56-232``:
rich-based live TUI driven by ProberStats)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

__all__ = ["MonitoringLevel", "ProberStats", "start_dashboard"]


class MonitoringLevel:
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"
    AUTO = "auto"


@dataclass
class ProberStats:
    """Per-run stats snapshot (reference ``ProberStats``,
    ``src/engine/graph.rs:554-566``)."""

    epoch: int = 0
    operators: int = 0
    errors: int = 0
    input_rows: int = 0
    output_rows: int = 0
    latency_ms: float | None = None
    connectors: dict[str, dict] = field(default_factory=dict)


def collect_stats(sched: Any) -> ProberStats:
    ctx = sched.ctx
    return ProberStats(
        epoch=ctx.time,
        operators=len(sched.graph.nodes),
        errors=len(ctx.error_log),
    )


def start_dashboard(sched: Any, refresh_per_second: float = 4.0) -> threading.Thread:
    """Live rich dashboard on the terminal (call before ``sched.run``)."""
    from rich.live import Live
    from rich.table import Table as RichTable

    def render() -> RichTable:
        stats = collect_stats(sched)
        t = RichTable(title="pathway_tpu")
        t.add_column("metric")
        t.add_column("value")
        t.add_row("epoch", str(stats.epoch))
        t.add_row("operators", str(stats.operators))
        t.add_row("errors", str(stats.errors))
        return t

    def loop() -> None:
        with Live(render(), refresh_per_second=refresh_per_second) as live:
            while not sched._stop.is_set():
                time.sleep(1.0 / refresh_per_second)
                live.update(render())

    t = threading.Thread(target=loop, daemon=True, name="pw_dashboard")
    t.start()
    return t
