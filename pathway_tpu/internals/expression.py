"""Column expression AST.

Capability parity with reference ``python/pathway/internals/expression.py``
(1179 LoC) + ``src/engine/expression.rs``: lazily-built expression trees over
table columns, supporting arithmetic/comparison/boolean operators, casts,
apply (sync & async UDF), if_else/coalesce/require, pointers, tuples,
indexing, and method namespaces (``.dt``, ``.str``, ``.num``).

Unlike the reference (which interprets a typed Rust enum row-by-row), our
engine *compiles* each expression tree into a Python closure over the row
tuple once per operator build — and the numeric plane bypasses rowwise eval
entirely via batched jitted executors.
"""

from __future__ import annotations

import math
from typing import TYPE_CHECKING, Any, Callable, Iterable, Mapping

from pathway_tpu.internals import api
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import keys

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class ColumnExpression:
    """Base class of all expressions."""

    _dtype: dt.DType = dt.ANY

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("+", self, _wrap(other))

    def __radd__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("+", _wrap(other), self)

    def __sub__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("-", self, _wrap(other))

    def __rsub__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("-", _wrap(other), self)

    def __mul__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("*", self, _wrap(other))

    def __rmul__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("*", _wrap(other), self)

    def __truediv__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("/", self, _wrap(other))

    def __rtruediv__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("/", _wrap(other), self)

    def __floordiv__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("//", self, _wrap(other))

    def __rfloordiv__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("//", _wrap(other), self)

    def __mod__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("%", self, _wrap(other))

    def __rmod__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("%", _wrap(other), self)

    def __pow__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("**", self, _wrap(other))

    def __rpow__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("**", _wrap(other), self)

    def __matmul__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("@", self, _wrap(other))

    def __neg__(self) -> "ColumnExpression":
        return UnaryExpression("-", self)

    def __abs__(self) -> "ColumnExpression":
        return ApplyExpression(abs, dt.ANY, (self,), {})

    # -- comparison ---------------------------------------------------------
    def __eq__(self, other: Any) -> "ColumnExpression":  # type: ignore[override]
        return BinaryExpression("==", self, _wrap(other))

    def __ne__(self, other: Any) -> "ColumnExpression":  # type: ignore[override]
        return BinaryExpression("!=", self, _wrap(other))

    def __lt__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("<", self, _wrap(other))

    def __le__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("<=", self, _wrap(other))

    def __gt__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression(">", self, _wrap(other))

    def __ge__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression(">=", self, _wrap(other))

    # -- boolean ------------------------------------------------------------
    def __and__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("&", self, _wrap(other))

    def __rand__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("&", _wrap(other), self)

    def __or__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("|", self, _wrap(other))

    def __ror__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("|", _wrap(other), self)

    def __xor__(self, other: Any) -> "ColumnExpression":
        return BinaryExpression("^", self, _wrap(other))

    def __invert__(self) -> "ColumnExpression":
        return UnaryExpression("~", self)

    def __bool__(self) -> bool:
        raise TypeError(
            "ColumnExpression is lazy and cannot be used in a boolean context; "
            "use & | ~ instead of and/or/not, and .is_none() instead of `is None`."
        )

    def __hash__(self) -> int:
        return id(self)

    # -- misc ---------------------------------------------------------------
    def __getitem__(self, item: Any) -> "ColumnExpression":
        return GetExpression(self, _wrap(item), check_if_exists=False)

    def get(self, item: Any, default: Any = None) -> "ColumnExpression":
        return GetExpression(self, _wrap(item), default=_wrap(default), check_if_exists=True)

    def is_none(self) -> "ColumnExpression":
        return IsNoneExpression(self)

    def is_not_none(self) -> "ColumnExpression":
        return UnaryExpression("~", IsNoneExpression(self))

    def to_string(self) -> "ColumnExpression":
        return ApplyExpression(
            lambda x: "" if x is None else str(x), dt.STR, (self,), {}
        )

    @property
    def dt(self) -> Any:
        from pathway_tpu.internals.expressions import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def str(self) -> Any:
        from pathway_tpu.internals.expressions import StringNamespace

        return StringNamespace(self)

    @property
    def num(self) -> Any:
        from pathway_tpu.internals.expressions import NumericalNamespace

        return NumericalNamespace(self)

    # -- infrastructure -----------------------------------------------------
    def _children(self) -> Iterable["ColumnExpression"]:
        return ()

    def _substitute(self, mapping: Mapping[Any, "Table"]) -> "ColumnExpression":
        """Replace this/left/right placeholders with concrete tables."""
        return self._rebuild([c._substitute(mapping) for c in self._children()])

    def _rebuild(self, children: list["ColumnExpression"]) -> "ColumnExpression":
        return self

    def _references(self) -> list["ColumnReference"]:
        # NOTE: keyed dict, not a set — ColumnReference overloads __eq__ to
        # build lazy expressions, so set/``in`` operations would call it.
        out: dict[tuple, ColumnReference] = {}
        stack: list[ColumnExpression] = [self]
        while stack:
            e = stack.pop()
            if isinstance(e, ColumnReference):
                out.setdefault((id(e._table), e._name), e)
            stack.extend(e._children())
        return list(out.values())

    def _compile(self, resolver: Callable[["ColumnReference"], Callable[[tuple], Any]]) -> Callable[[tuple], Any]:
        """Compile to a closure ``row -> value``; ``resolver`` maps column
        references to accessors."""
        raise NotImplementedError(type(self))

    @property
    def _deps_tables(self) -> set[Any]:
        return {r._table for r in self._references()}


def _wrap(value: Any) -> ColumnExpression:
    if isinstance(value, ColumnExpression):
        return value
    return ConstExpression(value)


def smart_name(expr: ColumnExpression) -> str | None:
    if isinstance(expr, ColumnReference):
        return expr._name
    return None


class ConstExpression(ColumnExpression):
    def __init__(self, value: Any):
        self._value = value
        self._dtype = dt.dtype_of_value(value)

    def __repr__(self) -> str:
        return f"Const({self._value!r})"

    def _compile(self, resolver):
        v = self._value
        return lambda row: v


class ColumnReference(ColumnExpression):
    """``table.colname`` / ``pw.this.colname``."""

    def __init__(self, table: Any, name: str):
        self._table = table
        self._name = name

    @property
    def _dtype(self) -> dt.DType:  # type: ignore[override]
        if self._name == "id":
            return dt.POINTER
        dtypes = getattr(self._table, "_dtypes", None)
        if dtypes is not None and self._name in dtypes:
            return dtypes[self._name]
        return dt.ANY

    def __repr__(self) -> str:
        return f"<{getattr(self._table, '_name', self._table)}.{self._name}>"

    @property
    def table(self) -> Any:
        return self._table

    @property
    def name(self) -> str:
        return self._name

    def _substitute(self, mapping):
        from pathway_tpu.internals.thisclass import ThisMetaclass

        if isinstance(self._table, ThisMetaclass):
            target = mapping.get(self._table)
            if target is None:
                raise ValueError(f"Cannot resolve placeholder {self._table}")
            if self._name == "id":
                return target.id
            return ColumnReference(target, self._name)
        return self

    def _compile(self, resolver):
        return resolver(self)

    def __eq__(self, other: Any) -> ColumnExpression:  # type: ignore[override]
        return BinaryExpression("==", self, _wrap(other))

    def __hash__(self) -> int:
        return hash((id(self._table), self._name))


_BIN_OPS: dict[str, Callable[[Any, Any], Any]] = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: _true_div(a, b),
    "//": lambda a, b: a // b,
    "%": lambda a, b: a % b,
    "**": lambda a, b: a**b,
    "@": lambda a, b: a @ b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "&": lambda a, b: a & b,
    "|": lambda a, b: a | b,
    "^": lambda a, b: a ^ b,
}


def _true_div(a: Any, b: Any) -> Any:
    if isinstance(a, int) and isinstance(b, int) and not isinstance(a, bool) and not isinstance(b, bool):
        if b == 0:
            raise ZeroDivisionError("division by zero")
        return a / b
    return a / b

_CMP_OPS = {"==", "!=", "<", "<=", ">", ">=" }


class BinaryExpression(ColumnExpression):
    def __init__(self, op: str, left: ColumnExpression, right: ColumnExpression):
        from pathway_tpu.internals.type_interpreter import binary_result_dtype

        self._op = op
        self._left = left
        self._right = right
        # build-time operator typing (reference type_interpreter.py):
        # raises TypeInterpreterError on e.g. STR + INT before the graph runs
        self._dtype = binary_result_dtype(op, left._dtype, right._dtype)

    def __repr__(self) -> str:
        return f"({self._left!r} {self._op} {self._right!r})"

    def _children(self):
        return (self._left, self._right)

    def _rebuild(self, children):
        return BinaryExpression(self._op, children[0], children[1])

    def _compile(self, resolver):
        f = _BIN_OPS[self._op]
        lc = self._left._compile(resolver)
        rc = self._right._compile(resolver)
        op = self._op

        def run(row: tuple) -> Any:
            a = lc(row)
            b = rc(row)
            if a is api.ERROR or b is api.ERROR:
                return api.ERROR
            try:
                return f(a, b)
            except TypeError:
                if a is None or b is None:
                    if op == "==":
                        return a is b
                    if op == "!=":
                        return a is not b
                    return None
                return api.ERROR
            except (ZeroDivisionError, ValueError, OverflowError):
                return api.ERROR

        return run


class UnaryExpression(ColumnExpression):
    _OPS: dict[str, Callable[[Any], Any]] = {"-": lambda a: -a, "~": lambda a: (not a) if isinstance(a, bool) else ~a}

    def __init__(self, op: str, operand: ColumnExpression):
        from pathway_tpu.internals.type_interpreter import unary_result_dtype

        self._op = op
        self._operand = operand
        self._dtype = unary_result_dtype(op, operand._dtype)

    def _children(self):
        return (self._operand,)

    def _rebuild(self, children):
        return UnaryExpression(self._op, children[0])

    def _compile(self, resolver):
        f = self._OPS[self._op]
        c = self._operand._compile(resolver)

        def run(row: tuple) -> Any:
            v = c(row)
            if v is api.ERROR:
                return api.ERROR
            if v is None:
                return None
            try:
                return f(v)
            except TypeError:
                return api.ERROR

        return run


class IsNoneExpression(ColumnExpression):
    _dtype = dt.BOOL

    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return IsNoneExpression(children[0])

    def _compile(self, resolver):
        c = self._expr._compile(resolver)
        return lambda row: (lambda v: api.ERROR if v is api.ERROR else v is None)(c(row))


class IfElseExpression(ColumnExpression):
    """``pw.if_else(cond, a, b)``."""

    def __init__(self, cond: ColumnExpression, then: ColumnExpression, else_: ColumnExpression):
        self._cond = cond
        self._then = then
        self._else = else_
        self._dtype = dt.lub(then._dtype, else_._dtype)

    def _children(self):
        return (self._cond, self._then, self._else)

    def _rebuild(self, children):
        return IfElseExpression(*children)

    def _compile(self, resolver):
        cc = self._cond._compile(resolver)
        tc = self._then._compile(resolver)
        ec = self._else._compile(resolver)

        def run(row: tuple) -> Any:
            c = cc(row)
            if c is api.ERROR:
                return api.ERROR
            return tc(row) if c else ec(row)

        return run


class CoalesceExpression(ColumnExpression):
    def __init__(self, *args: ColumnExpression):
        self._args = args
        non_none = [a._dtype for a in args]
        self._dtype = dt.lub_many(*non_none) if non_none else dt.ANY

    def _children(self):
        return self._args

    def _rebuild(self, children):
        return CoalesceExpression(*children)

    def _compile(self, resolver):
        cs = [a._compile(resolver) for a in self._args]

        def run(row: tuple) -> Any:
            for c in cs:
                v = c(row)
                if v is not None:
                    return v
            return None

        return run


class RequireExpression(ColumnExpression):
    """``pw.require(value, *deps)`` — None if any dep is None."""

    def __init__(self, value: ColumnExpression, *deps: ColumnExpression):
        self._value = value
        self._deps = deps
        self._dtype = dt.Optional(value._dtype)

    def _children(self):
        return (self._value, *self._deps)

    def _rebuild(self, children):
        return RequireExpression(children[0], *children[1:])

    def _compile(self, resolver):
        vc = self._value._compile(resolver)
        dcs = [d._compile(resolver) for d in self._deps]

        def run(row: tuple) -> Any:
            for c in dcs:
                if c(row) is None:
                    return None
            return vc(row)

        return run


class ApplyExpression(ColumnExpression):
    """``pw.apply(f, *args)`` — a Python UDF evaluated row-wise (reference
    ``eval_apply`` ``internals/graph_runner/expression_evaluator.py:404``)."""

    def __init__(
        self,
        fun: Callable,
        return_type: Any,
        args: tuple[ColumnExpression, ...],
        kwargs: Mapping[str, ColumnExpression],
        *,
        propagate_none: bool = False,
        deterministic: bool = True,
    ):
        self._fun = fun
        self._args = tuple(_wrap(a) for a in args)
        self._kwargs = {k: _wrap(v) for k, v in kwargs.items()}
        self._dtype = dt.wrap(return_type)
        self._propagate_none = propagate_none
        self._deterministic = deterministic
        from pathway_tpu.engine.graph import _user_trace

        #: user file:line of the pw.apply(...) call — attached to runtime
        #: error-log entries (reference internals/trace.py)
        self._trace = _user_trace()

    def _children(self):
        return (*self._args, *self._kwargs.values())

    def _rebuild(self, children):
        n = len(self._args)
        return type(self)(
            self._fun,
            self._dtype,
            tuple(children[:n]),
            dict(zip(self._kwargs.keys(), children[n:])),
            propagate_none=self._propagate_none,
            deterministic=self._deterministic,
        )

    def _compile(self, resolver):
        acs = [a._compile(resolver) for a in self._args]
        kcs = {k: v._compile(resolver) for k, v in self._kwargs.items()}
        fun = self._fun
        propagate_none = self._propagate_none
        trace = self._trace

        def run(row: tuple) -> Any:
            args = [c(row) for c in acs]
            kwargs = {k: c(row) for k, c in kcs.items()}
            if any(a is api.ERROR for a in args) or any(v is api.ERROR for v in kwargs.values()):
                return api.ERROR
            if propagate_none and (any(a is None for a in args) or any(v is None for v in kwargs.values())):
                return None
            try:
                return fun(*args, **kwargs)
            except Exception as e:
                from pathway_tpu.internals.parse_graph import G

                G.log_error(
                    f"apply({getattr(fun, '__name__', fun)!r}) failed: {e!r}",
                    trace=trace,
                )
                return api.ERROR

        return run


class AsyncApplyExpression(ApplyExpression):
    """``pw.apply_async`` — batched per-timestamp via the async executor
    (reference ``map_named_async``, ``src/engine/dataflow/operators.rs:269``)."""


class FullyAsyncApplyExpression(ApplyExpression):
    """``pw.apply_with_full_async`` — results arrive at later timestamps,
    column dtype becomes Future (reference fully-async UDF executor)."""


class BatchApplyExpression(AsyncApplyExpression):
    """Epoch-batched UDF: ``_fun`` receives one LIST per argument (all the
    epoch's rows at once) and returns an aligned list of results.  This is
    the host contract for jitted TPU executors — one compiled call per
    epoch instead of the reference's per-row torch calls
    (``xpacks/llm/embedders.py:270-327``)."""


class CastExpression(ColumnExpression):
    def __init__(self, target: dt.DType, expr: ColumnExpression):
        self._target = target
        self._expr = expr
        self._dtype = target

    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return CastExpression(self._target, children[0])

    def _compile(self, resolver):
        c = self._expr._compile(resolver)
        target = self._target.strip_optional()

        def run(row: tuple) -> Any:
            v = c(row)
            if v is api.ERROR or v is None:
                return v
            try:
                if target == dt.INT:
                    return int(v)
                if target == dt.FLOAT:
                    return float(v)
                if target == dt.BOOL:
                    return bool(v)
                if target == dt.STR:
                    return str(v)
                return v
            except (ValueError, TypeError):
                return api.ERROR

        return run


class ConvertExpression(ColumnExpression):
    """Json→scalar conversion: ``.as_int()`` etc."""

    def __init__(self, target: dt.DType, expr: ColumnExpression, *, unwrap: bool = False):
        self._target = target
        self._expr = expr
        self._unwrap = unwrap
        self._dtype = target if unwrap else dt.Optional(target)

    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return ConvertExpression(self._target, children[0], unwrap=self._unwrap)

    def _compile(self, resolver):
        from pathway_tpu.internals.json import Json

        c = self._expr._compile(resolver)
        target = self._target.strip_optional()
        unwrap = self._unwrap

        def run(row: tuple) -> Any:
            v = c(row)
            if v is api.ERROR:
                return api.ERROR
            if isinstance(v, Json):
                v = v.value
            if v is None:
                return api.ERROR if unwrap else None
            try:
                if target == dt.INT:
                    if isinstance(v, bool) or not isinstance(v, (int, float)):
                        return api.ERROR
                    return int(v)
                if target == dt.FLOAT:
                    if isinstance(v, bool) or not isinstance(v, (int, float)):
                        return api.ERROR
                    return float(v)
                if target == dt.BOOL:
                    return v if isinstance(v, bool) else api.ERROR
                if target == dt.STR:
                    return v if isinstance(v, str) else api.ERROR
                return v
            except (ValueError, TypeError):
                return api.ERROR

        return run


class PointerExpression(ColumnExpression):
    """``table.pointer_from(*cols)``."""

    _dtype = dt.POINTER

    def __init__(self, table: Any, *args: ColumnExpression, instance: ColumnExpression | None = None, optional: bool = False):
        self._ptr_table = table
        self._args = tuple(_wrap(a) for a in args)
        self._instance = instance
        self._optional = optional

    def _children(self):
        return self._args if self._instance is None else (*self._args, self._instance)

    def _rebuild(self, children):
        if self._instance is None:
            return PointerExpression(self._ptr_table, *children, optional=self._optional)
        return PointerExpression(
            self._ptr_table, *children[:-1], instance=children[-1], optional=self._optional
        )

    def _substitute(self, mapping):
        from pathway_tpu.internals.thisclass import ThisMetaclass

        table = self._ptr_table
        if isinstance(table, ThisMetaclass):
            table = mapping.get(table, table)
        children = [c._substitute(mapping) for c in self._args]
        inst = self._instance._substitute(mapping) if self._instance is not None else None
        return PointerExpression(table, *children, instance=inst, optional=self._optional)

    def _compile(self, resolver):
        acs = [a._compile(resolver) for a in self._args]
        optional = self._optional

        def run(row: tuple) -> Any:
            vals = [c(row) for c in acs]
            if optional and any(v is None for v in vals):
                return None
            return keys.ref_scalar(*vals)

        return run


class MakeTupleExpression(ColumnExpression):
    def __init__(self, *args: ColumnExpression):
        self._args = tuple(_wrap(a) for a in args)
        self._dtype = dt.Tuple(*[a._dtype for a in self._args])

    def _children(self):
        return self._args

    def _rebuild(self, children):
        return MakeTupleExpression(*children)

    def _compile(self, resolver):
        acs = [a._compile(resolver) for a in self._args]
        return lambda row: tuple(c(row) for c in acs)


class GetExpression(ColumnExpression):
    def __init__(
        self,
        obj: ColumnExpression,
        index: ColumnExpression,
        default: ColumnExpression | None = None,
        *,
        check_if_exists: bool,
    ):
        self._obj = obj
        self._index = index
        self._default = default if default is not None else ConstExpression(None)
        self._check = check_if_exists
        base = obj._dtype.strip_optional()
        if base == dt.JSON:
            self._dtype = dt.Optional(dt.JSON) if check_if_exists else dt.JSON
        else:
            self._dtype = dt.ANY

    def _children(self):
        return (self._obj, self._index, self._default)

    def _rebuild(self, children):
        return GetExpression(children[0], children[1], children[2], check_if_exists=self._check)

    def _compile(self, resolver):
        from pathway_tpu.internals.json import Json

        oc = self._obj._compile(resolver)
        ic = self._index._compile(resolver)
        dc = self._default._compile(resolver)
        check = self._check

        def run(row: tuple) -> Any:
            obj = oc(row)
            idx = ic(row)
            if obj is api.ERROR or idx is api.ERROR:
                return api.ERROR
            try:
                if isinstance(obj, Json):
                    inner = obj.value
                    v = inner[idx]
                    return v if isinstance(v, Json) else Json(v)
                return obj[idx]
            except (KeyError, IndexError, TypeError):
                return dc(row) if check else api.ERROR

        return run


class UnwrapExpression(ColumnExpression):
    def __init__(self, expr: ColumnExpression):
        self._expr = expr
        self._dtype = expr._dtype.strip_optional()

    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return UnwrapExpression(children[0])

    def _compile(self, resolver):
        c = self._expr._compile(resolver)

        def run(row: tuple) -> Any:
            v = c(row)
            return api.ERROR if v is None else v

        return run


class FillErrorExpression(ColumnExpression):
    def __init__(self, expr: ColumnExpression, replacement: ColumnExpression):
        self._expr = expr
        self._replacement = _wrap(replacement)
        self._dtype = dt.lub(expr._dtype, self._replacement._dtype)

    def _children(self):
        return (self._expr, self._replacement)

    def _rebuild(self, children):
        return FillErrorExpression(children[0], children[1])

    def _compile(self, resolver):
        c = self._expr._compile(resolver)
        rc = self._replacement._compile(resolver)

        def run(row: tuple) -> Any:
            v = c(row)
            return rc(row) if v is api.ERROR else v

        return run


class MethodCallExpression(ColumnExpression):
    """Namespace method (``.dt.hour()``, ``.str.upper()`` …) — stored as a
    plain function over evaluated operands."""

    def __init__(self, name: str, fun: Callable, return_type: Any, *args: ColumnExpression, propagate_none: bool = True):
        self._method_name = name
        self._fun = fun
        self._args = tuple(_wrap(a) for a in args)
        self._dtype = dt.wrap(return_type)
        self._propagate_none = propagate_none

    def _children(self):
        return self._args

    def _rebuild(self, children):
        return MethodCallExpression(
            self._method_name, self._fun, self._dtype, *children, propagate_none=self._propagate_none
        )

    def _compile(self, resolver):
        acs = [a._compile(resolver) for a in self._args]
        fun = self._fun
        propagate_none = self._propagate_none

        def run(row: tuple) -> Any:
            vals = [c(row) for c in acs]
            if any(v is api.ERROR for v in vals):
                return api.ERROR
            if propagate_none and any(v is None for v in vals):
                return None
            try:
                return fun(*vals)
            except Exception:
                return api.ERROR

        return run


class ReducerExpression(ColumnExpression):
    """A reducer applied in a ``.reduce(...)`` context, e.g.
    ``pw.reducers.sum(pw.this.x)``."""

    def __init__(self, reducer: Any, *args: ColumnExpression, **kwargs: Any):
        self._reducer = reducer
        self._args = tuple(_wrap(a) for a in args)
        self._reducer_kwargs = kwargs
        self._dtype = reducer.return_dtype([a._dtype for a in self._args])

    def _children(self):
        return self._args

    def _rebuild(self, children):
        return ReducerExpression(self._reducer, *children, **self._reducer_kwargs)

    def _compile(self, resolver):
        raise TypeError(
            f"Reducer {self._reducer.name} can only be used inside .reduce(...)"
        )


# -- public constructors ----------------------------------------------------

def if_else(cond: Any, then: Any, else_: Any) -> ColumnExpression:
    """Lazy conditional: only the taken branch evaluates per row.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... a
    ... -2
    ... 3
    ... ''')
    >>> out = t.select(sign=pw.if_else(t.a >= 0, 1, -1))
    >>> pw.debug.compute_and_print(out, include_id=False)
    sign
    -1
    1
    """
    return IfElseExpression(_wrap(cond), _wrap(then), _wrap(else_))


def coalesce(*args: Any) -> ColumnExpression:
    """First non-None argument, evaluated lazily left to right.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... a | b
    ... 1 |
    ...   | 5
    ... ''')
    >>> out = t.select(v=pw.coalesce(t.a, t.b, 0))
    >>> pw.debug.compute_and_print(out, include_id=False)
    v
    1
    5
    """
    return CoalesceExpression(*[_wrap(a) for a in args])


def require(value: Any, *deps: Any) -> ColumnExpression:
    return RequireExpression(_wrap(value), *[_wrap(d) for d in deps])


def cast(target_type: Any, expr: Any) -> ColumnExpression:
    return CastExpression(dt.wrap(target_type), _wrap(expr))


class DeclareTypeExpression(ColumnExpression):
    """Static type assertion WITHOUT runtime conversion (reference
    ``pw.declare_type``): the value passes through untouched, only the
    declared dtype changes."""

    def __init__(self, target: dt.DType, expr: ColumnExpression):
        self._dtype = target
        self._expr = expr

    def __repr__(self) -> str:
        return f"declare_type({self._dtype!r}, {self._expr!r})"

    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return DeclareTypeExpression(self._dtype, children[0])

    def _compile(self, resolver):
        return self._expr._compile(resolver)


def declare_type(target_type: Any, expr: Any) -> ColumnExpression:
    return DeclareTypeExpression(dt.wrap(target_type), _wrap(expr))


def unwrap(expr: Any) -> ColumnExpression:
    return UnwrapExpression(_wrap(expr))


def fill_error(expr: Any, replacement: Any) -> ColumnExpression:
    return FillErrorExpression(_wrap(expr), _wrap(replacement))


def make_tuple(*args: Any) -> ColumnExpression:
    return MakeTupleExpression(*[_wrap(a) for a in args])


def apply(fun: Callable, *args: Any, **kwargs: Any) -> ColumnExpression:
    """Apply a Python function per row (reference ``pw.apply``).

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... name
    ... alice
    ... bob
    ... ''')
    >>> out = t.select(length=pw.apply(len, t.name))
    >>> pw.debug.compute_and_print(out, include_id=False)
    length
    3
    5
    """
    import typing as _t

    hints = {}
    try:
        hints = _t.get_type_hints(fun)
    except Exception:
        pass
    ret = hints.get("return", dt.ANY)
    return ApplyExpression(fun, ret, args, kwargs)


def apply_with_type(fun: Callable, ret_type: Any, *args: Any, **kwargs: Any) -> ColumnExpression:
    return ApplyExpression(fun, ret_type, args, kwargs)


def apply_async(fun: Callable, *args: Any, **kwargs: Any) -> ColumnExpression:
    import typing as _t

    hints = {}
    try:
        hints = _t.get_type_hints(fun)
    except Exception:
        pass
    ret = hints.get("return", dt.ANY)
    return AsyncApplyExpression(fun, ret, args, kwargs)


def assert_table_has_columns(*a: Any, **k: Any) -> None:  # compat helper
    pass


__all__ = [
    "ColumnExpression",
    "ColumnReference",
    "ConstExpression",
    "BinaryExpression",
    "UnaryExpression",
    "IfElseExpression",
    "CoalesceExpression",
    "RequireExpression",
    "ApplyExpression",
    "AsyncApplyExpression",
    "FullyAsyncApplyExpression",
    "CastExpression",
    "ConvertExpression",
    "PointerExpression",
    "MakeTupleExpression",
    "GetExpression",
    "UnwrapExpression",
    "FillErrorExpression",
    "MethodCallExpression",
    "ReducerExpression",
    "IsNoneExpression",
    "if_else",
    "coalesce",
    "require",
    "cast",
    "unwrap",
    "fill_error",
    "make_tuple",
    "apply",
    "apply_with_type",
    "apply_async",
]
