"""Connector resilience: supervised restart, backoff, circuit breaking.

The reference engine recovers from reader failures via persisted snapshots
(``src/connectors/mod.rs`` ``Connector::run`` + rewind): a connector that
dies is restarted and resumes from the last committed frontier.  This
module provides that layer for the epoch-synchronous engine:

- :class:`ConnectorRecoveryPolicy` — restart budget, exponential backoff
  (shared with the UDF retry layer: the delay schedule IS an
  :class:`~pathway_tpu.internals.udfs.ExponentialBackoffRetryStrategy`),
  circuit breaker, watchdog timeout and an ``on_failure`` mode.
- :class:`CircuitBreaker` — closed / open / half-open, so a source that
  fails in a tight loop stops consuming restart budget until a cool-down
  elapses.
- :class:`ConnectorSupervisor` — runs ``RowSource.run(events)`` on a
  reader thread, restarting per policy and resuming from the persistence
  snapshot offset (already-delivered rows are skipped, never re-emitted).

The scheduler spawns one supervisor per live input; a node opts in by
carrying a ``recovery_policy`` attribute (``input_table(...,
recovery_policy=...)``).  Nodes without a policy keep the historical
behaviour: one failure, logged, stream closed (``DEFAULT_POLICY``).
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable

from pathway_tpu.internals.udfs import ExponentialBackoffRetryStrategy

__all__ = [
    "BackgroundMaintenance",
    "BreakerState",
    "CircuitBreaker",
    "ClusterRunReport",
    "ClusterSupervisor",
    "ConnectorRecoveryPolicy",
    "ConnectorSupervisor",
    "DEFAULT_POLICY",
    "WatchdogTimeout",
]

_logger = logging.getLogger("pathway_tpu.resilience")

_ON_FAILURE_MODES = ("stop", "drop", "degrade")


class WatchdogTimeout(Exception):
    """A source made no progress within ``watchdog_timeout_s``."""


class BreakerState:
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure circuit breaker (closed → open → half-open).

    ``failure_threshold`` consecutive failures open the circuit; while
    open, :meth:`allow` refuses further attempts until ``reset_after_s``
    has elapsed, then exactly one probe attempt is allowed (half-open).
    A success closes the circuit; a failure re-opens it and restarts the
    cool-down.  ``clock`` is injectable so tests need not sleep."""

    def __init__(
        self,
        failure_threshold: int = 3,
        reset_after_s: float = 30.0,
        clock: Callable[[], float] = _time.monotonic,
    ):
        self.failure_threshold = max(1, failure_threshold)
        self.reset_after_s = reset_after_s
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._state = BreakerState.CLOSED
        self._opened_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            if (
                self._state == BreakerState.OPEN
                and self._clock() - self._opened_at >= self.reset_after_s
            ):
                return BreakerState.HALF_OPEN
            return self._state

    def allow(self) -> bool:
        """Whether the next attempt may proceed.  In the half-open window
        this consumes the single probe slot (the breaker re-arms as OPEN
        with a fresh cool-down until the probe reports back)."""
        with self._lock:
            if self._state == BreakerState.CLOSED:
                return True
            if self._state == BreakerState.HALF_OPEN:
                return False  # a probe is already in flight
            if self._clock() - self._opened_at >= self.reset_after_s:
                self._state = BreakerState.HALF_OPEN
                self._opened_at = self._clock()  # fresh cool-down if it fails
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._state = BreakerState.CLOSED

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if (
                self._state == BreakerState.HALF_OPEN
                or self._failures >= self.failure_threshold
            ):
                self._state = BreakerState.OPEN
                self._opened_at = self._clock()


@dataclass
class ConnectorRecoveryPolicy:
    """Restart policy for one connector (reference connector supervision).

    ``on_failure`` decides what happens once the restart budget is spent
    or the circuit breaker refuses further attempts:

    - ``"stop"``: the failure is recorded and the whole run is stopped.
    - ``"drop"``: the source's stream is closed; the run continues on the
      data delivered so far (the historical behaviour).
    - ``"degrade"``: like ``drop``, but the failure is routed into the
      global error-log table and the source's outputs are marked stale
      (``ctx.stale_sources`` + the connector's monitoring entry), so the
      run finishes and the degradation is observable instead of silent.
    """

    max_restarts: int = 3
    initial_delay_ms: int = 50
    backoff_factor: float = 2.0
    max_delay_ms: int | None = 10_000
    jitter_ms: int = 50
    full_jitter: bool = False
    seed: int | None = None
    #: no event (row/commit/close) for this long counts as a failure;
    #: the stalled attempt is fenced off and restarted.  None disables.
    watchdog_timeout_s: float | None = None
    on_failure: str = "stop"
    #: consecutive failures before the breaker opens; None disables the
    #: breaker (budget alone governs restarts)
    breaker_failure_threshold: int | None = None
    breaker_reset_after_s: float = 30.0

    def __post_init__(self) -> None:
        if self.on_failure not in _ON_FAILURE_MODES:
            raise ValueError(
                f"on_failure must be one of {_ON_FAILURE_MODES}, "
                f"got {self.on_failure!r}"
            )

    def backoff_strategy(self) -> ExponentialBackoffRetryStrategy:
        """The delay schedule, as the SAME policy object the UDF retry
        layer uses — one backoff implementation across the system."""
        return ExponentialBackoffRetryStrategy(
            max_retries=self.max_restarts,
            initial_delay=self.initial_delay_ms,
            backoff_factor=self.backoff_factor,
            jitter_ms=self.jitter_ms,
            max_delay_ms=self.max_delay_ms,
            full_jitter=self.full_jitter,
            seed=self.seed,
        )

    def make_breaker(
        self, clock: Callable[[], float] = _time.monotonic
    ) -> CircuitBreaker | None:
        if self.breaker_failure_threshold is None:
            return None
        return CircuitBreaker(
            failure_threshold=self.breaker_failure_threshold,
            reset_after_s=self.breaker_reset_after_s,
            clock=clock,
        )


#: nodes without an explicit policy: one failure, logged, stream closed —
#: exactly the pre-supervisor behaviour, so existing pipelines see no
#: change until they opt in
DEFAULT_POLICY = ConnectorRecoveryPolicy(max_restarts=0, on_failure="drop")


class _AttemptEvents:
    """Per-attempt shim around the live events chain.

    Tracks last-activity time (watchdog) and can be *fenced*: a stalled
    attempt's thread cannot be killed, so instead its event sink is cut —
    after :meth:`fence` nothing it emits reaches the engine, and
    cooperative readers observe ``stopped`` and exit.  ``close`` from the
    subject is recorded but NOT forwarded: the supervisor owns the single
    end-of-stream close."""

    def __init__(self, inner: Any):
        self._inner = inner
        self._fenced = False
        self.closed_by_subject = False
        self.last_activity = _time.monotonic()

    @property
    def stopped(self) -> bool:
        return self._fenced or self._inner.stopped

    @property
    def resume_offset(self) -> int:
        return getattr(self._inner, "resume_offset", 0)

    def fence(self) -> None:
        self._fenced = True

    def add(self, key: Any, values: tuple) -> None:
        if not self._fenced:
            self.last_activity = _time.monotonic()
            self._inner.add(key, values)

    def add_many(self, rows: list) -> None:
        if not self._fenced:
            self.last_activity = _time.monotonic()
            self._inner.add_many(rows)

    def add_frame(self, cap: Any) -> None:
        if not self._fenced:
            self.last_activity = _time.monotonic()
            self._inner.add_frame(cap)

    def remove(self, key: Any, values: tuple) -> None:
        if not self._fenced:
            self.last_activity = _time.monotonic()
            self._inner.remove(key, values)

    def commit(self) -> None:
        if not self._fenced:
            self.last_activity = _time.monotonic()
            self._inner.commit()

    def close(self) -> None:
        if not self._fenced:
            self.closed_by_subject = True


class _SkipEvents:
    """Drop the first ``skip`` data events (and any commits inside that
    prefix) before forwarding — the non-persistence analogue of
    ``_RecordingEvents.resume_offset``: a restarted deterministic reader
    re-emits its history and the prefix the engine already consumed must
    not be delivered twice."""

    def __init__(self, inner: Any, skip: int):
        self._inner = inner
        self.resume_offset = skip

    @property
    def stopped(self) -> bool:
        return self._inner.stopped

    def add(self, key: Any, values: tuple) -> None:
        if self.resume_offset > 0:
            self.resume_offset -= 1
            return
        self._inner.add(key, values)

    def add_many(self, rows: list) -> None:
        skip = min(self.resume_offset, len(rows))
        if skip:
            self.resume_offset -= skip
            rows = rows[skip:]
        if rows:
            self._inner.add_many(rows)

    def add_frame(self, cap: Any) -> None:
        from pathway_tpu.internals import native as _native

        native = _native.load()
        n = native.frame_len(cap)
        skip = min(self.resume_offset, n)
        if skip:
            self.resume_offset -= skip
            if skip == n:
                return
            cap = native.frame_slice(cap, skip, n)
        self._inner.add_frame(cap)

    def remove(self, key: Any, values: tuple) -> None:
        if self.resume_offset > 0:
            self.resume_offset -= 1
            return
        self._inner.remove(key, values)

    def commit(self) -> None:
        if self.resume_offset > 0:
            return
        self._inner.commit()

    def close(self) -> None:
        self._inner.close()


class ConnectorSupervisor:
    """Supervises one connector's reader thread.

    Each attempt runs ``subject.run`` on a fresh daemon thread against a
    fresh events chain built by ``make_events(resume)``, where ``resume``
    is the number of data events the engine has already consumed from
    this source (persistence-replayed prefix + rows delivered by earlier
    attempts).  With persistence attached, ``make_events`` wraps the sink
    in the recording layer whose ``resume_offset`` skips that prefix
    without re-recording it; without persistence the supervisor inserts
    :class:`_SkipEvents` for deterministic readers (or calls the reader's
    ``on_persistence_resume`` hook).
    """

    def __init__(
        self,
        node: Any,
        subject: Any,
        make_events: Callable[[int], Any],
        policy: ConnectorRecoveryPolicy | None,
        *,
        ctx: Any = None,
        stats: dict | None = None,
        stop_event: threading.Event | None = None,
        initial_resume: int = 0,
        skip_handled_by_events: bool = False,
        stop_runner: Callable[[], None] | None = None,
    ):
        self.node = node
        self.subject = subject
        self.make_events = make_events
        self.policy = policy if policy is not None else DEFAULT_POLICY
        self.ctx = ctx
        self.stats = stats if stats is not None else {}
        self._stop_event = stop_event or threading.Event()
        self._initial_resume = initial_resume
        #: True when make_events already returns a chain that skips the
        #: resume prefix itself (the persistence recording wrapper)
        self._skip_handled = skip_handled_by_events
        self._stop_runner = stop_runner
        self._backoff = self.policy.backoff_strategy()
        self._breaker = self.policy.make_breaker()
        self.restarts = 0
        self.stats.setdefault("restarts", 0)
        self.stats.setdefault("failures", 0)

    # ------------------------------------------------------------------
    def start(self) -> threading.Thread:
        t = threading.Thread(
            target=self._supervise,
            daemon=True,
            name=f"pw_supervisor_{self.node.name}#{self.node.id}",
        )
        t.start()
        return t

    # ------------------------------------------------------------------
    def _delivered(self) -> int:
        """Data events this run has consumed from this source: the
        replayed prefix plus everything the base events sink counted
        (the stats dict is shared across attempts)."""
        return (
            self._initial_resume
            + self.stats.get("rows", 0)
            + self.stats.get("retractions", 0)
        )

    def _build_attempt(self, resume: int) -> _AttemptEvents:
        events = self.make_events(resume)
        if resume > 0 and not self._skip_handled:
            if getattr(self.subject, "deterministic_replay", False):
                events = _SkipEvents(events, resume)
            else:
                hook = getattr(self.subject, "on_persistence_resume", None)
                if hook is not None:
                    hook(resume)
                else:
                    _logger.warning(
                        "restarting input %r after %d delivered events but "
                        "its reader is not deterministically replayable and "
                        "defines no on_persistence_resume(n) hook; "
                        "re-delivered rows will be double-counted",
                        self.node.name,
                        resume,
                    )
        return _AttemptEvents(events)

    def _run_attempt(self, att: _AttemptEvents) -> BaseException | None:
        """Run one attempt; returns the failure (exception or watchdog
        verdict) or None on clean completion."""
        box: dict[str, BaseException] = {}

        def body() -> None:
            try:
                self.subject.run(att)
            except BaseException as e:  # noqa: BLE001 — reported to policy
                box["exc"] = e

        t = threading.Thread(
            target=body,
            daemon=True,
            name=f"pw_reader_{self.node.name}#{self.node.id}",
        )
        t.start()
        timeout = self.policy.watchdog_timeout_s
        tick = 0.05 if timeout is None else min(0.05, timeout / 4.0)
        while t.is_alive():
            t.join(tick)
            if self._stop_event.is_set():
                # shutdown: the reader sees stopped=True and exits; give
                # it a moment, then abandon it (daemon)
                t.join(0.5)
                return None
            if (
                timeout is not None
                and t.is_alive()
                and _time.monotonic() - att.last_activity > timeout
                # a reader parked by ingest backpressure (IngestCredit
                # pause) is waiting, not hung — fencing it would turn
                # overload into a spurious restart storm
                and not self.stats.get("paused")
            ):
                att.fence()  # the zombie may never die; cut its sink
                return WatchdogTimeout(
                    f"source {self.node.name!r} made no progress for "
                    f"{timeout}s"
                )
        return box.get("exc")

    def _supervise(self) -> None:
        from pathway_tpu.internals.telemetry import get_telemetry

        telemetry = get_telemetry()
        att: _AttemptEvents | None = None
        attempt = 0
        while True:
            att = self._build_attempt(
                self._delivered() if attempt else self._initial_resume
            )
            self.stats["state"] = "live"
            failure = self._run_attempt(att)
            if failure is None:
                if self._breaker is not None:
                    self._breaker.record_success()
                break
            self.stats["failures"] += 1
            self.stats["last_error"] = repr(failure)
            telemetry.counter("connector.failures")
            if self._breaker is not None:
                self._breaker.record_failure()
                if self._breaker.state == BreakerState.OPEN:
                    telemetry.counter("connector.breaker_open")
            _logger.error(
                "connector %s failed (attempt %d): %r",
                self.node.name,
                attempt + 1,
                failure,
            )
            if self._stop_event.is_set():
                break
            can_restart = self.restarts < self.policy.max_restarts and (
                self._breaker is None or self._breaker.allow()
            )
            if not can_restart:
                self._give_up(failure)
                break
            delay = self._backoff.next_delay(self.restarts)
            self.restarts += 1
            self.stats["restarts"] += 1
            telemetry.counter("connector.restarts")
            _logger.warning(
                "restarting connector %s in %.3fs (restart %d/%d, resuming "
                "past %d delivered events)",
                self.node.name,
                delay,
                self.restarts,
                self.policy.max_restarts,
                self._delivered(),
            )
            if self._stop_event.wait(delay):
                break
            attempt += 1
        # exactly one end-of-stream close, owned by the supervisor — the
        # scheduler's run loop exits once every primary source closed
        self.make_close(att)

    def make_close(self, att: _AttemptEvents | None) -> None:
        if att is not None and not att._fenced:
            att._inner.close()
        else:
            # the live chain was fenced (watchdog): close via a fresh sink
            self.make_events(self._delivered()).close()

    def _give_up(self, failure: BaseException) -> None:
        from pathway_tpu.internals.telemetry import get_telemetry

        mode = self.policy.on_failure
        msg = (
            f"connector {self.node.name}#{self.node.id} gave up after "
            f"{self.restarts} restart(s): {failure!r}"
        )
        self.stats["state"] = "failed" if mode == "stop" else mode
        if mode == "degrade":
            # keep the run alive; the failure lands in the global
            # error-log table and the outputs are flagged stale
            self.stats["stale"] = True
            get_telemetry().counter("connector.dlq_events")
            if self.ctx is not None:
                self.ctx.log_error(self.node, msg)
                self.ctx.stale_sources.add(self.node.id)
            return
        if mode == "stop":
            if self.ctx is not None:
                self.ctx.log_error(self.node, msg)
            _logger.error("%s; stopping the run (on_failure='stop')", msg)
            if self._stop_runner is not None:
                self._stop_runner()
            return
        # "drop": historical behaviour — loud log, stream closes, the run
        # continues on whatever was delivered
        _logger.error("%s; dropping the source (on_failure='drop')", msg)


# --------------------------------------------------------------------------
# cluster-level supervision
# --------------------------------------------------------------------------


def _probe_port_range(n: int, start: int = 11000) -> int:
    """Find a contiguous range of ``n`` free TCP ports on 127.0.0.1.

    A fresh range per cluster generation keeps a respawned mesh away from
    TIME_WAIT sockets and half-dead listeners left by the generation it
    replaces.
    """
    import socket as _socket

    base = start + (os.getpid() % 500) * 16
    step = max(n, 1)
    for offset in range(0, 4000, step):
        cand = base + offset
        socks: list[Any] = []
        try:
            for i in range(n):
                s = _socket.socket()
                s.bind(("127.0.0.1", cand + i))
                socks.append(s)
            return cand
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no free range of {n} ports found near {base}")


@dataclass
class ClusterRunReport:
    """Outcome of a supervised cluster run.

    ``recovery_seconds`` has one entry per restart: wall time from the moment
    a worker failure was observed to the moment every replacement process was
    spawned — the whole cluster's downtime window under
    ``restart_scope="generation"``, the single rank's under ``"rank"``
    (survivors never stop).  ``rank_restarts`` maps pid -> per-rank restart
    count (empty under generation scope).
    """

    returncode: int
    restarts: int
    recovery_seconds: list[float] = field(default_factory=list)
    total_seconds: float = 0.0
    failures: list[str] = field(default_factory=list)
    rank_restarts: dict[int, int] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class ClusterSupervisor:
    """Restart a multi-process cluster run after worker death.

    The supervisor owns the whole mesh: it spawns one OS process per
    ``PATHWAY_PROCESS_ID`` with the standard env contract and watches
    their exit codes.  What a nonzero exit triggers is the
    ``restart_scope``:

    - ``"generation"`` (default, the legacy semantics): tear down the
      survivors and respawn *all* of them.  This is the only correct
      granularity when the workers run the fail-together mesh policy — a
      surviving worker cannot rejoin a half-dead mesh: peers fail their
      sockets as soon as one side dies, and epoch consensus needs every
      rank present.
    - ``"rank"`` (per-rank failover, ISSUE 13): respawn ONLY the dead
      rank, on the same port range, with ``PATHWAY_CLUSTER_INCARNATION``
      bumped so the replacement's dial handshake is admitted as a rejoin
      by the survivors' isolate-policy mesh
      (``engine/cluster._ProcessLinks``).  Survivors never stop; the
      replacement restores its state from its snapshot + offset tail and
      rejoins.  The supervisor exports
      ``PATHWAY_CLUSTER_FAIL_POLICY=isolate`` to the workers under this
      scope (overridable via ``env``) because per-rank restart is only
      sound on an isolating mesh.

    Rollback to the last globally-consistent checkpoint is not the
    supervisor's job — the workers' own ``("snap_presence",)`` allgather
    refuses any checkpoint epoch that is missing on some rank or skewed
    across ranks, so a respawned cluster converges on the newest epoch
    that every worker persisted (or replays from scratch when there is
    none), and file sinks truncate back to their checkpointed watermark
    before appending.

    Restart budget and backoff pacing reuse ``ConnectorRecoveryPolicy``
    so cluster supervision tunes exactly like connector supervision.
    The budget counts the current *failure streak*, not lifetime
    restarts: after ``healthy_reset_polls`` consecutive healthy poll
    ticks the streak (and with it the backoff schedule) resets, so an
    unrelated failure hours later starts from the initial delay instead
    of inheriting a maxed-out schedule and an exhausted budget.
    """

    def __init__(
        self,
        argv: list[str],
        n_processes: int,
        *,
        threads: int = 1,
        env: dict[str, str] | None = None,
        policy: ConnectorRecoveryPolicy | None = None,
        log_dir: str | None = None,
        cwd: str | None = None,
        first_port_factory: Callable[[int], int] | None = None,
        grace_s: float = 5.0,
        poll_interval_s: float = 0.02,
        restart_scope: str = "generation",
        healthy_reset_polls: int | None = 250,
    ) -> None:
        if n_processes < 1:
            raise ValueError("n_processes must be >= 1")
        if restart_scope not in ("generation", "rank"):
            raise ValueError(
                f"restart_scope must be 'generation' or 'rank', "
                f"got {restart_scope!r}"
            )
        self.argv = list(argv)
        self.n_processes = n_processes
        self.threads = threads
        self.extra_env = dict(env or {})
        self.policy = policy or ConnectorRecoveryPolicy(
            max_restarts=3, initial_delay_ms=50, max_delay_ms=2_000, jitter_ms=0
        )
        self.log_dir = log_dir
        self.cwd = cwd
        self._first_port_factory = first_port_factory or _probe_port_range
        self.grace_s = grace_s
        self.poll_interval_s = poll_interval_s
        self.restart_scope = restart_scope
        #: consecutive healthy poll ticks after which the failure streak
        #: (budget + backoff position) resets; None disables the reset
        self.healthy_reset_polls = healthy_reset_polls
        self._stop_event = threading.Event()

    def stop(self) -> None:
        """Ask a running :meth:`run` to tear everything down and return."""
        self._stop_event.set()

    # -- process plumbing ---------------------------------------------------

    def _spawn_rank(
        self,
        generation: int,
        first_port: int,
        pid_: int,
        incarnation: int = 0,
    ) -> tuple[subprocess.Popen[bytes], Any]:
        env = dict(os.environ)
        if self.restart_scope == "rank":
            # per-rank restart is only sound on an isolating mesh: the
            # survivors must quiesce one peer, not fail together
            env["PATHWAY_CLUSTER_FAIL_POLICY"] = "isolate"
        env.update(self.extra_env)
        env.update(
            {
                "PATHWAY_THREADS": str(self.threads),
                "PATHWAY_PROCESSES": str(self.n_processes),
                "PATHWAY_PROCESS_ID": str(pid_),
                "PATHWAY_FIRST_PORT": str(first_port),
                # surfaces as pathway_tpu_worker_restarts_total
                "PATHWAY_WORKER_RESTARTS": str(
                    incarnation if self.restart_scope == "rank" else generation
                ),
                # the rejoin handshake: survivors admit a replacement
                # whose dial advertises a newer incarnation
                "PATHWAY_CLUSTER_INCARNATION": str(incarnation),
            }
        )
        log_f: Any = subprocess.DEVNULL
        if self.log_dir is not None:
            suffix = f"_i{incarnation}" if incarnation else ""
            log_f = open(
                os.path.join(
                    self.log_dir, f"gen{generation}_p{pid_}{suffix}.log"
                ),
                "wb",
            )
        proc = subprocess.Popen(
            self.argv,
            env=env,
            cwd=self.cwd,
            stdout=log_f,
            stderr=subprocess.STDOUT,
        )
        return proc, log_f

    def _spawn_generation(
        self, generation: int, first_port: int
    ) -> list[tuple[subprocess.Popen[bytes], Any]]:
        return [
            self._spawn_rank(generation, first_port, pid_)
            for pid_ in range(self.n_processes)
        ]

    def _terminate(self, procs: list[tuple[subprocess.Popen[bytes], Any]]) -> None:
        for proc, _ in procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = _time.monotonic() + self.grace_s
        for proc, _ in procs:
            if proc.poll() is None:
                try:
                    proc.wait(max(0.0, deadline - _time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(5.0)
        for _, log_f in procs:
            if log_f is not subprocess.DEVNULL:
                log_f.close()

    @staticmethod
    def _close_logs(procs: list[tuple[subprocess.Popen[bytes], Any]]) -> None:
        for _, log_f in procs:
            if log_f is not subprocess.DEVNULL:
                log_f.close()

    # -- main loop ----------------------------------------------------------

    def run(self, timeout: float | None = None) -> ClusterRunReport:
        """Run the cluster to completion, restarting on worker death."""
        from pathway_tpu.internals.telemetry import get_telemetry

        telemetry = get_telemetry()
        backoff = self.policy.backoff_strategy()
        t0 = _time.monotonic()
        generation = 0
        #: consecutive-failure streak: drives the backoff position AND
        #: the restart budget; resets after a stable-healthy window so an
        #: unrelated failure later doesn't inherit a maxed-out schedule
        failure_streak = 0
        healthy_polls = 0
        recovery_seconds: list[float] = []
        failures: list[str] = []
        rank_restarts: dict[int, int] = {}
        failed_at: float | None = None

        def merge_trace() -> None:
            # flight recorder: the workers spool per-rank Chrome-trace
            # dumps (chaos-kill flush, liveness flush, atexit); whenever a
            # generation ends — restart or completion — fold them into one
            # stitched merged_trace.json so a post-mortem never has to
            spool = self.extra_env.get("PATHWAY_TRACE_DIR") or os.environ.get(
                "PATHWAY_TRACE_DIR"
            )
            if spool:
                from pathway_tpu.internals import tracing as _tracing

                _tracing.merge_trace_dir(spool)

        def report(rc: int) -> ClusterRunReport:
            merge_trace()
            return ClusterRunReport(
                returncode=rc,
                restarts=generation + sum(rank_restarts.values()),
                recovery_seconds=recovery_seconds,
                total_seconds=_time.monotonic() - t0,
                failures=failures,
                rank_restarts=dict(rank_restarts),
            )

        def tick_healthy() -> None:
            nonlocal failure_streak, healthy_polls
            healthy_polls += 1
            if (
                failure_streak
                and self.healthy_reset_polls is not None
                and healthy_polls >= self.healthy_reset_polls
            ):
                _logger.info(
                    "cluster stable for %d polls: failure streak %d reset",
                    healthy_polls,
                    failure_streak,
                )
                failure_streak = 0

        while True:
            first_port = self._first_port_factory(self.n_processes)
            procs = self._spawn_generation(generation, first_port)
            if failed_at is not None:
                recovery_seconds.append(_time.monotonic() - failed_at)
                failed_at = None
            failed_rc: int | None = None
            while True:
                if self._stop_event.is_set():
                    self._terminate(procs)
                    failures.append(f"generation {generation}: stopped by supervisor")
                    return report(-1)
                if timeout is not None and _time.monotonic() - t0 > timeout:
                    self._terminate(procs)
                    failures.append(f"generation {generation}: supervisor timeout")
                    return report(124)
                codes = [proc.poll() for proc, _ in procs]
                bad = [
                    (i, c) for i, c in enumerate(codes) if c is not None and c != 0
                ]
                if bad:
                    failed_rc = bad[0][1]
                    failures.append(
                        f"generation {generation}: worker process "
                        f"{bad[0][0]} exited {failed_rc}"
                    )
                    if self.restart_scope != "rank":
                        break
                    # per-rank failover: respawn ONLY the dead ranks, on
                    # the same port range — survivors keep running and
                    # admit the replacements as rejoins
                    rank_failed_at = _time.monotonic()
                    telemetry.counter("cluster.worker_failures")
                    _logger.warning(
                        "%s; respawning only that rank (survivors keep "
                        "running)",
                        failures[-1],
                    )
                    if failure_streak >= self.policy.max_restarts:
                        _logger.error(
                            "cluster gave up after a streak of %d rank "
                            "restart(s); last failure: %s",
                            failure_streak,
                            failures[-1],
                        )
                        self._terminate(procs)
                        return report(failed_rc)
                    delay = backoff.next_delay(failure_streak)
                    if self._stop_event.wait(delay):
                        failures.append(
                            f"generation {generation}: stopped during backoff"
                        )
                        self._terminate(procs)
                        return report(-1)
                    failure_streak += 1
                    healthy_polls = 0
                    for i, _c in bad:
                        _dead, old_log = procs[i]
                        if old_log is not subprocess.DEVNULL:
                            old_log.close()
                        rank_restarts[i] = rank_restarts.get(i, 0) + 1
                        procs[i] = self._spawn_rank(
                            generation, first_port, i, rank_restarts[i]
                        )
                        telemetry.counter("cluster.restarts")
                    recovery_seconds.append(
                        _time.monotonic() - rank_failed_at
                    )
                    continue
                if all(c == 0 for c in codes):
                    self._close_logs(procs)
                    return report(0)
                tick_healthy()
                self._stop_event.wait(self.poll_interval_s)

            # one worker died: the run is lost — tear down the survivors,
            # pace by the policy's backoff, and respawn the whole mesh
            failed_at = _time.monotonic()
            telemetry.counter("cluster.worker_failures")
            _logger.warning("%s; tearing down survivors", failures[-1])
            self._terminate(procs)
            if failure_streak >= self.policy.max_restarts:
                _logger.error(
                    "cluster gave up after a streak of %d restart(s); "
                    "last failure: %s",
                    failure_streak,
                    failures[-1],
                )
                return report(failed_rc if failed_rc is not None else 1)
            delay = backoff.next_delay(failure_streak)
            if self._stop_event.wait(delay):
                failures.append(f"generation {generation}: stopped during backoff")
                return report(-1)
            telemetry.counter("cluster.restarts")
            merge_trace()  # fold the dead generation's dumps in now
            failure_streak += 1
            healthy_polls = 0
            generation += 1
            _logger.warning(
                "respawning cluster (generation %d; failure streak %d of "
                "at most %d)",
                generation,
                failure_streak,
                self.policy.max_restarts,
            )


class BackgroundMaintenance:
    """Single-flight guarded worker for background index maintenance.

    The segmented index (``stdlib/indexing/segments.py``) hands its merge
    jobs here so compaction runs off the query path.  One job is in
    flight at a time (merges are not reentrant); a failing job is retried
    on the same schedule connectors use
    (:class:`~pathway_tpu.internals.udfs.ExponentialBackoffRetryStrategy`)
    and gives up after ``max_retries``, counting the failure in telemetry
    so /metrics shows maintenance that silently stopped making progress.
    """

    def __init__(
        self,
        name: str = "index-maintenance",
        *,
        max_retries: int = 2,
        initial_delay_ms: int = 50,
        max_delay_ms: int = 2000,
    ):
        self.name = name
        self._backoff = ExponentialBackoffRetryStrategy(
            max_retries=max_retries,
            initial_delay=initial_delay_ms,
            jitter_ms=0,
            max_delay_ms=max_delay_ms,
        )
        self._max_retries = max_retries
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._closed = False

    @property
    def busy(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def submit(self, job: Callable[[], None]) -> bool:
        """Run ``job`` on the maintenance thread; ``False`` if one is
        already in flight (the caller re-submits on its next trigger)."""
        with self._lock:
            if self._closed or self.busy:
                return False
            self._thread = threading.Thread(
                target=self._run, args=(job,), daemon=True, name=self.name
            )
            self._thread.start()
            return True

    def _run(self, job: Callable[[], None]) -> None:
        from pathway_tpu.internals.telemetry import get_telemetry

        for attempt in range(self._max_retries + 1):
            try:
                job()
                return
            except Exception:  # noqa: BLE001
                get_telemetry().counter("index.merge_failures")
                _logger.exception("%s job failed (attempt %d)", self.name, attempt)
                if attempt >= self._max_retries or self._closed:
                    return
                _time.sleep(self._backoff.next_delay(attempt))

    def drain(self, timeout: float | None = 10.0) -> None:
        """Wait for the in-flight job (checkpoint/shutdown barrier)."""
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def close(self, timeout: float | None = 5.0) -> None:
        self._closed = True
        self.drain(timeout)
