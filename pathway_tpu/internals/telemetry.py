"""Telemetry — spans + metrics with OTLP/HTTP export (reference
``src/engine/telemetry.rs:37-436``: OpenTelemetry traces and metrics
around the graph run, process mem/CPU gauges, batch latency).

No hard dependency on the opentelemetry SDK: spans/metrics are recorded
in-process (queryable, cheap) and, when an OTLP endpoint is configured
(``pw.set_monitoring_config(server_endpoint=...)`` or
``PATHWAY_MONITORING_SERVER``), exported as OTLP/HTTP JSON with plain
urllib.  Usage telemetry (the reference phones home with a license key)
is intentionally NOT implemented.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any

__all__ = ["Telemetry", "get_telemetry", "set_monitoring_config"]

_logger = logging.getLogger("pathway_tpu.telemetry")


class Telemetry:
    """Per-process span/metric recorder with optional OTLP/HTTP export."""

    def __init__(self, endpoint: str | None = None, service_name: str = "pathway_tpu"):
        self.endpoint = endpoint
        self.service_name = service_name
        self.run_id = str(uuid.uuid4())
        self.spans: list[dict] = []
        self.gauges: dict[str, float] = {}
        #: monotonic counters (connector restarts, breaker trips, DLQ
        #: events — the resilience subsystem's telemetry surface)
        self.counters: dict[str, int] = {}
        self._lock = threading.Lock()

    # -- spans ----------------------------------------------------------
    @contextmanager
    def span(self, name: str, **attrs: Any):
        """Record a span around a block (reference spans
        ``graph_runner.run`` / ``graph_runner.build``)."""
        t0 = time.time()
        try:
            yield
        finally:
            rec = {
                "name": name,
                "start_s": t0,
                "duration_ms": (time.time() - t0) * 1000.0,
                "attributes": attrs,
            }
            with self._lock:
                self.spans.append(rec)
                del self.spans[:-500]  # bound memory
            self._export_span(rec)

    # -- metrics --------------------------------------------------------
    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def counter(self, name: str, inc: int = 1) -> int:
        """Increment (and return) a monotonic counter — exported with the
        gauges and surfaced in the monitoring snapshot."""
        with self._lock:
            v = self.counters.get(name, 0) + inc
            self.counters[name] = v
            return v

    def snapshot_counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counters)

    def record_process_metrics(self) -> None:
        """Process memory/CPU gauges (reference telemetry.rs:316-395)."""
        try:
            import resource

            ru = resource.getrusage(resource.RUSAGE_SELF)
            self.gauge("process.memory.rss_kb", ru.ru_maxrss)
            self.gauge("process.cpu.user_s", ru.ru_utime)
            self.gauge("process.cpu.system_s", ru.ru_stime)
        except Exception:  # noqa: BLE001 — platform without resource
            pass

    # -- export ---------------------------------------------------------
    def _export_span(self, rec: dict) -> None:
        if not self.endpoint:
            return
        now_ns = int(rec["start_s"] * 1e9)
        payload = {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            _kv("service.name", self.service_name),
                            _kv("run.id", self.run_id),
                            _kv("license.tier", _license_tier()),
                        ]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "pathway_tpu"},
                            "spans": [
                                {
                                    "traceId": uuid.uuid4().hex,
                                    "spanId": uuid.uuid4().hex[:16],
                                    "name": rec["name"],
                                    "kind": 1,
                                    "startTimeUnixNano": str(now_ns),
                                    "endTimeUnixNano": str(
                                        now_ns + int(rec["duration_ms"] * 1e6)
                                    ),
                                    "attributes": [
                                        _kv(k, v)
                                        for k, v in rec["attributes"].items()
                                    ],
                                }
                            ],
                        }
                    ],
                }
            ]
        }
        self._post("/v1/traces", payload)

    def export_metrics(self) -> None:
        if not self.endpoint or not (self.gauges or self.counters):
            return
        now_ns = str(int(time.time() * 1e9))
        with self._lock:
            gauges = dict(self.gauges)
            # counters ride the same gauge export (cumulative values)
            gauges.update(
                {name: float(v) for name, v in self.counters.items()}
            )
        payload = {
            "resourceMetrics": [
                {
                    "resource": {
                        "attributes": [
                            _kv("service.name", self.service_name),
                            _kv("run.id", self.run_id),
                            _kv("license.tier", _license_tier()),
                        ]
                    },
                    "scopeMetrics": [
                        {
                            "scope": {"name": "pathway_tpu"},
                            "metrics": [
                                {
                                    "name": name,
                                    "gauge": {
                                        "dataPoints": [
                                            {
                                                "timeUnixNano": now_ns,
                                                "asDouble": value,
                                            }
                                        ]
                                    },
                                }
                                for name, value in gauges.items()
                            ],
                        }
                    ],
                }
            ]
        }
        self._post("/v1/metrics", payload)

    def _post(self, path: str, payload: dict) -> None:
        import urllib.request

        try:
            req = urllib.request.Request(
                self.endpoint.rstrip("/") + path,
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as e:  # noqa: BLE001 — telemetry must never break runs
            _logger.debug("telemetry export failed: %r", e)


def _license_tier() -> str:
    """Resource attribute like the reference's license-aware telemetry
    (``src/engine/telemetry.rs:62-143`` run_id/license attrs)."""
    try:
        from pathway_tpu.internals.license import get_license

        return get_license().tier
    except Exception:  # noqa: BLE001 — invalid license must not kill export
        return "unknown"


def _kv(key: str, value: Any) -> dict:
    if isinstance(value, bool):
        v: dict = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


_telemetry: Telemetry | None = None


def get_telemetry() -> Telemetry:
    global _telemetry
    if _telemetry is None:
        _telemetry = Telemetry(
            endpoint=os.environ.get("PATHWAY_MONITORING_SERVER") or None
        )
    return _telemetry


def set_monitoring_config(*, server_endpoint: str | None = None) -> None:
    """reference ``pw.set_monitoring_config``: OTLP/HTTP endpoint for
    spans + metrics export."""
    global _telemetry
    _telemetry = Telemetry(endpoint=server_endpoint)
