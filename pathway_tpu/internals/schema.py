"""Schema: declarative column typing for tables.

Capability parity with reference ``python/pathway/internals/schema.py`` (947
LoC): class-syntax schemas, ``column_definition`` with primary keys and
defaults, builders (``schema_from_types``, ``schema_builder``,
``schema_from_dict``), merging via ``|``, and per-schema properties
(append_only).
"""

from __future__ import annotations

import typing
from dataclasses import dataclass, field
from typing import Any, Mapping

from pathway_tpu.internals import dtype as dt

_NO_DEFAULT = object()


@dataclass
class ColumnDefinition:
    dtype: dt.DType = field(default_factory=lambda: dt.ANY)
    primary_key: bool = False
    default_value: Any = _NO_DEFAULT
    name: str | None = None
    append_only: bool | None = None

    @property
    def has_default(self) -> bool:
        return self.default_value is not _NO_DEFAULT


def column_definition(
    *,
    primary_key: bool = False,
    default_value: Any = _NO_DEFAULT,
    dtype: Any = None,
    name: str | None = None,
    append_only: bool | None = None,
) -> Any:
    """Declare column properties inside a Schema class (reference
    ``schema.py`` ``column_definition``)."""
    return ColumnDefinition(
        dtype=dt.wrap(dtype) if dtype is not None else dt.ANY,
        primary_key=primary_key,
        default_value=default_value,
        name=name,
        append_only=append_only,
    )


class SchemaProperties:
    def __init__(self, append_only: bool = False):
        self.append_only = append_only


class SchemaMetaclass(type):
    __columns__: dict[str, ColumnDefinition]
    __properties__: SchemaProperties

    def __new__(
        mcls,
        name: str,
        bases: tuple,
        namespace: dict,
        append_only: bool | None = None,
    ):
        # class-level kwargs (``class S(pw.Schema, append_only=True)``)
        # must not reach object.__init_subclass__, which rejects them
        return super().__new__(mcls, name, bases, namespace)

    def __init__(cls, name: str, bases: tuple, namespace: dict, append_only: bool | None = None) -> None:
        super().__init__(name, bases, namespace)
        columns: dict[str, ColumnDefinition] = {}
        for base in bases:
            if hasattr(base, "__columns__"):
                columns.update(base.__columns__)
        hints = namespace.get("__annotations__", {})
        # Resolve string annotations against the defining module when possible.
        module = namespace.get("__module__")
        globalns = vars(__import__(module, fromlist=["_"])) if module in __import__("sys").modules else {}
        for col_name, annotation in hints.items():
            if col_name.startswith("__"):
                continue
            if isinstance(annotation, str):
                try:
                    annotation = eval(annotation, dict(globalns), dict(vars(typing)))  # noqa: S307
                except Exception:
                    annotation = Any
            definition = namespace.get(col_name, None)
            if isinstance(definition, ColumnDefinition):
                cd = ColumnDefinition(
                    dtype=dt.wrap(annotation),
                    primary_key=definition.primary_key,
                    default_value=definition.default_value,
                    name=definition.name or col_name,
                    append_only=definition.append_only,
                )
            else:
                cd = ColumnDefinition(dtype=dt.wrap(annotation), name=col_name)
                if definition is not None and not callable(definition):
                    cd.default_value = definition
            columns[cd.name or col_name] = cd
        cls.__columns__ = columns
        base_ao = any(
            getattr(getattr(b, "__properties__", None), "append_only", False) for b in bases
        )
        cls.__properties__ = SchemaProperties(append_only=bool(append_only) or base_ao)

    # --- introspection -----------------------------------------------------
    def columns(cls) -> dict[str, ColumnDefinition]:
        return dict(cls.__columns__)

    def column_names(cls) -> list[str]:
        return list(cls.__columns__.keys())

    def keys(cls) -> list[str]:
        return cls.column_names()

    def primary_key_columns(cls) -> list[str] | None:
        pk = [n for n, c in cls.__columns__.items() if c.primary_key]
        return pk or None

    def typehints(cls) -> dict[str, Any]:
        return {n: c.dtype for n, c in cls.__columns__.items()}

    def dtypes(cls) -> dict[str, dt.DType]:
        return {n: c.dtype for n, c in cls.__columns__.items()}

    def __getitem__(cls, name: str) -> ColumnDefinition:
        return cls.__columns__[name]

    def __or__(cls, other: "SchemaMetaclass") -> "SchemaMetaclass":
        cols = dict(cls.__columns__)
        cols.update(other.__columns__)
        return schema_from_columns(cols, name=f"{cls.__name__}|{other.__name__}")

    def __repr__(cls) -> str:
        inner = ", ".join(f"{n}: {c.dtype!r}" for n, c in cls.__columns__.items())
        return f"<Schema {cls.__name__}({inner})>"

    def __str__(cls) -> str:
        return repr(cls)

    # --- derivation --------------------------------------------------------
    def with_types(cls, **kwargs: Any) -> "SchemaMetaclass":
        cols = dict(cls.__columns__)
        for n, t in kwargs.items():
            if n not in cols:
                raise ValueError(f"Schema has no column {n!r}")
            old = cols[n]
            cols[n] = ColumnDefinition(
                dtype=dt.wrap(t),
                primary_key=old.primary_key,
                default_value=old.default_value,
                name=n,
                append_only=old.append_only,
            )
        return schema_from_columns(cols, name=cls.__name__)

    def without(cls, *names: str) -> "SchemaMetaclass":
        cols = {n: c for n, c in cls.__columns__.items() if n not in names}
        return schema_from_columns(cols, name=cls.__name__)

    def update_properties(cls, **kwargs: Any) -> "SchemaMetaclass":
        out = schema_from_columns(dict(cls.__columns__), name=cls.__name__)
        for k, v in kwargs.items():
            setattr(out.__properties__, k, v)
        return out

    @property
    def append_only(cls) -> bool:
        return cls.__properties__.append_only


class Schema(metaclass=SchemaMetaclass):
    """Base class for user-declared schemas::

        class InputSchema(pw.Schema):
            doc: str
            rank: int = pw.column_definition(primary_key=True)
    """


def schema_from_columns(
    columns: Mapping[str, ColumnDefinition], name: str = "AnonymousSchema"
) -> SchemaMetaclass:
    cls = SchemaMetaclass(name, (Schema,), {"__module__": __name__, "__qualname__": name})
    cls.__columns__ = {
        n: ColumnDefinition(
            dtype=c.dtype,
            primary_key=c.primary_key,
            default_value=c.default_value,
            name=n,
            append_only=c.append_only,
        )
        for n, c in columns.items()
    }
    return cls


def schema_from_types(_name: str = "AnonymousSchema", **kwargs: Any) -> SchemaMetaclass:
    """``pw.schema_from_types(x=int, y=str)``."""
    return schema_from_columns(
        {n: ColumnDefinition(dtype=dt.wrap(t), name=n) for n, t in kwargs.items()},
        name=_name,
    )


def schema_from_dict(
    columns: Mapping[str, Any], name: str = "AnonymousSchema"
) -> SchemaMetaclass:
    cols: dict[str, ColumnDefinition] = {}
    for n, spec in columns.items():
        if isinstance(spec, ColumnDefinition):
            spec.name = spec.name or n
            cols[n] = spec
        elif isinstance(spec, dict):
            cols[n] = ColumnDefinition(
                dtype=dt.wrap(spec.get("dtype", Any)),
                primary_key=spec.get("primary_key", False),
                default_value=spec.get("default_value", _NO_DEFAULT),
                name=n,
            )
        else:
            cols[n] = ColumnDefinition(dtype=dt.wrap(spec), name=n)
    return schema_from_columns(cols, name=name)


class _SchemaBuilder:
    def __init__(self) -> None:
        self._cols: dict[str, ColumnDefinition] = {}


def schema_builder(
    columns: Mapping[str, ColumnDefinition], *, name: str = "AnonymousSchema", properties: SchemaProperties | None = None
) -> SchemaMetaclass:
    out = schema_from_columns(
        {n: c for n, c in columns.items()}, name=name
    )
    if properties is not None:
        out.__properties__ = properties
    return out


def schema_from_pandas(df: Any, *, id_from: list[str] | None = None, name: str = "PandasSchema") -> SchemaMetaclass:
    import numpy as np

    cols: dict[str, ColumnDefinition] = {}
    for col in df.columns:
        kind = df[col].dtype.kind
        mapped: Any
        if kind == "i":
            mapped = dt.INT
        elif kind == "f":
            mapped = dt.FLOAT
        elif kind == "b":
            mapped = dt.BOOL
        elif kind == "M":
            mapped = dt.DATE_TIME_NAIVE
        elif kind == "m":
            mapped = dt.DURATION
        else:
            sample = df[col].dropna()
            if len(sample) and all(isinstance(v, str) for v in sample):
                mapped = dt.STR
            else:
                mapped = dt.ANY
        cols[str(col)] = ColumnDefinition(
            dtype=mapped, name=str(col), primary_key=bool(id_from and col in id_from)
        )
    del np
    return schema_from_columns(cols, name=name)


def is_schema(obj: Any) -> bool:
    return isinstance(obj, SchemaMetaclass)
