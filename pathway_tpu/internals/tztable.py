"""Packed timezone transition tables for the native expression VM.

``dt.to_utc`` / ``dt.to_naive_in_timezone`` take a timezone NAME as a
build-time constant, so the zone's full transition table can be resolved
once at graph build and shipped to the VM as a constant operand; the
native method then converts each row with a binary search over int64
arrays instead of a Python ``ZoneInfo`` call per value.

The tables come from the pure-Python ``zoneinfo._zoneinfo`` loader (the
C-accelerated class hides them), which reads the same TZif data the
runtime closures use:

- ``_trans_utc``     — utc-side bisection keys (epoch seconds)
- ``_trans_local``   — local-side keys, one list per ``fold``
- ``_ttinfos[i]``    — offset applying AFTER transition ``i``
- ``_tti_before``    — offset before the first transition
- ``_tz_after``      — footer: a fixed offset, or a POSIX DST rule
                       (``_TZStr``) the native path does NOT evaluate —
                       out-of-range rows fall back to Python per value.

A zone that cannot be packed yields the 2-tuple ``(name, fallback)``
sentinel — NEVER ``None``: a ``None`` operand would propagate-to-None
through the VM and silently wipe every row.
"""

from __future__ import annotations

from typing import Any, Callable

_packed_cache: dict[str, tuple | None] = {}


def _packed(tz_name: str) -> tuple | None:
    """Arrays + runtime instance for ``tz_name``, or None if unpackable."""
    try:
        import zoneinfo
        from array import array
        from zoneinfo import _zoneinfo as zp

        src = zp.ZoneInfo(tz_name)  # pure-Python impl exposes the tables
        zi = zoneinfo.ZoneInfo(tz_name)  # runtime instance (identity checks)

        def _secs(td: Any) -> int:
            if td.microseconds != 0:  # sub-second offset: not packable
                raise ValueError(tz_name)
            return td.days * 86400 + td.seconds

        trans_utc = tuple(src._trans_utc)
        lk0, lk1 = (tuple(v) for v in src._trans_local)
        offs = tuple(_secs(t.utcoff) for t in src._ttinfos)
        off_before = _secs(src._tti_before.utcoff)
        after = src._tz_after
        after_off = _secs(after.utcoff) if isinstance(after, zp._ttinfo) else None
        if not (len(trans_utc) == len(lk0) == len(lk1) == len(offs)):
            return None

        def pack(xs: tuple) -> bytes:
            return array("q", xs).tobytes()

        return (
            pack(trans_utc),
            pack(lk0),
            pack(lk1),
            pack(offs),
            off_before,
            after_off,
            zi,
        )
    except Exception:  # noqa: BLE001 — unknown zone, odd TZif, no tzdata
        return None


def build_tz_table(tz_name: str, fallback: Callable) -> tuple:
    """Native operand for one ``to_utc``/``to_naive_in_timezone`` site.

    ``fallback`` is the call site's own conversion closure (semantic
    ground truth); the native method invokes it per value for anything
    the packed table cannot answer exactly.
    """
    if tz_name not in _packed_cache:
        _packed_cache[tz_name] = _packed(tz_name)
    packed = _packed_cache[tz_name]
    if packed is None:
        return (tz_name, fallback)
    return (tz_name, *packed, fallback)
